"""RabiaEngine: the host event loop around the vectorized consensus kernel.

Reference parity: rabia-engine/src/engine.rs — the engine drives
propose → vote-R1 → vote-R2 → decide → apply (:184-236 run loop, :288-347
propose path, :381-746 message handlers, :684-706 apply, :748-844 sync,
:846-907 heartbeat/sync initiation, :923-947 receive loop). The consensus
*math* of those handlers (vote rules, tallies, coin, decision) lives in the
node kernel — :class:`rabia_tpu.kernel.host_driver.HostNodeKernel` (numpy,
the host hot loop) or :class:`rabia_tpu.kernel.phase_driver.NodeKernel`
(JAX, the device path) — and runs for all S shards in one call per round;
this module is everything around it: message routing, slot lifecycle, batch
payloads, state-machine application, persistence, heartbeats, sync and
stats.

Hot-path design (SURVEY.md §7.4.4): everything per-round is **columnar** —
vote vectors arrive as numpy arrays (:class:`~rabia_tpu.core.messages.
_VoteVector`), are routed to the kernel ledger with bulk scatters, and the
kernel outbox is turned back into broadcast vote vectors with bulk gathers.
Per-shard Python runs only on *events* (slot open, decision record, batch
apply), never in per-round scans.

Protocol notes (deliberate divergences from the reference implementation,
both fixing documented deviations — SURVEY.md §3.1):

1. Round-1 AND round-2 votes are **broadcast** to all replicas (the spec's
   reliable-broadcast model, docs/weak_mvc.ivy:133-186), not unicast to the
   proposer.
2. The round-2 tie-break is a **common coin** shared by construction
   (same seed + (shard, slot, phase) on every replica), not per-node RNG.

Slot model: each shard carries an ordered log of decision slots. The
proposer of (shard, slot) rotates deterministically
(:func:`rabia_tpu.engine.leader.slot_proposer`); non-proposers forward
their submissions to the upcoming proposer (NewBatch). A crashed proposer's
slot times out on peers, who open it with vote V0 — weak MVC then decides
V0 (a null slot) and the rotation moves on: leaderless liveness without
elections.
"""

from __future__ import annotations

import asyncio
import ctypes
import logging
import os
import time
import uuid
from typing import Optional, Sequence

import numpy as np

from rabia_tpu.core.blocks import PayloadBlock
from rabia_tpu.core.config import RabiaConfig
from rabia_tpu.core.errors import (
    PersistenceError,
    QuorumNotAvailableError,
    RabiaError,
    ResponsesUnavailableError,
    ValidationError,
)
from rabia_tpu.core.messages import (
    Decision,
    DecisionEntry,
    HeartBeat,
    MessageType,
    NewBatch,
    ProposeBlock,
    ProtocolMessage,
    Propose,
    QuorumNotification,
    SyncRequest,
    SyncResponse,
    VoteEntry,
    VoteRound1,
    VoteRound2,
)
from rabia_tpu.core.network import (
    ClusterConfig,
    NetworkEventHandler,
    NetworkMonitor,
    NetworkTransport,
)
from rabia_tpu.core.persistence import PersistedEngineState, PersistenceLayer
from rabia_tpu.core.serialization import Serializer
from rabia_tpu.core.state_machine import StateMachine, VectorStateMachine
from rabia_tpu.core.tracing import span
from rabia_tpu.obs.flight import (
    FRE_ADVANCE,
    FRE_APPLY,
    FRE_CARRY,
    FRE_CAST_R2,
    FRE_DECIDE,
    FRE_DROP,
    FRE_FRAME_IN,
    FRE_FRAME_OUT,
    FRE_OPEN,
    FRE_PROPOSE,
    FRE_ROUTE1,
    FRE_ROUTE2,
    FRE_STALE,
    FRE_STEP_DECIDE,
    FRE_SUBMIT,
    FRE_WAL,
    fr_hash,
)
from rabia_tpu.core.types import (
    ABSENT,
    V0,
    V1,
    BatchId,
    CommandBatch,
    NodeId,
    StateValue,
    sorted_nodes,
)
from rabia_tpu.core.validation import MessageValidator
from rabia_tpu.engine.leader import LeaderSelector, slot_proposer, slot_proposer_vec
from rabia_tpu.engine.state import (
    EngineRuntime,
    EngineStatistics,
    PendingSubmission,
    SlotRecord,
)
from rabia_tpu.kernel.host_driver import HostNodeKernel
from rabia_tpu.kernel.phase_driver import (
    NodeKernel,
    R1_WAIT,
    R2_WAIT,
    pack_phase,
    unpack_phase,
)

logger = logging.getLogger("rabia_tpu.engine")

_MAX_SUBMIT_ATTEMPTS = 3
_MVC_MASK = (1 << 16) - 1


def _aligned_i8(shape, fill: int, align: int = 64) -> np.ndarray:
    """An i8 array on a 64-byte-aligned base: XLA's CPU client adopts
    aligned external buffers zero-copy via dlpack; unaligned ones get a
    defensive copy (which would silently defeat zero_copy_inbox on the
    small shard counts whose numpy allocations aren't page-backed)."""
    n = int(np.prod(shape))
    raw = np.full(n + align, np.int8(fill), np.int8)
    off = (-raw.ctypes.data) % align
    return raw[off : off + n].reshape(shape)


class _OutBlock:
    """Proposer-side pending block: aggregates per-shard outcomes into one
    client future (one response list — or Exception — per covered shard)."""

    __slots__ = ("block", "future", "responses", "remaining", "created_at")

    def __init__(self, block: PayloadBlock, future: asyncio.Future):
        self.block = block
        self.future = future
        self.responses: list = [None] * len(block)
        self.remaining = len(block)
        self.created_at = time.time()

    def settle(self, i: int, outcome) -> None:
        if self.responses[i] is None:
            self.responses[i] = outcome
            self.remaining -= 1
            if self.remaining == 0 and self.future is not None and not self.future.done():
                self.future.set_result(self.responses)

    def settle_many(self, idxs, outcomes) -> None:
        """Bulk settle (the native runtime's wave events): one pass, one
        future check — per-entry settle() calls measurably tax the
        proposer side at thousands of entries per wave."""
        responses = self.responses
        hit = 0
        for i, o in zip(idxs, outcomes):
            if responses[i] is None:
                responses[i] = o
                hit += 1
        if hit:
            self.remaining -= hit
            if (
                self.remaining == 0
                and self.future is not None
                and not self.future.done()
            ):
                self.future.set_result(responses)


class _BlockRef:
    """Registry record for a live block (incoming or our own)."""

    __slots__ = ("block", "out", "src_row", "remaining", "registered_at")

    def __init__(self, block: PayloadBlock, out, src_row: int):
        self.block = block
        self.out = out
        self.src_row = src_row
        self.remaining = len(block)
        self.registered_at = time.time()


class _Wake:
    """Single-waiter wake signal: ``asyncio.Event`` semantics without the
    inner Task that ``wait_for(event.wait(), t)`` spawns. A transport
    notify resumes the run loop in ONE ready-queue generation instead of
    three (set → inner-task wakeup → outer-task wakeup), which was worth
    ~1 ms of the serial commit path under a busy loop (VERDICT r05 weak
    #1: config-1 p50 regression)."""

    __slots__ = ("_flag", "_fut")

    def __init__(self) -> None:
        self._flag = False
        self._fut: Optional[asyncio.Future] = None

    def set(self) -> None:
        self._flag = True
        f = self._fut
        if f is not None and not f.done():
            f.set_result(None)

    def clear(self) -> None:
        self._flag = False

    def is_set(self) -> bool:
        return self._flag

    async def wait(self, timeout: float) -> None:
        """Wait until set() or `timeout` elapses (no exception either way)."""
        if self._flag:
            return
        loop = asyncio.get_running_loop()
        f = loop.create_future()
        self._fut = f
        h = loop.call_later(timeout, self._timeout, f)
        try:
            await f
        finally:
            h.cancel()
            self._fut = None

    @staticmethod
    def _timeout(f: asyncio.Future) -> None:
        if not f.done():
            f.set_result(None)


class _EngineNetHandler(NetworkEventHandler):
    """Connectivity events → engine pause/resume (engine.rs:983-997).

    Losing quorum pauses consensus (no new slots, no kernel rounds, no
    retransmission — inbound traffic still drains so Decisions/sync adopt
    passively); restoration resumes it. Both transitions are announced with
    a QuorumNotification broadcast (messages.rs:132-136 parity)."""

    def __init__(self, engine: "RabiaEngine") -> None:
        self.engine = engine

    async def on_node_connected(self, node: NodeId) -> None:
        logger.info(
            "%s: peer %s connected", self.engine.node_id.short(), node.short()
        )

    async def on_node_disconnected(self, node: NodeId) -> None:
        logger.warning(
            "%s: peer %s disconnected", self.engine.node_id.short(), node.short()
        )

    async def on_partition_detected(self, reachable) -> None:
        logger.warning(
            "%s: partition detected — reachable %d/%d",
            self.engine.node_id.short(),
            len(reachable),
            self.engine.cluster.total_nodes,
        )

    async def on_quorum_lost(self) -> None:
        e = self.engine
        e._paused = True
        e.rt.is_active = False
        e.journal.record(
            e.journal.QUORUM_LOST, active=len(e.rt.active_nodes)
        )
        logger.warning("%s: quorum LOST — consensus paused", e.node_id.short())
        e._send(
            QuorumNotification(
                has_quorum=False,
                active_nodes=tuple(sorted_nodes(e.rt.active_nodes)),
            )
        )

    async def on_quorum_restored(self) -> None:
        e = self.engine
        e._paused = False
        e.rt.is_active = True
        e.journal.record(
            e.journal.QUORUM_RESTORED, active=len(e.rt.active_nodes)
        )
        logger.info(
            "%s: quorum RESTORED — consensus resumed", e.node_id.short()
        )
        e._send(
            QuorumNotification(
                has_quorum=True,
                active_nodes=tuple(sorted_nodes(e.rt.active_nodes)),
            )
        )


class RabiaEngine:
    """One replica's consensus engine (engine.rs:25-42 analog).

    Generic over the three core seams: ``state_machine`` (bytes interface),
    ``transport`` and optional ``persistence`` — construct with any
    implementations of those ABCs (the reference's `RabiaEngine<SM, NT, PL>`
    type parameters).
    """

    def __init__(
        self,
        cluster: ClusterConfig,
        state_machine: StateMachine,
        transport: NetworkTransport,
        persistence: Optional[PersistenceLayer] = None,
        config: Optional[RabiaConfig] = None,
    ) -> None:
        self.cluster = cluster
        self.node_id = cluster.node_id
        self.sm = state_machine
        self.transport = transport
        self.persistence = persistence
        self.config = config or RabiaConfig()

        self.R = cluster.total_nodes
        self.me = cluster.replica_index(self.node_id)
        kc = self.config.kernel
        self.S = kc.padded_shards
        self.n_shards = max(1, kc.num_shards)
        # The coin seed must be identical cluster-wide (it IS the common
        # coin); randomization_seed defaults to 0 for all nodes.
        seed = self.config.randomization_seed or 0
        self._host_kernel = kc.backend != "jax"
        self._substeps = max(1, int(kc.device_substeps))
        self._zc_inbox = bool(kc.zero_copy_inbox) and not self._host_kernel
        if not self._host_kernel:
            # fenced: the device-array engine backend is for DIRECTLY-
            # ATTACHED accelerators; on tunneled hardware the per-tick
            # readback floor caps it ~75x below the host kernel
            # (jax_engine_r03). The mesh plane (parallel/) is the
            # supported device story for windowed consensus.
            logger.warning(
                "KernelConfig.backend='jax' selected: intended for "
                "directly-attached accelerators only (see "
                "docs/PERFORMANCE.md, 'Engine kernel backends')"
            )
        kernel_cls = HostNodeKernel if self._host_kernel else NodeKernel
        self.kernel = kernel_cls(
            self.S, self.R, self.me, coin_p1=kc.coin_p1, seed=seed
        )
        self.kstate = self.kernel.init_state()
        self.rt = EngineRuntime(self.S)
        self.serializer = Serializer(self.config.serialization)
        self.validator = MessageValidator(self.config.validation)
        self.leader = LeaderSelector(cluster.all_nodes)
        self._paused = False
        self.monitor = NetworkMonitor(cluster, handler=_EngineNetHandler(self))

        # host mirrors of kernel arrays (aliases in host-kernel mode,
        # refreshed copies in jax mode)
        self._refresh_mirrors()

        # vote stash: arrays appended at ingest, routed to the kernel in
        # bulk once per tick ([(row, shards, slots, mvcs, vals)] per round)
        self._restep = False
        self._stash1: list[tuple] = []
        self._stash2: list[tuple] = []
        # carry: future-(slot, phase) votes kept across ticks (same tuple
        # shape); bounded in _route_votes
        self._carry1: list[tuple] = []
        self._carry2: list[tuple] = []
        # adopted-decision plane consumed by the next node_step
        # (64-byte-aligned so zero_copy_inbox adoption is actually
        # zero-copy — see _aligned_i8)
        self._dec_plane = _aligned_i8(self.S, ABSENT)
        if not self._host_kernel:
            self._inbox1 = _aligned_i8((self.S, self.R), ABSENT)
            self._inbox2 = _aligned_i8((self.S, self.R), ABSENT)
        self._shard_ids = np.arange(self.S, dtype=np.int64)
        # reused open planes: (mask, slots_full, init_full) — consumers
        # only read masked positions (start_slots/node_cycle are
        # mask-gated), so stale unmasked values are never observed
        self._open_planes = (
            np.zeros(self.S, bool),
            np.zeros(self.S, np.int64),
            np.full(self.S, V0, np.int8),
        )
        self._apply_dirty: set[int] = set()
        # pipelined apply stage (engine/apply_plane.py): inline up to a
        # budget, backlog drains off-tick so consensus keeps rounding
        from rabia_tpu.engine.apply_plane import ApplyPlane

        self._apply_plane = ApplyPlane(self)
        # native columnar helpers (hostkernel.cpp); None -> numpy paths
        from rabia_tpu.native.build import load_hostkernel

        self._hk_lib = load_hostkernel()
        self._open_bufs = (
            np.zeros(self.n_shards, np.int64),
            np.zeros(self.n_shards, np.uint8),
        )
        # raw-pointer tuples cached once: per-tick ndarray.ctypes
        # marshalling costs more than the C scans themselves at small S
        if self._hk_lib is not None:
            rt = self.rt
            self._open_scan_args = (
                self.n_shards,
                rt.next_slot.ctypes.data, rt.applied_upto.ctypes.data,
                rt.in_flight.ctypes.data, rt.queue_len.ctypes.data,
                rt.prop_flag.ctypes.data, rt.dec_flag.ctypes.data,
                rt.votes_seen_slot.ctypes.data,
                rt.tainted_upto.ctypes.data,
                self._open_bufs[0].ctypes.data,
                self._open_bufs[1].ctypes.data,
            )
            self._stall_scan_args = (
                self.n_shards,
                rt.in_flight.ctypes.data,
                rt.last_progress.ctypes.data,
            )
        else:
            self._open_scan_args = None
            self._stall_scan_args = None

        # block lane (bulk proposals — rabia_tpu.core.blocks):
        # registry of live blocks by small int handle; columnar bindings
        self._blk_registry: dict[int, _BlockRef] = {}
        self._blk_next_ref = 1
        self._blk_pending_ref = np.full(self.S, -1, np.int64)
        self._blk_pending_idx = np.zeros(self.S, np.int64)
        self._blk_pending_slot = np.full(self.S, -1, np.int64)
        self._cur_blk_ref = np.full(self.S, -1, np.int64)
        self._cur_blk_idx = np.zeros(self.S, np.int64)
        self._pending_block_announces: list[ProposeBlock] = []
        self._last_blk_retransmit: dict[int, float] = {}
        self._is_vector_sm = isinstance(state_machine, VectorStateMachine)

        # write-ahead vote barrier: _barrier[s] is persisted BEFORE this
        # replica's first vote in any slot >= the previous barrier, so a
        # restart knows exactly which slots may hold its pre-crash votes
        self._barrier = np.zeros(self.S, np.int64)
        # read-index floor: the RESTORED barrier. decided_frontier() must
        # never under-report a slot this replica voted round 2 in, and a
        # pre-crash vote can sit above the restored next_slot (cast after
        # the last checkpoint) — the barrier bounds all of them
        self._frontier_floor = np.zeros(self.S, np.int64)
        self._restored_at = 0.0
        self._pending_proposes: list[Propose] = []

        self._row_to_node = {i: n for i, n in enumerate(cluster.all_nodes)}
        self._node_to_row = {n: i for i, n in enumerate(cluster.all_nodes)}
        # native per-tick fast path (ingest→route→tally→outbox in one C
        # call; Python only on events). RABIA_PY_TICK=1 forces the Python
        # paths, which stay the semantics owner (conformance pinned by
        # tests/test_native_tick.py + the seeded fuzz schedules).
        self._rk = None
        if (
            self._host_kernel
            and self._hk_lib is not None
            and hasattr(self._hk_lib, "rk_ctx_create")
            and os.environ.get("RABIA_PY_TICK") != "1"
            and self.R <= 64
        ):
            try:
                from rabia_tpu.engine.native_tick import NativeTick

                self._rk = NativeTick(self, self._hk_lib)
            except Exception:
                logger.exception(
                    "native tick unavailable; using the Python tick path"
                )
                self._rk = None
        # native engine runtime (native/runtime.cpp): a GIL-free io/tick
        # thread runs ingest→route→tally→decide→apply→result end-to-end
        # for C-transport clusters; Python is demoted to control plane
        # (engine/runtime_bridge.py). RABIA_PY_RUNTIME=1 forces today's
        # asyncio orchestration, which stays the semantics owner behind
        # the run_schedule_on_runtime_paths conformance gate.
        # durability plane (persistence/native_wal.py): when the
        # persistence layer is a WAL, decided waves stage into it from
        # the apply paths and the vote barrier rides its group-commit
        # lane — which is what lets the native runtime engage on a
        # durable cluster (the historical persistence gate below)
        self._wal = (
            persistence
            if getattr(persistence, "supports_wal", False)
            else None
        )
        self._rtm = None
        if self._rk is not None and (
            persistence is None
            or (self._wal is not None and getattr(self._wal, "native", False))
        ):
            try:
                from rabia_tpu.engine.runtime_bridge import (
                    RuntimeBridge,
                    runtime_available,
                )
                from rabia_tpu.native.build import load_runtime

                if runtime_available(self):
                    rtm_lib = load_runtime()
                    if rtm_lib is not None:
                        self._rtm = RuntimeBridge(self, rtm_lib)
            except Exception:
                logger.exception(
                    "native runtime unavailable; using the asyncio "
                    "orchestration"
                )
                self._rtm = None
        self._seen_batches: set = set()  # dedup of forwarded batch ids
        self._seen_order: list = []  # insertion order for bounded eviction
        # decided-frontier hook (rabia_tpu/gateway): callbacks fired once
        # per tick when the applied frontier advanced (scalar or block
        # lane) — the gateway's read-index waiters ride this instead of
        # polling the runtime arrays
        self._frontier_listeners: list = []
        self._frontier_dirty = False
        # cached per-transport drain accessors (resolved once, not per tick)
        self._recv_borrow = getattr(
            transport, "receive_borrowed_nowait", None
        )
        self._recv_nowait = getattr(transport, "receive_nowait", None)
        # address-level drain for the native tick (net/tcp.py): the C
        # ingest reads vote frames straight from the arena address
        self._recv_raw = getattr(transport, "receive_raw_nowait", None)
        self._bg_tasks: set = set()  # strong refs: loop holds tasks weakly
        self._running = False
        self._stopped = asyncio.Event()
        self._stopped.set()  # not running yet: shutdown() must not hang
        self._wake = _Wake()  # wake-on-inbox / wake-on-submit
        self._notify_wired = False
        self._dirty = False  # committed something since last save
        self._last_heartbeat = 0.0
        self._last_cleanup = 0.0
        self._last_monitor = 0.0
        self._last_repair: dict[int, float] = {}  # sender row -> last repair
        self._peer_progress: dict[NodeId, tuple[int, float]] = {}
        self._peer_quorum_views: dict[NodeId, tuple[bool, float]] = {}

        if self.n_shards > self.S:
            raise ValidationError("num_shards exceeds padded kernel width")

        self._init_obs()

    # ------------------------------------------------------------------
    # Observability (rabia_tpu/obs — docs/OBSERVABILITY.md taxonomy)
    # ------------------------------------------------------------------

    def _init_obs(self) -> None:
        """Register this replica's metrics + anomaly journal.

        Pull-based: gauges/source-backed counters read runtime state (and
        the native C counter blocks, zero-copy) at scrape time; the only
        hot-path costs are plain int increments on EVENT paths. The
        native tick and ``RABIA_PY_TICK=1`` feed the SAME metric names —
        native counts ride the rk counter block, Python-path counts ride
        the ``_py_*`` event tallies, and the exported value is their sum
        (each path leaves the other's source at zero), so the
        conformance gate can assert counter parity across tick paths."""
        from rabia_tpu.core.tracing import tracer
        from rabia_tpu.obs import AnomalyJournal, MetricsRegistry
        from rabia_tpu.obs.flight import FlightRecorder

        m = self.metrics = MetricsRegistry()
        m.attach_tracer(tracer)
        self.journal = AnomalyJournal()
        # flight recorder (docs/OBSERVABILITY.md "Flight recorder"): the
        # Python event ring. On the native tick path the per-frame kinds
        # live in the C ring (rk_flight); RABIA_PY_TICK=1 feeds the same
        # kinds here; engine lifecycle events (submit/propose/decide/
        # apply) land here on BOTH paths. flight_events() merges.
        self.flight = FlightRecorder()
        self._last_flight_dump = 0.0
        # severe anomalies auto-dump the merged rings to RABIA_FLIGHT_DIR
        # (a no-op when the env var is unset)
        self.journal.on_severe = self._flight_autodump
        self._tick_count = 0
        self._slow_ticks = 0
        # Python-path event tallies (the RABIA_PY_TICK twin of the rk
        # counter block; also counts frames the native ingest declined)
        self._py_frames = {"vote1": 0, "vote2": 0, "decision": 0}
        self._py_drops = {"spoof": 0, "skew": 0, "malformed": 0}
        self._py_stale = 0
        self._last_dials = 0
        # a tick slower than half the phase timeout (floored for test
        # configs with tiny timeouts) is an anomaly worth journaling
        self._slow_tick_s = max(0.25, self.config.phase_timeout / 2)

        rt = self.rt
        n = self.n_shards

        def rk_ctr(name):
            rk = self._rk
            return rk.counter(name) if rk is not None else 0

        # -- engine progress (deterministic across tick paths: the
        #    conformance parity set) ------------------------------------
        m.counter(
            "engine_decided_total",
            "Slots decided by this replica, by decided value",
            {"value": "v1"},
            fn=lambda: rt.decided_v1,
        )
        m.counter(
            "engine_decided_total", "", {"value": "v0"},
            fn=lambda: rt.decided_v0,
        )
        m.counter(
            "engine_applied_slots_total",
            "Contiguously applied slots across shards",
            fn=lambda: int(rt.applied_upto[:n].sum()),
        )
        m.counter(
            "engine_state_version",
            "V1 batches applied (the replicated-state version)",
            fn=lambda: rt.state_version,
        )
        # -- liveness / load --------------------------------------------
        m.gauge(
            "engine_has_quorum", "1 while this replica sees a quorum",
            fn=lambda: 1 if rt.has_quorum else 0,
        )
        m.gauge(
            "engine_active_nodes", "Peers considered active",
            fn=lambda: len(rt.active_nodes),
        )
        m.gauge(
            "engine_pending_batches", "Locally queued submissions",
            fn=lambda: int(rt.queue_len[:n].sum()),
        )
        m.gauge(
            "engine_in_flight_shards", "Shards with an open consensus slot",
            fn=lambda: int(rt.in_flight[:n].sum()),
        )
        m.gauge(
            "engine_native_tick",
            "1 when the native rk tick context is active",
            fn=lambda: 1 if self._rk is not None else 0,
        )
        # -- native engine runtime (runtime.cpp RTM counter block) -------
        m.gauge(
            "engine_native_runtime",
            "1 when the GIL-free runtime thread owns the commit path",
            fn=lambda: 1 if self._rtm is not None else 0,
        )

        def rtm_ctr(name):
            rtm = self._rtm
            return rtm.counter(name) if rtm is not None else 0

        for name in (
            "loops", "wakes_frame", "wakes_idle", "frames_native",
            "frames_block", "frames_escalated", "cmds", "opens_scalar",
            "opens_block", "ticks", "decided_scalar", "waves_native",
            "waves_py", "slots_applied", "ev_records", "ev_stalls",
            "retransmits", "stale_repairs", "pauses",
        ):
            m.counter(
                f"runtime_{name}_total",
                "Native runtime counter (runtime.cpp RTM block)",
                fn=lambda r=name: rtm_ctr(r),
            )
        # the acceptance counter: commit-path transitions that required
        # the GIL. Zero growth while waves_native grows = the steady-state
        # commit path never re-enters Python.
        m.counter(
            "runtime_gil_handoffs_total",
            "Decided waves whose decide->apply->result needed Python",
            fn=lambda: rtm_ctr("gil_handoffs"),
        )
        # -- consensus-health telemetry (chaos plane: the paper's
        #    randomized-termination curve, docs/SCENARIOS.md). Three
        #    sources feed ONE metric identity, mirroring the tick-path
        #    convention above: the rk tick context's C bins (native tick
        #    AND the GIL-free runtime share the ctx), HostNodeKernel's
        #    host bins (RABIA_PY_TICK / host-kernel engines), and the
        #    engine's device-window bins — each path leaves the others'
        #    sources at zero.
        self._dev_phase_hist = np.zeros(32, np.int64)
        self._dev_phase_sum = 0
        phase_bounds = tuple(float(b) for b in range(1, 33))

        def phase_curve():
            hist = np.zeros(32, np.int64)
            ssum = 0
            rk = self._rk
            if rk is not None:
                h = np.asarray(rk.phase_hist, np.int64)
                hist[: len(h)] += h
                for sib in getattr(rk, "siblings", ()):
                    sh = np.asarray(sib.phase_hist, np.int64)
                    hist[: len(sh)] += sh
                ssum += rk.counter("phase_sum")  # sums siblings itself
            kern = getattr(self, "kernel", None)
            kh = getattr(kern, "phase_hist", None)
            if kh is not None:
                hist[: len(kh)] += np.asarray(kh, np.int64)
                ssum += int(kern.phase_sum)
            hist += self._dev_phase_hist
            ssum += self._dev_phase_sum
            # bin p (decisions taking p phases) lands in bucket bound p,
            # i.e. index p-1. The sources' top bin (31) is a CLAMP —
            # "exactly 31 OR more" — so it rides the TOP bound (32,
            # claiming <= 32: true for 31, best-effort for the
            # astronomically rare beyond) instead of mislabeling the
            # extreme tail as <= 31. Bin 0 (impossible: deciding
            # requires an advance) joins it defensively.
            counts = [int(hist[j + 1]) for j in range(30)]
            counts.append(0)  # bound 31: absorbed into the clamp bucket
            counts.append(int(hist[31]) + int(hist[0]))
            return counts, int(hist.sum()), float(ssum)

        m.histogram(
            "phases_to_decide",
            "Weak-MVC phases each locally tally-decided slot took "
            "(1 = decided in its first phase); the randomized-"
            "termination evidence curve",
            buckets=phase_bounds,
            fn=phase_curve,
        )

        # -- per-phase consensus dwell (the critical-path decomposer's
        #    consensus segments, obs/critpath.py): how long each phase
        #    ordinal took in wall time, not just how many phases ran.
        #    Native source: the rk ctx's RK_DWELL histogram block
        #    (hostkernel.cpp — the GIL-free runtime shares the ctx, so
        #    both native planes land there); Python twin: _py_dwell, fed
        #    by the engine's open/outbox processing on the RABIA_PY_TICK
        #    host path and the jax device path. Identical geometry (the
        #    SLO buckets), same metric name either way — the critpath
        #    name-parity test pins this.
        from rabia_tpu.obs.registry import (
            SLO_BUCKETS as _SLO_B,
            SLO_MIN_EXP,
            SLO_SUB_BITS,
        )

        n_slo = len(_SLO_B)
        self._py_dwell = np.zeros((8, n_slo + 2), np.uint64)
        self._dwell_t0 = np.zeros(self.S, np.int64)
        self._dwell_t0_slot = np.full(self.S, -1, np.int64)

        def dwell_row(row):
            agg = np.zeros(n_slo + 2, np.int64)
            rk = self._rk
            if rk is not None and rk.dwell_geometry == (
                n_slo, SLO_SUB_BITS, SLO_MIN_EXP
            ):
                for src in (rk, *getattr(rk, "siblings", ())):
                    if row < len(src.dwell):
                        agg += src.dwell[row].astype(np.int64)
            agg += self._py_dwell[row].astype(np.int64)
            return (
                [int(v) for v in agg[:n_slo]],
                int(agg[n_slo]),
                float(agg[n_slo + 1]) * 1e-9,
            )

        for pi in range(8):
            m.histogram(
                "consensus_phase_dwell_seconds",
                "Wall time each weak-MVC phase ordinal dwelt before its "
                "advance (top row clamps 8+; native RK_DWELL block + "
                "Python tick twin, SLO bucket geometry)",
                {"phase": str(pi + 1) if pi < 7 else "8+"},
                buckets=_SLO_B,
                fn=lambda r=pi: dwell_row(r),
            )

        def coin_ctr(i):
            kern = getattr(self, "kernel", None)
            cf = getattr(kern, "coin_flips", None)
            v = int(cf[i]) if cf is not None else 0
            rk = self._rk
            if rk is not None:
                v += rk.counter("coin_v1" if i else "coin_v0")
            return v

        m.counter(
            "coin_flips_total",
            "Common-coin flips by outcome (round-2 all-? tie-breaks). "
            "Covers the host/native decide paths; the jitted device "
            "kernel flips inside XLA and is not tallied here",
            {"outcome": "v0"},
            fn=lambda: coin_ctr(0),
        )
        m.counter(
            "coin_flips_total", "", {"outcome": "v1"},
            fn=lambda: coin_ctr(1),
        )
        m.counter(
            "engine_ticks_total", "Engine loop ticks",
            fn=lambda: self._tick_count,
        )
        m.counter(
            "engine_slow_ticks_total",
            "Ticks exceeding the slow-tick threshold (journaled)",
            fn=lambda: self._slow_ticks,
        )
        self._syncs = 0
        m.counter(
            "engine_syncs_total", "Snapshot syncs initiated",
            fn=lambda: self._syncs,
        )
        # -- pipelined apply stage (engine/apply_plane.py) ---------------
        m.gauge(
            "apply_backlog_shards",
            "Shards with decided slots queued to the apply-plane drain",
            fn=lambda: self._apply_plane.backlog,
        )
        m.counter(
            "apply_deferred_slots_total",
            "Slots applied by the apply-plane drain task (off-tick)",
            fn=lambda: self._apply_plane.deferred_slots,
        )
        m.counter(
            "apply_drains_total",
            "Apply-plane drain task activations",
            fn=lambda: self._apply_plane.drains,
        )
        # -- native apply plane (statekernel SKC counter block), when the
        #    state machine exposes one ---------------------------------
        sk_plane = getattr(self.sm, "_native_plane", None)
        if sk_plane is not None:
            for name in ("waves", "ops", "errors", "cas_misses"):
                m.counter(
                    f"apply_native_{name}_total",
                    "Native apply plane counter (statekernel SKC block)",
                    fn=lambda r=name, pl=sk_plane: pl.counter(r),
                )
            m.gauge(
                "apply_native_plane",
                "1 when the statekernel apply plane is active",
                fn=lambda: 1,
            )
        m.counter(
            "engine_flight_records_total",
            "Flight-recorder records written (native ring + Python ring)",
            fn=lambda: self.flight.head
            + (self._rk.flight_head() if self._rk is not None else 0),
        )
        # -- the per-tick pipeline (native rk counter block + Python
        #    event tallies feeding the same names) ----------------------
        for kind, rk_name in (
            ("vote1", "frames_vote1"),
            ("vote2", "frames_vote2"),
            ("decision", "frames_decision"),
        ):
            m.counter(
                "tick_frames_total",
                "Consensus frames ingested, by kind (native + Python paths)",
                {"kind": kind},
                fn=lambda k=kind, r=rk_name: rk_ctr(r) + self._py_frames[k],
            )
        for reason in ("spoof", "skew", "malformed"):
            m.counter(
                "tick_drops_total",
                "Frames dropped at ingest, by reason",
                {"reason": reason},
                fn=lambda r=reason: rk_ctr("drop_" + r) + self._py_drops[r],
            )
        m.counter(
            "tick_stale_votes_total",
            "Below-applied vote entries (answered by the targeted repair)",
            fn=lambda: rk_ctr("stale_votes") + self._py_stale,
        )
        m.gauge(
            "tick_carry_pending",
            "Future-(slot,phase) votes currently carried",
            fn=lambda: (
                self._rk.carry_count
                if self._rk is not None
                else sum(
                    1 if type(t[1]) is int else len(t[1])
                    for t in (self._carry1 + self._carry2)
                )
            ),
        )
        for name in (
            "carries", "ledger_scatters", "stages", "out_frames",
            "taint_hits", "opened", "frames_noop",
        ):
            m.counter(
                f"tick_native_{name}_total",
                "rk tick context counter (native path only)",
                fn=lambda r=name: rk_ctr(r),
            )
        # -- commit pipeline latency breakdown (event-path observes; all
        #    stages survive the native tick because record/apply stay
        #    Python events on both paths) -------------------------------
        self._h_stage = {
            stage: m.histogram(
                "commit_stage_seconds",
                "Commit pipeline latency by stage "
                "(submit→propose→decide→apply)",
                {"stage": stage},
            )
            for stage in (
                "submit_propose",
                "propose_decide",
                "decide_apply",
                "submit_apply",
            )
        }
        # -- SLO evidence plane (docs/OBSERVABILITY.md, "SLO histograms"
        #    + "Runtime stage profiler"). Both families are registered on
        #    EVERY runtime path with the same names and label sets —
        #    native contributions ride the runtime's RTH_*/RTS_* blocks
        #    (zero-copy at scrape time), Python-path contributions ride
        #    local observes/tallies, and each path leaves the other's
        #    source at zero, so the conformance story stays counter-parity
        #    shaped. rabia_slo_seconds{stage=submit_result} is fed by the
        #    gateway (Python on both paths). -------------------------------
        from rabia_tpu.obs.registry import (
            RUNTIME_STAGES,
            SLO_BUCKETS,
            SLO_STAGES,
        )

        def rtm_hist(stage):
            rtm = self._rtm
            return rtm.hist_stage(stage) if rtm is not None else None

        self._h_slo = {
            stage: m.histogram(
                "slo_seconds",
                "SLO latency histograms by pipeline stage "
                "(log-bucketed; native RTH block + Python observes)",
                {"stage": stage},
                buckets=SLO_BUCKETS,
                fn=(
                    (lambda s=stage: rtm_hist(s))
                    if stage in ("decide_apply", "broadcast")
                    else None
                ),
            )
            for stage in SLO_STAGES
        }
        # runtime stage profiler: cumulative seconds per commit-path-owner
        # loop stage. While the native runtime owns the commit path its
        # RTS block is the source; on the asyncio orchestration the run
        # loop accounts the same stage names (self._stage_ns) — summed per
        # scrape, the breakdown covers the owner thread's wall time.
        self._stage_ns = {s: 0 for s in RUNTIME_STAGES}
        self._stage_acc = 0
        self._loop_mark = 0
        self._bcast_carve = 0
        for sname in RUNTIME_STAGES:
            m.counter(
                "runtime_stage_seconds",
                "Commit-path owner loop time by stage (native RTS block "
                "or asyncio-loop accounting; `rabia_tpu profile` renders)",
                {"stage": sname},
                fn=lambda s=sname: self.stage_second(s),
            )
        # thread-per-shard-group runtime: per-worker stage series with a
        # `worker` label next to the aggregate above (single-worker and
        # asyncio runs keep the historical label set untouched)
        rtm0 = self._rtm
        if rtm0 is not None and getattr(rtm0, "workers", 1) > 1:
            for g in range(rtm0.workers):
                for sname in RUNTIME_STAGES:
                    m.counter(
                        "runtime_stage_seconds",
                        "Per-worker commit-path loop time by stage "
                        "(thread-per-shard-group runtime)",
                        {"stage": sname, "worker": str(g)},
                        fn=lambda s=sname, gg=g: (
                            self._rtm.stage_ns_worker(gg, s) * 1e-9
                            if self._rtm is not None
                            and gg < getattr(self._rtm, "workers", 1)
                            else 0.0
                        ),
                    )
        # -- durability plane (walkernel WLC counter block / Python twin
        #    tallies — persistence/native_wal.py), when the persistence
        #    layer is a WAL --------------------------------------------
        wal = self._wal
        if wal is not None:
            from rabia_tpu.persistence.native_wal import WAL_COUNTER_NAMES

            m.gauge(
                "wal_native",
                "1 when walkernel.cpp owns the WAL writer (0 = the "
                "RABIA_PY_WAL Python twin)",
                fn=lambda: 1 if wal.native else 0,
            )
            for name in WAL_COUNTER_NAMES:
                if name == "fsync_ns":
                    continue  # exported as wal_fsync_seconds_total below
                m.counter(
                    f"wal_{name}_total",
                    "Durability-plane counter (walkernel WLC block)",
                    fn=lambda r=name: wal.counters_dict().get(r, 0),
                )
            m.counter(
                "wal_fsync_seconds_total",
                "Cumulative seconds spent in WAL fsync (flush thread)",
                fn=lambda: wal.counters_dict().get("fsync_ns", 0) / 1e9,
            )
            m.gauge(
                "wal_staged_lsn", "Last staged WAL record LSN",
                fn=wal.staged_lsn,
            )
            m.gauge(
                "wal_durable_lsn",
                "Durability watermark: last fsynced WAL record LSN",
                fn=wal.durable_lsn,
            )
            m.counter(
                "wal_checkpoints_total",
                "Incremental snapshot checkpoints written",
                fn=lambda: wal.checkpoints,
            )
            m.counter(
                "wal_barrier_waits_total",
                "Durability-barrier watermark waits entered",
                fn=lambda: getattr(wal, "barrier_waits", 0),
            )
            m.counter(
                "wal_barrier_covered_total",
                "Client Results released by durability-barrier waits "
                "(covered/waits = the cross-session batching factor)",
                fn=lambda: getattr(wal, "barrier_covered", 0),
            )
            m.counter(
                "wal_gc_segments_total",
                "WAL segments garbage-collected below the snapshot frontier",
                fn=lambda: wal.gc_segments,
            )

            def wal_hist():
                h = wal.fsync_hist()
                if h is None:
                    return None
                counts, count, sum_ns = h
                return counts, count, sum_ns / 1e9

            m.histogram(
                "wal_fsync_seconds",
                "WAL fsync latency (group-commit flush thread; native "
                "WLH block, SLO bucket geometry)",
                buckets=SLO_BUCKETS,
                fn=wal_hist,
            )
        # -- transport (native counter block, when the transport has one)
        tc = getattr(self.transport, "transport_counters", None)
        if callable(tc):
            from rabia_tpu.net.tcp import RT_COUNTER_NAMES

            for name in RT_COUNTER_NAMES:
                m.counter(
                    f"transport_{name}_total",
                    "Native transport counter (transport.cpp RTC block)",
                    fn=lambda r=name: tc().get(r, 0),
                )

    def health(self) -> dict:
        """The /healthz document (served by the gateway admin surface and
        the HTTP shim): frontier positions, quorum view, anomaly tallies."""
        return {
            "status": "ok" if self.rt.has_quorum else "degraded",
            "node": str(self.node_id.value),
            "has_quorum": bool(self.rt.has_quorum),
            "active_nodes": len(self.rt.active_nodes),
            "native_tick": self._rk is not None,
            "native_runtime": self._rtm is not None,
            # active planes (runtime|tick|apply: native|python) — the
            # same ground truth the bench sweep lines record, so a
            # scrape can tell which path a replica is ACTUALLY on
            # (an env toggle or a silent native-build failure both
            # read as "python" here)
            "planes": {
                "runtime": "native" if self._rtm is not None else "python",
                "tick": "native" if self._rk is not None else "python",
                "apply": (
                    "native"
                    if getattr(self.sm, "_native_plane", None) is not None
                    else "python"
                ),
                # thread-per-shard-group worker count (1 = the
                # single-thread runtime or the asyncio orchestration)
                "runtime_workers": (
                    getattr(self._rtm, "workers", 1)
                    if self._rtm is not None
                    else 1
                ),
                # durability plane: which WAL writer owns the byte
                # format on this replica ("none" = not a durable
                # cluster) — the loadgen durable smoke cell pins
                # wal=native with --require-plane
                "wal": (
                    ("native" if getattr(self._wal, "native", False)
                     else "python")
                    if self._wal is not None
                    else "none"
                ),
            },
            "decided_frontier": self.decided_frontier().tolist(),
            "applied_frontier": self.applied_frontier().tolist(),
            "pending_batches": self.pending_queue_depth(),
            "state_version": int(self.rt.state_version),
            "anomalies": self.journal.counts(),
        }

    # -- flight recorder (obs/flight.py; docs/OBSERVABILITY.md) ------------

    def flight_events(self) -> list[dict]:
        """Merged flight timeline: the native tick ring (C fast path),
        the Python event ring, and the transport's frame in/out ring,
        sorted by monotonic ns (all three share CLOCK_MONOTONIC). Plain
        dicts with plain ints — JSON-serializable as-is."""
        from rabia_tpu.obs.flight import (
            native_ring_events,
            transport_ring_events,
        )

        evs = self.flight.snapshot()
        if self._rk is not None:
            evs.extend(native_ring_events(self._rk.flight_snapshot()))
            # sibling worker contexts (thread-per-shard-group runtime)
            for sib in getattr(self._rk, "siblings", ()):
                evs.extend(native_ring_events(sib.flight_snapshot()))
        # native runtime ring: thread wakeups + mailbox handoffs
        # (FRE_RT_WAKE / FRE_RT_HANDOFF), so timelines stay complete when
        # the asyncio loop is off the commit path
        if self._rtm is not None:
            evs.extend(native_ring_events(self._rtm.flight_snapshot()))
        # native apply plane (statekernel): one apply record per wave on
        # the C path, merged alongside the per-slot Python APPLY events
        sk_plane = getattr(self.sm, "_native_plane", None)
        if sk_plane is not None:
            try:
                evs.extend(
                    native_ring_events(sk_plane.flight_snapshot())
                )
            except Exception:  # a closed plane must not kill a dump
                pass
        tf = getattr(self.transport, "flight_snapshot", None)
        if callable(tf):
            try:
                evs.extend(transport_ring_events(tf()))
            except Exception:  # a closed transport must not kill a dump
                pass
        evs.sort(key=lambda e: e["t_ns"])
        return evs

    def flight_ring_state(self) -> list[dict]:
        """Head/wrap state for the rings :meth:`flight_events` merges
        (minus the transport frame ring, which keeps no total-written
        counter): the trace wrap-honesty stamps. A ring whose ``head``
        exceeds its retained window has evicted records, and any trace
        sliced from it may be silently partial — build_trace_slice
        compares ``oldest_t_ns`` against the batch's earliest event
        (obs/flight.slice_truncated) and marks the slice ``truncated``."""
        rings = [dict(self.flight.state(), ring="python")]

        def native_state(obj, name: str) -> None:
            try:
                head = int(obj.flight_head())
                snap = obj.flight_snapshot()
            except Exception:  # a closed plane must not kill a trace
                return
            retained = len(snap)
            rings.append(
                {
                    "ring": name,
                    "head": head,
                    "cap": retained,  # the retained-window size
                    "wrapped": head > retained,
                    "oldest_t_ns": (
                        int(snap[0]["t_ns"]) if retained else None
                    ),
                }
            )

        if self._rk is not None:
            native_state(self._rk, "rk")
            for i, sib in enumerate(getattr(self._rk, "siblings", ())):
                native_state(sib, f"rk_w{i + 1}")
        if self._rtm is not None:
            native_state(self._rtm, "rtm")
        sk_plane = getattr(self.sm, "_native_plane", None)
        if sk_plane is not None:
            native_state(sk_plane, "statekernel")
        return rings

    def dump_flight(
        self, path: Optional[str] = None, reason: str = "manual"
    ) -> Optional[str]:
        """Write the merged flight timeline to disk; returns the path.

        With no explicit ``path``, dumps into ``$RABIA_FLIGHT_DIR``
        (created if missing) or returns None when the env var is unset —
        the auto-dump hooks (severe anomalies, unclean shutdown) are
        opt-in so test runs don't litter."""
        from rabia_tpu.obs.flight import dump_events

        if path is None:
            d = os.environ.get("RABIA_FLIGHT_DIR")
            if not d:
                return None
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d,
                f"flight_{self.node_id.short()}_"
                f"{int(time.time() * 1000)}_{reason}.json",
            )
        return dump_events(
            path,
            self.flight_events(),
            meta={
                "node": str(self.node_id.value),
                "row": int(self.me),
                "reason": reason,
                "native_tick": self._rk is not None,
                "anomalies": self.journal.counts(),
            },
        )

    def _flight_autodump(self, kind: str) -> None:
        """Journal severe-kind hook: dump the rings while the evidence is
        still in the window (rate-limited; no-op without the env var)."""
        now = time.time()
        if now - self._last_flight_dump < 5.0:
            return
        self._last_flight_dump = now
        try:
            p = self.dump_flight(reason=kind)
            if p:
                logger.warning("flight recorder dumped to %s (%s)", p, kind)
        except Exception:
            logger.exception("flight auto-dump failed")

    # ------------------------------------------------------------------
    # Public API (the reference's EngineCommand surface, state.rs:300-307)
    # ------------------------------------------------------------------

    async def submit_batch(
        self, batch: CommandBatch, shard: Optional[int] = None
    ) -> asyncio.Future:
        """Accept a client batch for consensus on `shard`; returns a future
        resolving to the list of per-command responses once the batch
        commits (engine.rs:288-310 ProcessBatch path). Rejects without a
        quorum (engine.rs:289-297)."""
        if not self.rt.has_quorum:
            raise QuorumNotAvailableError(
                f"no quorum ({len(self.rt.active_nodes)}/{self.cluster.quorum_size})"
            )
        if batch.is_empty():
            raise ValidationError("empty batch")
        if len(batch.commands) > self.config.max_batch_size:
            raise ValidationError("batch exceeds max_batch_size")
        s = int(shard) if shard is not None else int(batch.shard)
        if not (0 <= s < self.n_shards):
            raise ValidationError(f"shard {s} out of range")
        self.flight.record(FRE_SUBMIT, shard=s, batch=fr_hash(batch.id))
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self.rt.shards[s].queue.append(PendingSubmission(batch=batch, future=fut))
        self._wake.set()  # wake the run loop: new work to propose
        return fut

    def proposer_eligible_shards(self) -> np.ndarray:
        """Shard indices this replica could open a block entry for RIGHT
        NOW (rotation proposer at the head slot, idle, nothing queued or
        bound). The block lane's eligibility mask, exposed for load
        drivers/ops tooling so they don't re-derive it from runtime
        internals."""
        n = self.n_shards
        rt = self.rt
        shards = self._shard_ids[:n]
        head = np.maximum(rt.next_slot[:n], rt.applied_upto[:n])
        elig = (
            (slot_proposer_vec(shards, head, self.R) == self.me)
            & ~rt.in_flight[:n]
            & (rt.queue_len[:n] == 0)
            & ~rt.prop_flag[:n]
            & (self._blk_pending_ref[:n] == -1)
            & (self._cur_blk_ref[:n] == -1)
            & (head >= rt.tainted_upto[:n])
        )
        return shards[elig]

    async def submit_block(self, block: PayloadBlock) -> asyncio.Future:
        """Accept a columnar block of batches (one per covered shard) for
        consensus — the bulk lane. Returns ONE future resolving to a list
        with one entry per covered shard: the response list, or an
        Exception instance for shards whose batch failed.

        Shards where this replica is the upcoming proposer ride the block
        fast path (one ProposeBlock broadcast, vectorized open/decide/
        apply); the rest demote to the scalar queue and are forwarded to
        their proposers as usual."""
        if not self.rt.has_quorum:
            raise QuorumNotAvailableError(
                f"no quorum ({len(self.rt.active_nodes)}/{self.cluster.quorum_size})"
            )
        if len(block) == 0:
            raise ValidationError("empty block")
        if int(block.shards.max()) >= self.n_shards:
            raise ValidationError("block shard out of range")
        # fail fast with the same limits receivers enforce on the announce
        # (and the scalar lane enforces on demoted batches) — otherwise an
        # oversized batch livelocks retrying instead of erroring here
        if int(block.counts.max()) > min(
            self.config.max_batch_size, self.config.validation.max_commands_per_batch
        ):
            raise ValidationError("block shard batch exceeds max batch size")
        if block.total_commands and (
            int(block.cmd_sizes.max()) > self.config.validation.max_command_size
        ):
            raise ValidationError("block command exceeds max command size")
        for i in range(len(block)):
            self.flight.record(
                FRE_SUBMIT, shard=int(block.shards[i]),
                batch=fr_hash(block.batch_id_for(i)),
            )
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        out = _OutBlock(block, fut)
        ref = self._register_block(block, out, self.me)
        shards = block.shards
        head = np.maximum(
            self.rt.next_slot[shards], self.rt.applied_upto[shards]
        )
        elig = (
            (slot_proposer_vec(shards, head, self.R) == self.me)
            & ~self.rt.in_flight[shards]
            & (self.rt.queue_len[shards] == 0)
            & ~self.rt.prop_flag[shards]
            & (self._blk_pending_ref[shards] == -1)
            & (self._cur_blk_ref[shards] == -1)
            & (head >= self.rt.tainted_upto[shards])
        )
        idxe = np.nonzero(elig)[0]
        if len(idxe):
            sh_e = shards[idxe]
            block.slots[idxe] = head[idxe]
            self._blk_pending_ref[sh_e] = ref
            self._blk_pending_idx[sh_e] = idxe
            self._blk_pending_slot[sh_e] = head[idxe]
        for i in np.nonzero(~elig)[0]:
            self._demote_block_entry(ref, int(i))
        self._wake.set()  # wake the run loop: new work to propose
        return fut

    def _register_block(self, block: PayloadBlock, out, src_row: int) -> int:
        ref = self._blk_next_ref
        self._blk_next_ref += 1
        self._blk_registry[ref] = _BlockRef(block, out, src_row)
        return ref

    def _unref_block(self, ref: int, count: int) -> None:
        rec = self._blk_registry.get(ref)
        if rec is None:
            return
        rec.remaining -= count
        if rec.remaining <= 0:
            del self._blk_registry[ref]
            self._last_blk_retransmit.pop(ref, None)

    def _demote_block_entry(self, ref: int, i: int) -> None:
        """Route one covered shard of a block through the scalar lane
        (ineligible at submit, V0 retry, or out-of-order decide)."""
        rec = self._blk_registry.get(ref)
        if rec is None:
            return
        block = rec.block
        s = int(block.shards[i])
        batch = block.materialize_batch(i)
        if getattr(batch, "aliases", ()):
            # coalescing lane: the scalar apply may bind a WIRE copy of
            # this batch (forwarded proposal) that cannot carry the
            # aliases — stash them on the shard for _batch_aliases
            self.rt.shards[s].alias_subs[batch.id] = batch.aliases
        subfut: asyncio.Future = asyncio.get_event_loop().create_future()
        out = rec.out

        if out is not None:

            def _settle(f: asyncio.Future, i=i, out=out):
                out.settle(i, f.exception() if f.exception() else f.result())

            subfut.add_done_callback(_settle)
        self.rt.shards[s].queue.append(
            PendingSubmission(batch=batch, future=subfut)
        )
        self._unref_block(ref, 1)

    async def get_statistics(self) -> EngineStatistics:
        return self.rt.stats(self.node_id)

    # -- decided-frontier surface (client gateway subsystem) ----------------

    def decided_frontier(self) -> np.ndarray:
        """Per-shard POTENTIAL decided frontier: slot index past every
        slot this replica has decided, plus the slot it is currently
        voting in (in flight counts as potentially decided elsewhere).

        The gateway's linearizable read-index rests on the quorum
        intersection this bound gives: a write committed at slot k
        required round-2 votes from a quorum, and each of those voters
        reports a frontier > k here (it was in flight at k when it
        voted, and the value only grows). Probing any quorum and taking
        the per-shard max therefore covers every write committed before
        the probe. Over-reporting merely delays a read; never report a
        frontier below a slot this replica has voted round 2 in — which
        is why the restored vote barrier floors the result: a pre-crash
        vote can sit above the restored ``next_slot`` (cast after the
        last checkpoint), but never at-or-above the persisted barrier."""
        n = self.n_shards
        rt = self.rt
        return np.maximum(
            np.maximum(rt.next_slot[:n], rt.applied_upto[:n])
            + rt.in_flight[:n].astype(np.int64),
            self._frontier_floor[:n],
        )

    def applied_frontier(self) -> np.ndarray:
        """Per-shard count of contiguously applied slots (a copy)."""
        return self.rt.applied_upto[: self.n_shards].copy()

    def pending_queue_depth(self) -> int:
        """Total locally queued submissions across shards — the gateway's
        admission-control signal (shed before the engine inbox saturates)."""
        return int(self.rt.queue_len[: self.n_shards].sum())

    def add_frontier_listener(self, cb) -> None:
        """Register a zero-arg callback fired (on the engine's loop, at
        most once per tick) whenever the applied frontier advances."""
        self._frontier_listeners.append(cb)

    def remove_frontier_listener(self, cb) -> None:
        try:
            self._frontier_listeners.remove(cb)
        except ValueError:
            pass

    async def trigger_sync(self) -> None:
        await self._initiate_sync()

    async def update_nodes(self, nodes: Sequence[NodeId]) -> None:
        """Membership change: recompute quorum + leader (engine.rs:142-153)."""
        self.rt.active_nodes = set(nodes) & set(self.cluster.all_nodes)
        self.rt.has_quorum = self.cluster.has_quorum(
            self.rt.active_nodes | {self.node_id}
        )
        self.leader.update_nodes(self.rt.active_nodes | {self.node_id})

    async def shutdown(self) -> None:
        self._running = False
        await self._stopped.wait()

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------

    async def initialize(self) -> None:
        """Restore persisted state then join the cluster (engine.rs:238-269)."""
        if self._wal is not None:
            # durability plane: snapshot-chain restore + WAL replay
            # through the same apply path as live traffic
            # (docs/DURABILITY.md recovery walkthrough)
            report = self._wal.recover_engine(self)
            self.flight.record(
                FRE_WAL, shard=0, slot=report["waves_replayed"], arg=1,
            )
            if self._rtm is not None:
                # mirror the restored frontiers into the bridge before
                # the runtime thread starts (it owns the columns after)
                self._rtm.adopt_restored_frontiers()
        elif self.persistence is not None:
            persisted = await self.persistence.load_engine_state()
            if persisted is not None:
                if persisted.snapshot is not None:
                    self.sm.restore_snapshot(persisted.snapshot)
                opened = np.asarray(persisted.per_shard_phase[: self.S], np.int64)
                applied = np.asarray(
                    persisted.per_shard_committed[: self.S], np.int64
                )
                self.rt.next_slot[: len(opened)] = opened
                self.rt.applied_upto[: len(applied)] = applied
                self.rt.state_version = persisted.state_version
                vers = np.asarray(persisted.per_shard_version[: self.S], np.int64)
                self.rt.v1_applied[: len(vers)] = vers
                logger.info(
                    "%s restored: %d slots applied",
                    self.node_id.short(),
                    int(self.rt.applied_upto.sum()),
                )
        # unconditionally: a replica that voted but crashed before its first
        # checkpoint has no main blob yet the barrier aux blob exists — that
        # early-life window is the most likely crash window
        await self._restore_vote_barrier()
        connected = await self.transport.get_connected_nodes()
        await self.update_nodes(connected | {self.node_id})

    async def _restore_vote_barrier(self) -> None:
        """Taint slots this replica may have voted in before the crash.

        Re-running consensus in such a slot could cast a DIFFERENT vote in
        the same (slot, phase) — equivocation that can violate agreement
        when f other replicas are simultaneously down. Tainted slots rejoin
        only via adopted peer Decisions or snapshot sync; if no vote traffic
        for them is observed within the release window, nobody holds our
        pre-crash votes and the taint lifts (see _open_slots).
        """
        self._restored_at = time.time()
        if self.persistence is None or self.R <= 1:
            return  # single replica: no peer can hold a conflicting view
        raw = await self.persistence.load_aux("vote_barrier")
        if raw is None:
            return
        barrier = np.frombuffer(raw, np.int64)[: self.n_shards]
        self._barrier[: len(barrier)] = barrier
        self._frontier_floor[: len(barrier)] = barrier
        n = len(barrier)
        taint = barrier > self.rt.applied_upto[:n]
        self.rt.tainted_upto[:n][taint] = barrier[taint]

    @property
    def _taint_release(self) -> float:
        # may be inf (asynchronous-safe mode): see config.taint_release_factor
        return self.config.taint_release_factor * self.config.phase_timeout

    def _tainted_blocked(self) -> bool:
        # applied_upto, not next_slot: a slot decided-but-unapplied before
        # the crash leaves applied_upto under the barrier while next_slot
        # is already past it — recovery still needs the sync
        n = self.n_shards
        return bool(
            (self.rt.applied_upto[:n] < self.rt.tainted_upto[:n]).any()
        )

    async def run(self) -> None:
        """Main loop: drain inbound, advance the kernel one round,
        transmit the outbox, apply decisions, periodic chores.

        Event-driven (the reference's select!-style loop,
        engine.rs:193-235): when the transport supports push
        notification the loop sleeps on a wake event — set by inbound
        delivery and by local submissions — and wakes only for work or
        for the next timer check, instead of pacing every round with a
        fixed sleep (round 3's p50 was dominated by exactly that tick
        alignment). Transports without notification fall back to
        polling at ``round_interval``."""
        self._running = True
        self._stopped.clear()
        await self.initialize()
        self._notify_wired = bool(
            self.transport.set_receive_notify(self._wake.set)
        )
        if self._rtm is not None:
            try:
                self._rtm.start()
            except Exception:
                # the reader thread may already be detached: the asyncio
                # fallback would silently drop inbound frames, so a
                # runtime start failure is fatal for this replica
                logger.exception("native runtime start failed")
                raise
        try:
            while self._running:
                # clear BEFORE draining: anything that lands after this
                # point either gets drained by this tick (a harmless
                # spurious wake later) or sets the event and cuts the
                # idle wait short — a wake can never be lost
                self._wake.clear()
                # stage profiler (asyncio-owner half): while the native
                # runtime owns the commit path its RTS block is the
                # source and this loop is control plane — account only
                # when the asyncio orchestration IS the owner, so the
                # exported breakdown never double-counts two threads.
                # The remainder between consecutive loop tops (yields,
                # journal writes, listener dispatch) lands in "other",
                # so the stage sum tracks the loop's wall time — the
                # same contract as the native RTS block.
                py_owner = self._rtm is None
                now0 = time.perf_counter_ns()
                if py_owner:
                    if self._loop_mark:
                        rem = now0 - self._loop_mark - self._stage_acc
                        if rem > 0:
                            self._stage_ns["other"] += rem
                    self._loop_mark = now0
                    self._stage_acc = 0
                    # a broadcast issued from a spawned task BETWEEN
                    # brackets (e.g. a sync request) credits "broadcast"
                    # and excludes itself from "other" via _stage_acc,
                    # but has no enclosing bracket to carve from — drop
                    # the pending carve so it can't dock the next
                    # iteration's first bracketed stage
                    self._bcast_carve = 0
                t_tick = time.perf_counter()
                if self._rtm is not None:
                    progressed = self._runtime_tick()
                else:
                    progressed = await self._tick()
                dt_tick = time.perf_counter() - t_tick
                if dt_tick > self._slow_tick_s:
                    self._slow_ticks += 1
                    self.journal.record(
                        self.journal.SLOW_TICK, dt_ms=round(dt_tick * 1e3, 2)
                    )
                t_per = time.perf_counter_ns()
                await self._periodic()
                if py_owner:
                    self._stg("timers", time.perf_counter_ns() - t_per)
                if progressed or self._restep:
                    # busy: yield to peers/transport, then loop again
                    await asyncio.sleep(0)
                    continue
                # returns on wake OR timeout (timer check: heartbeats,
                # phase timeouts) — no exception either way
                t_idle = time.perf_counter_ns()
                await self._wake.wait(self._idle_wait())
                if py_owner:
                    self._stg("idle", time.perf_counter_ns() - t_idle)
        except Exception:
            # unclean shutdown: the run loop died on an exception — dump
            # the flight rings while the evidence is still in the window
            # (no-op unless RABIA_FLIGHT_DIR is set), then re-raise
            try:
                p = self.dump_flight(reason="unclean-shutdown")
                if p:
                    logger.error("flight recorder dumped to %s", p)
            except Exception:
                logger.exception("flight dump on unclean shutdown failed")
            raise
        finally:
            # shutdown ordering: runtime thread drain (mid-wave applies
            # complete, the event mailbox empties into Python) → apply
            # plane flush → persistence checkpoint; the caller closes the
            # transport only after shutdown() returns
            if self._rtm is not None:
                try:
                    await self._rtm.stop()
                except Exception:
                    logger.exception("native runtime stop failed")
                finally:
                    # freeze counters + flight ring for late scrapes and
                    # dumps, then free the native context
                    self._rtm.close()
            # settle any deferred apply backlog before externalizing
            # state (persistence checkpoint, late stats readers)
            try:
                self._apply_plane.flush_sync()
            except Exception:
                logger.exception("apply-plane flush on shutdown failed")
            if self._dirty:
                await self._save_state()
            self.rt.is_active = False
            self._stopped.set()

    def stage_second(self, name: str) -> float:
        """Cumulative seconds the commit-path owner spent in one loop
        stage (native RTS block + asyncio-loop accounting — each path
        leaves the other's source at zero)."""
        ns = self._stage_ns.get(name, 0)
        rtm = self._rtm
        if rtm is not None:
            ns += rtm.stage_ns(name)
        return ns * 1e-9

    def stage_seconds(self) -> dict[str, float]:
        """The full ``rabia_runtime_stage_seconds`` breakdown as a dict
        (the serial-latency budget gate prints this on failure so an
        ambient-load flake carries its own diagnosis)."""
        from rabia_tpu.obs.registry import RUNTIME_STAGES

        return {s: self.stage_second(s) for s in RUNTIME_STAGES}

    def _stg(self, name: str, ns: int) -> None:
        """Asyncio-owner stage accounting: one named section's duration
        (kept with a per-iteration accumulator so the run loop can
        attribute the remainder to ``other`` — the stage sum tracks the
        owner loop's wall time, same contract as the native RTS block)."""
        if self._bcast_carve:
            # wire-staging time already credited to "broadcast" by
            # _stg_bcast happened inside this bracket — carve it out so
            # the enclosing stage doesn't count it twice
            ns = max(0, ns - self._bcast_carve)
            self._bcast_carve = 0
        self._stage_ns[name] += ns
        self._stage_acc += ns

    def _stg_ext(self, name: str, ns: int) -> None:
        """Stage accounting for control-plane components sharing this
        loop (the gateway's "gateway"/"serialization" brackets): credit
        the named stage and exclude the ns from the run loop's `other`
        remainder via the per-iteration accumulator. No carve handling —
        external brackets manage their own nesting."""
        self._stage_ns[name] = self._stage_ns.get(name, 0) + ns
        self._stage_acc += ns

    def _stg_bcast(self, ns: int) -> None:
        """Broadcast staging observed inside another stage's bracket
        (kernel outbox under "tick", heartbeats under "timers"): credit
        the broadcast stage directly and leave the same ns pending for
        _stg to subtract from the enclosing bracket — without this the
        asyncio profile prints broadcast=0 while "tick" silently absorbs
        the wire-staging time the native RTS block reports separately."""
        self._stage_ns["broadcast"] += ns
        self._stage_acc += ns
        self._bcast_carve += ns

    def _runtime_tick(self) -> bool:
        """One control-plane pass while the native runtime owns the
        commit path: drain the event mailbox (decisions, applied waves,
        escalated frames), then pump staged work (scalar opens, block
        waves, forwards) back down as commands."""
        self._tick_count += 1
        rtm = self._rtm
        n_ev = rtm.drain_events()
        rtm.pump()
        if self._frontier_dirty:
            self._frontier_dirty = False
            for cb in self._frontier_listeners:
                try:
                    cb()
                except Exception:  # a listener must never kill the loop
                    logger.exception("frontier listener failed")
        return bool(n_ev)

    def _idle_wait(self) -> float:
        """How long an idle loop may sleep before re-checking timers.

        With wake-on-inbox wired, the sleep only bounds timer
        granularity (heartbeats, phase-timeout retransmits, the
        monitor) — capped well under the smallest configured interval.
        Without it, the sleep IS the inbound poll period, so the old
        ``round_interval`` pacing is kept."""
        c = self.config
        if not self._notify_wired:
            return c.round_interval
        # capped by the smallest configured timer interval (a max()
        # floor above these would delay heartbeats/retransmits past
        # their configured periods); 0.5ms floor avoids busy-waking
        # when a test configures a microscopic phase_timeout
        return max(
            0.0005,
            min(0.05, c.heartbeat_interval / 4, c.phase_timeout / 8),
        )

    # ------------------------------------------------------------------
    # The round tick
    # ------------------------------------------------------------------

    async def _tick(self) -> bool:
        self._tick_count += 1
        pcns = time.perf_counter_ns
        t0 = pcns()
        with span("engine.tick.drain"):
            got_msgs = await self._drain_messages()
        self._stg("ingest", pcns() - t0)
        if self._paused:
            # quorum lost: consensus paused (engine.rs:983-997). Inbound
            # traffic above still adopts Decisions / answers sync, so a
            # healed minority catches up passively before resuming.
            return False
        t0 = pcns()
        with span("engine.tick.open"):
            self._forward_submissions()
            bulk = self._open_block_slots()
            opened = self._open_slots()
        stepped = False
        # step the kernel on NEW input (opens or arrivals) or when the last
        # step left ledger-resident progress pending (_restep): the kernel
        # advances one stage per step, so a stage transition (R1→R2 cast,
        # phase advance) can make votes ALREADY in the ledger/carry
        # decisive without any further peer traffic — most acutely for
        # R==1, where no peer traffic ever arrives. Otherwise idle steps
        # are pure dispatch waste; loss recovery is timeout-driven
        # (_check_timeouts), not step-driven.
        if opened or bulk is not None or got_msgs or self._restep:
            self._restep = False
            with span("engine.tick.kernel"):
                await self._kernel_round(opened, bulk)
            stepped = True
        self._stg("tick", pcns() - t0)  # open collection + kernel round
        t0 = pcns()
        with span("engine.tick.apply"):
            applied = self._apply_ready()
        self._stg("apply", pcns() - t0)
        t0 = pcns()
        with span("engine.tick.timeouts"):
            self._check_timeouts()
        self._stg("timers", pcns() - t0)
        if applied and self.persistence is not None:
            self._dirty = True
        if applied:
            self._frontier_dirty = True
        if self._frontier_dirty:
            self._frontier_dirty = False
            for cb in self._frontier_listeners:
                try:
                    cb()
                except Exception:  # a listener must never kill the loop
                    logger.exception("frontier listener failed")
        return bool(got_msgs or opened or bulk is not None or applied) and stepped

    def _anything_in_flight(self) -> bool:
        return bool(self.rt.in_flight[: self.n_shards].any())

    # -- inbound ------------------------------------------------------------

    async def _drain_messages(self, cap: int = 256) -> int:
        """Drain up to `cap` inbound messages (engine.rs:923-947).

        When the transport offers borrowed (zero-copy) frames, the codec
        decodes straight out of the native arena — no bytes-object copy
        per frame (SURVEY §7.4.7); the buffer is released immediately
        after decode, before the message is handled."""
        n = 0
        recv_borrow = self._recv_borrow
        recv_nowait = self._recv_nowait
        rk = self._rk
        rk_now = time.time() if rk is not None else 0.0
        rk_handled = 0
        node_to_row = self._node_to_row
        if rk is not None and self._recv_raw is not None:
            # address-level fast drain: arena frames feed the C ingest
            # with zero Python buffer wrapping; only frames the fast
            # path declines are materialized for the Python codec.
            # `seen` bounds the loop by frames CONSUMED (including
            # no-effect/dropped ones) so a stale or hostile flood cannot
            # hold the event loop for an unbounded drain.
            recv_raw = self._recv_raw
            seen = 0
            while seen < cap:
                item = recv_raw()
                if item is None:
                    break
                seen += 1
                sender, data, addr, ln, release = item
                if data is None and not addr:
                    # zero-length arena frame (the pool hands out a null
                    # base for 0-byte buffers): not ingestable — let the
                    # codec below reject and log it like any bad frame
                    data = b""
                row = node_to_row.get(sender)
                if row is not None:
                    if addr:
                        rc = rk.ingest_addr(addr, ln, row, rk_now)
                    else:
                        rc = rk.ingest(data, row, rk_now)
                    if rc != 0:
                        if release is not None:
                            release()
                        if rc > 0:
                            rk_handled += 1
                            if rc == 1:
                                n += 1
                        continue
                try:
                    try:
                        if data is None:
                            data = ctypes.string_at(addr, ln)
                        msg = self.serializer.deserialize(data)
                    finally:
                        if release is not None:
                            release()
                    self.validator.validate_message(msg)
                    self._handle_message(sender, msg)
                    n += 1
                except RabiaError as e:
                    self._py_drops["malformed"] += 1
                    srow = node_to_row.get(sender)
                    self.flight.record(
                        FRE_DROP,
                        peer=srow if srow is not None else 0xFFFF,
                        arg=3,
                    )
                    logger.warning(
                        "dropping bad message from %s: %s", sender, e
                    )
            if rk_handled:
                rk.finish_drain(self)
            return n
        seen = 0
        while seen < cap:
            seen += 1
            release = None
            if recv_borrow is not None:
                item = recv_borrow()
                if item is None:
                    break
                sender, data, release = item
            elif recv_nowait is not None:
                item = recv_nowait()
                if item is None:
                    break
                sender, data = item
            else:
                try:
                    sender, data = await self.transport.receive(
                        timeout=0.0005
                    )
                except RabiaError:
                    break
            if rk is not None:
                # native fast path: vote/decision frames are decoded,
                # validated and scattered straight out of the frame buffer
                # (the transport arena under zero-copy recv) — no Python
                # message objects. rc 0 = not a fast-path frame.
                row = node_to_row.get(sender)
                if row is not None:
                    rc = rk.ingest(data, row, rk_now)
                    if rc != 0:
                        if release is not None:
                            release()
                        if rc > 0:
                            rk_handled += 1
                            if rc == 1:
                                # rc 2 = consumed with no effects (all
                                # entries stale): don't charge a kernel
                                # round for it
                                n += 1
                        continue
            try:
                try:
                    msg = self.serializer.deserialize(data)
                finally:
                    if release is not None:
                        release()
                self.validator.validate_message(msg)
                self._handle_message(sender, msg)
                n += 1
            except RabiaError as e:
                self._py_drops["malformed"] += 1
                srow = node_to_row.get(sender)
                self.flight.record(
                    FRE_DROP,
                    peer=srow if srow is not None else 0xFFFF,
                    arg=3,
                )
                logger.warning("dropping bad message from %s: %s", sender, e)
        if rk_handled:
            rk.finish_drain(self)
        return n

    def _handle_message(self, sender: NodeId, msg: ProtocolMessage) -> None:
        """Route one validated message into host buffers (engine.rs:349-379)."""
        if sender != msg.sender:
            # envelope sender must match the transport-authenticated peer:
            # otherwise one faulty peer could forge votes as every other
            # replica row and fabricate a quorum single-handedly
            self._py_drops["spoof"] += 1
            srow = self._node_to_row.get(sender)
            self.flight.record(
                FRE_DROP, peer=srow if srow is not None else 0xFFFF, arg=1
            )
            logger.warning(
                "dropping spoofed message: envelope %s via transport %s",
                msg.sender,
                sender,
            )
            return
        row = self._node_to_row.get(msg.sender)
        if row is None:
            logger.warning("message from unknown node %s", msg.sender)
            return
        self.rt.active_nodes.add(msg.sender)
        p = msg.payload
        # flight: per-frame ingest records. Never double-recorded on the
        # native path — frames the C ingest consumed (RK_HANDLED/RK_NOOP,
        # where it wrote its own FrEvent) never reach this handler; the
        # ones that DO arrive here are exactly those rk_ingest declined
        # (RK_PY) before any ring write, so they must be recorded here or
        # the trace shows votes materializing with no frame_in
        if isinstance(p, VoteRound1):
            self._py_frames["vote1"] += 1
            if len(p):
                self.flight.record(
                    FRE_FRAME_IN,
                    shard=int(p.shards[0]),
                    slot=int(p.phases[0]) >> 16,
                    peer=row,
                    arg=int(MessageType.VoteRound1),
                )
            self._ingest_vote_arrays(row, p.shards, p.phases, p.vals, 1)
        elif isinstance(p, VoteRound2):
            self._py_frames["vote2"] += 1
            if len(p):
                self.flight.record(
                    FRE_FRAME_IN,
                    shard=int(p.shards[0]),
                    slot=int(p.phases[0]) >> 16,
                    peer=row,
                    arg=int(MessageType.VoteRound2),
                )
            self._ingest_vote_arrays(row, p.shards, p.phases, p.vals, 2)
        elif isinstance(p, Decision):
            self._py_frames["decision"] += 1
            if len(p):
                self.flight.record(
                    FRE_FRAME_IN,
                    shard=int(p.shards[0]),
                    slot=int(p.phases[0]) >> 16,
                    peer=row,
                    arg=int(MessageType.Decision),
                )
            self._on_decision(p)
        elif isinstance(p, ProposeBlock):
            self._on_propose_block(row, p)
        elif isinstance(p, Propose):
            self._on_propose(row, p)
        elif isinstance(p, NewBatch):
            self._on_new_batch(p)
        elif isinstance(p, SyncRequest):
            self._on_sync_request(msg.sender, p)
        elif isinstance(p, SyncResponse):
            self._on_sync_response(msg.sender, p)
        elif isinstance(p, HeartBeat):
            self._peer_progress[msg.sender] = (p.committed_phase, time.time())
        elif isinstance(p, QuorumNotification):
            # informational: a peer's view of cluster health — logged and
            # kept for operators/stats (messages.rs:132-136)
            self._peer_quorum_views[msg.sender] = (
                p.has_quorum,
                time.time(),
            )
            if not p.has_quorum:
                logger.warning(
                    "%s: peer %s reports quorum lost (sees %d nodes)",
                    self.node_id.short(),
                    msg.sender.short(),
                    len(p.active_nodes),
                )

    def _on_propose(self, row: int, p: Propose) -> None:
        if not (0 <= p.shard < self.n_shards):
            return
        sh = self.rt.shards[p.shard]
        slot, _ = unpack_phase(p.phase)
        if slot < sh.applied_upto:
            return  # stale
        if slot_proposer(p.shard, slot, self.R) != row:
            # only the slot's rotation proposer may bind a batch to it;
            # otherwise any replica's (e.g. a confused restarted peer's)
            # Propose could bind divergent batch_ids to the same V1-decided
            # slot across the cluster
            logger.warning(
                "dropping Propose for shard %d slot %d from non-proposer row %d",
                p.shard,
                slot,
                row,
            )
            return
        rec = sh.decisions.get(slot)
        if rec is not None:
            if rec.batch_id is None:
                # slot decided V1 off peers' votes before the Propose got
                # here: repair the binding so apply doesn't need a snapshot
                # sync for a payload that just arrived
                rec.batch_id = p.batch_id
            elif rec.batch_id != p.batch_id:
                return  # slot already decided about a different batch
        # first proposal wins the slot binding; payloads are id-keyed so a
        # conflicting late proposal can't swap the bytes a decision applies
        sh.buf_propose.setdefault(slot, (p.batch_id, p.batch))
        if p.batch is not None:
            sh.payloads[p.batch_id] = p.batch
        if rec is not None and not rec.applied:
            # a late payload/binding may have just unwedged apply — the
            # apply scan is dirty-set driven, so re-mark the shard
            self._apply_dirty.add(p.shard)

    def _on_propose_block(self, row: int, p: ProposeBlock) -> None:
        """Receiver side of the bulk lane: bind the block's (shard, slot)
        proposals columnar; shards whose slot is current open V1 on the
        next tick's bulk open pass."""
        b = p.block
        n = self.n_shards
        # bounds-filter BEFORE any fancy indexing: wire shard indices are
        # attacker-controlled and an out-of-range index would raise out of
        # the drain loop
        inb = (b.shards >= 0) & (b.shards < n)
        if not inb.all():
            if not inb.any():
                return
            b = b.subset(np.nonzero(inb)[0])
        shards, slots = b.shards, b.slots
        ok = (slot_proposer_vec(shards, slots, self.R) == row) & (
            slots >= self.rt.applied_upto[shards]
        )
        # first binding wins: never displace an existing block or scalar
        # binding for the shard's window. Duplicate/partial-wave announces
        # of the same block id each register their own handle — bindings
        # index into the exact announced subset, and already-bound shards
        # are skipped here
        free = (
            (self._blk_pending_ref[shards] == -1)
            & (self._cur_blk_ref[shards] == -1)
            & ~self.rt.prop_flag[shards]
        )
        # only bind at-or-ahead of our head; behind-head slots are decided
        # or being decided without the payload (repair rides Propose/sync)
        head = np.maximum(
            self.rt.next_slot[shards], self.rt.applied_upto[shards]
        )
        accept = ok & free & (slots >= head)
        idxs = np.nonzero(accept)[0]
        if len(idxs) == 0:
            return
        ref = self._register_block(b, None, row)
        sh_a = shards[idxs]
        self._blk_pending_ref[sh_a] = ref
        self._blk_pending_idx[sh_a] = idxs
        self._blk_pending_slot[sh_a] = slots[idxs]

    def _open_block_slots(self):
        """Vectorized bulk open: every shard whose pending block binding
        matches its head slot starts consensus with vote V1 now.

        Returns (idx, slots) arrays or None."""
        n = self.n_shards
        rt = self.rt
        pend = self._blk_pending_slot[:n]
        if not (pend >= 0).any():
            return None
        head = np.maximum(rt.next_slot[:n], rt.applied_upto[:n])
        ready = (
            (pend == head)
            & ~rt.in_flight[:n]
            & (rt.tainted_upto[:n] <= head)
        )
        if not ready.any():
            return None
        idx = np.nonzero(ready)[0]
        self._cur_blk_ref[idx] = self._blk_pending_ref[idx]
        self._cur_blk_idx[idx] = self._blk_pending_idx[idx]
        self._blk_pending_ref[idx] = -1
        self._blk_pending_slot[idx] = -1
        now = time.time()
        rt.in_flight[idx] = True
        np.maximum.at(rt.next_slot, idx, head[idx])
        rt.opened_at[idx] = now
        rt.last_progress[idx] = now
        # proposer side: announce blocks whose shards just opened (after
        # the vote barrier — _kernel_round flushes the announces)
        own = self._cur_blk_ref[idx]
        own_refs = np.unique(own)
        for ref in own_refs:
            rec = self._blk_registry.get(int(ref))
            if rec is None or rec.out is None:
                continue
            sel = idx[own == ref]
            bidx = self._cur_blk_idx[sel]
            if len(bidx) == len(rec.block):
                announce = rec.block
            else:
                announce = rec.block.subset(bidx)
            self._pending_block_announces.append(ProposeBlock(block=announce))
        return idx, head[idx]

    def _finish_block_slots(self, idx: np.ndarray) -> None:
        """Vectorized decide+apply for block-bound shards: record
        bookkeeping with array ops, group by block, bulk-apply V1 waves."""
        rt = self.rt
        slots = np.asarray(self._cur_slot)[idx].astype(np.int64)
        vals = np.asarray(self._decided)[idx]
        refs = self._cur_blk_ref[idx]
        bidxs = self._cur_blk_idx[idx]

        in_order = rt.applied_upto[idx] == slots
        if not in_order.all():
            # a sync overtook these shards mid-flight: route per shard
            # through the scalar ledger (rare)
            for j in np.nonzero(~in_order)[0]:
                s = int(idx[j])
                ref, bi = int(refs[j]), int(bidxs[j])
                rec = self._blk_registry.get(ref)
                if rec is not None:
                    sh = rt.shards[s]
                    bid = rec.block.batch_id_for(bi)
                    sh.payloads[bid] = rec.block.materialize_batch(bi)
                    sh.buf_propose.setdefault(int(slots[j]), (bid, None))
                    if rec.out is not None:
                        rec.out.settle(
                            bi,
                            ResponsesUnavailableError("block shard overtaken by sync"),
                        )
                    if rt.applied_upto[s] > int(slots[j]):
                        # the snapshot already covered this slot: the
                        # scalar lane will never apply the demoted batch
                        # here, so its coalescing-lane aliases would be
                        # lost — register them ids-only (no responses)
                        # so a covered client's session-loss replay
                        # dedups into the repair/unavailable path
                        # instead of re-proposing a double apply
                        self.register_applied_aliases(
                            s, int(slots[j]),
                            rec.block.alias_ids_for(bi), stage=False,
                        )
                    self._unref_block(ref, 1)
                self._cur_blk_ref[s] = -1
                self._record_decision(s, int(slots[j]), int(vals[j]), None)
            keep = in_order
            idx, slots, vals, refs, bidxs = (
                idx[keep],
                slots[keep],
                vals[keep],
                refs[keep],
                bidxs[keep],
            )
            if len(idx) == 0:
                return

        v1 = vals == V1
        # V0 (null) slots: nothing applies. Only the PROPOSER requeues the
        # batch (scalar lane, next rotation); receivers just drop their
        # binding — every binder requeueing would commit the batch once per
        # replica under fresh ids, defeating dedup
        if (~v1).any():
            for j in np.nonzero(~v1)[0]:
                ref = int(refs[j])
                rec = self._blk_registry.get(ref)
                if rec is None:
                    continue
                if rec.out is not None:
                    self._demote_block_entry(ref, int(bidxs[j]))
                else:
                    self._unref_block(ref, 1)
        # V1 waves: group by block, apply in bulk
        lost: list[int] = []  # positions whose block is gone — scalar path
        if v1.any():
            v1_idx = np.nonzero(v1)[0]
            wave_refs = refs[v1_idx]
            for ref in np.unique(wave_refs):
                rec = self._blk_registry.get(int(ref))
                sel = v1_idx[wave_refs == ref]
                if rec is None:
                    # registry entry gone (GC raced a very old stall):
                    # NEVER silently skip the apply — route through the
                    # scalar ledger so the payload-missing slot stalls
                    # apply and sync repairs it
                    lost.extend(sel.tolist())
                    continue
                bsel = bidxs[sel].astype(np.int64)
                want = rec.out is not None
                try:
                    with span("sm.apply"):
                        if self._is_vector_sm:
                            responses = self.sm.apply_block(
                                rec.block, bsel, want_responses=want
                            )
                        else:
                            responses = [
                                self.sm.apply_batch(
                                    rec.block.materialize_batch(int(bi))
                                )
                                for bi in bsel
                            ]
                except Exception as e:
                    # deterministic apply failure (same on every replica):
                    # consume the slots, fail the submitter's entries
                    logger.warning("block apply failed (ref %s): %s", ref, e)
                    responses = None
                    if want:
                        err = RabiaError(f"apply failed: {e}")
                        for bi in bsel:
                            rec.out.settle(int(bi), err)
                if want and responses is not None:
                    for bi, resp in zip(bsel, responses):
                        rec.out.settle(int(bi), resp)
                if rec.block.aliases:
                    # coalescing lane: per-client alias ids into the
                    # dedup ledger (aliases exist only on own blocks)
                    for k, (j, bi) in enumerate(zip(sel, bsel)):
                        self.register_applied_aliases(
                            int(idx[j]), int(slots[j]),
                            rec.block.alias_ids_for(int(bi)),
                            None if responses is None else responses[k],
                            have_responses=want,
                        )
                if self._wal is not None:
                    # durability plane: stage each applied entry with its
                    # ops (slices of the block payload) under the SAME
                    # deterministic batch id the scalar lane would use,
                    # so recovery repopulates the dedup ledger correctly
                    # — and enter it into the LIVE ledger too (round 15:
                    # a failover replay at THIS replica's gateway must
                    # dedup; durable clusters only, so the persistence-
                    # free bulk lanes stay free of per-entry dict work)
                    blk = rec.block
                    boffs = blk.cmd_offsets
                    bstarts = blk.shard_starts
                    bdata = blk.data
                    for j, bi in zip(sel, bsel):
                        lo, hi = int(bstarts[bi]), int(bstarts[bi + 1])
                        ebid = blk.batch_id_for(int(bi))
                        rt.shards[int(idx[j])].applied_ids[ebid] = None
                        self._wal_stage(
                            int(idx[j]), int(slots[j]), 1,
                            bid_bytes=ebid.value.bytes,
                            ops=[
                                bytes(bdata[boffs[k] : boffs[k + 1]])
                                for k in range(lo, hi)
                            ],
                        )
                self._unref_block(int(ref), len(bsel))
            rt.state_version += int(v1.sum()) - len(lost)
            good = (
                np.setdiff1d(v1_idx, np.asarray(lost, np.int64))
                if lost
                else v1_idx
            )
            np.add.at(rt.v1_applied, idx[good], 1)
            self.rt.last_apply_time = time.time()
        if lost:
            keep = np.ones(len(idx), bool)
            for j in lost:
                s = int(idx[j])
                self._cur_blk_ref[s] = -1
                self._record_decision(s, int(slots[j]), int(vals[j]), None)
                keep[j] = False
            idx, slots, vals = idx[keep], slots[keep], vals[keep]
            v1 = vals == V1
            if len(idx) == 0:
                return

        if self._wal is not None and (~v1).any():
            # V0 slots stage payload-less frontier records (replay
            # advances past them without applying anything)
            for j in np.nonzero(~v1)[0]:
                self._wal_stage(int(idx[j]), int(slots[j]), 0)
        # columnar bookkeeping for the whole wave. Flight records are
        # BOUNDED per wave: this is the vectorized bulk lane (tens of
        # thousands of decisions/s), where per-slot Python records would
        # tax exactly the path the lane exists to keep columnar — and a
        # full wave would churn straight through the 4096-cap ring
        # anyway. (No batch hash either: block entries are traced by
        # (shard, slot), not session coordinates.)
        for j in range(min(len(idx), 64)):
            self.flight.record(
                FRE_DECIDE, shard=int(idx[j]), slot=int(slots[j]),
                arg=int(vals[j]),
            )
            self.flight.record(
                FRE_APPLY, shard=int(idx[j]), slot=int(slots[j]),
                arg=int(vals[j]),
            )
        rt.applied_upto[idx] = slots + 1
        rt.next_slot[idx] = slots + 1
        self._frontier_dirty = True
        rt.in_flight[idx] = False
        rt.opened_at[idx] = 0.0
        rt.head_fwd_at[idx] = 0.0
        self._cur_blk_ref[idx] = -1
        # decided-value ring: the stale-vote repair's answer source for
        # bulk slots (which never materialize SlotRecords)
        ring = slots & (rt.DEC_RING - 1)
        rt.dec_ring_val[idx, ring] = vals
        rt.dec_ring_slot[idx, ring] = slots
        n_v1 = int(v1.sum())
        rt.decided_v1 += n_v1
        rt.decided_v0 += len(idx) - n_v1
        if self.persistence is not None and len(idx):
            self._dirty = True

    # -- vote ingest (columnar) ---------------------------------------------

    def _ingest_vote_arrays(
        self,
        row: int,
        shards: np.ndarray,
        phases: np.ndarray,
        vals: np.ndarray,
        round_no: int,
    ) -> None:
        """Stash one sender's vote vector for this tick's bulk route.

        Cheap per-message side effects happen eagerly (vectorized): stale
        drop, taint-traffic marking, votes-seen tracking for slot opening.
        """
        n = self.n_shards
        if shards.shape[0] == 1:
            # scalar fast path: the serial/low-shard deployment shape
            # sends one-entry vote vectors, where every fancy-indexing
            # step below costs more than the whole scalar transcription
            rt = self.rt
            s = shards[0].item()
            if s < 0 or s >= n:
                return
            ph = phases[0].item()
            slot = ph >> 16
            if slot < rt.applied_upto[s]:
                self._py_stale += 1
                self.flight.record(
                    FRE_STALE, shard=s, slot=slot, peer=row, arg=round_no
                )
                self._repair_stale_sender(
                    row, shards, np.asarray([slot], np.int64)
                )
                return
            if slot < rt.tainted_upto[s]:
                rt.taint_traffic[s] = time.time()
            if slot > rt.votes_seen_slot[s]:
                rt.votes_seen_slot[s] = slot
            stash = self._stash1 if round_no == 1 else self._stash2
            # fully scalar entry — _route_votes dispatches on type(shards)
            stash.append(
                (row, s, slot, ph & _MVC_MASK, vals[0].item())
            )
            return
        # full bounds check here (the wire validator no longer scans vote
        # vectors element-wise): negative or oversized indices would
        # wrap/raise in every fancy-indexing step below
        ok = (shards >= 0) & (shards < n)
        if not ok.all():
            shards, phases, vals = shards[ok], phases[ok], vals[ok]
        if len(shards) == 0:
            return
        slots = phases >> 16
        live = slots >= self.rt.applied_upto[shards]
        if not live.all():
            # the sender is voting in slots we already decided: it missed
            # the Decision (loss / heal) — answer with a targeted repair
            # instead of letting it stall into the sync path
            self._py_stale += int((~live).sum())
            for s_st, sl_st in zip(shards[~live][:64], slots[~live][:64]):
                self.flight.record(
                    FRE_STALE, shard=int(s_st), slot=int(sl_st), peer=row,
                    arg=round_no,
                )
            self._repair_stale_sender(row, shards[~live], slots[~live])
            shards, phases, vals, slots = (
                shards[live],
                phases[live],
                vals[live],
                slots[live],
            )
        if len(shards) == 0:
            return
        tainted = slots < self.rt.tainted_upto[shards]
        if tainted.any():
            # peers are deciding tainted slots: hold the taint (sliding
            # quiet-window — the column stores the last-seen time)
            self.rt.taint_traffic[shards[tainted]] = time.time()
        np.maximum.at(self.rt.votes_seen_slot, shards, slots)
        mvcs = phases & _MVC_MASK
        stash = self._stash1 if round_no == 1 else self._stash2
        stash.append((row, shards, slots, mvcs, vals))

    def _buffer_votes(
        self, row: int, votes: tuple[VoteEntry, ...], round_no: int
    ) -> None:
        """Compat shim: ingest a tuple-of-VoteEntry vote vector."""
        vv = VoteRound1(votes=votes)
        self._ingest_vote_arrays(row, vv.shards, vv.phases, vv.vals, round_no)

    def _repair_stale_sender(
        self, row: int, shards: np.ndarray, slots: np.ndarray
    ) -> None:
        """Unicast Decisions (with bindings) for decided slots a lagging
        sender is still voting in. Rate-limited per sender; slots already
        GC'd from the ledger fall back to the sync path on the sender."""
        now = time.time()
        if len(shards) > 64:
            # a storm of stale votes from one sender: a peer is far
            # behind (or replaying) — journaled for triage alongside the
            # rate-limited repair below
            self.journal.record(
                self.journal.STALE_STORM, row=row, entries=int(len(shards))
            )
        last = self._last_repair.get(row, 0.0)
        if now - last < max(0.05, self.config.phase_timeout / 4):
            return
        entries: list[DecisionEntry] = []
        rt = self.rt
        for s, slot in zip(shards[:256], slots[:256]):
            s, slot = int(s), int(slot)
            rec = rt.shards[s].decisions.get(slot)
            if rec is not None:
                entries.append(
                    DecisionEntry(
                        shard=s,
                        phase=pack_phase(slot, 0),
                        decision=rec.value,
                        batch_id=rec.batch_id,
                    )
                )
                continue
            # bulk-lane slots have no SlotRecord: the decided-value ring
            # still answers for the last DEC_RING slots per shard
            ring = slot & (rt.DEC_RING - 1)
            if rt.dec_ring_slot[s, ring] == slot:
                entries.append(
                    DecisionEntry(
                        shard=s,
                        phase=pack_phase(slot, 0),
                        decision=StateValue(int(rt.dec_ring_val[s, ring])),
                        batch_id=None,
                    )
                )
        if entries:
            self._last_repair[row] = now
            self._send(
                Decision(decisions=tuple(entries)),
                recipient=self._row_to_node[row],
            )

    def _route_votes(self) -> None:
        """Offer every stashed/carried vote matching a shard's current
        (slot, phase) to the kernel ledger; keep future votes for later
        ticks; drop stale ones. One vectorized pass per sender batch."""
        for round_no, stash, carry in (
            (1, self._stash1, self._carry1),
            (2, self._stash2, self._carry2),
        ):
            if not stash and not carry:
                continue
            items = carry + stash
            stash.clear()
            carry.clear()
            for row, shards, slots, mvcs, vals in items:
                if type(shards) is int:
                    # scalar entry (one-vote vector, see ingest fast path)
                    s = shards
                    if slots < self.rt.applied_upto[s]:
                        continue  # stale: decided+applied while stashed
                    if (
                        self.rt.in_flight[s]
                        and slots == self._cur_slot[s]
                        and mvcs == self._cur_phase[s]
                    ):
                        if self._host_kernel:
                            led = (
                                self.kstate.led1
                                if round_no == 1
                                else self.kstate.led2
                            )
                            if led[row, s] == ABSENT:
                                led[row, s] = vals
                                self.flight.record(
                                    FRE_ROUTE1 if round_no == 1
                                    else FRE_ROUTE2,
                                    shard=s, slot=slots, peer=row,
                                    arg=int(vals),
                                )
                        else:
                            plane = (
                                self._inbox1
                                if round_no == 1
                                else self._inbox2
                            )
                            if plane[s, row] == ABSENT:
                                plane[s, row] = vals
                                self.flight.record(
                                    FRE_ROUTE1 if round_no == 1
                                    else FRE_ROUTE2,
                                    shard=s, slot=slots, peer=row,
                                    arg=int(vals),
                                )
                    else:
                        self.flight.record(
                            FRE_CARRY, shard=s, slot=slots, peer=row,
                            arg=round_no,
                        )
                        carry.append((row, s, slots, mvcs, vals))
                    continue
                live = slots >= self.rt.applied_upto[shards]
                if not live.all():
                    shards, slots, mvcs, vals = (
                        shards[live],
                        slots[live],
                        mvcs[live],
                        vals[live],
                    )
                if len(shards) == 0:
                    continue
                cur = (
                    self.rt.in_flight[shards]
                    & (slots == self._cur_slot[shards])
                    & (mvcs == self._cur_phase[shards])
                )
                if cur.any():
                    sh_c = shards[cur]
                    v_c = vals[cur]
                    sl_c = slots[cur]
                    for j in range(len(sh_c)):
                        self.flight.record(
                            FRE_ROUTE1 if round_no == 1 else FRE_ROUTE2,
                            shard=int(sh_c[j]), slot=int(sl_c[j]),
                            peer=row, arg=int(v_c[j]),
                        )
                    if self._host_kernel:
                        self.kernel.offer_votes(
                            self.kstate, round_no, row, sh_c, v_c
                        )
                    else:
                        plane = self._inbox1 if round_no == 1 else self._inbox2
                        cell = plane[sh_c, row]
                        w = cell == ABSENT
                        plane[sh_c[w], row] = v_c[w]
                    if cur.all():
                        continue
                    keep = ~cur
                    shards, slots, mvcs, vals = (
                        shards[keep],
                        slots[keep],
                        mvcs[keep],
                        vals[keep],
                    )
                carry.append((row, shards, slots, mvcs, vals))
        # bound the carry: genuinely unreachable future votes must not
        # accumulate without limit (validation bounds phase jumps, but a
        # malicious/buggy peer could still flood)
        for carry in (self._carry1, self._carry2):
            total = sum(
                1 if type(t[1]) is int else len(t[1]) for t in carry
            )
            cap = 8 * self.S * self.R
            while carry and total > cap:
                t = carry.pop(0)[1]
                total -= 1 if type(t) is int else len(t)

    def _on_decision(self, p: Decision) -> None:
        """Vectorized decision ingest: current-slot decisions go straight to
        the adoption plane; gap/future/bid-bearing entries fall back to the
        per-entry path (rare outside crash recovery)."""
        if self._rtm is not None:
            # runtime mode: escalated Decision frames (gaps, bid-bearing
            # recovery) must not touch the adopted-decision plane or the
            # consensus columns — the runtime thread owns both. The
            # bridge records/buffers them dict-side and adopts at the
            # head through CMD_DECIDE.
            return self._rtm.on_peer_decisions(p)
        n = self.n_shards
        shards, phases, vals = p.shards, p.phases, p.vals
        ok = shards < n
        if not ok.all():
            if p.bids is not None:
                self._on_decision_entries(p)
                return
            shards, phases, vals = shards[ok], phases[ok], vals[ok]
        if len(shards) == 0:
            return
        slots = phases >> 16
        stale = slots < self.rt.applied_upto[shards]
        cur = (
            ~stale
            & self.rt.in_flight[shards]
            & (slots == self._cur_slot[shards])
        )
        if p.bids is None and bool(cur.all()):
            self._dec_plane[shards] = vals
            return
        if p.bids is None:
            sh_c = shards[cur]
            self._dec_plane[sh_c] = vals[cur]
            rest = ~cur & ~stale
            if not rest.any():
                return
            idxs = np.nonzero(rest)[0]
            for i in idxs:
                self._on_decision_one(
                    int(shards[i]), int(slots[i]), int(vals[i]), None
                )
        else:
            self._on_decision_entries(p)

    def _on_decision_entries(self, p: Decision) -> None:
        for i, (s, ph, v) in enumerate(zip(p.shards, p.phases, p.vals)):
            s = int(s)
            if not (0 <= s < self.n_shards):
                continue
            slot = int(ph) >> 16
            if slot < self.rt.applied_upto[s]:
                continue
            self._on_decision_one(s, slot, int(v), p.bid_at(i))

    def _on_decision_one(self, s: int, slot: int, value: int, bid) -> None:
        sh = self.rt.shards[s]
        rec = sh.decisions.get(slot)
        if rec is not None:
            if rec.batch_id is None and bid is not None:
                rec.batch_id = bid  # late binding repair
                if not rec.applied:
                    self._apply_dirty.add(s)
            return
        if sh.in_flight and slot == int(self._cur_slot[s]):
            self._dec_plane[s] = value
            if bid is not None and slot not in sh.buf_propose:
                sh.buf_propose[slot] = (bid, None)
            return
        if slot < max(sh.next_slot, sh.applied_upto):
            # gap slot (below the head, e.g. decided-but-lost across a
            # crash): it will never "become current" again, so adopt the
            # peer decision immediately — buffering it would wedge apply
            # at the gap forever
            self._record_decision(s, slot, value, bid)
            if bid is not None and slot not in sh.buf_propose:
                sh.buf_propose[slot] = (bid, None)
            return
        # buffered only: recorded when the slot becomes current, either
        # via kernel adoption (in flight) or in _open_slots — keeps slot
        # recording contiguous so apply order never skips a slot
        sh.buf_decision[slot] = (value, bid)
        if bid is not None and slot not in sh.buf_propose:
            sh.buf_propose[slot] = (bid, None)

    def _on_new_batch(self, p: NewBatch) -> None:
        """A peer forwards a submission for us to propose (see module doc)."""
        if not (0 <= p.shard < self.n_shards):
            return
        if p.batch.id in self._seen_batches:
            return
        self._seen_batches.add(p.batch.id)
        self._seen_order.append(p.batch.id)
        self.rt.shards[p.shard].queue.append(PendingSubmission(batch=p.batch))

    # -- submission forwarding / slot opening --------------------------------

    def _forward_submissions(self) -> None:
        """Send queued batches to the upcoming slot's proposer when that's
        not us. The submission stays queued locally (with its future) so the
        submitter can still answer its client; the proposer's copy drives
        consensus. Re-forwarded on timeout by `_check_timeouts`."""
        n = self.n_shards
        rt = self.rt
        queued = rt.queue_len[:n] > 0
        if not queued.any():
            return
        if not (queued & ~rt.in_flight[:n]).any():
            # everything queued rides a slot already in flight: nothing to
            # forward (the common state for the whole consensus window —
            # skip the proposer/clock chain below)
            return
        now = time.time()
        head = np.maximum(rt.next_slot[:n], rt.applied_upto[:n])
        proposer = slot_proposer_vec(self._shard_ids[:n], head, self.R)
        need = (
            queued
            & ~rt.in_flight[:n]
            & (proposer != self.me)
            & (
                (rt.head_fwd_at[:n] == 0.0)
                | (now - rt.head_fwd_at[:n] >= self.config.phase_timeout)
            )
        )
        if not need.any():
            return
        for s in np.nonzero(need)[0]:
            s = int(s)
            sh = rt.shards[s]
            sub = sh.queue[0]
            if sub.forwarded_at and now - sub.forwarded_at < self.config.phase_timeout:
                rt.head_fwd_at[s] = sub.forwarded_at
                continue
            sub.forwarded_at = now
            rt.head_fwd_at[s] = now
            if not sub.first_forwarded_at:
                sub.first_forwarded_at = now
            target = self._row_to_node[int(proposer[s])]
            self._send(
                NewBatch(shard=s, batch=sub.batch), recipient=target
            )

    def _open_slots(self) -> list[tuple[int, int, int]]:
        """Decide which shards open a new decision slot this round.

        Returns [(shard, slot, initial_vote)]. Cases:
          - we are the proposer and have a queued batch → open V1 + Propose;
          - a Propose arrived for the slot → open V1;
          - peers are already voting on the slot (or a timeout expired on a
            forwarded submission) → open V0 after a grace period.

        Candidate shards are selected with one columnar scan; the per-shard
        decision logic below runs only for shards that can actually act.
        """
        n = self.n_shards
        rt = self.rt
        lib = self._hk_lib
        if lib is not None:
            # one C pass over the columns; an idle tick costs one int
            head, cand = self._open_bufs
            if not lib.rk_open_scan(*self._open_scan_args):
                return []
        else:
            head = np.maximum(rt.next_slot[:n], rt.applied_upto[:n])
            cand = ~rt.in_flight[:n] & (
                (rt.queue_len[:n] > 0)
                | rt.prop_flag[:n]
                | rt.dec_flag[:n]
                | (rt.votes_seen_slot[:n] >= head)
                | (rt.tainted_upto[:n] > 0)
            )
            if not cand.any():
                return []
        now = time.time()
        grace = min(max(self.config.phase_timeout / 10.0, 0.02), 1.0)
        opened: list[tuple[int, int, int]] = []
        propose_entries: list[Propose] = []
        alive_set = self.rt.active_nodes | {self.node_id}  # hoisted: hot loop
        for s in np.nonzero(cand)[0]:
            s = int(s)
            sh = rt.shards[s]
            slot = int(head[s])
            if slot in sh.decisions:  # decided while we weren't looking
                sh.next_slot = slot + 1
                continue
            bd = sh.buf_decision.get(slot)
            if bd is not None and bd[0] in (V0, V1):
                # a peer already broadcast this slot's decision: adopt it
                # without running consensus locally
                self._record_decision(s, slot, bd[0], bd[1])
                continue
            if slot < sh.tainted_upto:
                # restart-equivocation guard: this replica may have voted in
                # this slot before crashing — never cast fresh votes. The
                # slot resolves via an adopted peer Decision (above), via
                # snapshot sync, or — when a full release window passes
                # with NO tainted-slot vote traffic — the taint lifts:
                # in-flight peers retransmit every phase_timeout, so a
                # quiet window several times that proves nobody live holds
                # our pre-crash votes. (A sliding window, not a latch —
                # traffic that stopped long ago must not wedge a shard
                # whose rotation parks on this replica.)
                quiet_since = max(
                    self._restored_at, float(rt.taint_traffic[s])
                )
                # the quiet window only proves anything about CONNECTED
                # peers: an absent (partitioned/paused) peer is exactly
                # the one that could still hold our pre-crash votes. With
                # the full membership in view, release after one window;
                # with peers missing, hold out 4x longer — a dead peer
                # must not wedge the shard forever, but a partitioned one
                # gets ample time to heal and retransmit (which refreshes
                # taint_traffic, restarting the window).
                full_view = len(alive_set) >= len(self.cluster.all_nodes)
                release = self._taint_release * (1.0 if full_view else 4.0)
                if now - quiet_since > release:
                    sh.tainted_upto = 0
                continue
            proposer_row = slot_proposer(s, slot, self.R)
            # never propose a batch that already committed in another slot
            # (duplicate-forwarding race): settle it from the dedup ledger
            while sh.queue and sh.queue[0].batch.id in sh.applied_ids:
                done_sub = sh.queue.popleft()
                self._settle_from_ledger(sh, done_sub)
            if slot in sh.buf_propose:
                # an existing binding wins the slot — never rebind, even as
                # the proposer: re-proposing a different batch for a slot
                # that already carries one could bind divergent batch_ids
                # across replicas (retransmits go through _check_timeouts)
                opened.append((s, slot, V1))
            elif proposer_row == self.me and sh.queue:
                sub = sh.queue[0]
                self._h_stage["submit_propose"].observe(
                    now - sub.submitted_at
                )
                self.flight.record(
                    FRE_PROPOSE, shard=s, slot=slot,
                    batch=fr_hash(sub.batch.id),
                )
                sh.payloads[sub.batch.id] = sub.batch
                sh.buf_propose[slot] = (sub.batch.id, sub.batch)
                propose_entries.append(
                    Propose(
                        shard=s,
                        phase=pack_phase(slot, 0),
                        batch_id=sub.batch.id,
                        value=StateValue.V1,
                        batch=sub.batch,
                    )
                )
                opened.append((s, slot, V1))
            else:
                votes_seen = rt.votes_seen_slot[s] >= slot
                if votes_seen:
                    if sh.opened_at == 0.0:
                        sh.opened_at = now  # start the grace clock
                    elif now - sh.opened_at > grace:
                        opened.append((s, slot, V0))
                elif sh.queue and sh.queue[0].first_forwarded_at and (
                    now - sh.queue[0].first_forwarded_at
                    > (
                        self.config.phase_timeout
                        if self._row_to_node[proposer_row] in alive_set
                        # known-dead proposer: short-circuit after one grace
                        # period instead of a transient-heartbeat-gap
                        # instant null slot
                        else max(grace, self.config.phase_timeout / 4)
                    )
                ):
                    # forwarded proposer unresponsive: force a null slot to
                    # rotate the proposer (leaderless liveness).
                    # first_forwarded_at, not forwarded_at — the periodic
                    # re-forward refreshes the latter, which must not reset
                    # the give-up clock.
                    opened.append((s, slot, V0))
        if opened:
            idx = np.fromiter((o[0] for o in opened), np.int64, len(opened))
            slots_arr = np.fromiter((o[1] for o in opened), np.int64, len(opened))
            rt.in_flight[idx] = True
            np.maximum.at(rt.next_slot, idx, slots_arr)
            rt.opened_at[idx] = now
            rt.last_progress[idx] = now
        # Proposes are NOT sent here: the vote barrier must be durable
        # before any proposal for a newly opened slot reaches the wire —
        # otherwise a crash-restart could rebind a different batch to a slot
        # some peer already bound. _kernel_round flushes these right after
        # the barrier save.
        self._pending_proposes.extend(propose_entries)
        return opened

    # -- the kernel round ----------------------------------------------------

    def _dwell_observe(self, idx, new_ph) -> None:
        """Python-twin per-phase dwell observe (host/device tick paths;
        the native path's twin lives in rk_tick). ``new_ph`` holds each
        shard's post-advance phase = the 1-based ordinal of the phase
        that just completed. The slot guard skips shards whose stamp
        belongs to an earlier slot (armed outside _flight_open)."""
        from rabia_tpu.obs.registry import slo_bucket_index

        now = time.monotonic_ns()
        cur = np.asarray(self._cur_slot)
        for j in range(len(idx)):
            s = int(idx[j])
            if int(self._dwell_t0_slot[s]) != int(cur[s]):
                continue
            p = int(new_ph[j])
            if p >= 1:
                h = self._py_dwell[min(p, 8) - 1]
                ns = now - int(self._dwell_t0[s])
                h[slo_bucket_index(ns)] += 1
                h[-2] += 1
                h[-1] += ns
            self._dwell_t0[s] = now

    def _flight_open(self, idx, slots_arr, init_arr) -> None:
        """Flight OPEN records for slots armed outside the native tick's
        own open path (host-kernel/jax rounds, and the native round's
        Python-vote pre-arm, where rk_start_slots runs standalone and the
        C ring therefore records nothing)."""
        if len(idx):
            # phase-dwell stamp: the armed slots' phase 1 starts now
            t = time.monotonic_ns()
            ii = np.asarray(idx, np.int64)
            self._dwell_t0[ii] = t
            self._dwell_t0_slot[ii] = np.asarray(slots_arr, np.int64)
        for j in range(len(idx)):
            self.flight.record(
                FRE_OPEN, shard=int(idx[j]), slot=int(slots_arr[j]),
                arg=int(init_arr[j]),
            )
        if len(idx):
            self.flight.record(
                FRE_FRAME_OUT, shard=int(idx[0]), slot=int(slots_arr[0]),
                arg=int(MessageType.VoteRound1),
            )

    async def _kernel_round(
        self,
        opened: list[tuple[int, int, int]],
        bulk: Optional[tuple[np.ndarray, np.ndarray]] = None,
    ) -> None:
        if opened or bulk is not None:
            await self._advance_vote_barrier(opened, bulk)
        if self._pending_proposes:
            for pe in self._pending_proposes:
                self._send(pe)
            self._pending_proposes.clear()
        if self._pending_block_announces:
            for pb in self._pending_block_announces:
                self._send(pb)
            self._pending_block_announces.clear()
        have_opens = bool(opened) or bulk is not None
        idx = slots_arr = init_arr = None
        mask = slots_full = init_full = None
        if have_opens:
            if opened:
                k = len(opened)
                idx = np.fromiter((o[0] for o in opened), np.int64, k)
                slots_arr = np.fromiter((o[1] for o in opened), np.int64, k)
                init_arr = np.fromiter((o[2] for o in opened), np.int8, k)
            else:
                idx = np.zeros(0, np.int64)
                slots_arr = np.zeros(0, np.int64)
                init_arr = np.zeros(0, np.int8)
            if bulk is not None:
                b_idx, b_slots = bulk
                idx = np.concatenate([idx, b_idx])
                slots_arr = np.concatenate([slots_arr, b_slots])
                init_arr = np.concatenate(
                    [init_arr, np.full(len(b_idx), V1, np.int8)]
                )
            if self._host_kernel:
                # reused full-width planes (freshly allocating three
                # S-wide arrays per open tick measurably taxes the serial
                # shape); consumers only read masked positions
                mask, slots_full, init_full = self._open_planes
                mask[:] = False
            else:
                # jax backend: jnp.asarray may adopt these buffers
                # zero-copy while dispatch is still in flight — fresh
                # arrays per tick, as before
                mask = np.zeros(self.S, bool)
                slots_full = np.zeros(self.S, np.int64)
                init_full = np.full(self.S, V0, np.int8)
            mask[idx] = True
            slots_full[idx] = slots_arr
            init_full[idx] = init_arr

        if not self._host_kernel:
            return self._device_round(idx, slots_arr, init_arr, mask,
                                      slots_full, init_full)

        if self._rk is not None:
            return self._native_round(
                idx, slots_arr, init_arr, mask, slots_full, init_full
            )

        if have_opens:
            with span("engine.kernel.start"):
                self.kstate = self.kernel.start_slots(
                    self.kstate, mask, slots_full.astype(np.int32), init_full
                )
            self._refresh_mirrors()
            self._flight_open(idx, slots_arr, init_arr)
            self._send(
                VoteRound1(
                    shards=idx,
                    phases=(slots_arr << 16),
                    vals=init_arr,
                )
            )

        # Step to quiescence WITHIN the tick: the kernel advances one
        # stage per step, and a transition (R1→R2 cast, phase advance)
        # can make votes already ledger-resident decisive with no
        # further peer traffic. Looping route→step→outbox here collapses
        # those into one engine activation — e.g. a replica whose drain
        # delivered a full R1+R2 quorum proposes, advances and decides
        # in a single tick instead of three wake-ups. Bounded: a slot
        # crosses at most a few stages per delivery, so 4 covers the
        # deepest chain; anything left re-arms ``_restep`` for the next
        # tick exactly as before.
        for _ in range(4):
            with span("engine.kernel.route"):
                self._route_votes()
            prev_phase = self._cur_phase
            with span("engine.kernel.step"):
                self.kstate, outbox = self.kernel.node_step(
                    self.kstate, None, None, self._dec_plane
                )
            self._dec_plane.fill(ABSENT)
            self._refresh_mirrors()
            with span("engine.kernel.outbox"):
                self._process_outbox(outbox, prev_phase)
            if not self._restep:
                break
            self._restep = False

    def _native_round(
        self,
        idx: Optional[np.ndarray],
        slots_arr: Optional[np.ndarray],
        init_arr: Optional[np.ndarray],
        mask: Optional[np.ndarray],
        slots_full: Optional[np.ndarray],
        init_full: Optional[np.ndarray],
    ) -> None:
        """One engine tick on the native fast path: slot arming in place,
        then ONE C call chaining route→node_step→outbox rounds and framing
        outbound votes/decisions (hostkernel.cpp rk_tick). Python resumes
        only for events: decided slots to record/apply."""
        rk = self._rk
        py_votes = bool(
            self._stash1 or self._stash2 or self._carry1 or self._carry2
        )
        if py_votes and mask is not None:
            # votes injected through the Python ingest APIs (tests, compat
            # shims) must route AFTER slot arming, like the Python path —
            # arm separately, then route, then chain without opens
            with span("engine.kernel.start"):
                rk.start_slots(mask, slots_full, init_full)
            self._flight_open(idx, slots_arr, init_arr)
            self._send(
                VoteRound1(
                    shards=idx, phases=(slots_arr << 16), vals=init_arr
                )
            )
            mask = None
        if py_votes:
            # the Python scatter writes the same persistent ledger arrays
            # the C tick reads
            self._route_votes()
        # span name matches the host path's step (the chained C call IS
        # the route→step→outbox sequence)
        with span("engine.kernel.step"):
            if mask is not None:
                res = rk.tick(
                    open_mask=mask,
                    open_slots=slots_full,
                    open_init=init_full,
                )
            else:
                res = rk.tick()
        nbytes = int(res[0])
        if nbytes:
            t_bc = time.perf_counter_ns()
            rk.broadcast_out(self, nbytes)
            dt_bc = time.perf_counter_ns() - t_bc
            self._h_slo["broadcast"].observe(dt_bc * 1e-9)
            if self._rtm is None:
                self._stg_bcast(dt_bc)
        if res[4]:
            logger.warning(
                "native tick outbound buffer overflow; dropped frames "
                "recover via retransmit"
            )
        if res[2]:
            self._restep = True
        if res[1]:
            n = self.n_shards
            act = self.rt.in_flight[:n]
            done = self.kstate.done[:n] & act
            newly = rk.newly[:n].astype(bool) & act
            rk.newly[:n] = 0
            with span("engine.kernel.outbox"):
                # decision frames for newly decided slots were already
                # framed by rk_tick — record/apply only
                self._process_decided(done, newly, broadcast=False)

    def _device_round(
        self,
        idx: Optional[np.ndarray],
        slots_arr: Optional[np.ndarray],
        init_arr: Optional[np.ndarray],
        mask: Optional[np.ndarray],
        slots_full: Optional[np.ndarray],
        init_full: Optional[np.ndarray],
    ) -> None:
        """One engine tick on the jax backend: ONE fused device dispatch
        (start + ``device_substeps`` chained node_steps via node_cycle)
        and ONE batched device→host fetch — instead of per-stage
        dispatch/refresh pairs, which over a tunneled TPU link cost ~ms
        each (SURVEY.md §7.4.4 amortization lever)."""
        import jax
        import jax.numpy as jnp

        if idx is not None:
            # host-side mirror update (the device applies the same open
            # inside node_cycle): routing below must see the new slots
            self._cur_slot[idx] = slots_arr
            self._cur_phase[idx] = 0
            self._stage[idx] = R1_WAIT
            self._my_r1[idx] = init_arr
            self._my_r2[idx] = ABSENT
            self._decided[idx] = ABSENT
            self._done[idx] = False
            self._active[idx] = True
            self._flight_open(idx, slots_arr, init_arr)
            self._send(
                VoteRound1(
                    shards=idx,
                    phases=(slots_arr << 16),
                    vals=init_arr,
                )
            )
        with span("engine.kernel.route"):
            self._route_votes()
        prev_phase = self._cur_phase.copy()
        if mask is None:
            mask = np.zeros(self.S, bool)
            slots_full = np.zeros(self.S, np.int64)
            init_full = np.full(self.S, V0, np.int8)
        with span("engine.kernel.step"):
            if self._zc_inbox:
                # dlpack adoption: the device consumes the host inbox
                # planes in place — zero copies on a CPU/directly-
                # attached backend (pointer identity pinned in
                # tests/test_zero_copy.py), ONE H2D DMA elsewhere. The
                # planes must stay untouched until the tick's fetch
                # below forces completion; the resets move after it.
                ib1 = jax.dlpack.from_dlpack(self._inbox1)
                ib2 = jax.dlpack.from_dlpack(self._inbox2)
                dec = jax.dlpack.from_dlpack(self._dec_plane)
            else:
                ib1 = jnp.asarray(self._inbox1)
                ib2 = jnp.asarray(self._inbox2)
                dec = jnp.asarray(self._dec_plane)
            self.kstate, outboxes = self.kernel.node_cycle(
                self.kstate,
                jnp.asarray(mask),
                jnp.asarray(slots_full.astype(np.int32)),
                jnp.asarray(init_full),
                ib1,
                ib2,
                dec,
                self._substeps,
            )
            if not self._zc_inbox:
                self._inbox1.fill(ABSENT)
                self._inbox2.fill(ABSENT)
        if not self._zc_inbox:
            adopted = self._dec_plane != ABSENT
            self._dec_plane.fill(ABSENT)
        with span("engine.kernel.fetch"):
            st_np, ob_np = jax.device_get((self.kstate, outboxes))
        if self._zc_inbox:
            # fetch completed => node_cycle consumed the adopted planes;
            # only now may the host mutate them for the next tick
            del ib1, ib2, dec
            adopted = self._dec_plane != ABSENT
            self._inbox1.fill(ABSENT)
            self._inbox2.fill(ABSENT)
            self._dec_plane.fill(ABSENT)
        self._set_mirrors(st_np)
        with span("engine.kernel.outbox"):
            self._process_outbox_window(ob_np, prev_phase, adopted)

    async def _advance_vote_barrier(
        self,
        opened: list[tuple[int, int, int]],
        bulk: Optional[tuple[np.ndarray, np.ndarray]] = None,
    ) -> None:
        """Persist the vote barrier BEFORE the first vote of any newly
        opened slot leaves this replica (write-ahead), so a post-crash
        restore can taint every slot that may hold our votes.

        The barrier is advanced ``barrier_stride`` slots AHEAD of the
        opened slot, so one atomic-write+fsync amortizes over the next K
        opens per shard instead of landing on every consensus round's
        critical path. Cost: a restart may taint up to K-1 never-voted
        slots, which the taint-release window already resolves (restore
        path is deliberately conservative)."""
        if self.persistence is None:
            return
        stride = max(1, self.config.barrier_stride)
        changed = False
        for s, slot, _v in opened:
            if slot >= self._barrier[s]:
                self._barrier[s] = slot + stride
                changed = True
        if bulk is not None:
            b_idx, b_slots = bulk
            due = b_slots >= self._barrier[b_idx]
            if due.any():
                np.maximum.at(
                    self._barrier, b_idx[due], b_slots[due] + stride
                )
                changed = True
        if changed:
            await self.persistence.save_aux(
                "vote_barrier", self._barrier[: self.n_shards].tobytes()
            )

    def _refresh_mirrors(self) -> None:
        st = self.kstate
        if self._host_kernel:
            # host arrays: mirrors alias the kernel state (no copies)
            self._cur_slot = st.slot
            self._cur_phase = st.phase
            self._stage = st.stage
            self._my_r1 = st.my_r1
            self._my_r2 = st.my_r2
            self._done = st.done
            self._decided = st.decided
            self._active = st.active
        else:
            self._set_mirrors(st)

    def _set_mirrors(self, st) -> None:
        """Adopt host mirrors from a (fetched) kernel state. Mirrors must
        be WRITABLE: the device round updates them in place for opened
        slots before the fused dispatch."""
        self._cur_slot = np.array(st.slot, np.int64)
        self._cur_phase = np.array(st.phase, np.int64)
        self._stage = np.array(st.stage, np.int8)
        self._my_r1 = np.array(st.my_r1, np.int8)
        self._my_r2 = np.array(st.my_r2, np.int8)
        self._done = np.array(st.done, bool)
        self._decided = np.array(st.decided, np.int8)
        self._active = np.array(st.active, bool)

    def _process_outbox(self, outbox, prev_phase: np.ndarray) -> None:
        """Turn kernel outbox flags into broadcast messages + decisions —
        columnar gathers; per-shard Python only for newly decided slots."""
        n = self.n_shards
        rt = self.rt
        act = rt.in_flight[:n]
        # nonzero-once (then branch on idx.size): at small S the repeated
        # tiny-array .any() dispatches dominate the outbox cost
        cast_idx = np.nonzero(np.asarray(outbox.cast_r2)[:n] & act)[0]
        done = np.asarray(self._done)[:n] & act
        adv_all_idx = np.nonzero(np.asarray(outbox.advanced)[:n] & act)[0]
        adv_idx = adv_all_idx[~done[adv_all_idx]]
        done_idx = np.nonzero(done)[0]
        if not (cast_idx.size or adv_all_idx.size or done_idx.size):
            return
        now = time.time()
        # a stage transition may have made ledger-resident (or carried)
        # votes decisive — schedule one follow-up step (see _tick)
        if cast_idx.size or adv_all_idx.size:
            self._restep = True
        if adv_all_idx.size:
            # per-phase dwell closes on EVERY advance — deciding shards
            # (masked out of adv_idx below) still finish their final phase
            self._dwell_observe(
                adv_all_idx, np.asarray(outbox.new_phase)[adv_all_idx]
            )

        if cast_idx.size:
            idx = cast_idx
            slots = np.asarray(self._cur_slot)[idx].astype(np.int64)
            phases = (slots << 16) | np.asarray(prev_phase)[idx].astype(np.int64)
            r2v = np.asarray(outbox.r2_vals)[idx]
            for j in range(len(idx)):
                self.flight.record(
                    FRE_CAST_R2, shard=int(idx[j]), slot=int(slots[j]),
                    arg=int(r2v[j]),
                )
            self.flight.record(
                FRE_FRAME_OUT, shard=int(idx[0]), slot=int(slots[0]),
                arg=int(MessageType.VoteRound2),
            )
            self._send(
                VoteRound2(
                    shards=idx,
                    phases=phases,
                    vals=r2v,
                )
            )
            rt.last_progress[idx] = now

        if adv_idx.size:
            idx = adv_idx
            slots = np.asarray(self._cur_slot)[idx].astype(np.int64)
            new_ph = np.asarray(outbox.new_phase)[idx].astype(np.int64)
            phases = (slots << 16) | new_ph
            for j in range(len(idx)):
                self.flight.record(
                    FRE_ADVANCE, shard=int(idx[j]), slot=int(slots[j]),
                    arg=int(new_ph[j]) & 0xFF,
                )
            self.flight.record(
                FRE_FRAME_OUT, shard=int(idx[0]), slot=int(slots[0]),
                arg=int(MessageType.VoteRound1),
            )
            self._send(
                VoteRound1(
                    shards=idx,
                    phases=phases,
                    vals=np.asarray(outbox.new_r1)[idx],
                )
            )
            rt.last_progress[idx] = now

        if done_idx.size:
            newly = np.asarray(outbox.newly_decided)[:n] & act
            dec_vals = np.asarray(self._decided)
            cur = np.asarray(self._cur_slot)
            for s_new in np.nonzero(newly)[0]:
                self.flight.record(
                    FRE_STEP_DECIDE, shard=int(s_new),
                    slot=int(cur[s_new]), arg=int(dec_vals[s_new]),
                )
            self._process_decided(done, newly)

    def _process_outbox_window(
        self, ob, prev_phase: np.ndarray, adopted: Optional[np.ndarray] = None
    ) -> None:
        """Windowed twin of :meth:`_process_outbox`: one stacked outbox
        per chained substep (jax backend's node_cycle). Vote transitions
        are emitted per substep — a shard can legitimately cast R2 in one
        substep and advance (or decide) in a later one within the same
        dispatch, each with its own phase tag. ``adopted`` marks shards
        whose decision_in plane carried a value (they go done at substep
        0, like the host path's adopt)."""
        n = self.n_shards
        rt = self.rt
        act = rt.in_flight[:n]
        if not act.any():
            return
        now = time.time()
        K = len(ob.cast_r2)
        done_final = np.asarray(self._done)[:n] & act
        cur_slot = np.asarray(self._cur_slot)
        prev = np.asarray(prev_phase).astype(np.int64)
        newly_any = np.zeros(n, bool)
        # running done view, matching the host path's per-step `advanced &
        # ~done`: a phase-advance R1 is suppressed only if the shard is
        # done BY THAT SUBSTEP — using the final state would drop votes a
        # pivotal peer still needs (it decides later in the window)
        cum_done = (
            (adopted[:n] & act) if adopted is not None else np.zeros(n, bool)
        )
        for k in range(K):
            cast = ob.cast_r2[k][:n] & act
            if cast.any():
                i = np.nonzero(cast)[0]
                slots = cur_slot[i].astype(np.int64)
                for j in range(len(i)):
                    self.flight.record(
                        FRE_CAST_R2, shard=int(i[j]), slot=int(slots[j]),
                        arg=int(ob.r2_vals[k][i[j]]),
                    )
                self._send(
                    VoteRound2(
                        shards=i,
                        phases=(slots << 16) | prev[i],
                        vals=ob.r2_vals[k][i],
                    )
                )
                rt.last_progress[i] = now
            newly_k = ob.newly_decided[k][:n] & act
            if newly_k.any():
                # phases-to-decide telemetry for the device-kernel path
                # (the host paths account inside HostNodeKernel / the rk
                # tick context): post-advance phase == phases used
                i_new = np.nonzero(newly_k)[0]
                ph_new = np.asarray(ob.new_phase[k])[i_new].astype(np.int64)
                self._dev_phase_sum += int(ph_new.sum())
                np.add.at(
                    self._dev_phase_hist,
                    np.minimum(ph_new, len(self._dev_phase_hist) - 1),
                    1,
                )
            for s_new in np.nonzero(newly_k)[0]:
                self.flight.record(
                    FRE_STEP_DECIDE, shard=int(s_new),
                    slot=int(cur_slot[s_new]),
                    arg=int(np.asarray(self._decided)[s_new]),
                )
            newly_any |= newly_k
            cum_done |= newly_k
            adv_all_k = ob.advanced[k][:n] & act
            if adv_all_k.any():
                i_adv = np.nonzero(adv_all_k)[0]
                self._dwell_observe(
                    i_adv, np.asarray(ob.new_phase[k])[i_adv]
                )
            adv = ob.advanced[k][:n] & act & ~cum_done
            if adv.any():
                i = np.nonzero(adv)[0]
                slots = cur_slot[i].astype(np.int64)
                for j in range(len(i)):
                    self.flight.record(
                        FRE_ADVANCE, shard=int(i[j]), slot=int(slots[j]),
                        arg=int(ob.new_phase[k][i[j]]) & 0xFF,
                    )
                self._send(
                    VoteRound1(
                        shards=i,
                        phases=(slots << 16)
                        | ob.new_phase[k][i].astype(np.int64),
                        vals=ob.new_r1[k][i],
                    )
                )
                rt.last_progress[i] = now
            prev = np.where(
                np.asarray(ob.advanced[k], bool),
                np.asarray(ob.new_phase[k], np.int64),
                prev,
            )
        # ANY substep's transition schedules a follow-up tick: a phase
        # advance can make host-side CARRIED votes routable, which later
        # substeps cannot see (they only cascade on the device ledger) —
        # the next tick's _route_votes must get a chance to offer them
        any_trans = False
        for k in range(K):
            if (ob.cast_r2[k][:n] & act).any() or (
                ob.advanced[k][:n] & act
            ).any():
                any_trans = True
                break
        if any_trans:
            self._restep = True
        if done_final.any():
            self._process_decided(done_final, newly_any)

    def _process_decided(
        self, done: np.ndarray, newly: np.ndarray, broadcast: bool = True
    ) -> None:
        """Record decisions for every done in-flight shard; broadcast the
        newly decided ones (shared by both outbox processors; the native
        tick frames its own Decision broadcasts and passes False)."""
        rt = self.rt
        dec_idx = np.nonzero(done)[0]
        decided_vals = np.asarray(self._decided)
        cur_slot = np.asarray(self._cur_slot)
        blk = self._cur_blk_ref[dec_idx] != -1
        if blk.any():
            self._finish_block_slots(dec_idx[blk])
        for s in dec_idx[~blk]:
            s = int(s)
            sh = rt.shards[s]
            slot = int(cur_slot[s])
            bid = None
            bp = sh.buf_propose.get(slot)
            if bp is not None:
                bid = bp[0]
            elif self._blk_pending_slot[s] == slot:
                ref = int(self._blk_pending_ref[s])
                rec_blk = self._blk_registry.get(ref)
                if rec_blk is not None and rec_blk.out is None:
                    # a received block binding we never opened (e.g. we
                    # voted V0 after grace before its ProposeBlock
                    # arrived): use it as the payload source for the
                    # decided slot
                    bi = int(self._blk_pending_idx[s])
                    bid = rec_blk.block.batch_id_for(bi)
                    sh.payloads[bid] = rec_blk.block.materialize_batch(bi)
                    self._unref_block(ref, 1)
                    self._blk_pending_ref[s] = -1
                    self._blk_pending_slot[s] = -1
                # our own never-announced pending entries stay put:
                # _record_decision voids them into the scalar retry lane
            self._record_decision(s, slot, int(decided_vals[s]), bid)
        if broadcast and newly.any() and self.config.decision_broadcast:
            # steady-state Decisions are bid-free (fully columnar both
            # ways); a peer that never saw the Propose recovers the
            # binding from the late/retransmitted Propose or via sync
            idx = np.nonzero(newly)[0]
            slots = cur_slot[idx].astype(np.int64)
            self.flight.record(
                FRE_FRAME_OUT, shard=int(idx[0]), slot=int(slots[0]),
                arg=int(MessageType.Decision),
            )
            self._send(
                Decision(
                    shards=idx,
                    phases=(slots << 16),
                    vals=decided_vals[idx],
                )
            )

    def _void_pending_block(self, s: int) -> None:
        """A slot a pending block binding targeted resolved without it:
        release the binding. Our own never-announced entries retry through
        the scalar lane (no peer ever saw them, so no duplicate risk);
        received-block bindings are just dropped."""
        ref = int(self._blk_pending_ref[s])
        bi = int(self._blk_pending_idx[s])
        self._blk_pending_ref[s] = -1
        self._blk_pending_slot[s] = -1
        rec = self._blk_registry.get(ref)
        if rec is None:
            return
        if rec.out is not None:
            self._demote_block_entry(ref, bi)
        else:
            self._unref_block(ref, 1)

    def _record_decision(self, s: int, slot: int, value: int, batch_id) -> None:
        sh = self.rt.shards[s]
        if batch_id is None and value == V1:
            # bid-free Decision (the steady-state broadcast) adopted for a
            # slot whose Propose we HAVE: bind it here, or apply stalls
            # into a snapshot sync for a payload already on hand. Common
            # when a fast peer decides before this replica opened the slot
            # (the chained native tick makes one-tick decides routine).
            bp = sh.buf_propose.get(slot)
            if bp is not None:
                batch_id = bp[0]
        if self._blk_pending_slot[s] != -1 and self._blk_pending_slot[s] <= slot:
            self._void_pending_block(s)
        if slot in sh.decisions:
            rec = sh.decisions[slot]
        else:
            rec = SlotRecord(value=StateValue(value), batch_id=batch_id)
            sh.decisions[slot] = rec
            # one DECIDE record per slot, on BOTH tick paths (recording
            # stays a Python event even under the native tick)
            self.flight.record(
                FRE_DECIDE, shard=s, slot=slot, arg=value,
                batch=fr_hash(batch_id) if batch_id is not None else 0,
            )
            if value == V1:
                self.rt.decided_v1 += 1
            else:
                self.rt.decided_v0 += 1
        if sh.in_flight and int(self._cur_slot[s]) == slot:
            opened = float(self.rt.opened_at[s])
            if opened > 0.0:
                # open→decide for the slot this replica ran consensus on
                # (adopted decisions for never-opened slots carry no
                # local open time) — works on both tick paths: recording
                # is a Python event even under the native tick
                self._h_stage["propose_decide"].observe(
                    time.time() - opened
                )
            sh.in_flight = False
        sh.next_slot = max(sh.next_slot, slot + 1)
        sh.opened_at = 0.0
        ring = slot & (self.rt.DEC_RING - 1)
        self.rt.dec_ring_val[s, ring] = value
        self.rt.dec_ring_slot[s, ring] = slot
        # the next slot has a new proposer: restart the forward/give-up
        # clocks for whatever is still queued here
        self.rt.head_fwd_at[s] = 0.0
        for sub in sh.queue:
            sub.forwarded_at = 0.0
            sub.first_forwarded_at = 0.0
        self._apply_dirty.add(s)
        sh.gc_upto(sh.applied_upto)

    # -- decision application ------------------------------------------------

    def _wal_stage(
        self, s: int, slot: int, value: int, batch=None, bid_bytes=None,
        ops=None,
    ) -> None:
        """Stage one decided (shard, slot) into the durability plane's
        group-commit lane (no fsync here — the WAL's flush thread owns
        that; the gateway's result barrier waits on the watermark). A
        wedged log is journaled, never allowed to kill the apply path —
        results stop leaving (the barrier fails) which is the correct
        failure mode for lost durability."""
        p = self._wal
        if p is None:
            return
        if batch is not None:
            bid_bytes = batch.id.value.bytes
            ops = [c.data for c in batch.commands]
        try:
            p.stage_wave(int(s), int(slot), int(value), bid_bytes, ops)
        except PersistenceError:
            logger.exception("wal stage failed (shard %d slot %d)", s, slot)
            self.journal.record(
                self.journal.WAL_WEDGED, shard=int(s), slot=int(slot)
            )

    def _apply_ready(self) -> int:
        """Apply decided slots in order per shard, through the pipelined
        apply stage (engine/apply_plane.py): up to the inline budget
        applies synchronously (the serial commit path never waits for a
        scheduler hop); a deeper backlog queues to the drain task so the
        NEXT consensus round progresses while the state machine catches
        up. Returns slots applied inline."""
        if not self._apply_dirty:
            return 0
        dirty = self._apply_dirty
        self._apply_dirty = set()
        return self._apply_plane.apply_ready(dirty)

    def _apply_shard_ready(self, s: int, budget: int) -> tuple[int, bool]:
        """Apply up to ``budget`` ready slots of shard ``s`` in slot
        order (engine.rs:684-746). Returns (applied, more_ready) —
        ``more_ready`` means the next slot is decided and applicable
        right now (the apply plane keeps draining it)."""
        applied = 0
        sh = self.rt.shards[s]
        while True:
            if applied >= budget:
                return applied, True
            slot = sh.applied_upto
            wal_batch = None  # set iff this slot actually applies a batch
            rec = sh.decisions.get(slot)
            if rec is None or rec.applied:
                if rec is None:
                    break
                sh.applied_upto += 1
                continue
            if rec.value == StateValue.V1:
                batch = (
                    sh.payloads.get(rec.batch_id)
                    if rec.batch_id is not None
                    else None
                )
                if rec.batch_id is not None and rec.batch_id in sh.applied_ids:
                    # duplicate commit (same batch decided in an earlier
                    # slot): never apply twice; just settle the future
                    logger.debug(
                        "row %d shard %d slot %d: dedup-skip batch %s",
                        self.me, s, slot, rec.batch_id,
                    )
                    for i, sub in enumerate(list(sh.queue)):
                        if sub.batch.id == rec.batch_id:
                            del sh.queue[i]
                            self._settle_from_ledger(sh, sub)
                            break
                elif batch is None:
                    # decided V1 but never saw the payload: snapshot sync
                    # is the recovery path (engine.rs:748-844, §3.3)
                    self._spawn(self._initiate_sync())
                    break
                else:
                    try:
                        with span("sm.apply"):
                            responses = self.sm.apply_batch(batch)
                    except Exception as e:
                        # a committed batch the state machine rejects
                        # (undecodable command, app-level panic) fails
                        # DETERMINISTICALLY on every replica: consume
                        # the slot, fail the submitter — never let one
                        # bad command kill the consensus loop
                        logger.warning(
                            "apply failed for batch %s on shard %d: %s",
                            rec.batch_id,
                            s,
                            e,
                        )
                        responses = None
                    sh.applied_ids[rec.batch_id] = None
                    sh.applied_results[rec.batch_id] = responses
                    # demoted/forwarded coalesced entry: per-client alias
                    # ids keep their scalar-lane exactly-once bookkeeping
                    self.register_applied_aliases(
                        s, slot,
                        self._batch_aliases(sh, rec.batch_id, batch),
                        responses, have_responses=True,
                    )
                    wal_batch = batch
                    self.rt.state_version += 1
                    self.rt.v1_applied[s] += 1
                    if responses is not None:
                        self._resolve_local(sh, batch, responses)
                    else:
                        self._fail_local(sh, batch.id, RabiaError("apply failed"))
            else:
                self._requeue_null_slot(sh, slot, rec)
            rec.applied = True
            if self._wal is not None:
                # durability plane: stage the decided wave exactly as
                # applied (ops for a V1 apply; V0 / dedup-skip slots
                # stage payload-less frontier records)
                self._wal_stage(s, slot, int(rec.value), batch=wal_batch)
            self.flight.record(
                FRE_APPLY, shard=s, slot=slot, arg=int(rec.value),
                batch=(
                    fr_hash(rec.batch_id)
                    if rec.batch_id is not None
                    else 0
                ),
            )
            dt_da = time.time() - rec.decided_at
            self._h_stage["decide_apply"].observe(dt_da)
            self._h_slo["decide_apply"].observe(dt_da)
            sh.applied_upto += 1
            sh.gc_upto(sh.applied_upto)
            applied += 1
        return applied, False

    @staticmethod
    def _batch_aliases(sh, bid, batch) -> tuple:
        """Coalescing-lane aliases of an applied scalar batch: from the
        applied payload object itself, or — when the binding adopted a
        WIRE copy (a forwarded/demoted coalesced entry; the codec never
        carries local-only attrs) — from the shard's ``alias_subs``
        stash written at demote time. O(1): ordinary batches carry no
        aliases and the stash is empty outside the coalescing lane."""
        al = getattr(batch, "aliases", ())
        if al:
            if sh.alias_subs and bid is not None:
                sh.alias_subs.pop(bid, None)  # local copy won the bind
            return al
        if bid is None or not sh.alias_subs:
            return ()
        return sh.alias_subs.pop(bid, ())

    def register_applied_aliases(
        self, s: int, slot: int, aliases, responses=None,
        have_responses: bool = False, stage: bool = True,
    ) -> None:
        """Coalescing-lane exactly-once bookkeeping (docs/PERFORMANCE.md
        "Coalescing tier"): a multi-client entry commits ON THE WIRE
        under its lead client's deterministic ``(client_id, seq)``-derived
        id, and EVERY covered client's id (lead included) arrives here as
        an alias ``(bid_bytes16, op_lo, op_hi)`` with op indices relative
        to the entry. Each alias enters the PROPOSER-LOCAL
        ``alias_ledger`` (NOT ``applied_ids``: aliases never ride the
        wire, so only this replica would hold them — and the apply path
        dedup-skips on ``applied_ids`` membership, so an asymmetric
        entry would make THIS replica skip a re-proposed duplicate its
        peers apply, diverging replica state permanently; see the
        ``ShardRuntime.alias_ledger`` comment) with the client's slice
        of the entry's responses in ``applied_results``, and stages a
        K_LEDGER record on durable clusters — so a replayed Submit after
        session-state loss dedups at this gateway's pre-drive check
        (and settles from the ledger, with ONLY that client's responses)
        exactly like a scalar-lane commit, regardless of which lane the
        original rode. ``responses`` is the ENTRY's full response list
        (or None for a deterministic apply failure) when
        ``have_responses``; absent responses leave ``applied_results``
        untouched — and so does an id that already HAS a recorded
        result: the scalar lane writes the FULL entry response list
        under the entry's (== lead's) id before this runs, and
        ``_settle_from_ledger``/entry-level peer repair depend on that
        full list staying intact (the lead's replay path truncates to
        its own op count instead; its ops are the entry's prefix by
        construction). A replay whose responses were never recorded
        gets the honest terminal "committed but responses unavailable"
        after peer repair — per-client slices are NOT recoverable
        post-crash (K_LEDGER records carry ids, not op ranges).
        ``stage=False`` skips the K_LEDGER staging: used by the
        sync-overtake settle sites, where the covered slot has no local
        WAVE record to pair with (the live ``alias_ledger`` entry is
        the point there; crash durability of adopt-overtaken aliases is
        best-effort by design)."""
        if not aliases:
            return
        sh = self.rt.shards[s]
        wal = self._wal if stage else None
        for bid_bytes, lo, hi in aliases:
            bid_bytes = bytes(bid_bytes)
            bid = BatchId(uuid.UUID(bytes=bid_bytes))
            # the value is the client's op COUNT: the ledger-replay
            # serve path truncates a full-entry response list to the
            # RECORDED count, never trusting the replayed Submit's
            # arity (None after crash recovery — K_LEDGER has no ranges)
            sh.alias_ledger[bid] = int(hi) - int(lo)
            if have_responses and bid not in sh.applied_results:
                sh.applied_results[bid] = (
                    None if responses is None
                    else list(responses[int(lo):int(hi)])
                )
            if wal is not None:
                try:
                    wal.stage_ledger(s, slot, bid_bytes)
                except Exception:
                    logger.exception("alias ledger stage failed")
                    wal = None  # one failure wedges the log; stop here

    def _settle_from_ledger(self, sh, sub) -> None:
        """Settle a submitter future for a batch the ledger says is applied.

        Responses are None when the apply happened under a snapshot sync on
        another node — the commit is real but the per-command responses
        never existed here, so the future must FAIL with a distinct error
        rather than resolve with an empty list (callers index responses
        per command)."""
        sh.alias_subs.pop(sub.batch.id, None)  # demote stash: settled
        if sub.future is None or sub.future.done():
            return
        responses = sh.applied_results.get(sub.batch.id)
        if responses is None:
            from rabia_tpu.core.errors import ResponsesUnavailableError

            self.journal.record(
                self.journal.SYNC_OVERTAKE,
                shard=int(sh.shard),
                batch=str(sub.batch.id.value),
            )
            sub.future.set_exception(
                ResponsesUnavailableError(
                    "batch committed but responses unavailable (applied "
                    "via snapshot sync, or the state machine rejected it)"
                )
            )
        else:
            sub.future.set_result(responses)

    def _resolve_local(self, sh, batch: CommandBatch, responses: list[bytes]) -> None:
        """Resolve the submitter future if this batch was queued locally."""
        for i, sub in enumerate(list(sh.queue)):
            if sub.batch.id == batch.id:
                self._h_stage["submit_apply"].observe(
                    time.time() - sub.submitted_at
                )
                if sub.future is not None and not sub.future.done():
                    sub.future.set_result(responses)
                del sh.queue[i]
                break

    def _fail_local(self, sh, batch_id, err: Exception) -> None:
        for i, sub in enumerate(list(sh.queue)):
            if sub.batch.id == batch_id:
                if sub.future is not None and not sub.future.done():
                    sub.future.set_exception(err)
                del sh.queue[i]
                break

    def _requeue_null_slot(self, sh, slot: int, rec: SlotRecord) -> None:
        """A V0 (null) decision: the proposed batch (if it was ours) retries
        in a later slot, up to _MAX_SUBMIT_ATTEMPTS."""
        if rec.batch_id is None:
            return
        for i, sub in enumerate(list(sh.queue)):
            if sub.batch.id == rec.batch_id:
                sub.attempts += 1
                if sub.attempts >= _MAX_SUBMIT_ATTEMPTS:
                    if sub.future is not None and not sub.future.done():
                        sub.future.set_exception(
                            RabiaError(f"batch rejected after {sub.attempts} attempts")
                        )
                    del sh.queue[i]
                else:
                    sub.forwarded_at = 0.0
                    sub.first_forwarded_at = 0.0
                break

    # -- timeouts ------------------------------------------------------------

    def _check_timeouts(self) -> None:
        """Retransmit current votes (and proposal) for stalled shards —
        liveness under message loss (host policy per SURVEY.md §7.4.1)."""
        n = self.n_shards
        rt = self.rt
        now = time.time()
        timeout = self.config.phase_timeout
        if self._stall_scan_args is not None:
            # C pre-scan: a healthy tick exits on one int
            if not self._hk_lib.rk_stall_scan(
                *self._stall_scan_args, now, timeout
            ):
                return
        stalled = rt.in_flight[:n] & (now - rt.last_progress[:n] >= timeout)
        if not stalled.any():
            return
        idxs = np.nonzero(stalled)[0]
        r1_mask = np.asarray(self._my_r1)[idxs] != ABSENT
        r2_mask = (np.asarray(self._stage)[idxs] == R2_WAIT) & (
            np.asarray(self._my_r2)[idxs] != ABSENT
        )
        slots = np.asarray(self._cur_slot)[idxs].astype(np.int64)
        phases = (slots << 16) | np.asarray(self._cur_phase)[idxs].astype(np.int64)
        if r1_mask.any():
            # retransmits go through the Python send path on BOTH tick
            # paths — record them unconditionally (the C ring only sees
            # frames rk_tick itself emits)
            self.flight.record(
                FRE_FRAME_OUT, shard=int(idxs[r1_mask][0]),
                slot=int(slots[r1_mask][0]),
                arg=int(MessageType.VoteRound1),
            )
            self._send(
                VoteRound1(
                    shards=idxs[r1_mask],
                    phases=phases[r1_mask],
                    vals=np.asarray(self._my_r1)[idxs[r1_mask]],
                )
            )
        if r2_mask.any():
            self.flight.record(
                FRE_FRAME_OUT, shard=int(idxs[r2_mask][0]),
                slot=int(slots[r2_mask][0]),
                arg=int(MessageType.VoteRound2),
            )
            self._send(
                VoteRound2(
                    shards=idxs[r2_mask],
                    phases=phases[r2_mask],
                    vals=np.asarray(self._my_r2)[idxs[r2_mask]],
                )
            )
        for i, s in enumerate(idxs):
            s = int(s)
            sh = rt.shards[s]
            slot = int(slots[i])
            bp = sh.buf_propose.get(slot)
            if bp is not None and slot_proposer(s, slot, self.R) == self.me:
                self._send(
                    Propose(
                        shard=s,
                        phase=pack_phase(slot, 0),
                        batch_id=bp[0],
                        value=StateValue.V1,
                        batch=bp[1],
                    )
                )
        # stalled block-bound shards we proposed: rebroadcast the block
        # (rate-limited per block) so peers that lost the ProposeBlock can
        # bind and vote V1
        stalled_refs = np.unique(self._cur_blk_ref[idxs])
        for ref in stalled_refs:
            ref = int(ref)
            if ref == -1:
                continue
            rec = self._blk_registry.get(ref)
            if rec is None or rec.out is None:
                continue
            if now - self._last_blk_retransmit.get(ref, 0.0) < timeout:
                continue
            self._last_blk_retransmit[ref] = now
            # retransmit only the slot-assigned entries: demoted shards
            # keep slot -1, which receivers' validators rightly reject
            assigned = rec.block.slots >= 0
            if assigned.all():
                self._send(ProposeBlock(block=rec.block))
            elif assigned.any():
                self._send(
                    ProposeBlock(block=rec.block.subset(np.nonzero(assigned)[0]))
                )
        rt.last_progress[idxs] = now

    # -- sync protocol (engine.rs:748-844) -----------------------------------

    async def _initiate_sync(self) -> None:
        # retry window: a lost SyncRequest/Response must not gate recovery
        # on the full sync_timeout — lossy networks are exactly when sync
        # is needed most
        retry_after = min(self.config.sync_timeout, 4 * self.config.phase_timeout)
        if self.rt.sync_started_at is not None and (
            time.time() - self.rt.sync_started_at < retry_after
        ):
            return
        self.rt.sync_started_at = time.time()
        self.rt.sync_responses.clear()
        self._syncs += 1
        total_applied = int(self.rt.applied_upto.sum())
        self._send(
            SyncRequest(
                current_phase=total_applied, state_version=self.rt.state_version
            )
        )

    def _on_sync_request(self, sender: NodeId, p: SyncRequest) -> None:
        if self._rtm is not None:
            # quiesce the runtime thread: the snapshot and the per-shard
            # frontiers must be a consistent cut of the native plane. If
            # the pause times out, serving a torn cut is worse than
            # staying silent — the requester simply retries.
            with self._rtm.paused() as pz:
                if pz.ok:
                    return self._serve_sync(sender, p)
            return None
        return self._serve_sync(sender, p)

    def _serve_sync(self, sender: NodeId, p: SyncRequest) -> None:
        # settle any deferred apply backlog first: the snapshot (and the
        # ahead/behind comparison below) must reflect the decided
        # ledger, not the drain task's progress — a lagging peer's
        # recovery must not wait on our apply pipelining
        self._apply_plane.flush_sync()
        total_applied = int(self.rt.applied_upto.sum())
        if total_applied <= p.current_phase:
            return  # not ahead; stay silent (engine.rs:763-779)
        snap = self.sm.create_snapshot()
        snap_bytes = snap.to_bytes()
        # ship the FULL in-memory dedup horizon (64x max_pending per shard)
        # whenever it fits the transport frame: a synced replica with a
        # truncated ledger double-applies any batch whose late duplicate
        # commit lands beyond the shipped horizon. The id budget is what
        # remains of the frame after the snapshot and the per-shard u64
        # sections (plus header slack) — a response that overflows the
        # frame cap is dropped by the transport and sync never completes.
        budget = self.config.tcp.buffers.max_frame_size - len(snap_bytes)
        budget -= 2 * 8 * self.S + 65536  # per-shard u64 sections + slack
        id_cap = min(
            64 * self.config.max_pending_batches,
            max(0, budget) // (24 * max(1, self.n_shards)),
        )
        applied_ids = (
            tuple(
                (s, bid)
                for s, sh in enumerate(self.rt.shards[: self.n_shards])
                for bid in list(sh.applied_ids)[-id_cap:]
            )
            if id_cap > 0  # [-0:] would ship the ENTIRE horizon
            else ()
        )
        self._send(
            SyncResponse(
                responder_phase=total_applied,
                state_version=self.rt.state_version,
                snapshot=snap_bytes,
                per_shard_phase=tuple(self.rt.applied_upto.tolist()),
                applied_ids=applied_ids,
                per_shard_version=tuple(self.rt.v1_applied.tolist()),
            ),
            recipient=sender,
        )

    def _on_sync_response(self, sender: NodeId, p: SyncResponse) -> None:
        self.rt.sync_responses[sender] = (
            p.responder_phase,
            p.state_version,
            p.snapshot,
            p.per_shard_phase,
            p.applied_ids,
            p.per_shard_version,
        )
        # only strictly-ahead peers respond at all, so any usable response
        # resolves immediately — waiting for a quorum of responders can
        # stall forever when just one peer is ahead
        total_applied = int(self.rt.applied_upto.sum())
        if p.responder_phase > total_applied or (
            len(self.rt.sync_responses) + 1 >= self.cluster.quorum_size
        ):
            self._resolve_sync()

    def _resolve_sync(self) -> None:
        if self._rtm is not None:
            # adoption mutates the consensus columns and the store plane:
            # the runtime thread must be parked for the duration, and the
            # bridge's apply mirror re-anchors afterwards. A timed-out
            # pause means the thread is still the single writer — adopt
            # nothing (the sync retry window re-requests) rather than
            # race it.
            with self._rtm.paused() as pz:
                if not pz.ok:
                    return
                self._adopt_sync()
                self._rtm._applied = np.maximum(
                    self._rtm._applied,
                    self.rt.applied_upto[: self.n_shards],
                )
                self._rtm._cmd_slot[:] = -1
            return
        return self._adopt_sync()

    def _adopt_sync(self) -> None:
        """Adopt the most advanced responder's snapshot (engine.rs:806-844).

        Adoption is PER SHARD: state and counters are taken only for
        shards where the responder is ahead. Restoring the whole snapshot
        while we are ahead on some shards would regress those shards'
        state beneath our unchanged counters — a state/counter divergence
        that then poisons every snapshot we later serve. State machines
        expose ``restore_shards`` for this; a monolithic SM (no per-shard
        restore) only adopts from a responder that is ahead-or-equal on
        EVERY shard (a superset view — always true for single-shard
        configs), otherwise it waits for per-shard repair/decisions or a
        superset responder.
        """
        if not self.rt.sync_responses:
            return
        best = max(self.rt.sync_responses.values(), key=lambda r: r[0])
        total_applied = int(self.rt.applied_upto.sum())
        self.rt.sync_started_at = None
        if best[0] <= total_applied or best[2] is None:
            return
        from rabia_tpu.core.state_machine import Snapshot

        snap = Snapshot.from_bytes(best[2])
        resp_applied = np.asarray(best[3][: self.S], np.int64)
        ours = self.rt.applied_upto[: len(resp_applied)]
        ahead = np.nonzero(resp_applied > ours)[0]
        if len(ahead) == 0:
            return
        restore_shards = getattr(self.sm, "restore_shards", None)
        if restore_shards is not None:
            restore_shards(snap, ahead.tolist())
        else:
            if bool((resp_applied < ours).any()):
                logger.warning(
                    "%s sync: responder not a superset and state machine "
                    "has no per-shard restore — waiting for repair/decisions",
                    self.node_id.short(),
                )
                return
            self.sm.restore_snapshot(snap)
        # advance the version by the responder's V1-APPLY surplus on the
        # adopted shards only — adopting the responder's GLOBAL version
        # under mixed per-shard progress would over-advertise local state,
        # and counting adopted SLOTS would count null (V0) slots that no
        # other increment site counts, drifting versions apart
        resp_v1 = np.asarray(best[5][: self.S], np.int64)
        if len(resp_v1) == len(self.rt.v1_applied):
            surplus = resp_v1[ahead] - self.rt.v1_applied[ahead]
            self.rt.state_version += int(np.maximum(surplus, 0).sum())
            self.rt.v1_applied[ahead] = np.maximum(
                resp_v1[ahead], self.rt.v1_applied[ahead]
            )
        else:  # responder on an incompatible shard layout: slot-count bound
            self.rt.state_version += int((resp_applied[ahead] - ours[ahead]).sum())
        logger.debug(
            "row %d sync adopt: shards %s ours %s -> resp %s",
            self.me, ahead.tolist(),
            ours[ahead].tolist(), resp_applied[ahead].tolist(),
        )
        for s in ahead.tolist():
            s = int(s)
            applied = int(resp_applied[s])
            sh = self.rt.shards[s]
            if applied > sh.applied_upto:
                # mark skipped slots as applied-elsewhere
                for slot in range(sh.applied_upto, applied):
                    sh.decisions.setdefault(
                        slot, SlotRecord(value=StateValue.V0)
                    ).applied = True
                sh.applied_upto = applied
                sh.next_slot = max(sh.next_slot, applied)
                sh.in_flight = False
                # overtaken block bindings are void (the registry ages out)
                if self._cur_blk_ref[s] != -1:
                    rec = self._blk_registry.get(int(self._cur_blk_ref[s]))
                    if rec is not None and rec.out is not None:
                        rec.out.settle(
                            int(self._cur_blk_idx[s]),
                            ResponsesUnavailableError("block shard overtaken by sync"),
                        )
                    if rec is not None:
                        # voided binding: the wave committed inside the
                        # adopted snapshot — its proposer-local aliases
                        # would be lost with it; keep the ids so covered
                        # clients' replays dedup instead of re-applying
                        self.register_applied_aliases(
                            s, max(0, applied - 1),
                            rec.block.alias_ids_for(
                                int(self._cur_blk_idx[s])
                            ),
                            stage=False,
                        )
                    self._cur_blk_ref[s] = -1
                if self._blk_pending_slot[s] != -1 and self._blk_pending_slot[s] < applied:
                    self._void_pending_block(s)
                self._apply_dirty.add(s)
                sh.gc_upto(applied)
        # inherit the responder's dedup ledger: batches already applied via
        # the snapshot must never re-apply here if they commit again later.
        # (applied_results stays empty for them: "responses unavailable" in
        # _settle_from_ledger.)
        for s, bid in best[4]:
            if 0 <= s < self.n_shards:
                self.rt.shards[s].applied_ids.setdefault(bid, None)
        self.rt.sync_responses.clear()
        self._frontier_dirty = True
        if self._wal is not None:
            # the adopted slots never staged WAL records here: until a
            # checkpoint captures the adopted state, a crash would
            # recover a pre-adoption chain with a slot gap (replay stops
            # at the gap and re-syncs — correct but slow). Pull the next
            # checkpoint forward.
            self._dirty = True
            self._wal.request_checkpoint()
        logger.info("%s sync: jumped to %d applied", self.node_id.short(), best[0])

    # -- periodic chores -----------------------------------------------------

    async def _periodic(self) -> None:
        now = time.time()
        if now - self._last_heartbeat >= self.config.heartbeat_interval:
            self._last_heartbeat = now
            total_applied = int(self.rt.applied_upto.sum())
            self._send(
                HeartBeat(
                    current_phase=int(self.rt.next_slot.max(initial=0)),
                    committed_phase=total_applied,
                )
            )
            # lag detection: a peer ahead while we make NO local progress
            # triggers a snapshot sync — a straggler that missed Decisions
            # (loss, healed partition) has no other path back
            # (engine.rs:889-907 analog). The local-idle condition prevents
            # snapshot storms under healthy multi-shard load, where
            # aggregate committed counts skew by a few slots at any instant.
            if self._peer_progress:
                best_peer = max(v[0] for v in self._peer_progress.values())
                # "idle" = no APPLY and no consensus TRANSITION (cast /
                # advance / retransmit refresh last_progress): an engine
                # mid-decision on a slow tick path (e.g. the fenced jax
                # backend compiling its first dispatch) must not be
                # declared a straggler and sync-overtaken — that settles
                # its own submitters' futures as responses-unavailable.
                # A genuinely wedged in-flight shard still recovers: its
                # retransmits draw the targeted stale-vote repair, and
                # the severe-lag branch below syncs regardless.
                last_activity = max(
                    self.rt.last_apply_time,
                    float(
                        self.rt.last_progress[: self.n_shards].max(
                            initial=0.0
                        )
                    ),
                )
                locally_idle = (
                    time.time() - last_activity
                    > 2 * self.config.phase_timeout
                )
                # mild lag only matters when we're stuck (aggregate counts
                # skew by a few slots under healthy multi-shard load);
                # severe lag — sync_lag_slots scaled by the shard count —
                # warrants a sync even while some shards still progress
                mild = best_peer > total_applied and locally_idle
                severe = best_peer >= total_applied + (
                    self.config.sync_lag_slots * max(4, self.n_shards)
                )
                if mild or severe:
                    await self._initiate_sync()
            if self._tainted_blocked():
                # tainted slots can only resolve via peer Decisions or
                # snapshot sync — keep asking (self-rate-limited by the
                # retry window; heartbeat cadence is ample for a path that
                # waits on the taint-release window anyway, and the scan
                # is per-tick numpy otherwise)
                await self._initiate_sync()
        if now - self._last_monitor >= max(self.config.heartbeat_interval, 0.2):
            self._last_monitor = now
            tc = getattr(self.transport, "transport_counters", None)
            if callable(tc):
                # redial churn: steady-state has ~zero dials; a burst
                # inside one monitor window means peers are flapping
                dials = tc().get("dials", 0)
                delta = dials - self._last_dials
                self._last_dials = dials
                if delta >= 8:
                    self.journal.record(
                        self.journal.REDIAL_CHURN, dials=int(delta)
                    )
            connected = await self.transport.get_connected_nodes()
            # refresh membership BEFORE the monitor fires its handlers:
            # QuorumNotification broadcasts read rt.active_nodes and must
            # describe the NEW view, not the stale one
            await self.update_nodes(connected | {self.node_id})
            await self.monitor.observe(connected)
        if now - self._last_cleanup >= self.config.cleanup_interval:
            self._last_cleanup = now
            self._gc()
            # block registry GC: entries whose shards all resolved through
            # other paths (sync overtake, V0 without binding) never hit
            # remaining==0 — age them out
            horizon = max(60.0, 4 * self.config.sync_timeout)
            # never evict a block an in-flight or pending binding still
            # references — dropping one would skip its apply on decide
            live_refs = set(
                np.unique(
                    np.concatenate(
                        [self._cur_blk_ref, self._blk_pending_ref]
                    )
                ).tolist()
            )
            for ref in [
                r
                for r, rec in self._blk_registry.items()
                if now - rec.registered_at > horizon and r not in live_refs
            ]:
                self._blk_registry.pop(ref)
                self._last_blk_retransmit.pop(ref, None)
        if self._dirty:
            # durability plane: decided waves are ALREADY durable in the
            # log — checkpoints only bound recovery time and enable GC,
            # so they run on the WalPersistence pacing (bytes appended /
            # elapsed time), not once per dirty tick like the blob path
            if self._wal is None or self._wal.checkpoint_due():
                self._dirty = False
                await self._save_state()

    def _gc(self) -> None:
        """Bound memory: drop old buffers + seen-batch ids (state.rs:191-243)."""
        for sh in self.rt.shards[: self.n_shards]:
            sh.gc_upto(sh.applied_upto)
            if len(sh.decisions) > self.config.max_phase_history:
                cut = sh.applied_upto - self.config.max_phase_history
                for k in [k for k in sh.decisions if k < cut]:
                    del sh.decisions[k]
            # drop payloads nothing references anymore (e.g. batches whose
            # slots kept deciding V0 and were abandoned) — without this a
            # long-running replica leaks every rejected batch's bytes
            live = {sub.batch.id for sub in sh.queue}
            live.update(bid for bid, _ in sh.buf_propose.values())
            live.update(
                rec.batch_id
                for slot, rec in sh.decisions.items()
                if rec.batch_id is not None and not rec.applied
            )
            for bid in [b for b in sh.payloads if b not in live]:
                del sh.payloads[bid]
            if len(sh.applied_results) > 2 * self.config.max_pending_batches:
                # response CACHE only — evicting here can no longer
                # re-enable a duplicate apply (dedup lives in applied_ids)
                for bid in list(sh.applied_results)[
                    : len(sh.applied_results) - self.config.max_pending_batches
                ]:
                    del sh.applied_results[bid]
            if len(sh.alias_subs) > self.config.max_pending_batches:
                # demote-stash safety cap (normally popped at apply or
                # ledger settle; a wedged demoted entry must not pin it)
                for bid in list(sh.alias_subs)[
                    : len(sh.alias_subs) - self.config.max_pending_batches
                ]:
                    del sh.alias_subs[bid]
            # the dedup ledger is id-only (16B entries): keep a far deeper
            # horizon, evicted FIFO only to bound truly long runs
            id_cap = 64 * self.config.max_pending_batches
            if len(sh.applied_ids) > id_cap:
                for bid in list(sh.applied_ids)[: len(sh.applied_ids) - id_cap]:
                    del sh.applied_ids[bid]
            if len(sh.alias_ledger) > id_cap:
                # same id-only horizon for the coalescing lane's
                # proposer-local per-client dedup ids
                for bid in list(sh.alias_ledger)[
                    : len(sh.alias_ledger) - id_cap
                ]:
                    del sh.alias_ledger[bid]
        # evict oldest seen-batch ids, never the whole dedup set at once
        cap = 10 * self.config.max_pending_batches
        while len(self._seen_order) > cap:
            self._seen_batches.discard(self._seen_order.pop(0))

    async def _save_state(self) -> None:
        if self.persistence is None:
            return
        if self._wal is not None:
            # durability plane: incremental checkpoint (statekernel delta
            # frames when the native plane exists, a full snapshot blob
            # otherwise) + frontier record + WAL-prefix GC. Decided waves
            # are already durable in the log — the checkpoint only bounds
            # recovery time and enables GC, so it runs on the
            # WalPersistence pacing, not per dirty tick.
            def _meta() -> dict:
                n = self.n_shards
                return {
                    "next_slot": self.rt.next_slot[:n].tolist(),
                    "applied_upto": self.rt.applied_upto[:n].tolist(),
                    "state_version": int(self.rt.state_version),
                    "v1_applied": self.rt.v1_applied[:n].tolist(),
                    "sm_version": int(getattr(self.sm, "_version", 0)),
                }

            # the runtime thread owns the statekernel while running: the
            # capture (meta read + delta export + mark + frontier read)
            # happens atomically under pause; file write + GC run unpaused
            if self._rtm is not None:
                with self._rtm.paused():
                    cap = self._wal.capture_checkpoint(_meta(), self.sm)
            else:
                cap = self._wal.capture_checkpoint(_meta(), self.sm)
            try:
                await self._wal.commit_checkpoint(cap)
            except PersistenceError:
                logger.exception("wal checkpoint commit failed")
                self.journal.record(self.journal.WAL_WEDGED, stage="ckpt")
                return
            self.flight.record(
                FRE_WAL, shard=0, slot=self._wal.checkpoints, arg=2,
            )
            return
        snap = self.sm.create_snapshot()
        state = PersistedEngineState(
            current_phase=int(self.rt.next_slot.max(initial=0)),
            last_committed_phase=int(self.rt.applied_upto.sum()),
            state_version=self.rt.state_version,
            snapshot=snap,
            per_shard_phase=self.rt.next_slot.tolist(),
            per_shard_committed=self.rt.applied_upto.tolist(),
            per_shard_version=self.rt.v1_applied.tolist(),
        )
        await self.persistence.save_engine_state(state)

    # -- outbound ------------------------------------------------------------

    def _spawn(self, coro) -> None:
        """Fire-and-forget with a strong reference (the event loop only
        holds tasks weakly; unreferenced tasks can be GC'd before running)."""
        task = asyncio.ensure_future(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    def _send(self, payload, recipient: Optional[NodeId] = None) -> None:
        msg = ProtocolMessage.new(self.node_id, payload, recipient)
        try:
            data = self.serializer.serialize(msg)
        except Exception:
            # a codec failure on one outbound message must never kill the
            # run loop — peers recover the dropped message via the normal
            # retransmit/repair/sync paths
            logger.exception(
                "dropping unserializable %s to %s",
                type(payload).__name__,
                recipient or "broadcast",
            )
            return
        try:
            if recipient is None:
                if self._rtm is None:
                    # the asyncio loop owns the commit path: its
                    # broadcast staging IS the SLO broadcast stage.
                    # While the native runtime owns it, the RTH block
                    # is the sole source — counting control-plane
                    # broadcasts (heartbeats, sync) here would
                    # mis-attribute them to the consensus stage.
                    t_bc = time.perf_counter_ns()
                    staged = self.transport.broadcast_nowait(data)
                    dt_bc = time.perf_counter_ns() - t_bc
                    self._h_slo["broadcast"].observe(dt_bc * 1e-9)
                    self._stg_bcast(dt_bc)
                else:
                    staged = self.transport.broadcast_nowait(data)
                if staged:
                    return
                self._spawn(self.transport.broadcast(data))
            else:
                if self.transport.send_to_nowait(recipient, data):
                    return
                self._spawn(self.transport.send_to(recipient, data))
        except Exception:
            # same containment as the codec guard above: one bad send
            # must not kill the run loop (peers recover via retransmit)
            logger.exception(
                "dropping failed send of %s to %s",
                type(payload).__name__,
                recipient or "broadcast",
            )
