"""RabiaEngine: the host event loop around the vectorized consensus kernel.

Reference parity: rabia-engine/src/engine.rs — the engine drives
propose → vote-R1 → vote-R2 → decide → apply (:184-236 run loop, :288-347
propose path, :381-746 message handlers, :684-706 apply, :748-844 sync,
:846-907 heartbeat/sync initiation, :923-947 receive loop). The consensus
*math* of those handlers (vote rules, tallies, coin, decision) lives in
:class:`rabia_tpu.kernel.phase_driver.NodeKernel` and runs for all S shards
in one jitted call per round; this module is everything around it: message
routing, slot lifecycle, batch payloads, state-machine application,
persistence, heartbeats, sync and stats.

Protocol notes (deliberate divergences from the reference implementation,
both fixing documented deviations — SURVEY.md §3.1):

1. Round-1 AND round-2 votes are **broadcast** to all replicas (the spec's
   reliable-broadcast model, docs/weak_mvc.ivy:133-186), not unicast to the
   proposer.
2. The round-2 tie-break is a **common coin** shared by construction
   (same seed + (shard, slot, phase) on every replica), not per-node RNG.

Slot model: each shard carries an ordered log of decision slots. The
proposer of (shard, slot) rotates deterministically
(:func:`rabia_tpu.engine.leader.slot_proposer`); non-proposers forward
their submissions to the upcoming proposer (NewBatch). A crashed proposer's
slot times out on peers, who open it with vote V0 — weak MVC then decides
V0 (a null slot) and the rotation moves on: leaderless liveness without
elections.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Optional, Sequence

import numpy as np

from rabia_tpu.core.config import RabiaConfig
from rabia_tpu.core.errors import QuorumNotAvailableError, RabiaError, ValidationError
from rabia_tpu.core.messages import (
    Decision,
    DecisionEntry,
    HeartBeat,
    NewBatch,
    ProtocolMessage,
    Propose,
    SyncRequest,
    SyncResponse,
    VoteEntry,
    VoteRound1,
    VoteRound2,
)
from rabia_tpu.core.network import ClusterConfig, NetworkMonitor, NetworkTransport
from rabia_tpu.core.persistence import PersistedEngineState, PersistenceLayer
from rabia_tpu.core.serialization import Serializer
from rabia_tpu.core.state_machine import StateMachine
from rabia_tpu.core.types import (
    ABSENT,
    V0,
    V1,
    CommandBatch,
    NodeId,
    StateValue,
)
from rabia_tpu.core.validation import MessageValidator
from rabia_tpu.engine.leader import LeaderSelector, slot_proposer
from rabia_tpu.engine.state import (
    EngineRuntime,
    EngineStatistics,
    PendingSubmission,
    SlotRecord,
)
from rabia_tpu.kernel.phase_driver import NodeKernel, R2_WAIT, pack_phase, unpack_phase

logger = logging.getLogger("rabia_tpu.engine")

_MAX_SUBMIT_ATTEMPTS = 3


class RabiaEngine:
    """One replica's consensus engine (engine.rs:25-42 analog).

    Generic over the three core seams: ``state_machine`` (bytes interface),
    ``transport`` and optional ``persistence`` — construct with any
    implementations of those ABCs (the reference's `RabiaEngine<SM, NT, PL>`
    type parameters).
    """

    def __init__(
        self,
        cluster: ClusterConfig,
        state_machine: StateMachine,
        transport: NetworkTransport,
        persistence: Optional[PersistenceLayer] = None,
        config: Optional[RabiaConfig] = None,
    ) -> None:
        self.cluster = cluster
        self.node_id = cluster.node_id
        self.sm = state_machine
        self.transport = transport
        self.persistence = persistence
        self.config = config or RabiaConfig()

        self.R = cluster.total_nodes
        self.me = cluster.replica_index(self.node_id)
        kc = self.config.kernel
        self.S = kc.padded_shards
        self.n_shards = max(1, kc.num_shards)
        # The coin seed must be identical cluster-wide (it IS the common
        # coin); randomization_seed defaults to 0 for all nodes.
        seed = self.config.randomization_seed or 0
        self.kernel = NodeKernel(
            self.S, self.R, self.me, coin_p1=kc.coin_p1, seed=seed
        )
        self.kstate = self.kernel.init_state()
        self.rt = EngineRuntime(self.S)
        self.serializer = Serializer(self.config.serialization)
        self.validator = MessageValidator(self.config.validation)
        self.leader = LeaderSelector(cluster.all_nodes)
        self.monitor = NetworkMonitor(cluster)

        # host mirrors of kernel arrays (refreshed after each node_step)
        self._cur_slot = np.zeros(self.S, np.int64)
        self._cur_phase = np.zeros(self.S, np.int64)
        self._stage = np.zeros(self.S, np.int8)
        self._my_r1 = np.full(self.S, ABSENT, np.int8)
        self._my_r2 = np.full(self.S, ABSENT, np.int8)
        self._done = np.zeros(self.S, bool)
        self._decided = np.full(self.S, ABSENT, np.int8)
        self._active = np.zeros(self.S, bool)

        # write-ahead vote barrier: _barrier[s] is persisted BEFORE this
        # replica's first vote in any slot >= the previous barrier, so a
        # restart knows exactly which slots may hold its pre-crash votes
        self._barrier = np.zeros(self.S, np.int64)
        self._restored_at = 0.0
        self._pending_proposes: list[Propose] = []

        self._row_to_node = {i: n for i, n in enumerate(cluster.all_nodes)}
        self._node_to_row = {n: i for i, n in enumerate(cluster.all_nodes)}
        self._seen_batches: set = set()  # dedup of forwarded batch ids
        self._seen_order: list = []  # insertion order for bounded eviction
        self._bg_tasks: set = set()  # strong refs: loop holds tasks weakly
        self._running = False
        self._stopped = asyncio.Event()
        self._stopped.set()  # not running yet: shutdown() must not hang
        self._dirty = False  # committed something since last save
        self._last_heartbeat = 0.0
        self._last_cleanup = 0.0
        self._last_monitor = 0.0
        self._peer_progress: dict[NodeId, tuple[int, float]] = {}

        if self.n_shards > self.S:
            raise ValidationError("num_shards exceeds padded kernel width")

    # ------------------------------------------------------------------
    # Public API (the reference's EngineCommand surface, state.rs:300-307)
    # ------------------------------------------------------------------

    async def submit_batch(
        self, batch: CommandBatch, shard: Optional[int] = None
    ) -> asyncio.Future:
        """Accept a client batch for consensus on `shard`; returns a future
        resolving to the list of per-command responses once the batch
        commits (engine.rs:288-310 ProcessBatch path). Rejects without a
        quorum (engine.rs:289-297)."""
        if not self.rt.has_quorum:
            raise QuorumNotAvailableError(
                f"no quorum ({len(self.rt.active_nodes)}/{self.cluster.quorum_size})"
            )
        if batch.is_empty():
            raise ValidationError("empty batch")
        if len(batch.commands) > self.config.max_batch_size:
            raise ValidationError("batch exceeds max_batch_size")
        s = int(shard) if shard is not None else int(batch.shard)
        if not (0 <= s < self.n_shards):
            raise ValidationError(f"shard {s} out of range")
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self.rt.shards[s].queue.append(PendingSubmission(batch=batch, future=fut))
        return fut

    async def get_statistics(self) -> EngineStatistics:
        return self.rt.stats(self.node_id)

    async def trigger_sync(self) -> None:
        await self._initiate_sync()

    async def update_nodes(self, nodes: Sequence[NodeId]) -> None:
        """Membership change: recompute quorum + leader (engine.rs:142-153)."""
        self.rt.active_nodes = set(nodes) & set(self.cluster.all_nodes)
        self.rt.has_quorum = self.cluster.has_quorum(
            self.rt.active_nodes | {self.node_id}
        )
        self.leader.update_nodes(self.rt.active_nodes | {self.node_id})

    async def shutdown(self) -> None:
        self._running = False
        await self._stopped.wait()

    # ------------------------------------------------------------------
    # Lifecycle
    # ------------------------------------------------------------------

    async def initialize(self) -> None:
        """Restore persisted state then join the cluster (engine.rs:238-269)."""
        if self.persistence is not None:
            persisted = await self.persistence.load_engine_state()
            if persisted is not None:
                if persisted.snapshot is not None:
                    self.sm.restore_snapshot(persisted.snapshot)
                for s, (opened, applied) in enumerate(
                    zip(persisted.per_shard_phase, persisted.per_shard_committed)
                ):
                    if s < self.S:
                        self.rt.shards[s].next_slot = opened
                        self.rt.shards[s].applied_upto = applied
                self.rt.state_version = persisted.state_version
                logger.info(
                    "%s restored: %d slots applied",
                    self.node_id.short(),
                    sum(sh.applied_upto for sh in self.rt.shards),
                )
        # unconditionally: a replica that voted but crashed before its first
        # checkpoint has no main blob yet the barrier aux blob exists — that
        # early-life window is the most likely crash window
        await self._restore_vote_barrier()
        connected = await self.transport.get_connected_nodes()
        await self.update_nodes(connected | {self.node_id})

    async def _restore_vote_barrier(self) -> None:
        """Taint slots this replica may have voted in before the crash.

        Re-running consensus in such a slot could cast a DIFFERENT vote in
        the same (slot, phase) — equivocation that can violate agreement
        when f other replicas are simultaneously down. Tainted slots rejoin
        only via adopted peer Decisions or snapshot sync; if no vote traffic
        for them is observed within the release window, nobody holds our
        pre-crash votes and the taint lifts (see _open_slots).
        """
        self._restored_at = time.time()
        if self.persistence is None or self.R <= 1:
            return  # single replica: no peer can hold a conflicting view
        raw = await self.persistence.load_aux("vote_barrier")
        if raw is None:
            return
        barrier = np.frombuffer(raw, np.int64)
        for s in range(min(len(barrier), self.n_shards)):
            self._barrier[s] = barrier[s]
            sh = self.rt.shards[s]
            if barrier[s] > sh.applied_upto:
                sh.tainted_upto = int(barrier[s])

    @property
    def _taint_release(self) -> float:
        return 4 * self.config.phase_timeout

    def _tainted_blocked(self) -> bool:
        # applied_upto, not next_slot: a slot decided-but-unapplied before
        # the crash leaves applied_upto under the barrier while next_slot
        # is already past it — recovery still needs the sync
        return any(
            sh.applied_upto < sh.tainted_upto
            for sh in self.rt.shards[: self.n_shards]
        )

    async def run(self) -> None:
        """Main loop (engine.rs:184-236): drain inbound, advance the kernel
        one round, transmit the outbox, apply decisions, periodic chores."""
        self._running = True
        self._stopped.clear()
        await self.initialize()
        try:
            while self._running:
                progressed = await self._tick()
                await self._periodic()
                # pace rounds; yield even when busy (engine.rs:233 analog)
                await asyncio.sleep(
                    0 if progressed else self.config.round_interval
                )
        finally:
            if self._dirty:
                await self._save_state()
            self.rt.is_active = False
            self._stopped.set()

    # ------------------------------------------------------------------
    # The round tick
    # ------------------------------------------------------------------

    async def _tick(self) -> bool:
        got_msgs = await self._drain_messages()
        self._forward_submissions()
        opened = self._open_slots()
        stepped = False
        if opened or got_msgs or self._anything_in_flight():
            await self._kernel_round(opened)
            stepped = True
        applied = self._apply_ready()
        self._check_timeouts()
        if applied and self.persistence is not None:
            self._dirty = True
        return bool(got_msgs or opened or applied) and stepped

    def _anything_in_flight(self) -> bool:
        return any(
            sh.in_flight for sh in self.rt.shards[: self.n_shards]
        )

    # -- inbound ------------------------------------------------------------

    async def _drain_messages(self, cap: int = 256) -> int:
        """Drain up to `cap` inbound messages (engine.rs:923-947)."""
        n = 0
        recv_nowait = getattr(self.transport, "receive_nowait", None)
        while n < cap:
            if recv_nowait is not None:
                item = recv_nowait()
                if item is None:
                    break
            else:
                try:
                    item = await self.transport.receive(timeout=0.0005)
                except RabiaError:
                    break
            sender, data = item
            try:
                msg = self.serializer.deserialize(data)
                self.validator.validate_message(msg)
                self._handle_message(sender, msg)
                n += 1
            except RabiaError as e:
                logger.warning("dropping bad message from %s: %s", sender, e)
        return n

    def _handle_message(self, sender: NodeId, msg: ProtocolMessage) -> None:
        """Route one validated message into host buffers (engine.rs:349-379)."""
        if sender != msg.sender:
            # envelope sender must match the transport-authenticated peer:
            # otherwise one faulty peer could forge votes as every other
            # replica row and fabricate a quorum single-handedly
            logger.warning(
                "dropping spoofed message: envelope %s via transport %s",
                msg.sender,
                sender,
            )
            return
        row = self._node_to_row.get(msg.sender)
        if row is None:
            logger.warning("message from unknown node %s", msg.sender)
            return
        self.rt.active_nodes.add(msg.sender)
        p = msg.payload
        if isinstance(p, Propose):
            self._on_propose(row, p)
        elif isinstance(p, VoteRound1):
            self._buffer_votes(row, p.votes, round_no=1)
        elif isinstance(p, VoteRound2):
            self._buffer_votes(row, p.votes, round_no=2)
        elif isinstance(p, Decision):
            self._on_decision(p)
        elif isinstance(p, NewBatch):
            self._on_new_batch(p)
        elif isinstance(p, SyncRequest):
            self._on_sync_request(msg.sender, p)
        elif isinstance(p, SyncResponse):
            self._on_sync_response(msg.sender, p)
        elif isinstance(p, HeartBeat):
            self._peer_progress[msg.sender] = (p.committed_phase, time.time())

    def _on_propose(self, row: int, p: Propose) -> None:
        if not (0 <= p.shard < self.n_shards):
            return
        sh = self.rt.shards[p.shard]
        slot, _ = unpack_phase(p.phase)
        if slot < sh.applied_upto:
            return  # stale
        if slot_proposer(p.shard, slot, self.R) != row:
            # only the slot's rotation proposer may bind a batch to it;
            # otherwise any replica's (e.g. a confused restarted peer's)
            # Propose could bind divergent batch_ids to the same V1-decided
            # slot across the cluster
            logger.warning(
                "dropping Propose for shard %d slot %d from non-proposer row %d",
                p.shard,
                slot,
                row,
            )
            return
        rec = sh.decisions.get(slot)
        if rec is not None:
            if rec.batch_id is None:
                # slot decided V1 off peers' votes before the Propose got
                # here: repair the binding so apply doesn't need a snapshot
                # sync for a payload that just arrived
                rec.batch_id = p.batch_id
            elif rec.batch_id != p.batch_id:
                return  # slot already decided about a different batch
        # first proposal wins the slot binding; payloads are id-keyed so a
        # conflicting late proposal can't swap the bytes a decision applies
        sh.buf_propose.setdefault(slot, (p.batch_id, p.batch))
        if p.batch is not None:
            sh.payloads[p.batch_id] = p.batch

    def _buffer_votes(
        self, row: int, votes: tuple[VoteEntry, ...], round_no: int
    ) -> None:
        for v in votes:
            if not (0 <= v.shard < self.n_shards):
                continue
            sh = self.rt.shards[v.shard]
            slot, mvc = unpack_phase(v.phase)
            if slot < sh.applied_upto:
                continue
            if slot < sh.tainted_upto:
                sh.taint_traffic = True  # peers are deciding: keep waiting
            buf = sh.buf_r1 if round_no == 1 else sh.buf_r2
            buf.setdefault((slot, mvc), {}).setdefault(row, int(v.vote))

    def _on_decision(self, p: Decision) -> None:
        for d in p.decisions:
            if not (0 <= d.shard < self.n_shards):
                continue
            sh = self.rt.shards[d.shard]
            slot, _ = unpack_phase(d.phase)
            if slot < sh.applied_upto:
                continue
            rec = sh.decisions.get(slot)
            if rec is not None:
                if rec.batch_id is None and d.batch_id is not None:
                    rec.batch_id = d.batch_id  # late binding repair
                continue
            if slot < max(sh.next_slot, sh.applied_upto):
                # gap slot (below the head, e.g. decided-but-lost across a
                # crash): it will never "become current" again, so adopt the
                # peer decision immediately — buffering it would wedge apply
                # at the gap forever
                self._record_decision(s, slot, int(d.decision), d.batch_id)
                if d.batch_id is not None and slot not in sh.buf_propose:
                    sh.buf_propose[slot] = (d.batch_id, None)
                continue
            # buffered only: recorded when the slot becomes current, either
            # via kernel adoption (in flight) or in _open_slots — keeps slot
            # recording contiguous so apply order never skips a slot
            sh.buf_decision[slot] = (int(d.decision), d.batch_id)
            if d.batch_id is not None and slot not in sh.buf_propose:
                sh.buf_propose[slot] = (d.batch_id, None)

    def _on_new_batch(self, p: NewBatch) -> None:
        """A peer forwards a submission for us to propose (see module doc)."""
        if not (0 <= p.shard < self.n_shards):
            return
        if p.batch.id in self._seen_batches:
            return
        self._seen_batches.add(p.batch.id)
        self._seen_order.append(p.batch.id)
        self.rt.shards[p.shard].queue.append(PendingSubmission(batch=p.batch))

    # -- submission forwarding / slot opening --------------------------------

    def _forward_submissions(self) -> None:
        """Send queued batches to the upcoming slot's proposer when that's
        not us. The submission stays queued locally (with its future) so the
        submitter can still answer its client; the proposer's copy drives
        consensus. Re-forwarded on timeout by `_check_timeouts`."""
        now = time.time()
        for s in range(self.n_shards):
            sh = self.rt.shards[s]
            if not sh.queue or sh.in_flight:
                continue
            slot = max(sh.next_slot, sh.applied_upto)
            target_row = slot_proposer(s, slot, self.R)
            if target_row == self.me:
                continue
            sub = sh.queue[0]
            if sub.forwarded_at and now - sub.forwarded_at < self.config.phase_timeout:
                continue
            sub.forwarded_at = now
            if not sub.first_forwarded_at:
                sub.first_forwarded_at = now
            target = self._row_to_node[target_row]
            self._send(
                NewBatch(shard=s, batch=sub.batch), recipient=target
            )

    def _open_slots(self) -> list[tuple[int, int, int]]:
        """Decide which shards open a new decision slot this round.

        Returns [(shard, slot, initial_vote)]. Cases:
          - we are the proposer and have a queued batch → open V1 + Propose;
          - a Propose arrived for the slot → open V1;
          - peers are already voting on the slot (or a timeout expired on a
            forwarded submission) → open V0 after a grace period.
        """
        now = time.time()
        grace = min(max(self.config.phase_timeout / 10.0, 0.02), 1.0)
        opened: list[tuple[int, int, int]] = []
        propose_entries: list[Propose] = []
        alive_set = self.rt.active_nodes | {self.node_id}  # hoisted: hot loop
        for s in range(self.n_shards):
            sh = self.rt.shards[s]
            if sh.in_flight:
                continue
            slot = max(sh.next_slot, sh.applied_upto)
            if slot in sh.decisions:  # decided while we weren't looking
                sh.next_slot = slot + 1
                continue
            bd = sh.buf_decision.get(slot)
            if bd is not None and bd[0] in (V0, V1):
                # a peer already broadcast this slot's decision: adopt it
                # without running consensus locally
                self._record_decision(s, slot, bd[0], bd[1])
                continue
            if slot < sh.tainted_upto:
                # restart-equivocation guard: this replica may have voted in
                # this slot before crashing — never cast fresh votes. The
                # slot resolves via an adopted peer Decision (above), via
                # snapshot sync, or — when no vote traffic for tainted slots
                # has been seen for the whole release window — the taint
                # lifts (nobody out there holds our pre-crash votes).
                if (
                    not sh.taint_traffic
                    and now - self._restored_at > self._taint_release
                ):
                    sh.tainted_upto = 0
                continue
            proposer_row = slot_proposer(s, slot, self.R)
            # never propose a batch that already committed in another slot
            # (duplicate-forwarding race): settle it from the dedup ledger
            while sh.queue and sh.queue[0].batch.id in sh.applied_ids:
                done_sub = sh.queue.popleft()
                self._settle_from_ledger(sh, done_sub)
            if slot in sh.buf_propose:
                # an existing binding wins the slot — never rebind, even as
                # the proposer: re-proposing a different batch for a slot
                # that already carries one could bind divergent batch_ids
                # across replicas (retransmits go through _check_timeouts)
                opened.append((s, slot, V1))
            elif proposer_row == self.me and sh.queue:
                sub = sh.queue[0]
                sh.payloads[sub.batch.id] = sub.batch
                sh.buf_propose[slot] = (sub.batch.id, sub.batch)
                propose_entries.append(
                    Propose(
                        shard=s,
                        phase=pack_phase(slot, 0),
                        batch_id=sub.batch.id,
                        value=StateValue.V1,
                        batch=sub.batch,
                    )
                )
                opened.append((s, slot, V1))
            else:
                votes_seen = any(
                    k[0] == slot for k in sh.buf_r1
                ) or any(k[0] == slot for k in sh.buf_r2)
                if votes_seen:
                    if sh.opened_at == 0.0:
                        sh.opened_at = now  # start the grace clock
                    elif now - sh.opened_at > grace:
                        opened.append((s, slot, V0))
                elif sh.queue and sh.queue[0].first_forwarded_at and (
                    now - sh.queue[0].first_forwarded_at
                    > (
                        self.config.phase_timeout
                        if self._row_to_node[proposer_row] in alive_set
                        # known-dead proposer: short-circuit after one grace
                        # period instead of a transient-heartbeat-gap
                        # instant null slot
                        else max(grace, self.config.phase_timeout / 4)
                    )
                ):
                    # forwarded proposer unresponsive: force a null slot to
                    # rotate the proposer (leaderless liveness).
                    # first_forwarded_at, not forwarded_at — the periodic
                    # re-forward refreshes the latter, which must not reset
                    # the give-up clock.
                    opened.append((s, slot, V0))
        for s, slot, _v in opened:
            sh = self.rt.shards[s]
            sh.in_flight = True
            sh.next_slot = max(sh.next_slot, slot) + 0  # opened, +1 on decide
            sh.opened_at = now
            sh.last_progress = now
        # Proposes are NOT sent here: the vote barrier must be durable
        # before any proposal for a newly opened slot reaches the wire —
        # otherwise a crash-restart could rebind a different batch to a slot
        # some peer already bound. _kernel_round flushes these right after
        # the barrier save.
        self._pending_proposes.extend(propose_entries)
        return opened

    # -- the kernel round ----------------------------------------------------

    async def _kernel_round(self, opened: list[tuple[int, int, int]]) -> None:
        import jax.numpy as jnp

        if opened:
            await self._advance_vote_barrier(opened)
        if self._pending_proposes:
            for pe in self._pending_proposes:
                self._send(pe)
            self._pending_proposes.clear()
        if opened:
            mask = np.zeros(self.S, bool)
            slots = np.zeros(self.S, np.int32)
            init = np.full(self.S, V0, np.int8)
            r1_entries: list[VoteEntry] = []
            for s, slot, v in opened:
                mask[s] = True
                slots[s] = slot
                init[s] = v
                r1_entries.append(
                    VoteEntry(shard=s, phase=pack_phase(slot, 0), vote=StateValue(v))
                )
            self.kstate = self.kernel.start_slots(
                self.kstate, jnp.asarray(mask), jnp.asarray(slots), jnp.asarray(init)
            )
            self._refresh_mirrors()
            self._send(VoteRound1(votes=tuple(r1_entries)))

        inbox1, inbox2, dec_in = self._fill_inboxes()
        self.kstate, outbox = self.kernel.node_step(
            self.kstate,
            jnp.asarray(inbox1),
            jnp.asarray(inbox2),
            jnp.asarray(dec_in),
        )
        prev_phase = self._cur_phase.copy()
        prev_stage = self._stage.copy()
        self._refresh_mirrors()
        self._process_outbox(outbox, prev_phase, prev_stage)

    async def _advance_vote_barrier(
        self, opened: list[tuple[int, int, int]]
    ) -> None:
        """Persist the vote barrier BEFORE the first vote of any newly
        opened slot leaves this replica (write-ahead), so a post-crash
        restore can taint every slot that may hold our votes.

        The barrier is advanced ``barrier_stride`` slots AHEAD of the
        opened slot, so one atomic-write+fsync amortizes over the next K
        opens per shard instead of landing on every consensus round's
        critical path. Cost: a restart may taint up to K-1 never-voted
        slots, which the taint-release window already resolves (restore
        path is deliberately conservative)."""
        if self.persistence is None:
            return
        stride = max(1, self.config.barrier_stride)
        changed = False
        for s, slot, _v in opened:
            if slot >= self._barrier[s]:
                self._barrier[s] = slot + stride
                changed = True
        if changed:
            await self.persistence.save_aux(
                "vote_barrier", self._barrier[: self.n_shards].tobytes()
            )

    def _refresh_mirrors(self) -> None:
        st = self.kstate
        self._cur_slot = np.asarray(st.slot, np.int64)
        self._cur_phase = np.asarray(st.phase, np.int64)
        self._stage = np.asarray(st.stage, np.int8)
        self._my_r1 = np.asarray(st.my_r1, np.int8)
        self._my_r2 = np.asarray(st.my_r2, np.int8)
        self._done = np.asarray(st.done, bool)
        self._decided = np.asarray(st.decided, np.int8)
        self._active = np.asarray(st.active, bool)

    def _fill_inboxes(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Re-offer buffered votes matching each shard's current (slot,
        phase) to the kernel; the device ledger ignores what it already has."""
        inbox1 = np.full((self.S, self.R), ABSENT, np.int8)
        inbox2 = np.full((self.S, self.R), ABSENT, np.int8)
        dec_in = np.full(self.S, ABSENT, np.int8)
        for s in range(self.n_shards):
            sh = self.rt.shards[s]
            if not sh.in_flight:
                continue
            key = (int(self._cur_slot[s]), int(self._cur_phase[s]))
            for row, vote in sh.buf_r1.get(key, {}).items():
                inbox1[s, row] = vote
            for row, vote in sh.buf_r2.get(key, {}).items():
                inbox2[s, row] = vote
            d = sh.buf_decision.get(key[0])
            if d is not None and d[0] in (V0, V1):
                dec_in[s] = d[0]
        return inbox1, inbox2, dec_in

    def _process_outbox(self, outbox, prev_phase: np.ndarray, prev_stage: np.ndarray) -> None:
        """Turn kernel outbox flags into broadcast messages + decisions."""
        cast_r2 = np.asarray(outbox.cast_r2, bool)
        r2_vals = np.asarray(outbox.r2_vals, np.int8)
        advanced = np.asarray(outbox.advanced, bool)
        new_r1 = np.asarray(outbox.new_r1, np.int8)
        new_phase = np.asarray(outbox.new_phase, np.int64)
        newly_dec = np.asarray(outbox.newly_decided, bool)

        r1_entries: list[VoteEntry] = []
        r2_entries: list[VoteEntry] = []
        dec_entries: list[DecisionEntry] = []
        now = time.time()
        for s in range(self.n_shards):
            sh = self.rt.shards[s]
            if not sh.in_flight:
                continue
            slot = int(self._cur_slot[s])
            if cast_r2[s]:
                r2_entries.append(
                    VoteEntry(
                        shard=s,
                        phase=pack_phase(slot, int(prev_phase[s])),
                        vote=StateValue(int(r2_vals[s])),
                    )
                )
                sh.last_progress = now
            if advanced[s] and not newly_dec[s] and not self._done[s]:
                r1_entries.append(
                    VoteEntry(
                        shard=s,
                        phase=pack_phase(slot, int(new_phase[s])),
                        vote=StateValue(int(new_r1[s])),
                    )
                )
                sh.last_progress = now
            if self._done[s]:
                value = int(self._decided[s])
                bid = None
                bp = sh.buf_propose.get(slot)
                if bp is not None:
                    bid = bp[0]
                if newly_dec[s]:
                    dec_entries.append(
                        DecisionEntry(
                            shard=s,
                            phase=pack_phase(slot, 0),
                            decision=StateValue(value),
                            batch_id=bid,
                        )
                    )
                self._record_decision(s, slot, value, bid)
        if r2_entries:
            self._send(VoteRound2(votes=tuple(r2_entries)))
        if r1_entries:
            self._send(VoteRound1(votes=tuple(r1_entries)))
        if dec_entries:
            self._send(Decision(decisions=tuple(dec_entries)))

    def _record_decision(self, s: int, slot: int, value: int, batch_id) -> None:
        sh = self.rt.shards[s]
        if slot in sh.decisions:
            rec = sh.decisions[slot]
        else:
            rec = SlotRecord(value=StateValue(value), batch_id=batch_id)
            sh.decisions[slot] = rec
            if value == V1:
                self.rt.decided_v1 += 1
            else:
                self.rt.decided_v0 += 1
        if sh.in_flight and int(self._cur_slot[s]) == slot:
            sh.in_flight = False
        sh.next_slot = max(sh.next_slot, slot + 1)
        sh.opened_at = 0.0
        # the next slot has a new proposer: restart the forward/give-up
        # clocks for whatever is still queued here
        for sub in sh.queue:
            sub.forwarded_at = 0.0
            sub.first_forwarded_at = 0.0
        sh.gc_upto(sh.applied_upto)

    # -- decision application ------------------------------------------------

    def _apply_ready(self) -> int:
        """Apply decided slots in order per shard (engine.rs:684-746)."""
        applied = 0
        for s in range(self.n_shards):
            sh = self.rt.shards[s]
            while True:
                slot = sh.applied_upto
                rec = sh.decisions.get(slot)
                if rec is None or rec.applied:
                    if rec is None:
                        break
                    sh.applied_upto += 1
                    continue
                if rec.value == StateValue.V1:
                    batch = (
                        sh.payloads.get(rec.batch_id)
                        if rec.batch_id is not None
                        else None
                    )
                    if rec.batch_id is not None and rec.batch_id in sh.applied_ids:
                        # duplicate commit (same batch decided in an earlier
                        # slot): never apply twice; just settle the future
                        for i, sub in enumerate(list(sh.queue)):
                            if sub.batch.id == rec.batch_id:
                                del sh.queue[i]
                                self._settle_from_ledger(sh, sub)
                                break
                    elif batch is None:
                        # decided V1 but never saw the payload: snapshot sync
                        # is the recovery path (engine.rs:748-844, §3.3)
                        self._spawn(self._initiate_sync())
                        break
                    else:
                        responses = self.sm.apply_batch(batch)
                        sh.applied_ids[rec.batch_id] = None
                        sh.applied_results[rec.batch_id] = responses
                        self.rt.state_version += 1
                        self._resolve_local(sh, batch, responses)
                else:
                    self._requeue_null_slot(sh, slot, rec)
                rec.applied = True
                sh.applied_upto += 1
                sh.gc_upto(sh.applied_upto)
                applied += 1
        if applied:
            self.rt.last_apply_time = time.time()
        return applied

    def _settle_from_ledger(self, sh, sub) -> None:
        """Settle a submitter future for a batch the ledger says is applied.

        Responses are None when the apply happened under a snapshot sync on
        another node — the commit is real but the per-command responses
        never existed here, so the future must FAIL with a distinct error
        rather than resolve with an empty list (callers index responses
        per command)."""
        if sub.future is None or sub.future.done():
            return
        responses = sh.applied_results.get(sub.batch.id)
        if responses is None:
            sub.future.set_exception(
                RabiaError(
                    "batch committed (applied via snapshot sync); "
                    "responses unavailable"
                )
            )
        else:
            sub.future.set_result(responses)

    def _resolve_local(self, sh, batch: CommandBatch, responses: list[bytes]) -> None:
        """Resolve the submitter future if this batch was queued locally."""
        for i, sub in enumerate(list(sh.queue)):
            if sub.batch.id == batch.id:
                if sub.future is not None and not sub.future.done():
                    sub.future.set_result(responses)
                del sh.queue[i]
                break

    def _requeue_null_slot(self, sh, slot: int, rec: SlotRecord) -> None:
        """A V0 (null) decision: the proposed batch (if it was ours) retries
        in a later slot, up to _MAX_SUBMIT_ATTEMPTS."""
        if rec.batch_id is None:
            return
        for i, sub in enumerate(list(sh.queue)):
            if sub.batch.id == rec.batch_id:
                sub.attempts += 1
                if sub.attempts >= _MAX_SUBMIT_ATTEMPTS:
                    if sub.future is not None and not sub.future.done():
                        sub.future.set_exception(
                            RabiaError(f"batch rejected after {sub.attempts} attempts")
                        )
                    del sh.queue[i]
                else:
                    sub.forwarded_at = 0.0
                    sub.first_forwarded_at = 0.0
                break

    # -- timeouts ------------------------------------------------------------

    def _check_timeouts(self) -> None:
        """Retransmit current votes (and proposal) for stalled shards —
        liveness under message loss (host policy per SURVEY.md §7.4.1)."""
        now = time.time()
        timeout = self.config.phase_timeout
        r1_entries: list[VoteEntry] = []
        r2_entries: list[VoteEntry] = []
        for s in range(self.n_shards):
            sh = self.rt.shards[s]
            if not sh.in_flight or now - sh.last_progress < timeout:
                continue
            slot = int(self._cur_slot[s])
            mvc = int(self._cur_phase[s])
            if self._my_r1[s] != ABSENT:
                r1_entries.append(
                    VoteEntry(s, pack_phase(slot, mvc), StateValue(int(self._my_r1[s])))
                )
            if self._stage[s] == R2_WAIT and self._my_r2[s] != ABSENT:
                r2_entries.append(
                    VoteEntry(s, pack_phase(slot, mvc), StateValue(int(self._my_r2[s])))
                )
            bp = sh.buf_propose.get(slot)
            if bp is not None and slot_proposer(s, slot, self.R) == self.me:
                self._send(
                    Propose(
                        shard=s,
                        phase=pack_phase(slot, 0),
                        batch_id=bp[0],
                        value=StateValue.V1,
                        batch=bp[1],
                    )
                )
            sh.last_progress = now
        if r1_entries:
            self._send(VoteRound1(votes=tuple(r1_entries)))
        if r2_entries:
            self._send(VoteRound2(votes=tuple(r2_entries)))

    # -- sync protocol (engine.rs:748-844) -----------------------------------

    async def _initiate_sync(self) -> None:
        # retry window: a lost SyncRequest/Response must not gate recovery
        # on the full sync_timeout — lossy networks are exactly when sync
        # is needed most
        retry_after = min(self.config.sync_timeout, 4 * self.config.phase_timeout)
        if self.rt.sync_started_at is not None and (
            time.time() - self.rt.sync_started_at < retry_after
        ):
            return
        self.rt.sync_started_at = time.time()
        self.rt.sync_responses.clear()
        total_applied = sum(sh.applied_upto for sh in self.rt.shards)
        self._send(
            SyncRequest(
                current_phase=total_applied, state_version=self.rt.state_version
            )
        )

    def _on_sync_request(self, sender: NodeId, p: SyncRequest) -> None:
        total_applied = sum(sh.applied_upto for sh in self.rt.shards)
        if total_applied <= p.current_phase:
            return  # not ahead; stay silent (engine.rs:763-779)
        snap = self.sm.create_snapshot()
        # recent ids only: the in-memory dedup horizon (64x max_pending per
        # shard) would overflow the 16 MiB transport frame cap at scale —
        # a duplicate commit of a batch older than the retransmit horizon
        # is not reachable through live traffic anyway
        id_cap = 2 * self.config.max_pending_batches
        applied_ids = tuple(
            (s, bid)
            for s, sh in enumerate(self.rt.shards[: self.n_shards])
            for bid in list(sh.applied_ids)[-id_cap:]
        )
        self._send(
            SyncResponse(
                responder_phase=total_applied,
                state_version=self.rt.state_version,
                snapshot=snap.to_bytes(),
                per_shard_phase=tuple(
                    sh.applied_upto for sh in self.rt.shards
                ),
                applied_ids=applied_ids,
            ),
            recipient=sender,
        )

    def _on_sync_response(self, sender: NodeId, p: SyncResponse) -> None:
        self.rt.sync_responses[sender] = (
            p.responder_phase,
            p.state_version,
            p.snapshot,
            p.per_shard_phase,
            p.applied_ids,
        )
        # only strictly-ahead peers respond at all, so any usable response
        # resolves immediately — waiting for a quorum of responders can
        # stall forever when just one peer is ahead
        total_applied = sum(sh.applied_upto for sh in self.rt.shards)
        if p.responder_phase > total_applied or (
            len(self.rt.sync_responses) + 1 >= self.cluster.quorum_size
        ):
            self._resolve_sync()

    def _resolve_sync(self) -> None:
        """Adopt the most advanced responder's snapshot (engine.rs:806-844)."""
        if not self.rt.sync_responses:
            return
        best = max(self.rt.sync_responses.values(), key=lambda r: r[0])
        total_applied = sum(sh.applied_upto for sh in self.rt.shards)
        self.rt.sync_started_at = None
        if best[0] <= total_applied or best[2] is None:
            return
        from rabia_tpu.core.state_machine import Snapshot

        snap = Snapshot.from_bytes(best[2])
        self.sm.restore_snapshot(snap)
        self.rt.state_version = best[1]
        for s, applied in enumerate(best[3]):
            if s >= self.S:
                break
            sh = self.rt.shards[s]
            if applied > sh.applied_upto:
                # mark skipped slots as applied-elsewhere
                for slot in range(sh.applied_upto, applied):
                    sh.decisions.setdefault(
                        slot, SlotRecord(value=StateValue.V0)
                    ).applied = True
                sh.applied_upto = applied
                sh.next_slot = max(sh.next_slot, applied)
                sh.in_flight = False
                sh.gc_upto(applied)
        # inherit the responder's dedup ledger: batches already applied via
        # the snapshot must never re-apply here if they commit again later.
        # (applied_results stays empty for them: "responses unavailable" in
        # _settle_from_ledger.)
        for s, bid in best[4]:
            if 0 <= s < self.n_shards:
                self.rt.shards[s].applied_ids.setdefault(bid, None)
        self.rt.sync_responses.clear()
        logger.info("%s sync: jumped to %d applied", self.node_id.short(), best[0])

    # -- periodic chores -----------------------------------------------------

    async def _periodic(self) -> None:
        now = time.time()
        if now - self._last_heartbeat >= self.config.heartbeat_interval:
            self._last_heartbeat = now
            total_applied = sum(sh.applied_upto for sh in self.rt.shards)
            self._send(
                HeartBeat(
                    current_phase=max(sh.next_slot for sh in self.rt.shards),
                    committed_phase=total_applied,
                )
            )
            # lag detection: a peer ahead while we make NO local progress
            # triggers a snapshot sync — a straggler that missed Decisions
            # (loss, healed partition) has no other path back
            # (engine.rs:889-907 analog). The local-idle condition prevents
            # snapshot storms under healthy multi-shard load, where
            # aggregate committed counts skew by a few slots at any instant.
            if self._peer_progress:
                best_peer = max(v[0] for v in self._peer_progress.values())
                locally_idle = (
                    time.time() - self.rt.last_apply_time
                    > 2 * self.config.phase_timeout
                )
                # mild lag only matters when we're stuck (aggregate counts
                # skew by a few slots under healthy multi-shard load);
                # severe lag — sync_lag_slots scaled by the shard count —
                # warrants a sync even while some shards still progress
                mild = best_peer > total_applied and locally_idle
                severe = best_peer >= total_applied + (
                    self.config.sync_lag_slots * max(4, self.n_shards)
                )
                if mild or severe:
                    await self._initiate_sync()
        if self._tainted_blocked():
            # tainted slots can only resolve via peer Decisions or snapshot
            # sync — keep asking (self-rate-limited by the retry window)
            await self._initiate_sync()
        if now - self._last_monitor >= max(self.config.heartbeat_interval, 0.2):
            self._last_monitor = now
            connected = await self.transport.get_connected_nodes()
            await self.monitor.observe(connected)
            await self.update_nodes(connected | {self.node_id})
        if now - self._last_cleanup >= self.config.cleanup_interval:
            self._last_cleanup = now
            self._gc()
        if self._dirty:
            self._dirty = False
            await self._save_state()

    def _gc(self) -> None:
        """Bound memory: drop old buffers + seen-batch ids (state.rs:191-243)."""
        for sh in self.rt.shards[: self.n_shards]:
            sh.gc_upto(sh.applied_upto)
            if len(sh.decisions) > self.config.max_phase_history:
                cut = sh.applied_upto - self.config.max_phase_history
                for k in [k for k in sh.decisions if k < cut]:
                    del sh.decisions[k]
            # drop payloads nothing references anymore (e.g. batches whose
            # slots kept deciding V0 and were abandoned) — without this a
            # long-running replica leaks every rejected batch's bytes
            live = {sub.batch.id for sub in sh.queue}
            live.update(bid for bid, _ in sh.buf_propose.values())
            live.update(
                rec.batch_id
                for slot, rec in sh.decisions.items()
                if rec.batch_id is not None and not rec.applied
            )
            for bid in [b for b in sh.payloads if b not in live]:
                del sh.payloads[bid]
            if len(sh.applied_results) > 2 * self.config.max_pending_batches:
                # response CACHE only — evicting here can no longer
                # re-enable a duplicate apply (dedup lives in applied_ids)
                for bid in list(sh.applied_results)[
                    : len(sh.applied_results) - self.config.max_pending_batches
                ]:
                    del sh.applied_results[bid]
            # the dedup ledger is id-only (16B entries): keep a far deeper
            # horizon, evicted FIFO only to bound truly long runs
            id_cap = 64 * self.config.max_pending_batches
            if len(sh.applied_ids) > id_cap:
                for bid in list(sh.applied_ids)[: len(sh.applied_ids) - id_cap]:
                    del sh.applied_ids[bid]
        # evict oldest seen-batch ids, never the whole dedup set at once
        cap = 10 * self.config.max_pending_batches
        while len(self._seen_order) > cap:
            self._seen_batches.discard(self._seen_order.pop(0))

    async def _save_state(self) -> None:
        if self.persistence is None:
            return
        snap = self.sm.create_snapshot()
        state = PersistedEngineState(
            current_phase=max(sh.next_slot for sh in self.rt.shards),
            last_committed_phase=sum(sh.applied_upto for sh in self.rt.shards),
            state_version=self.rt.state_version,
            snapshot=snap,
            per_shard_phase=[sh.next_slot for sh in self.rt.shards],
            per_shard_committed=[sh.applied_upto for sh in self.rt.shards],
        )
        await self.persistence.save_engine_state(state)

    # -- outbound ------------------------------------------------------------

    def _spawn(self, coro) -> None:
        """Fire-and-forget with a strong reference (the event loop only
        holds tasks weakly; unreferenced tasks can be GC'd before running)."""
        task = asyncio.ensure_future(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    def _send(self, payload, recipient: Optional[NodeId] = None) -> None:
        msg = ProtocolMessage.new(self.node_id, payload, recipient)
        data = self.serializer.serialize(msg)
        if recipient is None:
            self._spawn(self.transport.broadcast(data))
        else:
            self._spawn(self.transport.send_to(recipient, data))
