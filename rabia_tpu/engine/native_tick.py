"""ctypes bridge to the native per-tick fast path (hostkernel.cpp).

The engine's hot loop — decode vote/decision frames, ingest side effects,
ledger scatter, chained node_step rounds, outbound vote framing — runs in
one C call per tick when this bridge is active; Python is touched only for
events (decisions ready to record/apply, sync, membership, timeouts).

The bridge registers raw pointers to the engine's columnar runtime arrays
and the kernel's persistent state arrays ONCE at construction; from then
on the C side mutates them in place. The engine guarantees those arrays
are never reallocated while the bridge is alive (in native-tick mode the
kernel state is stepped in place, not functionally copied).

Semantics owner: the Python paths in engine/engine.py. The env toggle
``RABIA_PY_TICK=1`` forces them (mirroring ``RABIA_PY_DEVPACK``); the
seeded fuzz schedules and tests/test_native_tick.py pin identical
decision sequences, ledgers and wire behavior between the two.
"""

from __future__ import annotations

import ctypes
import logging
import time

import numpy as np

logger = logging.getLogger("rabia_tpu.engine.native_tick")

_STALE_CAP = 1024

# Names of the rk tick context's counter block, in RKC_* index order
# (hostkernel.cpp). The block is versioned append-only: a newer library
# may expose MORE counters than this list names (ignored), an older one
# fewer (read as 0). These feed the same metric names the Python tick
# path feeds from its event counters — docs/OBSERVABILITY.md taxonomy.
RK_COUNTER_NAMES = (
    "ticks",
    "stages",
    "frames_vote1",
    "frames_vote2",
    "frames_decision",
    "frames_noop",
    "drop_spoof",
    "drop_skew",
    "drop_malformed",
    "stale_votes",
    "taint_hits",
    "carries",
    "ledger_scatters",
    "out_frames",
    "decided",
    "opened",
    # consensus-health telemetry (RKC v2, chaos plane)
    "coin_v0",
    "coin_v1",
    "phase_sum",
)


class NativeTick:
    """One engine's native tick context (see module doc)."""

    def __init__(self, engine, lib) -> None:
        self.lib = lib
        e = engine
        kst = e.kstate
        rt = e.rt
        kernel = e.kernel
        dims = np.asarray(
            [
                e.S,
                e.n_shards,
                e.R,
                e.me,
                kernel.quorum,
                kernel.f1,
                kernel.seed & 0xFFFFFFFF,
                kernel._coin_threshold,
                rt.DEC_RING,
                1 if e.config.decision_broadcast else 0,
            ],
            np.int64,
        )
        self.newly = np.zeros(e.S, np.uint8)
        # pointer registration order is the rk_ctx_create contract
        arrays = [
            rt.next_slot,
            rt.applied_upto,
            rt.in_flight,
            rt.votes_seen_slot,
            rt.tainted_upto,
            rt.taint_traffic,
            rt.last_progress,
            rt.dec_ring_slot,
            rt.dec_ring_val,
            kst.slot,
            kst.phase,
            kst.stage,
            kst.my_r1,
            kst.my_r2,
            kst.led1,
            kst.led2,
            kst.decided,
            kst.done,
            kst.active,
            e._dec_plane,
            self.newly,
        ]
        for a in arrays:
            if not a.flags.c_contiguous:
                raise ValueError("native tick requires contiguous arrays")
        # strong refs: the C side holds raw pointers into these
        self._arrays = arrays
        ptrs = np.asarray([a.ctypes.data for a in arrays], np.int64)
        uuid_tbl = np.frombuffer(
            b"".join(n.value.bytes for n in e.cluster.all_nodes), np.uint8
        ).copy()
        fparams = np.asarray(
            [e.config.validation.max_future_skew, e.config.validation.max_age],
            np.float64,
        )
        self.ctx = lib.rk_ctx_create(
            dims.ctypes.data,
            ptrs.ctypes.data,
            uuid_tbl.ctypes.data,
            fparams.ctypes.data,
        )
        if not self.ctx:
            raise RuntimeError("rk_ctx_create failed")
        # outbound frame buffer: the open-broadcast VoteRound1 frame plus
        # 4 chained iterations x (R1 + R2 + Decision) frames, each
        # bounded by n entries
        n = e.n_shards
        self._out_cap = (72 + 13 * n) + 4 * (3 * 72 + (13 + 13 + 14) * n) + 4096
        self._out = np.empty(self._out_cap, np.uint8)
        self._res = np.zeros(8, np.int64)
        self._st_rows = np.zeros(_STALE_CAP, np.int64)
        self._st_shards = np.zeros(_STALE_CAP, np.int64)
        self._st_slots = np.zeros(_STALE_CAP, np.int64)
        # cached raw pointers (per-call ndarray.ctypes marshalling costs
        # more than the C work at small shard counts)
        self._out_ptr = self._out.ctypes.data
        self._res_ptr = self._res.ctypes.data
        self._st_ptrs = (
            self._st_rows.ctypes.data,
            self._st_shards.ctypes.data,
            self._st_slots.ctypes.data,
        )
        self._kst_ptrs = tuple(a.ctypes.data for a in kst)
        self._geom = (e.S, e.R, e.me)
        # observability: zero-copy ndarray view over the context's C
        # counter block — the registry reads cells at scrape time, the
        # hot path never crosses into Python for them
        if hasattr(lib, "rk_counters"):
            n_ctr = int(lib.rk_counters_count())
            self.counters_version = int(lib.rk_counters_version())
            cbuf = (ctypes.c_uint64 * n_ctr).from_address(
                lib.rk_counters(self.ctx)
            )
            self.counters = np.frombuffer(cbuf, np.uint64)
        else:  # stale prebuilt hostkernel: metrics read as zeros
            self.counters_version = 0
            self.counters = np.zeros(len(RK_COUNTER_NAMES), np.uint64)
        # phases-to-decide histogram: zero-copy view over the context's
        # C bins (bin p = local decisions that took p weak-MVC phases).
        # Shared with the GIL-free runtime thread (same rk ctx), so a
        # scrape may see a torn in-flight bin — metrics-grade.
        if hasattr(lib, "rk_phase_hist"):
            n_ph = int(lib.rk_phase_hist_len())
            pbuf = (ctypes.c_uint64 * n_ph).from_address(
                lib.rk_phase_hist(self.ctx)
            )
            self.phase_hist = np.frombuffer(pbuf, np.uint64)
        else:  # stale prebuilt hostkernel
            self.phase_hist = np.zeros(32, np.uint64)
        # per-phase consensus dwell histograms: zero-copy (phases, stride)
        # view over the context's C block (RK_DWELL ABI — RTH-style rows
        # of buckets + count + sum_ns). Geometry tuple lets the exporter
        # verify the block matches the registry's SLO buckets before
        # decoding. Same torn-read caveat as phase_hist: metrics-grade.
        if hasattr(lib, "rk_dwell"):
            n_dp = int(lib.rk_dwell_phases())
            n_db = int(lib.rk_dwell_buckets())
            self.dwell_version = int(lib.rk_dwell_version())
            self.dwell_geometry = (
                n_db,
                int(lib.rk_dwell_sub_bits()),
                int(lib.rk_dwell_min_exp()),
            )
            dbuf = (ctypes.c_uint64 * (n_dp * (n_db + 2))).from_address(
                lib.rk_dwell(self.ctx)
            )
            self.dwell = np.frombuffer(dbuf, np.uint64).reshape(
                n_dp, n_db + 2
            )
        else:  # stale prebuilt hostkernel: dwell reads as zeros
            self.dwell_version = 0
            self.dwell_geometry = (100, 2, 10)
            self.dwell = np.zeros((8, 102), np.uint64)
        # flight recorder: zero-copy structured view over the context's C
        # event ring (hostkernel.cpp FrEvent ABI — obs/flight.FR_DTYPE)
        from rabia_tpu.obs.flight import FR_DTYPE

        self._fr_frozen = None
        if hasattr(lib, "rk_flight"):
            if int(lib.rk_flight_record_size()) != FR_DTYPE.itemsize:
                raise RuntimeError(
                    "flight record ABI mismatch: C "
                    f"{int(lib.rk_flight_record_size())}B vs Python "
                    f"{FR_DTYPE.itemsize}B"
                )
            cap = int(lib.rk_flight_cap())
            self.flight_version = int(lib.rk_flight_version())
            fbuf = (ctypes.c_uint8 * (cap * FR_DTYPE.itemsize)).from_address(
                lib.rk_flight(self.ctx)
            )
            self._fr_view = np.frombuffer(fbuf, FR_DTYPE)
        else:  # stale prebuilt hostkernel: an empty ring
            self.flight_version = 0
            self._fr_view = np.zeros(0, FR_DTYPE)
        # sibling worker contexts (thread-per-shard-group runtime): the
        # bridge creates one extra NativeTick per additional worker and
        # registers them here so counter()/phase-hist scrapes cover the
        # whole shard space (each sibling only ever ticks its own range)
        self.siblings: list["NativeTick"] = []

    def counter(self, name: str) -> int:
        """One named counter from the block, summed over this context and
        any sibling worker contexts (0 for unknown/short blocks)."""
        try:
            i = RK_COUNTER_NAMES.index(name)
        except ValueError:
            return 0
        total = int(self.counters[i]) if i < len(self.counters) else 0
        for sib in self.siblings:
            if i < len(sib.counters):
                total += int(sib.counters[i])
        return total

    def counters_dict(self) -> dict[str, int]:
        return {n: self.counter(n) for n in RK_COUNTER_NAMES}

    def set_range(self, lo: int, hi: int, salt: int = 0) -> None:
        """Restrict this context to shard-group range [lo, hi) with a
        message-id salt (thread-per-shard-group runtime). Call only
        while no thread is inside the context."""
        if self.ctx is not None and hasattr(self.lib, "rk_set_range"):
            self.lib.rk_set_range(self.ctx, lo, hi, salt)

    def flight_head(self) -> int:
        """Total flight records ever written by the C ring."""
        if self.ctx is None or not hasattr(self.lib, "rk_flight_head"):
            return 0
        return int(self.lib.rk_flight_head(self.ctx))

    def flight_snapshot(self) -> np.ndarray:
        """Chronological copy of the live ring window (FR_DTYPE records,
        oldest first). Single-writer (the engine loop); a foreign-thread
        scrape may see one torn in-flight record — metrics-grade."""
        if self._fr_frozen is not None:
            return self._fr_frozen
        if self.ctx is None or len(self._fr_view) == 0:
            from rabia_tpu.obs.flight import FR_DTYPE

            return np.zeros(0, FR_DTYPE)
        head = self.flight_head()
        cap = len(self._fr_view)
        if head <= cap:
            return self._fr_view[:head].copy()
        i = head % cap
        return np.concatenate([self._fr_view[i:], self._fr_view[:i]])

    def close(self) -> None:
        if self.ctx:
            # freeze the last counter values and the flight ring BEFORE
            # destroying the context: both live in its memory, but late
            # scrapes/dumps (post-shutdown stats, crash dumps) must read
            # the final state, not freed memory
            self.counters = self.counters.copy()
            self.phase_hist = self.phase_hist.copy()
            self.dwell = self.dwell.copy()
            self._fr_frozen = self.flight_snapshot()
            ctx, self.ctx = self.ctx, None
            self.lib.rk_ctx_destroy(ctx)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- ingest --------------------------------------------------------------

    def ingest(self, data, row: int, now: float) -> int:
        """Offer one wire frame (bytes / memoryview over the transport
        arena) to the native ingest. Returns 1 handled, 0 not-a-fast-path
        frame (caller deserializes), -1 dropped (malformed/spoofed/
        validation-failed)."""
        if type(data) is bytes:
            # ctypes passes the bytes buffer as void* directly (no copy)
            return self.lib.rk_ingest(self.ctx, data, len(data), row, now)
        buf = np.frombuffer(data, np.uint8)
        return self.lib.rk_ingest(
            self.ctx, buf.ctypes.data, len(buf), row, now
        )

    def ingest_addr(self, addr: int, length: int, row: int, now: float) -> int:
        """Same, but straight from a native arena address (zero Python
        buffer wrapping — the borrowed-frame TCP drain)."""
        return self.lib.rk_ingest(self.ctx, addr, length, row, now)

    def finish_drain(self, engine) -> None:
        """Post-drain event work: mark senders active, run the rate-limited
        stale-vote repair for any stale reports the C ingest buffered."""
        lib = self.lib
        mask = lib.rk_rows_seen(self.ctx)
        while mask:
            row = (mask & -mask).bit_length() - 1
            mask &= mask - 1
            node = engine._row_to_node.get(row)
            if node is not None and node != engine.node_id:
                engine.rt.active_nodes.add(node)
        k = int(
            lib.rk_drain_stale(self.ctx, *self._st_ptrs, _STALE_CAP)
        )
        if k:
            rows = self._st_rows[:k]
            if k <= 4:  # the steady-state case: a couple of late votes
                seen = set()
                for i in range(k):
                    row = int(rows[i])
                    if row in seen:
                        continue
                    seen.add(row)
                    sel = rows == row
                    engine._repair_stale_sender(
                        row, self._st_shards[:k][sel], self._st_slots[:k][sel]
                    )
            else:
                for row in np.unique(rows):
                    sel = rows == row
                    engine._repair_stale_sender(
                        int(row),
                        self._st_shards[:k][sel],
                        self._st_slots[:k][sel],
                    )

    # -- slot lifecycle / the chained tick ------------------------------------

    def start_slots(self, mask, slots_full, init_full) -> None:
        """In-place rk_start_slots on the persistent kernel arrays (the
        functional HostNodeKernel.start_slots would reallocate state and
        orphan the registered pointers)."""
        S, R, me = self._geom
        m = np.ascontiguousarray(mask).view(np.uint8)
        sl = np.ascontiguousarray(slots_full, np.int32)
        iv = np.ascontiguousarray(init_full, np.int8)
        self.lib.rk_start_slots(
            S, R, me,
            m.ctypes.data, sl.ctypes.data, iv.ctypes.data,
            *self._kst_ptrs,
        )

    def tick(
        self,
        now: float | None = None,
        open_mask=None,
        open_slots=None,
        open_init=None,
    ) -> np.ndarray:
        """Chained route -> node_step -> outbox rounds (up to 4), framing
        outbound votes/decisions into the internal buffer. When the open
        arrays are given, the covered shards are armed in place and their
        VoteRound1 open broadcast is framed first. Returns the result
        vector [out_bytes, done_any, restep, frames, overflow]."""
        if open_mask is not None:
            m = np.ascontiguousarray(open_mask).view(np.uint8)
            sl = np.ascontiguousarray(open_slots, np.int32)
            iv = np.ascontiguousarray(open_init, np.int8)
            args = (m.ctypes.data, sl.ctypes.data, iv.ctypes.data)
        else:
            args = (0, 0, 0)
        self.lib.rk_tick(
            self.ctx,
            time.time() if now is None else now,
            self._out_ptr,
            self._out_cap,
            4,
            *args,
            self._res_ptr,
        )
        return self._res

    def broadcast_out(self, engine, nbytes: int) -> None:
        """Hand the tick's outbound frames to the transport: one native
        batch call for the C++ TCP plane, per-frame broadcast_nowait for
        Python transports (spawned broadcasts for transports without a
        sync path, exactly like engine._send)."""
        transport = engine.transport
        handle = getattr(transport, "_handle", None)
        tlib = getattr(transport, "_lib", None)
        if handle and tlib is not None and hasattr(tlib, "rt_broadcast_frames"):
            rc = tlib.rt_broadcast_frames(handle, self._out_ptr, nbytes)
            if rc >= 0:
                return
            logger.warning("rt_broadcast_frames rejected batch (rc=%s)", rc)
        mv = memoryview(self._out)
        pos = 0
        bcast = transport.broadcast_nowait
        while pos + 4 <= nbytes:
            ln = int.from_bytes(mv[pos : pos + 4], "little")
            frame = bytes(mv[pos + 4 : pos + 4 + ln])
            if not bcast(frame):
                engine._spawn(transport.broadcast(frame))
            pos += 4 + ln

    # -- introspection (tests / stats) ----------------------------------------

    @property
    def dropped_frames(self) -> int:
        return int(self.lib.rk_dropped(self.ctx))

    @property
    def carry_count(self) -> int:
        return int(self.lib.rk_carry_count(self.ctx))
