"""Deterministic leader selection (informational) and slot proposers.

Reference parity: rabia-engine/src/leader.rs — "leader = min NodeId in the
sorted cluster view", no elections, no terms, recomputed on membership
change (`determine_leader` leader.rs:54-56); `LeadershipInfo` record. As in
the reference, the leader plays **no role in consensus** (engine.rs:127-153
uses it only for observability).

New here: :func:`slot_proposer` — the rotating per-(shard, slot) proposer
this framework uses to serialize proposals for one decision slot. Rotation
(not leadership) preserves Rabia's leaderless guarantee: a crashed
proposer's slot times out, the cluster decides V0 (null), and the next slot
rotates to a live proposer — no election protocol, no terms.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Iterable, Optional

from rabia_tpu.core.types import NodeId, sorted_nodes


@dataclass(frozen=True)
class LeadershipInfo:
    """Current informational leader (leader.rs LeadershipInfo analog)."""

    leader: Optional[NodeId]
    since: float
    cluster_size: int

    def is_leader(self, node: NodeId) -> bool:
        return self.leader == node


class LeaderSelector:
    """Min-NodeId deterministic selector (leader.rs:35-140)."""

    def __init__(self, nodes: Iterable[NodeId] = ()) -> None:
        self._nodes: list[NodeId] = sorted_nodes(nodes)
        self._info = LeadershipInfo(
            leader=self._nodes[0] if self._nodes else None,
            since=time.time(),
            cluster_size=len(self._nodes),
        )

    @property
    def current_leader(self) -> Optional[NodeId]:
        return self._info.leader

    @property
    def info(self) -> LeadershipInfo:
        return self._info

    def update_nodes(self, nodes: Iterable[NodeId]) -> Optional[NodeId]:
        """Recompute on membership change; returns the (possibly new) leader."""
        ns = sorted_nodes(nodes)
        new_leader = ns[0] if ns else None
        if new_leader != self._info.leader or len(ns) != self._info.cluster_size:
            self._info = LeadershipInfo(
                leader=new_leader, since=time.time(), cluster_size=len(ns)
            )
        self._nodes = ns
        return new_leader

    def is_leader(self, node: NodeId) -> bool:
        return self._info.is_leader(node)


def slot_proposer(shard: int, slot: int, n_replicas: int) -> int:
    """Replica row responsible for proposing (shard, slot).

    Deterministic rotation — every replica computes the same answer with no
    coordination, and consecutive slots of one shard rotate through all
    replicas so a crashed proposer only costs its own slots (which decide V0
    by timeout and move on).

    Keep :func:`slot_proposer_vec` in lockstep with any change here — the
    engine's columnar scans use the vectorized form.
    """
    return (shard + slot) % n_replicas


def slot_proposer_vec(shards, slots, n_replicas: int):
    """Vectorized :func:`slot_proposer` over numpy shard/slot arrays."""
    return (shards + slots) % n_replicas
