"""Pipelined apply stage: decided slots drain decoupled from the tick.

Before this module the engine applied EVERY ready slot inline in
``_tick`` (engine.rs:684-746 parity): a deep decided backlog — a healed
replica adopting hundreds of Decisions, a slow state machine, a post-
crash resync — stalled the consensus tick behind state-machine work, so
peers timed out and retransmitted into exactly the replica that was
busiest (docs/PERFORMANCE.md, transport tier).

The split: :meth:`ApplyPlane.apply_ready` applies up to an inline budget
synchronously (the serial commit path keeps its latency — one decided
slot never waits for a scheduler hop), and defers anything beyond it to
a background drain task that applies bounded chunks with a yield between
chunks — decided batches queue here while the NEXT consensus round
progresses on the loop. Frontier semantics are unchanged: a slot's
``applied_upto`` advance, its flight APPLY record, its submitter-future
settle and the gateway frontier listeners all still happen exactly at
apply time, in per-shard slot order (the drain never reorders a shard's
log; it only moves WHEN the tail of a backlog applies).

The state-machine work itself rides the native apply plane
(apps/native_store.py statekernel) when the store supports it;
``RABIA_PY_APPLY=1`` forces the Python ``KVStore.apply_batch`` path,
which remains the semantics owner.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time

logger = logging.getLogger("rabia_tpu.engine.apply_plane")


class ApplyPlane:
    """Per-engine apply scheduler (see module doc).

    ``inline_budget`` slots apply synchronously per tick; the rest queue
    to the drain task (``chunk`` slots per scheduling generation).
    ``RABIA_APPLY_INLINE`` overrides the budget (0 = defer everything —
    differential testing of the drain path)."""

    INLINE_BUDGET = 512
    CHUNK = 256

    def __init__(self, engine) -> None:
        self.engine = engine
        self._pending: set[int] = set()
        self._task: asyncio.Task | None = None
        self.deferred_slots = 0  # slots applied by the drain task
        self.drains = 0  # drain task activations
        env = os.environ.get("RABIA_APPLY_INLINE")
        self.inline_budget = (
            int(env) if env is not None else self.INLINE_BUDGET
        )

    @property
    def backlog(self) -> int:
        return len(self._pending)

    def apply_ready(self, dirty: set) -> int:
        """Apply ready slots of the dirty shards: inline up to the
        budget, the rest deferred to the drain. Returns slots applied
        INLINE (the tick's progress signal)."""
        e = self.engine
        applied = 0
        for s in dirty:
            budget = self.inline_budget - applied
            if budget <= 0:
                self._pending.add(s)
                continue
            n, more = e._apply_shard_ready(s, budget)
            applied += n
            if more:
                self._pending.add(s)
        if self._pending:
            self._ensure_drain()
        if applied:
            e.rt.last_apply_time = time.time()
        return applied

    def _ensure_drain(self) -> None:
        if self._task is None or self._task.done():
            self.drains += 1
            self._task = asyncio.ensure_future(self._drain())
            # strong ref + GC on completion (the engine loop holds tasks
            # weakly)
            e = self.engine
            e._bg_tasks.add(self._task)
            self._task.add_done_callback(e._bg_tasks.discard)

    async def _drain(self) -> None:
        """Apply the deferred backlog in bounded chunks, yielding to the
        event loop between chunks so consensus ticks interleave.

        A chunk is CHUNK slots ACROSS shards, not per shard: post-crash
        backlogs are typically wide-and-shallow (a thousand shards, one
        ready slot each), and a per-shard chunk would burn one scheduling
        generation per slot there."""
        e = self.engine
        while self._pending and e._running:
            done = 0
            while self._pending and done < self.CHUNK:
                s = next(iter(self._pending))
                n, more = e._apply_shard_ready(s, self.CHUNK - done)
                done += n
                if not more:
                    self._pending.discard(s)
                    continue
                if n == 0:
                    break  # budget exhausted mid-shard
            if done:
                self.deferred_slots += done
                e.rt.last_apply_time = time.time()
                e._frontier_dirty = True
                if e.persistence is not None:
                    e._dirty = True
                # wake the run loop: frontier listeners fire on-tick
                e._wake.set()
            # the pipelining: one scheduling generation per chunk lets
            # the run loop drain inbound + step the kernel in between
            await asyncio.sleep(0)

    def flush_sync(self) -> int:
        """Apply the ENTIRE backlog synchronously (snapshot serving and
        shutdown need the applied frontier caught up to the decided
        ledger before state is externalized)."""
        e = self.engine
        applied = 0
        while self._pending:
            s = next(iter(self._pending))
            n, more = e._apply_shard_ready(s, 1 << 30)
            applied += n
            if not more:
                self._pending.discard(s)
        if applied:
            e.rt.last_apply_time = time.time()
            e._frontier_dirty = True
            if e.persistence is not None:
                e._dirty = True
        return applied
