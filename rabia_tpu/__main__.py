"""`python -m rabia_tpu` — environment doctor + end-to-end selftest.

Self-contained (runs from a source checkout or an installed wheel):
reports the package version, the live JAX backend and device list, and
whether each native C++ component (codec, host kernel, TCP transport)
is loadable; `--selftest` then drives a miniature end-to-end stack —
device kernel decide, kernel-vs-oracle conformance, and a MeshEngine
commit with replica agreement — on whatever backend is live. The
reference ships runnable example binaries as its smoke story
(examples/Cargo.toml:7-41 in rabia-rs/rabia); this is the
one-command equivalent for a JAX deployment, where "does my
environment work" additionally means "does XLA compile for my
backend".

Usage:
    python -m rabia_tpu                    # environment report
    python -m rabia_tpu --selftest         # + compile and run the mini stack
    python -m rabia_tpu stats <host:port>  # scrape a gateway's /metrics
    python -m rabia_tpu stats <host:port> --kind health|journal
    python -m rabia_tpu stats <host:port> --kind journal \\
        --journal-kind slow_tick --last 10
    python -m rabia_tpu trace <host:port> [host:port ...] \\
        --client <uuid> --seq <n>          # cross-replica commit timeline
    python -m rabia_tpu profile <host:port> [--seconds 2]
                                           # runtime stage breakdown
    python -m rabia_tpu timeline <host:port> [host:port ...] \\
        [--last N] [--metric SUBSTR ...]   # per-second telemetry curves
    python -m rabia_tpu fleet-top <host:port> \\
        [--samples N] [--interval S]       # ring-discovered fleet pane:
                                           # per-gateway coalesce density,
                                           # slots/op, routing rates
"""

from __future__ import annotations

import argparse
import sys
import time


def _report() -> int:
    import rabia_tpu

    print(f"rabia-tpu {rabia_tpu.__version__}")
    import jax

    devs = jax.devices()
    print(f"jax {jax.__version__}; backend: {devs[0].platform}; "
          f"devices: {len(devs)} ({devs[0].device_kind})")
    from rabia_tpu.native import build

    codec = build.load_codec()
    print(f"native codec: {'ok' if codec else 'UNAVAILABLE (python fallback)'}")
    hk = build.load_hostkernel()
    print(f"native host kernel: {'ok' if hk else 'UNAVAILABLE (numpy fallback)'}")
    try:
        build.load_library()
        print("native TCP transport: ok")
    except Exception as e:  # no compiler / unsupported platform
        print(f"native TCP transport: UNAVAILABLE ({type(e).__name__})")
    return 0


def _selftest() -> int:
    import numpy as np
    import jax
    import jax.numpy as jnp

    t0 = time.perf_counter()
    from rabia_tpu.kernel import ClusterKernel

    S, R = 64, 5
    k = ClusterKernel(S, R, seed=42)
    votes = jnp.full((8, S, R), 1, jnp.int8)
    decided, _ = k.slot_pipeline(votes, jnp.ones((S, R), bool), 8)
    assert bool(np.all(np.asarray(decided) == 1)), "kernel decide failed"
    print(f"kernel: 8x{S} slots decided V1 "
          f"({time.perf_counter() - t0:.1f}s incl. compile)")

    # kernel vs executable spec on a lossy schedule
    t0 = time.perf_counter()
    from rabia_tpu.core.oracle import WeakMVCOracle
    from rabia_tpu.kernel import device_coin

    st = k.start_slot(
        k.init_state(),
        jnp.ones((S,), bool),
        jnp.full((S, R), 1, jnp.int8),
    )
    alive = jnp.asarray(
        np.broadcast_to(np.array([False, True, True, False, True]), (S, R))
    )
    st = k.run_rounds(st, alive, 80, jax.random.key(1), p_deliver=0.6)
    assert bool(np.all(np.asarray(st.decided) != 3)), (
        "minority crash + loss failed to decide"
    )
    del device_coin, WeakMVCOracle  # imports prove the spec surface loads
    print(f"fault path: minority crash + 40% loss decided every shard "
          f"({time.perf_counter() - t0:.1f}s)")

    # the full SMR stack: MeshEngine commit + replica agreement
    t0 = time.perf_counter()
    from rabia_tpu.core.state_machine import InMemoryStateMachine
    from rabia_tpu.parallel import MeshEngine

    eng = MeshEngine(InMemoryStateMachine, n_shards=8, n_replicas=3, window=2)
    futs = [eng.submit([f"SET k{i} v{i}"], shard=i % 8) for i in range(16)]
    applied = eng.flush()
    assert applied == 16 and all(f.result() == [b"OK"] for f in futs)
    snap = eng.sms[0].create_snapshot().data
    assert all(sm.create_snapshot().data == snap for sm in eng.sms), (
        "replica divergence"
    )
    print(f"engine: 16 batches committed, 3 replicas agree "
          f"({time.perf_counter() - t0:.1f}s)")
    print("selftest OK")
    return 0


def _parse_addr(addr: str) -> tuple[str, int] | None:
    host, _, port_s = addr.rpartition(":")
    if not host or not port_s.isdigit():
        return None
    return host, int(port_s)


def _stats(
    addr: str,
    kind: str,
    timeout: float,
    journal_kind: str | None = None,
    last: int | None = None,
) -> int:
    """Fetch one admin document from a live gateway over its native
    transport (the framed AdminRequest path — no HTTP shim required)."""
    import asyncio
    import json

    from rabia_tpu.core.messages import AdminKind
    from rabia_tpu.gateway import admin_fetch

    parsed = _parse_addr(addr)
    if parsed is None:
        print(f"stats: bad address {addr!r} (want host:port)", file=sys.stderr)
        return 2
    host, port = parsed
    kind_code = {
        "metrics": AdminKind.METRICS,
        "health": AdminKind.HEALTH,
        "journal": AdminKind.JOURNAL,
    }[kind]
    query = b""
    if kind == "journal" and (journal_kind is not None or last is not None):
        q: dict = {}
        if journal_kind is not None:
            q["kind"] = journal_kind
        if last is not None:
            q["last"] = last
        query = json.dumps(q).encode()
    try:
        body = asyncio.run(
            admin_fetch(
                host, port, int(kind_code), timeout=timeout, query=query
            )
        )
    except Exception as e:
        print(f"stats: {type(e).__name__}: {e}", file=sys.stderr)
        return 1
    if kind == "metrics":
        sys.stdout.write(body.decode(errors="replace"))
    else:
        print(json.dumps(json.loads(body.decode()), indent=2))
    return 0


def _trace(addrs: list[str], client: str, seq: int, timeout: float) -> int:
    """Follow one batch through the whole cluster: fetch each replica's
    flight-ring TraceSlice (AdminKind.TRACE), align the per-replica
    monotonic clocks off the fetch RTTs, and print one merged commit
    timeline (submit → propose → per-peer R1/R2 votes → decide → apply →
    result). See docs/OBSERVABILITY.md, "Cross-replica commit traces"."""
    import asyncio
    import uuid

    from rabia_tpu.obs.flight import collect_trace, render_timeline

    parsed = []
    for a in addrs:
        p = _parse_addr(a)
        if p is None:
            print(f"trace: bad address {a!r} (want host:port)",
                  file=sys.stderr)
            return 2
        parsed.append(p)
    try:
        cid = uuid.UUID(client)
    except ValueError:
        print(f"trace: bad client id {client!r} (want a UUID)",
              file=sys.stderr)
        return 2
    try:
        merged = asyncio.run(
            collect_trace(parsed, cid, seq, timeout=timeout)
        )
    except Exception as e:
        print(f"trace: {type(e).__name__}: {e}", file=sys.stderr)
        return 1
    if not merged:
        print(
            f"trace: no flight events for client={cid} seq={seq} "
            "(command too old for the rings, or never submitted here?)",
            file=sys.stderr,
        )
        return 1
    print(render_timeline(merged))
    return 0


def _slowlog(
    addr: str,
    replicas: list[str],
    fleet: list[str],
    last,
    as_json: bool,
    timeout: float,
) -> int:
    """Fetch a gateway's slow-Submit exemplar reservoir
    (AdminKind.SLOWLOG), decompose each exemplar's cross-tier flight
    trace into named critical-path segments, and print the table plus
    the worst exemplar's waterfall. See docs/OBSERVABILITY.md,
    "Critical path"."""
    import asyncio
    import json

    from rabia_tpu.obs.critpath import (
        collect_exemplar_trace,
        collect_slowlog,
        decompose,
        render_slowlog,
    )

    p0 = _parse_addr(addr)
    if p0 is None:
        print(f"slowlog: bad address {addr!r} (want host:port)",
              file=sys.stderr)
        return 2
    rep_addrs = []
    for a in replicas or [addr]:
        p = _parse_addr(a)
        if p is None:
            print(f"slowlog: bad replica address {a!r}", file=sys.stderr)
            return 2
        rep_addrs.append(p)
    fleet_addrs = []
    for a in fleet or []:
        p = _parse_addr(a)
        if p is None:
            print(f"slowlog: bad fleet address {a!r}", file=sys.stderr)
            return 2
        fleet_addrs.append(p)

    async def run():
        doc = await collect_slowlog(
            p0[0], p0[1], last=last, timeout=timeout
        )

        async def timeline_async(ex):
            return await collect_exemplar_trace(
                rep_addrs, ex, fleet_addrs=fleet_addrs, timeout=timeout
            )

        # decompose_exemplars takes a sync collector; each trace fetch
        # is itself sequential, so drive them one by one here
        decomps = []
        for ex in doc.get("exemplars", []):
            try:
                merged = await timeline_async(ex)
            except Exception as exc:  # noqa: BLE001 — keep the table
                decomps.append(
                    {
                        "ok": False,
                        "error": f"{type(exc).__name__}: {exc}",
                        "truncated": False,
                        "segments": {},
                        "total_s": 0.0,
                        "unattributed_s": 0.0,
                        "unattributed_frac": 0.0,
                        "exemplar": dict(ex),
                    }
                )
                continue
            d = decompose(
                merged,
                coalesced=ex.get("coalesced"),
                wall_s=ex.get("wall_s"),
            )
            d["exemplar"] = dict(ex)
            decomps.append(d)
        return doc, decomps

    try:
        doc, decomps = asyncio.run(run())
    except Exception as e:
        print(f"slowlog: {type(e).__name__}: {e}", file=sys.stderr)
        return 1
    if as_json:
        print(json.dumps({"slowlog": doc, "decompositions": decomps},
                         indent=2, default=str))
    else:
        print(render_slowlog(doc, decomps))
    return 0


def _profile(addr: str, seconds: float, timeout: float) -> int:
    """Two /metrics scrapes ``seconds`` apart -> the commit-path owner's
    per-stage time breakdown (rabia_runtime_stage_seconds deltas), with
    a coverage figure against the elapsed wall time between scrapes —
    "where did the wall move" as a scrape, not a guess. Works identically
    on the native runtime thread (RTS block) and the asyncio
    orchestration (loop accounting): same metric family either way."""
    import asyncio
    import time as _time

    from rabia_tpu.core.messages import AdminKind
    from rabia_tpu.gateway import admin_fetch
    from rabia_tpu.obs.registry import RUNTIME_STAGES, parse_prometheus_text

    parsed = _parse_addr(addr)
    if parsed is None:
        print(f"profile: bad address {addr!r} (want host:port)",
              file=sys.stderr)
        return 2
    host, port = parsed

    def scrape() -> tuple[dict, float]:
        body = asyncio.run(
            admin_fetch(host, port, int(AdminKind.METRICS), timeout=timeout)
        )
        return parse_prometheus_text(body.decode(errors="replace")), \
            _time.monotonic()

    def stage_of(m: dict, stage: str) -> float:
        return m.get(
            f'rabia_runtime_stage_seconds{{stage="{stage}"}}', 0.0
        )

    try:
        m0, t0 = scrape()
        _time.sleep(max(0.2, seconds))
        m1, t1 = scrape()
    except Exception as e:
        print(f"profile: {type(e).__name__}: {e}", file=sys.stderr)
        return 1
    if not any(
        k.startswith("rabia_runtime_stage_seconds") for k in m1
    ):
        print("profile: replica exports no rabia_runtime_stage_seconds "
              "(pre-SLO-plane build?)", file=sys.stderr)
        return 1
    elapsed = t1 - t0
    deltas = {s: stage_of(m1, s) - stage_of(m0, s) for s in RUNTIME_STAGES}
    total = sum(deltas.values())
    planes = 1.0 if m1.get("rabia_engine_native_runtime", 0.0) else 0.0
    print(
        f"runtime stage profile over {elapsed:.2f}s "
        f"(commit-path owner: "
        f"{'native runtime thread' if planes else 'asyncio loop'})"
    )
    print(f"{'stage':<16}{'time (s)':>12}{'share':>9}{'cumulative (s)':>17}")
    for s in sorted(RUNTIME_STAGES, key=lambda x: -deltas[x]):
        share = deltas[s] / elapsed * 100 if elapsed > 0 else 0.0
        print(f"{s:<16}{deltas[s]:>12.4f}{share:>8.1f}%"
              f"{stage_of(m1, s):>17.3f}")
    cov = total / elapsed * 100 if elapsed > 0 else 0.0
    print(f"{'-- sum':<16}{total:>12.4f}{cov:>8.1f}%  of wall between scrapes")

    # thread-per-shard-group runtime: per-worker breakdown next to the
    # aggregate (the worker-labeled series exist only with workers > 1)
    import re as _re

    workers = sorted(
        {
            m.group(1)
            for k in m1
            for m in [
                _re.match(
                    r'rabia_runtime_stage_seconds\{stage="[^"]+",'
                    r'worker="(\d+)"\}', k
                )
            ]
            if m
        },
        key=int,
    )
    if workers:
        def wstage(m: dict, g: str, stage: str) -> float:
            return m.get(
                f'rabia_runtime_stage_seconds{{stage="{stage}",'
                f'worker="{g}"}}', 0.0
            )

        print(f"\nper-worker breakdown ({len(workers)} shard groups):")
        hdr = f"{'stage':<16}" + "".join(
            f"{'w' + g + ' (s)':>12}" for g in workers
        )
        print(hdr)
        wtot = {g: 0.0 for g in workers}
        for s in RUNTIME_STAGES:
            row = f"{s:<16}"
            for g in workers:
                d = wstage(m1, g, s) - wstage(m0, g, s)
                wtot[g] += d
                row += f"{d:>12.4f}"
            print(row)
        row = f"{'-- sum':<16}"
        for g in workers:
            row += f"{wtot[g]:>12.4f}"
        print(row)
        row = f"{'-- coverage':<16}"
        for g in workers:
            c = wtot[g] / elapsed * 100 if elapsed > 0 else 0.0
            row += f"{c:>11.1f}%"
        print(row + "  of wall per worker")
    return 0


def _timeline(
    addrs: list[str],
    last: int | None,
    metrics: list[str] | None,
    as_json: bool,
    out: str | None,
    timeout: float,
) -> int:
    """Fetch every replica's per-second telemetry ring, clock-align them
    (RTT-midpoint offsets, the flight-recorder model) and print one
    merged multi-replica time series."""
    import asyncio
    import json

    from rabia_tpu.obs.telemetry import (
        collect_timeline,
        render_timeline_table,
    )

    parsed = []
    for a in addrs:
        p = _parse_addr(a)
        if p is None:
            print(f"timeline: bad address {a!r} (want host:port)",
                  file=sys.stderr)
            return 2
        parsed.append(p)
    try:
        rows = asyncio.run(
            collect_timeline(parsed, last=last, timeout=timeout)
        )
    except Exception as e:
        print(f"timeline: {type(e).__name__}: {e}", file=sys.stderr)
        return 1
    if out:
        with open(out, "w") as f:
            json.dump({"version": 1, "rows": rows}, f)
        print(f"timeline: {len(rows)} samples -> {out}", file=sys.stderr)
    if as_json:
        print(json.dumps(rows))
    else:
        print(render_timeline_table(rows, metrics=metrics or None))
    return 0


def _wal_dump(directory: str, records: bool, last) -> int:
    """Render a durability-plane directory (persistence/native_wal.py):
    segments with base LSNs and CRC status, the snapshot chain with its
    frontier, the latest vote barrier — and FLAG a torn tail (what a
    crash mid-group-commit looks like) instead of crashing on it."""
    from pathlib import Path

    from rabia_tpu.persistence.native_wal import (
        K_BARRIER,
        K_FRONTIER,
        K_LEDGER,
        K_WAVE,
        KIND_NAMES,
        decode_record,
        read_snap_file,
        scan_wal,
    )

    d = Path(directory)
    if not d.is_dir():
        print(f"not a directory: {directory}", file=sys.stderr)
        return 2
    scan = scan_wal(d)
    print(f"wal directory: {d}")
    if not scan.segments:
        print("  (no segments)")
    for seg in scan.segments:
        torn_here = scan.torn is not None and scan.torn["segment"] == seg["index"]
        status = "TORN" if torn_here else "ok"
        print(
            f"  {Path(seg['path']).name}: base_lsn={seg.get('base_lsn', '?')} "
            f"records={seg['records']} bytes={seg['bytes']} crc={status}"
        )
    if scan.torn is not None:
        t = scan.torn
        print(
            f"  !! torn tail: segment {t['segment']} offset {t['offset']} "
            f"({t['reason']}) — recovery truncates here; records before "
            f"the tear are the durable prefix"
        )
    kinds: dict = {}
    frontier = None
    barrier = None
    for _lsn, _seg, _off, payload in scan.records:
        rec = decode_record(payload)
        kinds[rec["kind"]] = kinds.get(rec["kind"], 0) + 1
        if rec["kind"] == K_FRONTIER:
            frontier = rec
        elif rec["kind"] == K_BARRIER:
            barrier = rec
    summary = ", ".join(
        f"{KIND_NAMES.get(k, k)}={n}" for k, n in sorted(kinds.items())
    )
    print(f"  records: {len(scan.records)} (lsn 1..{scan.last_lsn}) {summary}")
    chain = [read_snap_file(p) for p in sorted(d.glob("snap-*.dat"))]
    for info, p in zip(chain, sorted(d.glob("snap-*.dat"))):
        if info is None:
            print(f"  {p.name}: CORRUPT (crc/header)")
            continue
        meta = info["meta"]
        print(
            f"  {p.name}: {'full' if info['is_full'] else 'delta'} "
            f"kind={'kv' if info['kind'] else 'blob'} "
            f"frontier_lsn={info['frontier_lsn']} "
            f"state_version={meta.get('state_version')} "
            f"applied={sum(meta.get('applied_upto', []))}"
        )
    if frontier is not None:
        print(
            f"  snapshot frontier: snap_index={frontier['snap_index']} "
            f"state_version={frontier['state_version']} "
            f"applied={sum(frontier['applied'])}"
        )
    if barrier is not None:
        bv = barrier["barrier"]
        print(
            f"  vote barrier: max={max(bv) if bv else 0} "
            f"nonzero_shards={sum(1 for x in bv if x)}"
        )
    if records:
        recs = scan.records
        if last is not None:
            recs = recs[-last:]
        for lsn, seg, off, payload in recs:
            rec = decode_record(payload)
            kind = KIND_NAMES.get(rec["kind"], str(rec["kind"]))
            detail = ""
            if rec["kind"] == K_WAVE:
                ops = rec["ops"]
                bid = rec["bid"]
                detail = (
                    f" shard={rec['shard']} slot={rec['slot']} "
                    f"value={rec['value']} ops={len(ops) if ops else 0}"
                    f" bid={'-' if not bid or not any(bid) else bid.hex()[:16]}"
                )
            elif rec["kind"] == K_LEDGER:
                detail = (
                    f" shard={rec['shard']} slot={rec['slot']} "
                    f"bid={rec['bid'].hex()[:16]}"
                )
            elif rec["kind"] == K_FRONTIER:
                detail = f" snap_index={rec['snap_index']}"
            print(f"  lsn={lsn} seg={seg} off={off} {kind}{detail}")
    return 0


def _ring(addr: str, timeout: float, as_json: bool) -> int:
    """Dump a routed fleet's control-plane view from any one member:
    the consistent-hash ring (version, members), the shard -> gateway
    ownership table, and each member's live session count + routing
    counters (HEALTH fetched per member — an unreachable member prints
    as such instead of failing the whole dump). docs/FLEET.md."""
    import asyncio
    import json

    from rabia_tpu.core.messages import AdminKind
    from rabia_tpu.fleet.ring import HashRing
    from rabia_tpu.gateway import admin_fetch

    parsed = _parse_addr(addr)
    if parsed is None:
        print(f"ring: bad address {addr!r} (want host:port)", file=sys.stderr)
        return 2
    host, port = parsed

    async def fetch() -> dict:
        body = await admin_fetch(
            host, port, int(AdminKind.RING), timeout=timeout
        )
        doc = json.loads(body.decode())
        healths: dict = {}
        for m in (doc.get("ring") or {}).get("members", []):
            try:
                hb = await admin_fetch(
                    m["host"], m["port"], int(AdminKind.HEALTH),
                    timeout=timeout,
                )
                healths[m["name"]] = json.loads(hb.decode())
            except Exception as e:
                healths[m["name"]] = {"error": str(e)}
        doc["members_health"] = healths
        # shard-group liveness (fleet/groups.py): probe each group's
        # replica gateways so a dead group renders UNREACHABLE + stale
        # in the ownership table rather than silently absent
        if doc.get("groups"):
            self_health = healths.get(doc.get("self")) or {}
            group_alive: dict = {}
            for gid, addrs in enumerate(
                self_health.get("upstream_groups") or []
            ):
                alive = 0
                for gh, gp in addrs:
                    try:
                        await admin_fetch(
                            gh, gp, int(AdminKind.HEALTH),
                            timeout=min(timeout, 3.0),
                        )
                        alive += 1
                    except Exception:
                        pass
                group_alive[gid] = [alive, len(addrs)]
            doc["group_liveness"] = group_alive
        return doc

    try:
        doc = asyncio.run(fetch())
    except Exception as e:
        print(f"ring: fetch from {addr} failed: {e}", file=sys.stderr)
        return 1
    if as_json:
        print(json.dumps(doc, indent=2))
        return 0
    if doc.get("ring") is None and "group" in doc:
        # a REPLICA gateway answered: its RING document is the group
        # card (group id + owned shard ranges), not a fleet ring
        ranges = ", ".join(
            f"[{lo},{hi})" for lo, hi in (doc.get("shards") or [])
        )
        print(
            f"replica gateway {doc.get('node')}: "
            f"group={doc.get('group')} "
            f"owned shard ranges: {ranges or '(all — ungrouped)'} "
            f"of {doc.get('n_shards')} shards"
        )
        return 0
    ring_doc = doc.get("ring") or {}
    n_shards = int(doc.get("n_shards") or 0)
    print(
        f"ring version {ring_doc.get('version')}: "
        f"{len(ring_doc.get('members', []))} members, {n_shards} shards "
        f"(answered by {doc.get('self')})"
    )
    healths = doc["members_health"]
    for m in ring_doc.get("members", []):
        h = healths.get(m["name"], {})
        if "error" in h:
            print(
                f"  {m['name']:<12} {m['host']}:{m['port']}  "
                f"UNREACHABLE ({h['error']})"
            )
            continue
        st = h.get("stats", {})
        print(
            f"  {m['name']:<12} {m['host']}:{m['port']}  "
            f"sessions={h.get('sessions')} "
            f"shards={len(h.get('owned_shards', []))} "
            f"moved={st.get('moved')} cached={st.get('cached_replays')} "
            f"ledger_in={st.get('ledger_applied')} "
            f"ledger_out={st.get('ledger_sent')}"
        )
    ring = HashRing.from_doc(ring_doc)
    by_owner: dict = {}
    for s in range(n_shards):
        owner = ring.owner(s)
        name = owner.name if owner is not None else "?"
        by_owner.setdefault(name, []).append(s)
    for name in sorted(by_owner):
        shards = ",".join(str(s) for s in by_owner[name])
        print(f"  shards[{name}]: {shards}")
    groups = doc.get("groups")
    if groups:
        live = doc.get("group_liveness") or {}
        print(
            f"  group map v{groups.get('version')} "
            "(shard-range -> consensus group):"
        )
        for lo, hi, gid in groups.get("ranges", []):
            a = live.get(gid, live.get(str(gid)))
            status = ""
            if a is not None:
                alive, total = a
                status = f"  replicas {alive}/{total}"
                if alive == 0:
                    status += "  UNREACHABLE (stale)"
            print(f"    shards [{lo},{hi}) -> group {gid}{status}")
    return 0


def _fleet_top(
    addr: str,
    samples: int,
    interval: float,
    as_json: bool,
    out: str | None,
    timeout: float,
) -> int:
    """Ring-discovered fleet pane: bootstrap the whole two-tier
    inventory from one fleet gateway (RING members + each member's
    ``upstreams``), scrape everything, and print the per-gateway derived
    series — coalesce density, slots/op, routing rates — plus the
    fleet-level shared-resource figures (fsyncs/Result, off-consensus
    read fraction). Derived rates are counter DELTAS, so at least two
    samples are taken. docs/OBSERVABILITY.md, "Fleet plane"."""
    import asyncio
    import json

    from rabia_tpu.obs.fleet_obs import FleetAggregator, render_fleet_table

    parsed = _parse_addr(addr)
    if parsed is None:
        print(f"fleet-top: bad address {addr!r} (want host:port)",
              file=sys.stderr)
        return 2

    async def run() -> list[dict]:
        agg = FleetAggregator(parsed, timeout=timeout)
        await agg.refresh()
        docs = []
        for k in range(max(2, samples)):
            if k:
                await asyncio.sleep(max(0.1, interval))
            doc = await agg.sample()
            docs.append(doc)
            if not as_json:
                print(render_fleet_table(doc))
                print()
        return agg.series()

    try:
        series = asyncio.run(run())
    except Exception as e:
        print(f"fleet-top: {type(e).__name__}: {e}", file=sys.stderr)
        return 1
    if out:
        with open(out, "w") as f:
            json.dump({"version": 1, "series": series}, f)
        print(f"fleet-top: {len(series)} samples -> {out}", file=sys.stderr)
    if as_json:
        print(json.dumps(series[-1]))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m rabia_tpu",
        description=(__doc__ or "").split("\n")[0],
    )
    ap.add_argument("--selftest", action="store_true",
                    help="compile and run the mini end-to-end stack")
    sub = ap.add_subparsers(dest="cmd")
    sp = sub.add_parser(
        "stats",
        help="scrape a gateway's admin surface over the native transport",
    )
    sp.add_argument("addr", help="gateway host:port")
    sp.add_argument(
        "--kind", choices=("metrics", "health", "journal"),
        default="metrics",
    )
    sp.add_argument(
        "--journal-kind", default=None,
        help="journal only: filter entries by anomaly kind",
    )
    sp.add_argument(
        "--last", type=int, default=None,
        help="journal only: return the last N entries (default 64)",
    )
    sp.add_argument("--timeout", type=float, default=10.0)
    tp = sub.add_parser(
        "trace",
        help="reconstruct one command's cross-replica commit timeline "
        "from the flight recorders",
    )
    tp.add_argument(
        "addrs", nargs="+",
        help="gateway host:port (one per replica to include)",
    )
    tp.add_argument(
        "--client", required=True, help="client session id (UUID)"
    )
    tp.add_argument(
        "--seq", type=int, required=True, help="client command seq"
    )
    tp.add_argument("--timeout", type=float, default=10.0)
    sl = sub.add_parser(
        "slowlog",
        help="decompose a gateway's slowest Submit exemplars into "
        "critical-path segments (queue, park, per-phase consensus, "
        "fsync, fanout)",
    )
    sl.add_argument("addr", help="replica gateway host:port (slowlog source)")
    sl.add_argument(
        "--replicas", action="append", default=None,
        help="replica gateway host:port to trace against (repeatable; "
        "default: the slowlog addr only)",
    )
    sl.add_argument(
        "--fleet", action="append", default=None,
        help="fleet gateway host:port to include in traces (repeatable)",
    )
    sl.add_argument(
        "--last", type=int, default=None,
        help="only the N slowest exemplars",
    )
    sl.add_argument(
        "--json", action="store_true",
        help="print the reservoir + decompositions as JSON",
    )
    sl.add_argument("--timeout", type=float, default=10.0)
    pp = sub.add_parser(
        "profile",
        help="two-scrape runtime stage breakdown (where a commit-path "
        "second actually goes)",
    )
    pp.add_argument("addr", help="gateway host:port")
    pp.add_argument(
        "--seconds", type=float, default=2.0,
        help="window between the two /metrics scrapes",
    )
    pp.add_argument("--timeout", type=float, default=10.0)
    tl = sub.add_parser(
        "timeline",
        help="merge per-second telemetry rings from every replica into "
        "one clock-aligned time series",
    )
    tl.add_argument(
        "addrs", nargs="+",
        help="gateway host:port (one per replica to include)",
    )
    tl.add_argument(
        "--last", type=int, default=None,
        help="only the last N samples per replica",
    )
    tl.add_argument(
        "--metric", action="append", default=None,
        help="metric column (substring-matched against snapshot keys, "
        "matches summed; repeatable)",
    )
    tl.add_argument(
        "--json", action="store_true", help="print merged rows as JSON"
    )
    tl.add_argument(
        "--out", default=None, help="also write merged rows to this file"
    )
    tl.add_argument("--timeout", type=float, default=10.0)
    ft = sub.add_parser(
        "fleet-top",
        help="ring-discovered fleet pane: per-gateway coalesce density, "
        "slots/op and routing rates plus fleet-level shared-resource "
        "figures (docs/OBSERVABILITY.md)",
    )
    ft.add_argument("addr", help="any fleet gateway host:port (the seed)")
    ft.add_argument(
        "--samples", type=int, default=2,
        help="scrape rounds (min 2 — derived rates are counter deltas)",
    )
    ft.add_argument(
        "--interval", type=float, default=1.0,
        help="seconds between scrape rounds",
    )
    ft.add_argument(
        "--json", action="store_true",
        help="print the final derived sample as JSON instead of tables",
    )
    ft.add_argument(
        "--out", default=None,
        help="also write the whole derived series to this file",
    )
    ft.add_argument("--timeout", type=float, default=10.0)
    rg = sub.add_parser(
        "ring",
        help="dump a routed fleet's hash ring from any member: "
        "membership, shard ownership, per-gateway session counts "
        "(docs/FLEET.md)",
    )
    rg.add_argument("addr", help="any fleet gateway host:port")
    rg.add_argument(
        "--json", action="store_true",
        help="print the raw ring + per-member health as JSON",
    )
    rg.add_argument("--timeout", type=float, default=10.0)
    wd = sub.add_parser(
        "wal-dump",
        help="inspect a replica's durability-plane directory: segment "
        "headers, wave records, CRC status, snapshot frontier "
        "(docs/DURABILITY.md)",
    )
    wd.add_argument("dir", help="WAL directory (one replica's)")
    wd.add_argument(
        "--records", action="store_true",
        help="also print every record (default: per-segment summaries)",
    )
    wd.add_argument(
        "--last", type=int, default=None,
        help="with --records: only the last N records",
    )
    args = ap.parse_args(argv)
    if args.cmd == "wal-dump":
        return _wal_dump(args.dir, args.records, args.last)
    if args.cmd == "ring":
        return _ring(args.addr, args.timeout, args.json)
    if args.cmd == "fleet-top":
        return _fleet_top(
            args.addr, args.samples, args.interval, args.json, args.out,
            args.timeout,
        )
    if args.cmd == "stats":
        return _stats(
            args.addr, args.kind, args.timeout,
            journal_kind=args.journal_kind, last=args.last,
        )
    if args.cmd == "trace":
        return _trace(args.addrs, args.client, args.seq, args.timeout)
    if args.cmd == "slowlog":
        return _slowlog(
            args.addr, args.replicas, args.fleet, args.last, args.json,
            args.timeout,
        )
    if args.cmd == "profile":
        return _profile(args.addr, args.seconds, args.timeout)
    if args.cmd == "timeline":
        return _timeline(
            args.addrs, args.last, args.metric, args.json, args.out,
            args.timeout,
        )
    rc = _report()
    if rc == 0 and args.selftest:
        rc = _selftest()
    return rc


if __name__ == "__main__":
    sys.exit(main())
