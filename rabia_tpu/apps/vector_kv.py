"""Columnar vector KV store: the S-axis-native state machine.

The classic :class:`~rabia_tpu.apps.kvstore.KVStore` applies one Python
operation per command — fine for scalar traffic, but the block lane decides
*thousands* of shards per wave and per-op Python becomes the throughput
wall (SURVEY.md §7.4.4 applies to the apply plane exactly as it does to the
vote plane). This module keeps the whole partitioned store in **columnar
numpy arrays** — one open-addressing hash table over ``(shard, key)`` —
so a decided wave applies as a handful of array ops:

- keys hash with a vectorized splitmix64 fold over fixed-width key lanes;
- probing resolves all wave entries together (per-iteration "unique
  winner" insertion makes concurrent same-slot inserts deterministic and
  preserves wave order for duplicate keys);
- versions are per-shard monotonic counters, assigned columnar;
- responses are built as one structured array and split with ``tolist``.

Semantics match the classic store where they overlap: versioned entries,
per-shard version counters, created/updated timestamps, key/value size
limits. Values are ``bytes`` (the wire-native type); keys up to
``max_key_lanes*8`` bytes live in the table, longer keys fall back to a
dict side-store. No notification bus — the vector store trades the pub/sub
plane for wave throughput (use the classic store when you need
subscriptions).

No reference analog: the reference applies commands one at a time
(rabia-core/src/state_machine.rs:29-52); this is the TPU-first redesign of
that apply plane.
"""

from __future__ import annotations

import json
import struct
import time
from typing import Optional, Sequence

import numpy as np

from rabia_tpu.core.errors import StateMachineError
from rabia_tpu.core.state_machine import Snapshot, StateMachine, VectorStateMachine
from rabia_tpu.core.types import Command, CommandBatch

U64 = np.uint64
_EMPTY, _USED = np.uint8(0), np.uint8(1)

_C1 = U64(0xBF58476D1CE4E5B9)
_C2 = U64(0x94D049BB133111EB)
_GOLD64 = U64(0x9E3779B97F4A7C15)


def _mix64(x: np.ndarray) -> np.ndarray:
    x = x ^ (x >> U64(30))
    x = x * _C1
    x = x ^ (x >> U64(27))
    x = x * _C2
    x = x ^ (x >> U64(31))
    return x


class FrameSeq(Sequence):
    """Flat per-op responses as a lazy view over one fixed-width frame
    buffer — a decided wave's response bytes materialize only when a
    client actually reads them (the settle path stores the view)."""

    __slots__ = ("raw", "width", "n")

    def __init__(self, raw: bytes, width: int, n: int) -> None:
        self.raw = raw
        self.width = width
        self.n = n

    def __len__(self) -> int:
        return self.n

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self.n))]
        if i < 0:
            i += self.n
        if not (0 <= i < self.n):
            raise IndexError(i)
        return self.raw[i * self.width : (i + 1) * self.width]

    def __eq__(self, other) -> bool:
        if not isinstance(other, (list, tuple, Sequence)):
            return NotImplemented
        return len(self) == len(other) and all(
            a == b for a, b in zip(self, other)
        )

    def __repr__(self) -> str:
        return f"FrameSeq(n={self.n}, width={self.width})"


class FrameGroups(Sequence):
    """Per-shard response lists over a :class:`FrameSeq`, grouped by
    cumulative op counts — the lazy form of ``_regroup``."""

    __slots__ = ("frames", "bounds")

    def __init__(self, frames: FrameSeq, bounds: np.ndarray) -> None:
        self.frames = frames
        self.bounds = bounds  # i64[k+1] cumulative

    def __len__(self) -> int:
        return len(self.bounds) - 1

    def __getitem__(self, j):
        if isinstance(j, slice):
            return [self[i] for i in range(*j.indices(len(self)))]
        if j < 0:
            j += len(self)
        if not (0 <= j < len(self)):
            raise IndexError(j)
        a, b = int(self.bounds[j]), int(self.bounds[j + 1])
        return [self.frames[i] for i in range(a, b)]

    def __iter__(self):
        # explicit: the Sequence ABC fallback probes __getitem__ through
        # a generic wrapper per element (measurably slow on the ack path)
        frames = self.frames
        bl = self.bounds.tolist()  # one conversion, not 2 numpy reads/group
        for a, b in zip(bl, bl[1:]):
            yield [frames[i] for i in range(a, b)]

    def group_counts(self) -> np.ndarray:
        """i64[k] responses per group WITHOUT materializing any frame —
        the cheap ack count for clients that only need sizes."""
        return np.diff(self.bounds)

    def __eq__(self, other) -> bool:
        if not isinstance(other, (list, tuple, Sequence)):
            return NotImplemented
        return len(self) == len(other) and all(
            a == b for a, b in zip(self, other)
        )

    def __repr__(self) -> str:
        return f"FrameGroups(k={len(self)})"


class VectorKVStore:
    """Partitioned columnar KV store (see module doc).

    ``capacity`` is rounded up to a power of two and grows 2x when the
    table passes 70% load. ``max_key_lanes`` 8-byte lanes bound the inline
    key width (default 32 bytes); longer keys use the dict side-store.
    """

    def __init__(
        self,
        num_shards: int,
        capacity: int = 1 << 16,
        max_key_lanes: int = 4,
        max_key_length: int = 256,
        max_value_size: int = 1024 * 1024,
    ) -> None:
        self.num_shards = int(num_shards)
        self.L = int(max_key_lanes)
        self.K = self.L * 8
        self.max_key_length = int(max_key_length)
        self.max_value_size = int(max_value_size)
        C = 1
        while C < capacity:
            C <<= 1
        self._alloc(C)
        self.shard_version = np.zeros(self.num_shards, np.int64)
        self.count = 0
        self._overflow: dict[tuple[int, bytes], list] = {}
        self.total_operations = 0
        self.writes = 0
        self.reads = 0

    def _alloc(self, C: int) -> None:
        self.C = C
        self._mask = U64(C - 1)
        self.state = np.zeros(C, np.uint8)
        self.key_hash = np.zeros(C, U64)
        self.key_len = np.zeros(C, np.uint16)
        self.key_lanes = np.zeros((C, self.L), U64)
        self.shard_col = np.zeros(C, np.int64)
        # values are stored BY REFERENCE into their arrival buffer
        # (val_buf[s][val_off[s] : val_off[s]+val_len[s]]): a decided block
        # wave stores one shared bytes object + offset/length columns, with
        # zero per-value slicing on the apply path
        self.val_buf = np.empty(C, object)
        self.val_off = np.zeros(C, np.int64)
        self.val_len = np.zeros(C, np.int64)
        self.version = np.zeros(C, np.int64)
        self.created = np.zeros(C, np.float64)
        self.updated = np.zeros(C, np.float64)

    # -- hashing --------------------------------------------------------------

    def _hash(
        self, lanes: np.ndarray, klens: np.ndarray, shards: np.ndarray
    ) -> np.ndarray:
        h = np.full(len(klens), _GOLD64, U64)
        for i in range(self.L):
            h = _mix64(h ^ lanes[:, i])
        h = _mix64(h ^ klens.astype(U64) ^ (shards.astype(U64) << U64(17)))
        return np.where(h == 0, U64(1), h)

    def _lanes_from_keys(self, keys: Sequence[bytes]) -> tuple[np.ndarray, np.ndarray]:
        """Pack variable-length key bytes into zero-padded uint64 lanes."""
        n = len(keys)
        mat = np.zeros((n, self.K), np.uint8)
        klens = np.zeros(n, np.int64)
        for i, k in enumerate(keys):
            klens[i] = len(k)
            mat[i, : len(k)] = np.frombuffer(k, np.uint8)
        return mat.view(U64).reshape(n, self.L), klens

    # -- bulk write path ------------------------------------------------------

    def bulk_set(
        self,
        shards: np.ndarray,
        lanes: np.ndarray,  # u64[n, L] zero-padded key lanes
        klens: np.ndarray,  # i64[n]
        values,  # list[bytes] in wave order, OR (buffer, voffs, vlens)
        now: Optional[float] = None,
        ranks: Optional[np.ndarray] = None,
    ) -> np.ndarray:
        """Insert/update n entries in wave order; returns versions i64[n].

        Deterministic across replicas: resolution depends only on table
        state and wave content. Duplicate keys within one wave land in wave
        order (the later op updates the earlier one's slot). ``values`` as
        a ``(buffer, voffs, vlens)`` triple stores by reference with no
        per-value slicing (the block lane's path); ``buffer`` is one
        shared bytes object or an object array of per-op buffers.
        ``ranks`` overrides the
        per-op occurrence index used for version assignment (count of
        PRIOR ops on the same shard within this call) — required when
        equal shards are NOT contiguous runs, e.g. several concatenated
        waves (``apply_block_multi``); the default derivation assumes
        shard-major wave order.
        """
        if now is None:
            now = time.time()
        n = len(klens)
        if n == 0:
            return np.zeros(0, np.int64)
        if isinstance(values, tuple) and len(values[2]):
            if int(np.max(values[2])) > self.max_value_size:
                raise StateMachineError("value exceeds max_value_size")
        elif not isinstance(values, tuple):
            if any(len(v) > self.max_value_size for v in values):
                raise StateMachineError("value exceeds max_value_size")
        if (self.count + n) * 10 > self.C * 7:
            # size the growth to DEMAND: a single wave can exceed one
            # doubling, and an exhausted probe loop would leave half-
            # inserted ghost slots behind
            needed = self.count + n
            self._grow(
                max(self.C * 2, 1 << max(10, (needed * 2 - 1).bit_length()))
            )
        h = self._hash(lanes, klens, shards)
        slot = self._probe_or_insert(h, shards, lanes, klens, now)
        # versions: per-shard counters advance one per op, wave order
        # (shard-major waves make ranks the run offsets)
        base = self.shard_version[shards]
        rank = ranks if ranks is not None else self._run_ranks(shards)
        vers = base + rank + 1
        np.add.at(self.shard_version, shards, 1)
        # scatter payload columns (duplicate slots: numpy fancy assignment
        # applies in array order == wave order, so the last write wins)
        if isinstance(values, tuple):
            buffer, voffs, vlens = values
            self.val_buf[slot] = buffer
            self.val_off[slot] = voffs
            self.val_len[slot] = vlens
        else:
            vals_obj = np.empty(n, object)
            vals_obj[:] = values
            self.val_buf[slot] = vals_obj
            self.val_off[slot] = 0
            self.val_len[slot] = np.fromiter(
                (len(v) for v in values), np.int64, n
            )
        self.version[slot] = vers
        self.updated[slot] = now
        self.total_operations += n
        self.writes += n
        return vers

    def _value_at(self, s: int) -> bytes:
        buf = self.val_buf[s]
        a = int(self.val_off[s])
        b = a + int(self.val_len[s])
        if a == 0 and b == len(buf):
            return buf
        return buf[a:b]

    @staticmethod
    def _run_ranks(shards: np.ndarray) -> np.ndarray:
        n = len(shards)
        if n == 1:
            return np.zeros(1, np.int64)
        idx = np.arange(n)
        run_start = np.empty(n, bool)
        run_start[0] = True
        np.not_equal(shards[1:], shards[:-1], out=run_start[1:])
        return idx - np.maximum.accumulate(np.where(run_start, idx, 0))

    def _probe_or_insert(
        self,
        h: np.ndarray,
        shards: np.ndarray,
        lanes: np.ndarray,
        klens: np.ndarray,
        now: float,
    ) -> np.ndarray:
        """Resolve every wave entry to a table slot, inserting fresh keys.

        Linear probing; per iteration, unresolved entries targeting empty
        slots insert with a deterministic "first occurrence wins" rule and
        losers re-check the (now used) slot next iteration.
        """
        n = len(h)
        idx = (h & self._mask).astype(np.int64)
        slot_out = np.full(n, -1, np.int64)
        live = np.arange(n)
        for _ in range(self.C):
            st = self.state[idx]
            used = st == _USED
            match = used & (self.key_hash[idx] == h[live])
            if match.any():
                m = np.nonzero(match)[0]
                keep = (
                    (self.shard_col[idx[m]] == shards[live[m]])
                    & (self.key_len[idx[m]] == klens[live[m]])
                    & (self.key_lanes[idx[m]] == lanes[live[m]]).all(axis=1)
                )
                match[m[~keep]] = False
            empty = ~used
            if empty.any():
                cand = np.nonzero(empty)[0]
                # first occurrence per target slot wins the insert
                _, first = np.unique(idx[cand], return_index=True)
                w = cand[np.sort(first)]
                tgt = idx[w]
                self.state[tgt] = _USED
                self.key_hash[tgt] = h[live[w]]
                self.key_len[tgt] = klens[live[w]]
                self.key_lanes[tgt] = lanes[live[w]]
                self.shard_col[tgt] = shards[live[w]]
                self.version[tgt] = 0
                self.created[tgt] = now
                self.count += len(w)
                match[w] = True  # resolved as (fresh) slots
            resolved = match
            slot_out[live[resolved]] = idx[resolved]
            if resolved.all():
                return slot_out
            keep = ~resolved
            live = live[keep]
            # losers whose target just got used by the SAME key re-check the
            # slot next iteration (duplicate keys within one wave resolve as
            # updates, wave order); everything else advances. The re-check
            # must be a FULL key compare — a mere hash match would loop
            # forever on hash-colliding distinct keys.
            idx = idx[keep]
            again = self.state[idx] == _USED
            still_mine = (
                again
                & (self.key_hash[idx] == h[live])
                & (self.shard_col[idx] == shards[live])
                & (self.key_len[idx] == klens[live])
                & (self.key_lanes[idx] == lanes[live]).all(axis=1)
            )
            advance = ~still_mine
            idx = np.where(
                advance,
                ((idx.astype(U64) + U64(1)) & self._mask).astype(np.int64),
                idx,
            )
        raise StateMachineError("vector store probe loop exhausted (table full)")

    # -- bulk read/delete -----------------------------------------------------

    def _lookup(
        self, shards: np.ndarray, lanes: np.ndarray, klens: np.ndarray
    ) -> np.ndarray:
        """Slot per entry, -1 where absent (no mutation)."""
        n = len(klens)
        h = self._hash(lanes, klens, shards)
        idx = (h & self._mask).astype(np.int64)
        out = np.full(n, -1, np.int64)
        live = np.arange(n)
        for _ in range(self.C):
            st = self.state[idx]
            used = st == _USED
            miss = ~used
            match = used & (self.key_hash[idx] == h[live])
            if match.any():
                m = np.nonzero(match)[0]
                keep = (
                    (self.shard_col[idx[m]] == shards[live[m]])
                    & (self.key_len[idx[m]] == klens[live[m]])
                    & (self.key_lanes[idx[m]] == lanes[live[m]]).all(axis=1)
                )
                match[m[~keep]] = False
            out[live[match]] = idx[match]
            resolved = match | miss
            if resolved.all():
                return out
            keep = ~resolved
            live, idx = live[keep], idx[keep]
            idx = ((idx.astype(U64) + U64(1)) & self._mask).astype(np.int64)
        return out

    def bulk_get(
        self, shards: np.ndarray, lanes: np.ndarray, klens: np.ndarray
    ) -> tuple[np.ndarray, list]:
        """(versions i64[n] with -1 for missing, values list)."""
        slot = self._lookup(shards, lanes, klens)
        found = slot >= 0
        vers = np.where(found, self.version[np.maximum(slot, 0)], -1)
        vals = [
            self._value_at(s) if s >= 0 else None for s in slot.tolist()
        ]
        self.total_operations += len(klens)
        self.reads += len(klens)
        return vers, vals

    # -- grow -----------------------------------------------------------------

    def _grow(self, new_capacity: int) -> None:
        old_state = self.state
        old = (
            self.key_hash,
            self.key_len,
            self.key_lanes,
            self.shard_col,
            self.val_buf,
            self.val_off,
            self.val_len,
            self.version,
            self.created,
            self.updated,
        )
        used = np.nonzero(old_state == _USED)[0]
        self._alloc(new_capacity)
        self.count = 0
        if len(used) == 0:
            return
        kh = old[0][used]
        kl = old[1][used]
        lanes = old[2][used]
        shards = old[3][used]
        slot = self._probe_or_insert(
            kh, shards, lanes, kl.astype(np.int64), 0.0
        )
        self.val_buf[slot] = old[4][used]
        self.val_off[slot] = old[5][used]
        self.val_len[slot] = old[6][used]
        self.version[slot] = old[7][used]
        self.created[slot] = old[8][used]
        self.updated[slot] = old[9][used]

    # -- scalar conveniences (tests / service reads) --------------------------

    def set(self, shard: int, key: bytes, value: bytes) -> int:
        if len(key) > self.K:
            return self._overflow_set(shard, key, value)
        lanes, klens = self._lanes_from_keys([key])
        return int(
            self.bulk_set(np.array([shard], np.int64), lanes, klens, [value])[0]
        )

    def get(self, shard: int, key: bytes) -> Optional[tuple[bytes, int]]:
        if len(key) > self.K:
            ent = self._overflow.get((shard, key))
            return (ent[0], ent[1]) if ent else None
        lanes, klens = self._lanes_from_keys([key])
        vers, vals = self.bulk_get(np.array([shard], np.int64), lanes, klens)
        if vers[0] < 0:
            return None
        return vals[0], int(vers[0])

    def delete(self, shard: int, key: bytes) -> bool:
        """Tombstone-free delete: relocate the trailing cluster (classic
        open-addressing backward shift) — scalar path, deletes are rare."""
        if len(key) > self.K:
            if self._overflow.pop((shard, key), None) is None:
                return False
            self.shard_version[shard] += 1
            self.total_operations += 1
            self.writes += 1
            return True
        lanes, klens = self._lanes_from_keys([key])
        slot = self._lookup(np.array([shard], np.int64), lanes, klens)
        s = int(slot[0])
        if s < 0:
            return False
        self.total_operations += 1
        self.writes += 1
        self.shard_version[shard] += 1
        self.count -= 1
        # backward-shift deletion keeps probe chains intact
        i = s
        while True:
            self.state[i] = _EMPTY
            j = i
            while True:
                j = (j + 1) & int(self._mask)
                if self.state[j] != _USED:
                    return True
                home = int(self.key_hash[j] & self._mask)
                # can entry j move into the hole at i?
                if (i <= j and (home <= i or home > j)) or (
                    i > j and (home <= i and home > j)
                ):
                    self._move_entry(j, i)
                    i = j
                    break

    def _move_entry(self, src: int, dst: int) -> None:
        self.state[dst] = self.state[src]
        self.key_hash[dst] = self.key_hash[src]
        self.key_len[dst] = self.key_len[src]
        self.key_lanes[dst] = self.key_lanes[src]
        self.shard_col[dst] = self.shard_col[src]
        self.val_buf[dst] = self.val_buf[src]
        self.val_off[dst] = self.val_off[src]
        self.val_len[dst] = self.val_len[src]
        self.version[dst] = self.version[src]
        self.created[dst] = self.created[src]
        self.updated[dst] = self.updated[src]

    def _overflow_set(self, shard: int, key: bytes, value: bytes) -> int:
        if len(key) > self.max_key_length:
            raise StateMachineError("key too long")
        if len(value) > self.max_value_size:
            raise StateMachineError("value exceeds max_value_size")
        self.shard_version[shard] += 1
        v = int(self.shard_version[shard])
        now = time.time()
        ent = self._overflow.get((shard, key))
        if ent is None:
            self._overflow[(shard, key)] = [value, v, now, now]
        else:
            ent[0], ent[1], ent[3] = value, v, now
        self.total_operations += 1
        self.writes += 1
        return v

    def __len__(self) -> int:
        return self.count + len(self._overflow)

    # -- snapshot -------------------------------------------------------------

    def snapshot_bytes(self) -> bytes:
        used = np.nonzero(self.state == _USED)[0]
        # deterministic order: sort by (shard, key)
        if len(used):
            order = np.lexsort(
                (self.key_hash[used], self.shard_col[used])
            )
            used = used[order]
        parts = [struct.pack("<QI", len(used), self.num_shards)]
        parts.append(self.shard_version.tobytes())
        for s in used.tolist():
            klen = int(self.key_len[s])
            key = self.key_lanes[s].tobytes()[:klen]
            val = self._value_at(s)
            parts.append(
                struct.pack("<iHIqdd", int(self.shard_col[s]), klen, len(val),
                            int(self.version[s]), float(self.created[s]),
                            float(self.updated[s]))
            )
            parts.append(key)
            parts.append(val)
        over = [
            {
                "shard": sh,
                "key": key.hex(),
                "value": ent[0].hex(),
                "version": ent[1],
                "created": ent[2],
                "updated": ent[3],
            }
            for (sh, key), ent in sorted(self._overflow.items())
        ]
        parts.append(json.dumps(over).encode())
        return b"".join(parts)

    @staticmethod
    def _parse_snapshot(raw: bytes):
        """(shard_versions i64[N], rows, overflow_docs) where rows =
        (shards, keys, vals, vers, created, updated) parallel lists."""
        n, num_shards = struct.unpack_from("<QI", raw, 0)
        off = 12
        shard_versions = np.frombuffer(raw, np.int64, num_shards, offset=off).copy()
        off += 8 * num_shards
        shards, keys, vals, vers, created, updated = [], [], [], [], [], []
        head = struct.calcsize("<iHIqdd")
        for _ in range(n):
            sh, klen, vlen, ver, cr, up = struct.unpack_from("<iHIqdd", raw, off)
            off += head
            keys.append(raw[off : off + klen])
            off += klen
            vals.append(raw[off : off + vlen])
            off += vlen
            shards.append(sh)
            vers.append(ver)
            created.append(cr)
            updated.append(up)
        over = json.loads(raw[off:].decode()) if off < len(raw) else []
        return shard_versions, (shards, keys, vals, vers, created, updated), over

    def _bulk_load(self, rows) -> None:
        """Insert parsed rows into the (fresh) table."""
        shards, keys, vals, vers, created, updated = rows
        n = len(keys)
        if not n:
            return
        lanes, klens = self._lanes_from_keys(keys)
        sh_arr = np.asarray(shards, np.int64)
        if (self.count + n) * 10 > self.C * 7:
            self._grow(1 << max(10, ((self.count + n) * 2 - 1).bit_length()))
        h = self._hash(lanes, klens, sh_arr)
        slot = self._probe_or_insert(h, sh_arr, lanes, klens, 0.0)
        vo = np.empty(n, object)
        vo[:] = vals
        self.val_buf[slot] = vo
        self.val_off[slot] = 0
        self.val_len[slot] = np.fromiter((len(v) for v in vals), np.int64, n)
        self.version[slot] = np.asarray(vers, np.int64)
        self.created[slot] = np.asarray(created)
        self.updated[slot] = np.asarray(updated)

    def _absorb_overflow_docs(self, over, adopt=None) -> None:
        """Decode overflow entries into the side-store (optionally only
        the shards in ``adopt``) — one place owns the doc format."""
        for doc in over:
            if adopt is not None and doc["shard"] not in adopt:
                continue
            self._overflow[(doc["shard"], bytes.fromhex(doc["key"]))] = [
                bytes.fromhex(doc["value"]),
                doc["version"],
                doc["created"],
                doc["updated"],
            ]

    def restore_bytes(self, raw: bytes) -> None:
        shard_versions, rows, over = self._parse_snapshot(raw)
        self.num_shards = len(shard_versions)
        self.shard_version = shard_versions
        self._alloc(max(self.C, 1 << 10))
        self.count = 0
        self._overflow = {}
        self._bulk_load(rows)
        self._absorb_overflow_docs(over)

    def restore_shards_bytes(self, raw: bytes, shard_ids) -> None:
        """Replace ONLY the given shards' entries/counters from the
        snapshot, keeping every other shard's current state (sync adoption
        under mixed per-shard progress).

        Kept rows re-insert VECTORIZED from their stored hashes/lanes (the
        ``_grow`` pattern) — a per-row Python loop over a large store
        would stall the engine's event loop mid-sync."""
        adopt = set(int(s) for s in shard_ids)
        shard_versions, rows, over = self._parse_snapshot(raw)
        used = np.nonzero(self.state == _USED)[0]
        keep = used[
            ~np.isin(self.shard_col[used], np.asarray(sorted(adopt), np.int64))
        ]
        kept = (
            self.key_hash[keep].copy(),
            self.key_len[keep].copy(),
            self.key_lanes[keep].copy(),
            self.shard_col[keep].copy(),
            self.val_buf[keep].copy(),
            self.val_off[keep].copy(),
            self.val_len[keep].copy(),
            self.version[keep].copy(),
            self.created[keep].copy(),
            self.updated[keep].copy(),
        )
        kept_overflow = {
            k: v for k, v in self._overflow.items() if k[0] not in adopt
        }
        self._alloc(max(self.C, 1 << 10))
        self.count = 0
        self._overflow = kept_overflow
        if len(keep):
            needed = len(keep)
            if needed * 10 > self.C * 7:
                self._grow(1 << max(10, (needed * 2 - 1).bit_length()))
            slot = self._probe_or_insert(
                kept[0], kept[3], kept[2], kept[1].astype(np.int64), 0.0
            )
            self.val_buf[slot] = kept[4]
            self.val_off[slot] = kept[5]
            self.val_len[slot] = kept[6]
            self.version[slot] = kept[7]
            self.created[slot] = kept[8]
            self.updated[slot] = kept[9]
        # adopted shards come from the snapshot rows
        adopted_rows = tuple(
            [rows[j][i] for i in range(len(rows[0])) if int(rows[0][i]) in adopt]
            for j in range(6)
        )
        self._bulk_load(adopted_rows)
        self._absorb_overflow_docs(over, adopt)
        for s in adopt:
            if s < len(shard_versions) and s < len(self.shard_version):
                self.shard_version[s] = shard_versions[s]


# ---------------------------------------------------------------------------
# State machine adapter
# ---------------------------------------------------------------------------

_RESP_DT = np.dtype([("kind", "u1"), ("version", "<u4"), ("has", "u1")])


class VectorShardedKV(StateMachine, VectorStateMachine):
    """Engine-facing SM over :class:`VectorKVStore`.

    Block waves of binary SET ops apply fully vectorized (key windows
    gathered from the block's command buffer, one hash/probe/scatter pass,
    responses via one structured array). Non-SET ops and scalar batches
    take a per-op path with identical semantics. Command format is the
    binary kv op codec (rabia_tpu.apps.kvstore).
    """

    def __init__(self, num_shards: int, capacity: int = 1 << 16) -> None:
        self.store = VectorKVStore(num_shards, capacity=capacity)
        self.num_shards = int(num_shards)
        self._version = 0

    # -- block lane -----------------------------------------------------------

    def _decode_cols(
        self, block, idxs: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Flat (counts, op_shards, op_off, op_len) for the selected
        shard entries, wave order; offsets index the block's own data."""
        counts = block.counts[idxs]
        cmd_idx = (
            np.repeat(block.shard_starts[idxs], counts)
            + _concat_ranges(counts)
        )
        op_shards = np.repeat(block.shards[idxs], counts)
        op_off = block.cmd_offsets[cmd_idx]
        op_len = block.cmd_sizes[cmd_idx]
        return counts, op_shards, op_off, op_len

    def _pad_buf(self, raw: bytes) -> np.ndarray:
        data = np.frombuffer(raw, np.uint8)
        pad = np.zeros(self.store.K + 3, np.uint8)
        return np.concatenate([data, pad])

    def _set_mask(
        self,
        dbuf: np.ndarray,
        op_off: np.ndarray,
        op_len: np.ndarray,
    ) -> tuple[np.ndarray, np.ndarray]:
        """(klen i64[n], well-formed-SET mask bool[n])."""
        opcode = dbuf[op_off]
        klen = dbuf[op_off + 1].astype(np.int64) | (
            dbuf[op_off + 2].astype(np.int64) << 8
        )
        is_set = (
            (opcode == 1)
            & (op_len >= 3)
            & (klen > 0)
            & (klen <= self.store.K)
            & (3 + klen <= op_len)
            & (op_len - 3 - klen <= self.store.max_value_size)
        )
        return klen, is_set

    @staticmethod
    def _regroup(resp, counts: np.ndarray):
        """Regroup flat per-op responses per covered shard (lazily when
        the flat responses are a frame view)."""
        if isinstance(resp, FrameSeq):
            bounds = np.zeros(len(counts) + 1, np.int64)
            np.cumsum(counts, out=bounds[1:])
            return FrameGroups(resp, bounds)
        if bool((counts == 1).all()):
            return [[r] for r in resp]
        out: list[list[bytes]] = []
        pos = 0
        for c in counts.tolist():
            out.append(resp[pos : pos + c])
            pos += c
        return out

    def apply_block(
        self, block, idxs, want_responses: bool = True
    ) -> Optional[list[list[bytes]]]:
        idxs = np.asarray(idxs, np.int64)
        counts, op_shards, op_off, op_len = self._decode_cols(block, idxs)
        dbuf = self._pad_buf(block.data)
        klen, is_set = self._set_mask(dbuf, op_off, op_len)
        self._version += len(idxs)
        if bool(is_set.all()):
            resp = self._apply_sets(
                op_shards, dbuf, op_off, op_len, klen, block.data,
                want_responses,
            )
        else:
            resp = self._apply_mixed(
                op_shards, is_set, dbuf, op_off, op_len, klen, block.data
            )
        if resp is None:
            return None
        return self._regroup(resp, counts)

    def apply_block_multi(
        self, blocks, idxs_list, want_responses: bool = True
    ) -> Optional[list[Optional[list[list[bytes]]]]]:
        """Apply several decided waves (wave order = list order) in ONE
        vectorized pass when every op is a well-formed SET — the
        full-width block lane's bulk-write shape. Anything else falls
        back to sequential :meth:`apply_block` calls, preserving each
        wave's op-ordering semantics; on that path a wave that fails
        deterministically yields an ``Exception`` as ITS list entry
        (waves already applied keep their responses — per-wave failure
        granularity, matching sequential apply).

        Precondition (the block-lane invariant ``submit_block`` enforces):
        a block's covered shards are unique within that block — the
        cross-wave version ranks are derived from it.
        """
        if len(blocks) == 1:
            return [self.apply_block(blocks[0], idxs_list[0], want_responses)]
        per: list[tuple] = []
        ranks_parts: list[np.ndarray] = []
        prior = np.zeros(self.num_shards, np.int64)
        set_only = True
        for block, idxs in zip(blocks, idxs_list):
            idxs = np.asarray(idxs, np.int64)
            counts, op_shards, op_off, op_len = self._decode_cols(block, idxs)
            dbuf_j = self._pad_buf(block.data)
            klen_j, is_set_j = self._set_mask(dbuf_j, op_off, op_len)
            if not bool(is_set_j.all()):
                set_only = False
                break  # fallback path re-decodes per block anyway
            # occurrence rank = ops on the same shard in PRIOR waves +
            # the within-wave run offset (runs are contiguous per block)
            ranks_parts.append(
                prior[op_shards] + VectorKVStore._run_ranks(op_shards)
            )
            prior[block.shards[idxs]] += counts
            per.append(
                (idxs, counts, op_shards, op_off, op_len, klen_j, dbuf_j,
                 block.data)
            )
        if not set_only:
            # mixed waves: sequential applies keep cross-wave read/write
            # ordering exact (no mutation has happened yet). A wave that
            # fails deterministically becomes ITS entry's exception —
            # earlier waves' commits stay settled with real responses.
            out_seq: list = []
            for b, i in zip(blocks, idxs_list):
                try:
                    out_seq.append(self.apply_block(b, i, want_responses))
                except Exception as e:  # deterministic app failure
                    out_seq.append(e)
            return out_seq
        # ONE bulk_set over the concatenated columns. Keys gather from
        # each block's own padded buffer (already built for the SET
        # check); values reference each op's OWN block buffer through an
        # object column — retention per block, exactly like sequential
        # apply (no multi-wave buffer pinning, no payload concatenation).
        op_shards = np.concatenate([p[2] for p in per])
        klen = np.concatenate([p[5] for p in per])
        lanes = np.concatenate(
            [self._lanes_of(p[6], p[3], p[5]) for p in per]
        )
        voffs = np.concatenate([p[3] + 3 + p[5] for p in per])
        vlens = np.concatenate([p[4] - 3 - p[5] for p in per])
        n_total = len(op_shards)
        vbufs = np.empty(n_total, object)
        pos = 0
        for p in per:
            k = len(p[2])
            vbufs[pos : pos + k] = p[7]  # object scalar: one ref per op
            pos += k
        self._version += sum(len(p[0]) for p in per)
        vers = self.store.bulk_set(
            op_shards, lanes, klen, (vbufs, voffs, vlens),
            ranks=np.concatenate(ranks_parts),
        )
        if not want_responses:
            return None
        resp = self._vers_frames(vers)
        # per-block groups index the ONE flat frame view with absolute
        # bounds — no per-block slicing or copying
        out: list = []
        pos = 0
        for _idxs, counts, *_rest in per:
            tot = int(counts.sum())
            bounds = np.full(len(counts) + 1, pos, np.int64)
            bounds[1:] += np.cumsum(counts)
            out.append(FrameGroups(resp, bounds))
            pos += tot
        return out

    def _lanes_of(
        self, dbuf: np.ndarray, op_off: np.ndarray, klen: np.ndarray
    ) -> np.ndarray:
        """Zero-padded u64 key lanes [n, L] gathered from ``dbuf``; the
        gather only spans the widest ACTUAL key (Ku), zero-filling the
        rest — keys are usually far shorter than the table's max width."""
        n = len(op_off)
        K = self.store.K
        Ku = int(klen.max()) if n else 0
        if Ku < K:
            small = dbuf[(op_off + 3)[:, None] + np.arange(Ku)[None, :]]
            small = np.where(np.arange(Ku)[None, :] < klen[:, None], small, 0)
            win = np.zeros((n, K), np.uint8)
            win[:, :Ku] = small
        else:
            win = dbuf[(op_off + 3)[:, None] + np.arange(K)[None, :]]
            win = np.where(np.arange(K)[None, :] < klen[:, None], win, 0)
        return np.ascontiguousarray(win).view(U64).reshape(n, self.store.L)

    @staticmethod
    def _vers_frames(vers: np.ndarray) -> FrameSeq:
        """Version responses as n fixed 6-byte frames behind a lazy view
        (tobytes once; per-frame bytes slice on client read)."""
        arr = np.zeros(len(vers), _RESP_DT)
        arr["version"] = vers.astype(np.uint32)
        return FrameSeq(arr.tobytes(), 6, len(vers))

    def _apply_sets(
        self, op_shards, dbuf, op_off, op_len, klen, raw: bytes,
        want_responses: bool = True,
    ) -> Optional[list[bytes]]:
        lanes = self._lanes_of(dbuf, op_off, klen)
        vers = self.store.bulk_set(
            op_shards, lanes, klen,
            (raw, op_off + 3 + klen, op_len - 3 - klen),
        )
        if not want_responses:
            return None
        return self._vers_frames(vers)

    def _apply_mixed(
        self, op_shards, is_set, dbuf, op_off, op_len, klen, raw: bytes
    ) -> list[bytes]:
        from rabia_tpu.apps.kvstore import _result_bin

        resp: list[Optional[bytes]] = [None] * len(op_off)
        set_idx = np.nonzero(is_set)[0]
        if len(set_idx):
            sub = self._apply_sets(
                op_shards[set_idx],
                dbuf,
                op_off[set_idx],
                op_len[set_idx],
                klen[set_idx],
                raw,
            )
            for i, r in zip(set_idx.tolist(), sub):
                resp[i] = r
        for i in np.nonzero(~is_set)[0].tolist():
            a, b = int(op_off[i]), int(op_off[i] + op_len[i])
            resp[i] = self._apply_one(int(op_shards[i]), raw[a:b])
        return resp  # type: ignore[return-value]

    def _apply_one(self, shard: int, op: bytes) -> bytes:
        from rabia_tpu.apps.kvstore import _result_bin

        try:
            code = op[0]
            klen = int.from_bytes(op[1:3], "little")
            if 3 + klen > len(op):
                return _result_bin(
                    2, 0, f"malformed op: key length {klen} exceeds payload"
                )
            key = op[3 : 3 + klen]
            if code == 1:  # SET
                if len(key) > self.store.K:
                    v = self.store._overflow_set(shard, key, op[3 + klen :])
                else:
                    v = self.store.set(shard, key, op[3 + klen :])
                return _result_bin(0, v)
            if code == 2:  # GET
                got = self.store.get(shard, key)
                if got is None:
                    return _result_bin(1, 0)
                val, ver = got
                try:
                    text = val.decode("utf-8")
                except UnicodeDecodeError:
                    # the store holds raw bytes; the text-result wire form
                    # must not silently mangle them
                    return _result_bin(2, ver, "value is not utf-8 text")
                return _result_bin(0, ver, text)
            if code == 3:  # DEL
                ok = self.store.delete(shard, key)
                return _result_bin(0 if ok else 1, 0)
            if code == 4:  # EXISTS
                found = self.store.get(shard, key) is not None
                return _result_bin(0, 0, "true" if found else "false")
            return _result_bin(2, 0, f"unknown opcode {code}")
        except (IndexError, StateMachineError) as e:
            return _result_bin(2, 0, str(e))

    # -- scalar lane ----------------------------------------------------------

    def apply_command(self, command: Command) -> bytes:
        self._version += 1
        batch_shard = 0
        return self._apply_one(batch_shard, command.data)

    def apply_batch(self, batch: CommandBatch) -> list[bytes]:
        self._version += 1
        s = int(batch.shard) % self.num_shards
        return [self._apply_one(s, c.data) for c in batch.commands]

    # -- snapshot -------------------------------------------------------------

    def create_snapshot(self) -> Snapshot:
        return Snapshot.create(self._version, self.store.snapshot_bytes())

    def restore_snapshot(self, snapshot: Snapshot) -> None:
        snapshot.verify()
        self.store.restore_bytes(snapshot.data)
        self._version = snapshot.version

    def restore_shards(self, snapshot: Snapshot, shard_ids) -> None:
        """Per-shard sync adoption (see ShardedStateMachine.restore_shards)."""
        snapshot.verify()
        self.store.restore_shards_bytes(snapshot.data, shard_ids)
        self._version = max(self._version, snapshot.version)

    def get_state_summary(self) -> str:
        return f"{len(self.store)} keys / {self.num_shards} shards (vector)"


def _concat_ranges(counts: np.ndarray) -> np.ndarray:
    """[0..c0-1, 0..c1-1, ...] for the per-shard command offsets."""
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, np.int64)
    ends = np.cumsum(counts)
    out = np.arange(total, dtype=np.int64)
    out -= np.repeat(ends - counts, counts)
    return out
