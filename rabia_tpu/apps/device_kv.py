"""Device-resident KV table: the SET-dominant block lane's apply plane.

The round-3 MeshEngine applied every decided wave on the HOST (numpy
hash/probe in :class:`~rabia_tpu.apps.vector_kv.VectorKVStore`), so each
window cycle paid a device->host readback of the decided plane PLUS a
host apply pass. This module moves the table itself onto the device and
fuses "decide the window + apply every decided SET" into ONE jitted
program per window (VERDICT r03 item 2; reference behavior being
accelerated: rabia-kvstore/src/store.rs:313-348 apply_batch). Per
window, only a 3-word status vector crosses the tunnel back: version
responses are DERIVED on the host (a clean all-V1 full-width window
advances every shard's version counter by exactly its wave count), so
the readback is pure latency, not bandwidth.

Tunnel-shaped design (measured on the axon-tunneled v5e: ~100ms
round-trip latency, ~30MB/s host->device):
- the host pre-gathers each op's key/value bytes into fixed-width
  windows bucketed to the LARGEST ACTUAL width in the window (Ku/VWu,
  power-of-two, not the table max) and packs them as u32 words — the
  upload carries ~(key+value) bytes per op, no raw-buffer slack;
- the device table stores keys/values as u32 words too, so matching is
  word compares and updates are one-hot word selects — byte-wise
  dynamic-index gathers/scatters measured ~25ms/wave on TPU, the
  word-select formulation streams at vector speed;
- per-op versions never travel: ``vers[t, s] = shard_ver[s] + t + 1``
  on a clean window, computed by the engine from its host-side mirror.

Scope (the fast lane, not a general store): full-width blocks of
well-formed binary SET ops, one op per covered shard per wave, keys up
to ``key_lanes*8`` bytes, values up to ``value_width`` bytes, at most
``per_shard_capacity`` distinct keys per shard. Anything outside that
envelope — mixed ops, GETs, scalar batches, table overflow, a fault
outcome — makes the engine DEMOTE: the device state syncs down into the
host replica stores once and the cycle re-runs on the host path, which
remains the semantics owner. Behavioral conformance (versions returned,
final key->value/version content) is pinned against the host store in
tests/test_device_kv.py.

Table layout (all arrays sharded over the mesh shard axis; K4 = K/4,
VW4 = VW/4 u32 words):
  used     bool[S, P]      key_words u32[S, P, K4]  key_len  i32[S, P]
  version  i32[S, P]       val_words u32[S, P, VW4] val_len  i32[S, P]
  shard_ver i32[S]

Matching is a FULL-key compare against all P slots of the op's shard
(P is small; no hashing, no probe loop), so slot layout differs from
the host store but the observable key->(value, version) mapping cannot.
"""

from __future__ import annotations

from collections.abc import Sequence
from typing import NamedTuple, Optional, Union

import numpy as np

from rabia_tpu.core.types import V0, V1
from rabia_tpu.apps.vector_kv import _RESP_DT

__all__ = ["DeviceKVTable", "DeviceWindowOps", "MixedFrameGroups"]

_SET_HDR = 3  # binary SET op: u8 opcode(1) + u16 klen + key + value

# fixed odd multipliers for the dictionary packer's 64-bit row hash
# (collisions are VERIFIED against, never trusted — see pack_window_dict)
_HASH_W = (
    np.random.default_rng(0x5EED).integers(1, 2**62, 4, dtype=np.uint64)
    * 2
    + 1
)


def _fold_words(a: np.ndarray) -> np.ndarray:
    """Polynomial-fold a u8[..., B] byte plane into u64[...] — viewed
    as native u32/u64 words (B is a power of two >= 4), mod-2^64."""
    mul = np.uint64(0x9E3779B97F4A7C15)
    if a.shape[-1] % 8 == 0:
        w = a.view(np.uint64)
    else:
        w = a.view(np.uint32)
    h = np.zeros(a.shape[:-1], np.uint64)
    for j in range(w.shape[-1]):
        h *= mul
        h += w[..., j]
    return h


def _bucket(n: int, lo: int = 4) -> int:
    """Round up to a power of two (>= lo, multiple of 4 for u32 views)."""
    b = lo
    while b < n:
        b <<= 1
    return b


class DeviceWindowOps(NamedTuple):
    """One window's ops packed for device apply (host numpy arrays).

    ``kwin``/``vwin`` are the ops' key/value bytes, zero-padded to the
    window's bucketed widths and viewed as u32 words — the fused
    program compares/stores words, never bytes.
    """

    klen: np.ndarray  # i16[W, S] (0 = no op on this (wave, shard))
    vlen: np.ndarray  # i16[W, S]
    kwin: np.ndarray  # u32[W, S, Ku/4]
    vwin: np.ndarray  # u32[W, S, VWu/4]


class DeviceDictOps(NamedTuple):
    """One SET window dictionary-compressed for upload (host numpy).

    Zipf-skewed op streams repeat (key, value) rows heavily within a
    window; the upload then carries each shard's DISTINCT rows once
    (``dk``/``dv``/lens, D rows per shard) plus a byte-wide rank per
    (wave, shard) (``idx``) — for the BASELINE config-5 workload this
    is ~10x fewer bytes over the ~30MB/s tunnel than the row-packed
    form. Reference idea being extended: the serialization layer's
    compression threshold (rabia-core/src/serialization.rs:100-114),
    applied semantically to the device plane.
    """

    idx: np.ndarray  # u8[W, S] within-shard dictionary rank
    dkl: np.ndarray  # i16[S, D] key lengths
    dvl: np.ndarray  # i16[S, D] value lengths
    dk: np.ndarray  # u32[S, D, Ku/4] key words
    dv: np.ndarray  # u32[S, D, VWu/4] value words


def _pad_dict_idx(ops: DeviceDictOps, W: int) -> DeviceDictOps:
    """Pad the per-(wave, shard) rank plane to the static window size.

    Pad waves carry rank 0; that aliases a real dictionary row, but
    every consumer gates on the in-program depth mask (pad waves are
    not ``present``), so the expanded row is never applied or matched.
    Shared by all three dict dispatch paths so the pad semantics cannot
    diverge."""
    if ops.idx.shape[0] < W:
        pad = W - ops.idx.shape[0]
        ops = ops._replace(
            idx=np.concatenate(
                [ops.idx, np.zeros((pad, ops.idx.shape[1]), np.uint8)]
            )
        )
    return ops


def _get_frame(found: bool, ver: int, val: bytes) -> bytes:
    """One GET response frame, byte-for-byte the host store's framing
    (`_result_bin`) — shared by every lazy GET view so the encoding
    lives in exactly one place."""
    from rabia_tpu.apps.kvstore import _result_bin

    if not found:
        return _result_bin(1, 0)
    try:
        return _result_bin(0, ver, val.decode("utf-8"))
    except UnicodeDecodeError:
        return _result_bin(2, ver, "value is not utf-8 text")


class _ShardFrameGroups(Sequence):
    """Shared per-shard lazy response machinery for the window views
    below: group ``j`` covers ``shards[j]`` with exactly one frame,
    materialized by the subclass's ``_frame(shard)`` on client read."""

    __slots__ = ()

    def __len__(self) -> int:
        return len(self.shards)

    def __getitem__(self, j):
        if isinstance(j, slice):
            return [self[i] for i in range(*j.indices(len(self)))]
        if j < 0:
            j += len(self)
        if not (0 <= j < len(self)):
            raise IndexError(j)
        return [self._frame(int(self.shards[j]))]

    def __iter__(self):
        for j in range(len(self)):
            yield self[j]

    def group_counts(self) -> np.ndarray:
        return np.ones(len(self), np.int64)

    def __eq__(self, other) -> bool:
        if not isinstance(other, (list, tuple, Sequence)):
            return NotImplemented
        return len(self) == len(other) and all(
            a == b for a, b in zip(self, other)
        )


class GetFrameGroups(_ShardFrameGroups):
    """Lazy per-shard GET responses over one wave's lookup readback.

    Frames materialize only when a client reads them — the commit path
    stores this view (one object per block, no per-op Python).
    """

    __slots__ = ("shards", "found", "ver", "vlen", "valb")

    def __init__(self, shards, found, ver, vlen, val_words) -> None:
        self.shards = shards  # i64[k] covered shards, group order
        self.found = found  # bool[S]
        self.ver = ver  # i32[S]
        self.vlen = vlen  # i32[S]
        # contiguous: a fetched device array slice can come back with a
        # non-contiguous layout, which .view(uint8) rejects
        self.valb = np.ascontiguousarray(val_words).view(np.uint8)  # u8[S, VW]

    def _frame(self, s: int) -> bytes:
        return _get_frame(
            bool(self.found[s]),
            int(self.ver[s]),
            self.valb[s, : int(self.vlen[s])].tobytes(),
        )


class ResolvedGetFrameGroups(_ShardFrameGroups):
    """Per-shard GET responses resolved from HOST-side value segments —
    the zero-value-download read path.

    Only ``found`` bits and version words cross the tunnel (~5
    bytes/op); the value bytes come from a SNAPSHOT resolver over the
    engine's retained SET windows / re-promotion seed
    (``resolver(s, ver) -> bytes``), justified by (shard, version)
    uniquely identifying content: shard versions are a monotone
    counter, each value assigned exactly once. The snapshot pins
    exactly the segments live at settle time — later evictions in the
    engine cannot invalidate an already-settled response, and the view
    holds no reference back to the engine. Byte-for-byte the host
    store's GET framing; frames materialize on client read. The engine
    only constructs this view after its vectorized resolvability
    check — the resolver cannot miss."""

    __slots__ = ("shards", "found", "ver", "resolver")

    def __init__(self, shards, found, ver, resolver) -> None:
        self.shards = shards  # i64[k] covered shards, group order
        self.found = found  # bool[S]
        self.ver = ver  # i32[S]
        self.resolver = resolver

    def _frame(self, s: int) -> bytes:
        if not self.found[s]:
            return _get_frame(False, 0, b"")
        ver = int(self.ver[s])
        return _get_frame(True, ver, self.resolver(s, ver))


class MixedFrameGroups(_ShardFrameGroups):
    """Lazy per-shard responses for one MIXED wave (SET/GET/DEL/EXISTS
    ops in the same wave): SET ops answer with the derived 6-byte
    version frame (byte-identical to ``VectorShardedKV._vers_frames``),
    GET ops with the host store's GET framing over the lookup readback,
    DEL/EXISTS with their found-bit framing (byte-identical to the
    vector store's ``apply_op_bin``). One object per block, frames
    materialize on client read."""

    __slots__ = ("shards", "kind", "svers", "_get")

    def __init__(self, shards, kind_row, set_vers, get_frames) -> None:
        self.shards = shards  # i64[k] covered shards, group order
        self.kind = kind_row  # i8[S]: 1=SET 2=GET 3=DEL 4=EXISTS
        self.svers = set_vers  # i64[S] derived SET response versions
        # GetFrameGroups/ResolvedGetFrameGroups view for this wave —
        # also the carrier of the found bits DEL/EXISTS frames need
        self._get = get_frames

    def _frame(self, s: int) -> bytes:
        from rabia_tpu.apps.kvstore import _result_bin

        k = int(self.kind[s])
        if k == 1:
            arr = np.zeros(1, _RESP_DT)
            arr["version"] = np.uint32(self.svers[s])
            return arr.tobytes()
        if k == 3:  # DEL: found bit, no version/value (vector_kv framing)
            return _result_bin(0 if self._get.found[s] else 1, 0)
        if k == 4:  # EXISTS: boolean text
            return _result_bin(
                0, 0, "true" if self._get.found[s] else "false"
            )
        return self._get._frame(s)


class DeviceKVTable:
    """Device twin of the vector store's SET lane (see module doc)."""

    def __init__(
        self,
        n_shards: int,
        kernel,  # MeshPhaseKernel — decide plane + sharding owner
        *,
        per_shard_capacity: int = 64,
        key_lanes: int = 4,
        value_width: int = 64,
    ) -> None:
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from rabia_tpu.parallel.mesh import SHARD_AXIS

        self.n_shards = int(n_shards)
        self.kernel = kernel
        self.S = kernel.S  # padded shard width (mesh-divisible)
        self.P = int(per_shard_capacity)
        self.K = int(key_lanes) * 8
        self.VW = _bucket(int(value_width))
        self.K4 = self.K // 4
        self.VW4 = self.VW // 4
        S, Pc = self.S, self.P
        shard_sharding = NamedSharding(kernel.mesh, P(SHARD_AXIS))
        put = lambda a: jax.device_put(a, shard_sharding)
        self.state = (
            put(jnp.zeros((S, Pc), bool)),  # used
            put(jnp.zeros((S, Pc, self.K4), jnp.uint32)),  # key words
            put(jnp.zeros((S, Pc), jnp.int32)),  # key len
            put(jnp.zeros((S, Pc), jnp.int32)),  # version
            put(jnp.zeros((S, Pc, self.VW4), jnp.uint32)),  # value words
            put(jnp.zeros((S, Pc), jnp.int32)),  # value len
            put(jnp.zeros((S,), jnp.int32)),  # shard_ver
        )
        self._fused = None  # built per (W, Ku4, VWu4) — see decide_apply
        self._fused_cache: dict = {}
        # True when the most recent decide_apply/lookup_window built a
        # new program: the engine's latency governor must not read that
        # dispatch's wall time as window latency
        self.compiled_on_last_call = False

    # -- host-side packing -------------------------------------------------

    def _parse_block(self, b):
        """Shared per-block op parse for the window packers: returns
        ``(dbuf, off, klen, vlen, opcode)`` host arrays (dbuf is the
        block's bytes padded with K+header slack so fixed-width gathers
        past the last op stay in bounds), or None when the block is not
        one-op-per-shard / too short to parse."""
        if not bool((b.counts == 1).all()):
            return None
        raw = np.frombuffer(b.data, np.uint8)
        if len(raw) < _SET_HDR * len(b):
            return None
        off = b.cmd_offsets[:-1]
        ln = b.cmd_sizes
        dbuf = np.concatenate([raw, np.zeros(self.K + _SET_HDR, np.uint8)])
        opcode = dbuf[off]
        klen = dbuf[off + 1].astype(np.int64) | (
            dbuf[off + 2].astype(np.int64) << 8
        )
        vlen = ln - _SET_HDR - klen
        return dbuf, off, klen, vlen, opcode

    def _gather_window(self, blocks, allow: str) -> Optional[tuple]:
        """Shared validate + bucket + fixed-width gather behind the
        three window packers (``allow``: "set", "get" or "mixed") —
        including the end-of-buffer gather clamp, maintained ONCE.

        Returns ``(kind i8[W,S], klen i16[W,S], vlen i16[W,S],
        kwin u8[W,S,Ku], vwin u8[W,S,VWu])`` or None when any op is
        outside the requested envelope (wrong opcode, >1 op per shard,
        key/value over the table widths) — the caller demotes."""
        W = len(blocks)
        S = self.S
        parsed = []
        ku = vu = 4
        for b in blocks:
            pb = self._parse_block(b)
            if pb is None:
                return None
            dbuf, off, klen, vlen, opcode = pb
            is_set = opcode == 1
            is_get = opcode == 2
            is_del = opcode == 3
            is_exists = opcode == 4
            kind_ok = {
                "set": is_set,
                "get": is_get,
                # DEL and EXISTS join the mixed envelope: both carry
                # exactly a key (vlen==0 enforced below); DEL clears the
                # matched slot on device, EXISTS is a found-bit read
                "mixed": is_set | is_get | is_del | is_exists,
            }[allow]
            ok = (
                kind_ok
                & (klen > 0)
                & (klen <= self.K)
                & (vlen >= 0)
                & (vlen <= self.VW)
                & (is_set | (vlen == 0))  # GET carries exactly the key
            )
            if not bool(ok.all()):
                return None
            ku = max(ku, _bucket(int(klen.max())))
            vu = max(vu, _bucket(int(vlen.max(initial=0))))
            parsed.append((b, dbuf, off, klen, vlen, opcode))
        kind_w = np.zeros((W, S), np.int8)
        klen_w = np.zeros((W, S), np.int16)
        vlen_w = np.zeros((W, S), np.int16)
        kwin_w = np.zeros((W, S, ku), np.uint8)
        vwin_w = np.zeros((W, S, vu), np.uint8)
        kcols = np.arange(ku)[None, :]
        vcols = np.arange(vu)[None, :]
        # batch the W per-block gathers into ONE: concatenate the block
        # buffers and rebase the offsets — per-window numpy call count
        # drops from ~4W to ~8 (the W-loop was ~40% of the pack cost).
        # A value gather may run past its block's end into the next
        # block's bytes; the vlen mask zeroes those lanes, same as the
        # old per-block end-of-buffer clamp.
        sizes = [len(p[1]) for p in parsed]
        bases = np.zeros(W, np.int64)
        bases[1:] = np.cumsum(sizes[:-1])
        dbuf_all = np.concatenate([p[1] for p in parsed])
        off_all = np.concatenate(
            [p[2] + bases[t] for t, p in enumerate(parsed)]
        )
        klen_all = np.concatenate([p[3] for p in parsed])
        vlen_all = np.concatenate([p[4] for p in parsed])
        op_all = np.concatenate([p[5] for p in parsed])
        sh_all = np.concatenate([p[0].shards for p in parsed])
        n = self.n_shards
        grid = len(sh_all) == W * n and bool(
            (sh_all.reshape(W, n) == np.arange(n)[None, :]).all()
        )
        if grid:
            # full-width sorted blocks (the block lane's shape): the
            # scatter is a contiguous reshape-assign — advanced-index
            # scatters on 500k+ rows were ~half the gather cost
            kind_w[:, :n] = op_all.reshape(W, n)
            klen_w[:, :n] = klen_all.reshape(W, n)
            vlen_w[:, :n] = vlen_all.reshape(W, n)
            if self._native_pack_gather(
                dbuf_all, off_all, klen_all, vlen_all, n, kwin_w, vwin_w
            ):
                return kind_w, klen_w, vlen_w, kwin_w, vwin_w
        kw = dbuf_all[(off_all + _SET_HDR)[:, None] + kcols]
        kw = np.where(kcols < klen_all[:, None], kw, 0)
        vidx = np.minimum(
            (off_all + _SET_HDR + klen_all)[:, None] + vcols,
            len(dbuf_all) - 1,
        )
        vw = dbuf_all[vidx]
        vw = np.where(vcols < vlen_all[:, None], vw, 0)
        if grid:
            kwin_w[:, :n] = kw.reshape(W, n, ku)
            vwin_w[:, :n] = vw.reshape(W, n, vu)
        else:
            t_all = np.repeat(
                np.arange(W), [len(p[2]) for p in parsed]
            )
            kind_w[t_all, sh_all] = op_all
            klen_w[t_all, sh_all] = klen_all
            vlen_w[t_all, sh_all] = vlen_all
            kwin_w[t_all, sh_all] = kw
            vwin_w[t_all, sh_all] = vw
        return kind_w, klen_w, vlen_w, kwin_w, vwin_w

    def _native_pack_gather(
        self, dbuf_all, off_all, klen_all, vlen_all, n, kwin_w, vwin_w
    ) -> bool:
        """One-pass C gather of key/value bytes into the zeroed padded
        planes (GRID fast path only; op i = wave i//n, shard i%n). The
        numpy gather stays the semantics owner — False (library
        unavailable, ``RABIA_PY_DEVPACK=1``, or the C bounds check
        tripping) routes the caller to it. Byte-equivalence with the
        numpy path is pinned in tests/test_device_kv.py."""
        import os

        # =1 opts out, matching the docstring/tests convention — a plain
        # truthiness test made RABIA_PY_DEVPACK=0 ALSO disable the
        # native gather
        if os.environ.get("RABIA_PY_DEVPACK") == "1":
            return False
        from rabia_tpu.native.build import load_hostkernel

        lib = load_hostkernel()
        if lib is None:
            return False
        W_, S_, ku = kwin_w.shape
        vu = vwin_w.shape[2]
        dbuf_all = np.ascontiguousarray(dbuf_all)
        off64 = np.ascontiguousarray(off_all, np.int64)
        klen64 = np.ascontiguousarray(klen_all, np.int64)
        vlen64 = np.ascontiguousarray(vlen_all, np.int64)
        rc = lib.rk_pack_gather(
            dbuf_all.ctypes.data, len(dbuf_all),
            off64.ctypes.data, klen64.ctypes.data, vlen64.ctypes.data,
            len(off64), n, S_, _SET_HDR, ku, vu,
            kwin_w.ctypes.data, vwin_w.ctypes.data,
        )
        if rc != 0:
            # defensive bounds trip: rezero the partially-written
            # planes before the numpy path repopulates them
            kwin_w[...] = 0
            vwin_w[...] = 0
            return False
        return True

    def pack_window(self, blocks) -> Optional[DeviceWindowOps]:
        """Pack SET-only ``blocks`` (one per wave, FIFO order) into
        device inputs; None when outside the write lane's envelope —
        the caller demotes. All numpy, no per-op Python loop."""
        g = self._gather_window(blocks, "set")
        if g is None:
            return None
        return self._rows_from_gathered(g)

    @staticmethod
    def _rows_from_gathered(g: tuple) -> DeviceWindowOps:
        _kind, klen_w, vlen_w, kwin_w, vwin_w = g
        return DeviceWindowOps(
            klen_w,
            vlen_w,
            np.ascontiguousarray(kwin_w).view(np.uint32),
            np.ascontiguousarray(vwin_w).view(np.uint32),
        )

    def pack_window_dict(
        self, blocks, max_dict: int = 32
    ) -> Optional[DeviceDictOps]:
        """Dictionary-compress a SET window: per-shard distinct
        (key, value) rows + a rank per (wave, shard).

        Vectorized per-shard uniqueness via a 64-bit universal hash
        with FULL verification — every op row is compared bytewise
        against the dictionary row its rank points to, so a hash
        collision (or more than ``max_dict`` distinct rows per shard)
        returns None and the caller falls back to the row-packed
        upload. Correctness never rides on the hash."""
        g = self._gather_window(blocks, "set")
        if g is None:
            return None
        return self._dict_from_gathered(g, max_dict)

    def _dict_from_gathered(
        self, g: tuple, max_dict: int = 32
    ) -> Optional[DeviceDictOps]:
        _kind, klen_w, vlen_w, kwin_w, vwin_w = g
        W, S = klen_w.shape
        ku = kwin_w.shape[2]
        vu = vwin_w.shape[2]
        # 64-bit row hash: fold the byte planes as native u32/u64 WORDS
        # (widths are powers of two, so the views are exact) — no
        # [W,S,B]->u64 astype, no matmul; per-SHARD uniqueness via
        # axis-1 sorts over the W window positions — O(S * W log W) on
        # short rows instead of a global (S*W)-row lexsort. Both were
        # the dominant pack costs at W=128.
        h = klen_w.astype(np.uint64) * _HASH_W[0]
        h += vlen_w.astype(np.uint64) * _HASH_W[1]
        h += _fold_words(kwin_w) * _HASH_W[2]
        h += _fold_words(vwin_w) * _HASH_W[3]
        if bool((h == h[:1]).all()):
            # every wave repeats its shard's single row (the steady
            # state of uniform workloads): D=1 with wave 0 as the
            # representative, no per-shard sort — the argsort was the
            # dominant dict-build cost once the gather went native.
            # Verification below is the same full byte compare the
            # sorted path runs; the hash is still never trusted.
            ok = (
                (klen_w == klen_w[:1]).all()
                and (vlen_w == vlen_w[:1]).all()
                and (kwin_w == kwin_w[:1]).all()
                and (vwin_w == vwin_w[:1]).all()
            )
            if not bool(ok):
                return None
            # explicit copies: contiguous row views would alias (and
            # pin) the full [W, S, *] gather planes for as long as the
            # window is in flight — W times the bytes actually needed
            return DeviceDictOps(
                np.zeros((W, S), np.uint8),
                klen_w[:1].T.copy(),
                vlen_w[:1].T.copy(),
                kwin_w[0][:, None].copy().view(np.uint32),
                vwin_w[0][:, None].copy().view(np.uint32),
            )
        h = np.ascontiguousarray(h.T)  # [S, W]
        o = np.argsort(h, axis=1, kind="stable")
        hs = np.take_along_axis(h, o, axis=1)
        new = np.ones((S, W), bool)
        new[:, 1:] = hs[:, 1:] != hs[:, :-1]
        rank_sorted = np.cumsum(new, axis=1) - 1  # [S, W]
        D = int(rank_sorted[:, -1].max()) + 1
        if D > max_dict:
            return None
        rank = np.empty((S, W), np.int64)
        np.put_along_axis(rank, o, rank_sorted, axis=1)
        # representative wave per (shard, dict row): first occurrence
        rep_t = np.zeros((S, D), np.int64)
        s_new, pos_new = np.nonzero(new)
        rep_t[s_new, rank_sorted[s_new, pos_new]] = o[s_new, pos_new]
        s_cols = np.arange(S)[:, None]
        dkl = klen_w[rep_t, s_cols]  # [S, D]
        dvl = vlen_w[rep_t, s_cols]
        dkb = kwin_w[rep_t, s_cols]  # [S, D, ku]
        dvb = vwin_w[rep_t, s_cols]
        # hash verification: every op's bytes must equal its dictionary
        # row's bytes — a collision (2^-64, or adversarial) falls back
        # to the row-packed upload; correctness never rides on the hash
        rank_ts = rank.T  # [W, S]
        # (D == 1 can't reach here: the all-equal pre-check above
        # returned before the argsort in that case)
        sc = np.arange(S)[None, :]
        ok = (
            (klen_w == dkl[sc, rank_ts]).all()
            and (vlen_w == dvl[sc, rank_ts]).all()
            and (kwin_w == dkb[sc, rank_ts]).all()
            and (vwin_w == dvb[sc, rank_ts]).all()
        )
        if not bool(ok):
            return None
        return DeviceDictOps(
            np.ascontiguousarray(rank_ts.astype(np.uint8)),
            np.ascontiguousarray(dkl),
            np.ascontiguousarray(dvl),
            np.ascontiguousarray(dkb).view(np.uint32),
            np.ascontiguousarray(dvb).view(np.uint32),
        )

    def pack_window_auto(self, blocks):
        """Dictionary-compressed SET window when the stream repeats
        enough to pay off, else the row-packed form; None demotes.
        One gather pass serves both attempts."""
        g = self._gather_window(blocks, "set")
        if g is None:
            return None
        d = self._dict_from_gathered(g)
        if d is not None:
            return d
        return self._rows_from_gathered(g)

    def pack_mixed_window(self, blocks) -> Optional[tuple]:
        """Pack blocks whose ops are ANY interleaving of binary SET and
        GET — per op, not per block — into one device window.

        Returns ``(kind i8[W, S], DeviceWindowOps)`` (kind 0 = no op,
        1 = SET, 2 = GET; GET rows carry the key with vlen 0) or None
        when any op is outside the union envelope — the caller demotes.
        This removes the FIFO kind-boundary splits: an interleaved
        SET/GET workload runs full windows instead of
        window-per-kind-run (reference applies a mixed batch in one
        pass too: rabia-kvstore/src/store.rs:313-348)."""
        g = self._gather_window(blocks, "mixed")
        if g is None:
            return None
        kind_w, klen_w, vlen_w, kwin_w, vwin_w = g
        return kind_w, DeviceWindowOps(
            klen_w,
            vlen_w,
            np.ascontiguousarray(kwin_w).view(np.uint32),
            np.ascontiguousarray(vwin_w).view(np.uint32),
        )

    def pack_mixed_window_auto(self, blocks) -> Optional[tuple]:
        """Mixed window with the dictionary-compressed upload when the
        stream repeats enough to pay off, else row-packed; None demotes.

        Returns ``(kind, ops, vlen_plane, vwin_plane)`` where ``ops``
        is :class:`DeviceDictOps` or :class:`DeviceWindowOps` and the
        two planes are the FULL per-wave value planes — the engine's
        host-side value segments need them regardless of how the ops
        crossed the tunnel (a GET answers from (shard, version) →
        bytes, which only the uncompressed planes provide). One gather
        pass serves the dict attempt, the row fallback, and the
        segment planes."""
        g = self._gather_window(blocks, "mixed")
        if g is None:
            return None
        kind_w, klen_w, vlen_w, kwin_w, vwin_w = g
        vwin_u32 = np.ascontiguousarray(vwin_w).view(np.uint32)
        d = self._dict_from_gathered(g)
        if d is not None:
            return kind_w, d, vlen_w, vwin_u32
        return (
            kind_w,
            DeviceWindowOps(
                klen_w,
                vlen_w,
                np.ascontiguousarray(kwin_w).view(np.uint32),
                vwin_u32,
            ),
            vlen_w,
            vwin_u32,
        )

    # -- the fused programs --------------------------------------------------

    def _build_lookup(self, Ku4: int, D: Optional[int] = None):
        """Jitted GET window: consensus slot window + a read-only match
        over the table (no state mutation, no version advance). ``D``
        selects the dictionary-upload variant (per-shard distinct keys
        + a rank per (wave, shard), expanded on device)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        kernel = self.kernel
        Pc = self.P
        K4 = self.K4
        n = self.n_shards
        I8, I32 = jnp.int8, jnp.int32
        col = jnp.arange(self.S) < n

        def lookup(state, alive, base, depth, klen_t, kwin_t, *, W,
                   max_phases):
            used, keyw, klen, ver, valw, vlen, _sver = state
            wave = jnp.arange(W, dtype=I32)[:, None] < depth
            present = wave & col[None, :]
            votes = jnp.where(
                present[:, :, None], I8(V1), I8(V0)
            ) * jnp.ones((1, 1, kernel.R), I8)
            decided = kernel.slot_window(
                votes, alive, base, n_slots=W, max_phases=max_phases
            )
            all_v1 = jnp.all(jnp.where(present, decided == V1, True))

            def match_body(klen_w, kwin_w):
                klen_w = klen_w.astype(jnp.int32)
                eq = (
                    used
                    & (klen == klen_w[:, None])
                    & (keyw == kwin_w[:, None, :]).all(-1)
                )  # [S, P]
                found = eq.any(1) & (klen_w > 0)
                oh = eq & found[:, None]  # at most one slot matches
                rver = (ver * oh).sum(1)
                rvlen = (vlen * oh).sum(1)
                rval = (valw * oh[:, :, None]).sum(1)  # [S, VW4] u32
                return found, rver, rvlen, rval

            if D is None:
                kwin_full = jnp.pad(
                    kwin_t, ((0, 0), (0, 0), (0, K4 - Ku4))
                )
                xs = (klen_t, kwin_full)

                def wave_match(_, inp):
                    return None, match_body(*inp)
            else:
                # dictionary upload: klen_t is (idx, dkl, dk) — the key
                # dictionary only, value planes never cross the tunnel
                # here; expand each wave's per-shard rank into the
                # shard's distinct key row (GET streams repeat keys
                # like SET streams repeat rows)
                idx, dkl_raw, dk_raw = klen_t
                dk_full = jnp.pad(dk_raw, ((0, 0), (0, 0), (0, K4 - Ku4)))
                dkl = dkl_raw.astype(I32)
                dr = jnp.arange(D, dtype=I32)[None, :]
                xs = (idx,)

                def wave_match(_, inp):
                    (idx_w,) = inp
                    oh = idx_w.astype(I32)[:, None] == dr  # [S, D]
                    ohu = oh.astype(jnp.uint32)[:, :, None]
                    return None, match_body(
                        (dkl * oh).sum(1), (dk_full * ohu).sum(1)
                    )

            _, (found, rver, rvlen, rval) = lax.scan(wave_match, None, xs)
            return all_v1.astype(I32), found, rver, rvlen, rval

        return jax.jit(lookup, static_argnames=("W", "max_phases"))

    def pack_get_window_auto(self, blocks):
        """GET window with the dictionary-compressed upload when the key
        stream repeats enough, else the row-packed ``(klen, kwin)``
        pair; None demotes. One gather pass serves both attempts."""
        g = self._gather_window(blocks, "get")
        if g is None:
            return None
        d = self._dict_from_gathered(g)
        if d is not None:
            return d
        _kind, klen_w, _vlen, kwin_w, _vwin = g
        return klen_w, np.ascontiguousarray(kwin_w).view(np.uint32)

    def lookup_window(self, alive, base, depth: int, ops, W: int,
                      max_phases: int = 4, state=None):
        """Dispatch one consensus+lookup window against the CURRENT
        table (read-only; ``state`` overrides it so the pipelined lane
        can chain on an in-flight window's output). ``ops`` is either a
        row-packed ``(klen i16[W,S], kwin u32[W,S,Ku4])`` pair or a
        :class:`DeviceDictOps` (key dictionary; value planes unused).
        Returns DEVICE handles
        ``(all_v1, found[W,S], ver[W,S], vlen[W,S], val_words[W,S,VW4])``
        — the caller fetches selectively: found+ver are ~5 bytes/op;
        the value planes (~70 bytes/op) only need to cross the tunnel
        when a version cannot be resolved from the host-side value
        segments (see mesh_engine._dev_resolve), which is the eviction
        edge case, not the steady state."""
        import jax.numpy as jnp

        if isinstance(ops, DeviceDictOps):
            ops = _pad_dict_idx(ops, W)
            D = ops.dkl.shape[1]
            key = ("getdict", W, ops.dk.shape[2], D)
            fn = self._fused_cache.get(key)
            self.compiled_on_last_call = fn is None
            if fn is None:
                fn = self._build_lookup(key[2], D)
                self._fused_cache[key] = fn
            # only the key dictionary crosses the tunnel: the lookup
            # never reads values, and uploading the dead dv plane would
            # cost as much as the keys themselves at D=32
            kdict = (
                jnp.asarray(ops.idx),
                jnp.asarray(ops.dkl),
                jnp.asarray(ops.dk),
            )
            return fn(
                self.state if state is None else state,
                self.kernel.place(jnp.asarray(alive)),
                jnp.asarray(base),
                jnp.int32(depth),
                kdict,
                None,
                W=W,
                max_phases=max_phases,
            )
        klen, kwin = ops
        if klen.shape[0] < W:
            pad = W - klen.shape[0]
            klen = np.concatenate(
                [klen, np.zeros((pad,) + klen.shape[1:], klen.dtype)]
            )
            kwin = np.concatenate(
                [kwin, np.zeros((pad,) + kwin.shape[1:], kwin.dtype)]
            )
        key = ("get", W, kwin.shape[2])
        fn = self._fused_cache.get(key)
        self.compiled_on_last_call = fn is None
        if fn is None:
            fn = self._build_lookup(kwin.shape[2])
            self._fused_cache[key] = fn
        return fn(
            self.state if state is None else state,
            self.kernel.place(jnp.asarray(alive)),
            jnp.asarray(base),
            jnp.int32(depth),
            jnp.asarray(klen),
            jnp.asarray(kwin),
            W=W,
            max_phases=max_phases,
        )

    def _build_lookup_only(self, Ku4: int, D: Optional[int] = None):
        """Jitted CONSENSUS-FREE read window: the same read-only match
        scan as :meth:`_build_lookup`, with the slot window removed
        entirely — no votes, no phases, no collective. The read-index
        lane dispatches these for probe-covered GETs (the gateway's
        shared quorum probe round already established linearizability;
        the device table only has to answer), so reads consume ZERO
        consensus slots and the program crosses zero ICI bytes on a
        multi-chip mesh (pinned by benchmarks/ici_model.py via jaxpr
        inspection)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        K4 = self.K4
        I32 = jnp.int32

        def lookup_only(state, klen_t, kwin_t, *, W):
            used, keyw, klen, ver, valw, vlen, _sver = state

            def match_body(klen_w, kwin_w):
                klen_w = klen_w.astype(jnp.int32)
                eq = (
                    used
                    & (klen == klen_w[:, None])
                    & (keyw == kwin_w[:, None, :]).all(-1)
                )  # [S, P]
                found = eq.any(1) & (klen_w > 0)
                oh = eq & found[:, None]  # at most one slot matches
                rver = (ver * oh).sum(1)
                rvlen = (vlen * oh).sum(1)
                rval = (valw * oh[:, :, None]).sum(1)  # [S, VW4] u32
                return found, rver, rvlen, rval

            if D is None:
                kwin_full = jnp.pad(
                    kwin_t, ((0, 0), (0, 0), (0, K4 - Ku4))
                )
                xs = (klen_t, kwin_full)

                def wave_match(_, inp):
                    return None, match_body(*inp)
            else:
                idx, dkl_raw, dk_raw = klen_t
                dk_full = jnp.pad(dk_raw, ((0, 0), (0, 0), (0, K4 - Ku4)))
                dkl = dkl_raw.astype(I32)
                dr = jnp.arange(D, dtype=I32)[None, :]
                xs = (idx,)

                def wave_match(_, inp):
                    (idx_w,) = inp
                    oh = idx_w.astype(I32)[:, None] == dr  # [S, D]
                    ohu = oh.astype(jnp.uint32)[:, :, None]
                    return None, match_body(
                        (dkl * oh).sum(1), (dk_full * ohu).sum(1)
                    )

            _, (found, rver, rvlen, rval) = lax.scan(wave_match, None, xs)
            return found, rver, rvlen, rval

        return jax.jit(lookup_only, static_argnames=("W",))

    def lookup_only(self, ops, W: int, state=None):
        """Dispatch one consensus-free read window (the read-index
        lane's probe serve): ``ops`` exactly as :meth:`lookup_window`
        takes them (row-packed ``(klen, kwin)`` or a
        :class:`DeviceDictOps`), padded to the static window size ``W``
        (padding waves carry klen 0 and match nothing). Returns DEVICE
        handles ``(found[W,S], ver[W,S], vlen[W,S], val_words)`` — no
        all_v1 scalar, because nothing was decided. The caller fetches
        meta-only in the steady state, exactly like the slot-consuming
        GET window."""
        import jax.numpy as jnp

        if isinstance(ops, DeviceDictOps):
            ops = _pad_dict_idx(ops, W)
            D = ops.dkl.shape[1]
            key = ("rodict", W, ops.dk.shape[2], D)
            fn = self._fused_cache.get(key)
            self.compiled_on_last_call = fn is None
            if fn is None:
                fn = self._build_lookup_only(key[2], D)
                self._fused_cache[key] = fn
            kdict = (
                jnp.asarray(ops.idx),
                jnp.asarray(ops.dkl),
                jnp.asarray(ops.dk),
            )
            return fn(
                self.state if state is None else state,
                kdict,
                None,
                W=W,
            )
        klen, kwin = ops
        if klen.shape[0] < W:
            pad = W - klen.shape[0]
            klen = np.concatenate(
                [klen, np.zeros((pad,) + klen.shape[1:], klen.dtype)]
            )
            kwin = np.concatenate(
                [kwin, np.zeros((pad,) + kwin.shape[1:], kwin.dtype)]
            )
        key = ("ro", W, kwin.shape[2])
        fn = self._fused_cache.get(key)
        self.compiled_on_last_call = fn is None
        if fn is None:
            fn = self._build_lookup_only(key[2])
            self._fused_cache[key] = fn
        return fn(
            self.state if state is None else state,
            jnp.asarray(klen),
            jnp.asarray(kwin),
            W=W,
        )

    @staticmethod
    def _apply_set_wave(carry, ok_w, klen_t, vlen_t, kwin_t, vwin_t, Pc):
        """One SET wave over the table state — shared by the row-packed
        and dictionary-packed fused programs.

        Match: word compare against all P slots of the shard; stored
        tails beyond the op key are zero, as are the padded op words,
        so prefix equality + length equality IS full-key equality.
        Updates are one-hot word SELECTS, not dynamic-index scatters
        (which lower poorly on TPU)."""
        import jax.numpy as jnp

        used, keyw, klen, ver, valw, vlen, sver = carry
        eq = (
            used
            & (klen == klen_t[:, None])
            & (keyw == kwin_t[:, None, :]).all(-1)
        )  # [S, P]
        found = eq.any(1)
        slot = jnp.where(found, jnp.argmax(eq, 1), jnp.argmax(~used, 1))
        full = used.all(1)
        apply = ok_w & (found | ~full)
        overflow = jnp.any(ok_w & ~found & full)
        onehot = (
            jnp.arange(Pc)[None, :] == slot[:, None]
        ) & apply[:, None]  # [S, P]
        oh3 = onehot[:, :, None]
        used = used | onehot
        keyw = jnp.where(oh3, kwin_t[:, None, :], keyw)
        klen = jnp.where(onehot, klen_t[:, None], klen)
        new_ver = sver + 1
        ver = jnp.where(onehot, new_ver[:, None], ver)
        valw = jnp.where(oh3, vwin_t[:, None, :], valw)
        vlen = jnp.where(onehot, vlen_t[:, None], vlen)
        sver = jnp.where(apply, new_ver, sver)
        return (used, keyw, klen, ver, valw, vlen, sver), overflow

    def _build_fused_dict(self, Ku4: int, VWu4: int, D: int):
        """Jitted SET window on DICTIONARY-compressed ops: each wave
        expands its (wave, shard) rank into the shard's dictionary row
        with a one-hot select (D is small), then applies the shared
        SET wave body — upload bytes shrink ~10x on repetitive
        (Zipf) streams, the table math is unchanged."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        kernel = self.kernel
        S, Pc = self.S, self.P
        K4, VW4 = self.K4, self.VW4
        n = self.n_shards
        I8, I32 = jnp.int8, jnp.int32
        col = jnp.arange(S) < n

        def fused(state, alive, base, depth, ops, *, W, max_phases):
            wave = jnp.arange(W, dtype=I32)[:, None] < depth
            present = wave & col[None, :]
            votes = jnp.where(
                present[:, :, None], I8(V1), I8(V0)
            ) * jnp.ones((1, 1, kernel.R), I8)
            decided = kernel.slot_window(
                votes, alive, base, n_slots=W, max_phases=max_phases
            )
            all_v1 = jnp.all(jnp.where(present, decided == V1, True))

            dk_full = jnp.pad(ops.dk, ((0, 0), (0, 0), (0, K4 - Ku4)))
            dv_full = jnp.pad(ops.dv, ((0, 0), (0, 0), (0, VW4 - VWu4)))
            dkl = ops.dkl.astype(I32)
            dvl = ops.dvl.astype(I32)
            dr = jnp.arange(D, dtype=I32)[None, :]

            def wave_step(carry, inp):
                ok_w, idx_w = inp
                oh = idx_w.astype(I32)[:, None] == dr  # [S, D]
                ohu = oh.astype(jnp.uint32)[:, :, None]
                klen_t = (dkl * oh).sum(1)
                vlen_t = (dvl * oh).sum(1)
                kwin_t = (dk_full * ohu).sum(1)
                vwin_t = (dv_full * ohu).sum(1)
                return DeviceKVTable._apply_set_wave(
                    carry, ok_w, klen_t, vlen_t, kwin_t, vwin_t, Pc
                )

            new_state, over_w = lax.scan(
                wave_step, state, (present, ops.idx)
            )
            flags = jnp.stack(
                [
                    all_v1.astype(I32),
                    jnp.any(over_w).astype(I32),
                    jnp.any(
                        new_state[6] >= jnp.int32(2**31 - 2)
                    ).astype(I32),
                ]
            )
            return new_state, flags

        return jax.jit(fused, static_argnames=("W", "max_phases"))

    def _build_fused(self, Ku4: int, VWu4: int):
        import jax
        import jax.numpy as jnp
        from jax import lax

        kernel = self.kernel
        S, Pc = self.S, self.P
        K4, VW4 = self.K4, self.VW4
        n = self.n_shards
        I8, I32 = jnp.int8, jnp.int32
        col = jnp.arange(S) < n  # real (non-padding) shards

        def fused(state, alive, base, depth, ops, *, W, max_phases):
            # initial votes generated on device: every live replica
            # proposes V1 for the depth in-window waves of real shards
            wave = jnp.arange(W, dtype=I32)[:, None] < depth  # [W, 1]
            present = wave & col[None, :]  # [W, S]
            votes = jnp.where(
                present[:, :, None], I8(V1), I8(V0)
            ) * jnp.ones((1, 1, kernel.R), I8)
            decided = kernel.slot_window(
                votes, alive, base, n_slots=W, max_phases=max_phases
            )  # i8[W, S]
            all_v1 = jnp.all(jnp.where(present, decided == V1, True))

            # pad the op windows to the table widths once, outside the
            # scan (zero tails keep prefix-compare == full-key compare)
            kwin_full = jnp.pad(ops.kwin, ((0, 0), (0, 0), (0, K4 - Ku4)))
            vwin_full = jnp.pad(ops.vwin, ((0, 0), (0, 0), (0, VW4 - VWu4)))

            def wave_step(carry, inp):
                ok_w, klen_t, vlen_t, kwin_t, vwin_t = inp
                # op columns travel as i16 (upload bytes are the tunnel
                # wall); table arithmetic stays i32
                return DeviceKVTable._apply_set_wave(
                    carry,
                    ok_w,
                    klen_t.astype(jnp.int32),
                    vlen_t.astype(jnp.int32),
                    kwin_t,
                    vwin_t,
                    Pc,
                )

            new_state, over_w = lax.scan(
                wave_step,
                state,
                (present, ops.klen, ops.vlen, kwin_full, vwin_full),
            )
            flags = jnp.stack(
                [
                    all_v1.astype(I32),
                    jnp.any(over_w).astype(I32),
                    jnp.any(
                        new_state[6] >= jnp.int32(2**31 - 2)
                    ).astype(I32),
                ]
            )
            return new_state, flags

        return jax.jit(fused, static_argnames=("W", "max_phases"))

    def decide_apply(self, alive, base, depth: int, ops: DeviceWindowOps,
                     W: int, max_phases: int = 4, state=None):
        """Dispatch one fused decide+apply window. Returns device handles
        ``(new_state, flags)`` where ``flags`` is i32[3]:
        ``[all_v1, overflow, ver_overflow]`` — 12 bytes of readback.
        The caller ADOPTS ``new_state`` only when the flags are clean
        (and then derives version responses from its host-side counter
        mirror); otherwise it keeps the old state object (purely
        functional program — nothing was donated) and demotes.
        Accepts row-packed (:class:`DeviceWindowOps`) or
        dictionary-packed (:class:`DeviceDictOps`) windows."""
        import jax.numpy as jnp

        if isinstance(ops, DeviceDictOps):
            return self._decide_apply_dict(
                alive, base, depth, ops, W, max_phases, state
            )
        if ops.klen.shape[0] < W:
            # pack_window covers only the depth in-flight waves; pad to
            # the static window size (filler waves are masked out by the
            # in-program depth gate)
            pad = W - ops.klen.shape[0]
            ops = DeviceWindowOps(
                *(
                    np.concatenate(
                        [a, np.zeros((pad,) + a.shape[1:], a.dtype)]
                    )
                    for a in ops
                )
            )
        key = (W, ops.kwin.shape[2], ops.vwin.shape[2])
        fused = self._fused_cache.get(key)
        self.compiled_on_last_call = fused is None
        if fused is None:
            fused = self._build_fused(key[1], key[2])
            self._fused_cache[key] = fused
        dev_ops = DeviceWindowOps(*(jnp.asarray(a) for a in ops))
        return fused(
            self.state if state is None else state,
            self.kernel.place(jnp.asarray(alive)),
            jnp.asarray(base),
            jnp.int32(depth),
            dev_ops,
            W=W,
            max_phases=max_phases,
        )

    def _build_mixed(self, Ku4: int, VWu4: int, Gp: int,
                     D: Optional[int] = None):
        """Jitted MIXED window: consensus + per-op kind mask over the
        same table — SET ops mutate (identical update rules to
        :meth:`_build_fused`), GET ops read the wave-entry state (reads
        in wave t observe every apply from waves < t — the host store's
        FIFO semantics), all in ONE scan over the waves.

        ``Gp`` (static) is the padded count of GET-bearing waves; the
        program gathers those waves' lookup outputs ON DEVICE (the host
        knows the wave indices at pack time) and packs found/ver/vlen
        into one two-plane i32 tensor, so the readback is two transfers
        — not four take-dispatch round-trips over the ~12MB/s tunnel
        (measured: the four separate fetches cost ~0.5s per window,
        more than the window's compute).

        ``D`` selects the DICTIONARY-compressed upload variant: ops
        arrive as per-shard distinct rows + a rank per (wave, shard)
        (:class:`DeviceDictOps` — GET ops are (key, empty value) rows),
        expanded on device exactly like the pure-SET dict program. Same
        table math either way; only the upload shape differs."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        kernel = self.kernel
        S, Pc = self.S, self.P
        K4, VW4 = self.K4, self.VW4
        n = self.n_shards
        I8, I32 = jnp.int8, jnp.int32
        col = jnp.arange(S) < n

        def mixed(state, alive, base, depth, kind_w, gidx, ops, *, W,
                  max_phases):
            wave = jnp.arange(W, dtype=I32)[:, None] < depth
            present = wave & col[None, :]
            votes = jnp.where(
                present[:, :, None], I8(V1), I8(V0)
            ) * jnp.ones((1, 1, kernel.R), I8)
            decided = kernel.slot_window(
                votes, alive, base, n_slots=W, max_phases=max_phases
            )
            all_v1 = jnp.all(jnp.where(present, decided == V1, True))

            def step_body(carry, ok_w, kind_t, klen_t, vlen_t, kwin_t,
                          vwin_t):
                used, keyw, klen, ver, valw, vlen, sver = carry
                klen_t = klen_t.astype(jnp.int32)
                vlen_t = vlen_t.astype(jnp.int32)
                kind_t = kind_t.astype(jnp.int32)
                eq = (
                    used
                    & (klen == klen_t[:, None])
                    & (keyw == kwin_t[:, None, :]).all(-1)
                )  # [S, P]
                found = eq.any(1)
                # reads (GET/DEL/EXISTS found bits) are against the
                # wave-entry state, before this wave's applies touch the
                # table; gver/gval carry data for GET ops only (a DEL's
                # response is its found bit, an EXISTS's is a boolean)
                rsel = (kind_t >= 2) & (klen_t > 0)
                gsel = found & rsel
                oh_get = eq & (found & (kind_t == 2))[:, None]
                gver = (ver * oh_get).sum(1)
                gvlen = (vlen * oh_get).sum(1)
                gval = (valw * oh_get[:, :, None]).sum(1)
                # DEL applies: clear the matched slot (the table is
                # compare-all associative — no probe chains to repair,
                # unlike the host twin's open addressing) and bump the
                # shard version exactly like the host store's delete()
                # does on a successful delete
                del_hit = ok_w & (kind_t == 3) & found
                used = used & ~(eq & del_hit[:, None])
                sver = sver + del_hit
                # SET applies: same one-hot word-select update as the
                # pure-SET program, gated on this op BEING a SET
                is_set = ok_w & (kind_t == 1)
                slot = jnp.where(
                    found, jnp.argmax(eq, 1), jnp.argmax(~used, 1)
                )
                full = used.all(1)
                apply = is_set & (found | ~full)
                overflow = jnp.any(is_set & ~found & full)
                onehot = (
                    jnp.arange(Pc)[None, :] == slot[:, None]
                ) & apply[:, None]
                oh3 = onehot[:, :, None]
                used = used | onehot
                keyw = jnp.where(oh3, kwin_t[:, None, :], keyw)
                klen = jnp.where(onehot, klen_t[:, None], klen)
                new_ver = sver + 1
                ver = jnp.where(onehot, new_ver[:, None], ver)
                valw = jnp.where(oh3, vwin_t[:, None, :], valw)
                vlen = jnp.where(onehot, vlen_t[:, None], vlen)
                sver = jnp.where(apply, new_ver, sver)
                return (used, keyw, klen, ver, valw, vlen, sver), (
                    overflow,
                    gsel,
                    gver,
                    gvlen,
                    gval,
                )

            if D is None:
                # row-packed: per-wave planes uploaded directly
                kwin_full = jnp.pad(
                    ops.kwin, ((0, 0), (0, 0), (0, K4 - Ku4))
                )
                vwin_full = jnp.pad(
                    ops.vwin, ((0, 0), (0, 0), (0, VW4 - VWu4))
                )
                xs = (
                    present, kind_w, ops.klen, ops.vlen, kwin_full,
                    vwin_full,
                )

                def wave_step(carry, inp):
                    ok_w, kind_t, klen_t, vlen_t, kwin_t, vwin_t = inp
                    return step_body(
                        carry, ok_w, kind_t, klen_t, vlen_t, kwin_t, vwin_t
                    )
            else:
                # dictionary-packed: expand each wave's per-shard rank
                # into the shard's dictionary row (same one-hot select
                # as the pure-SET dict program — GET rows are just
                # (key, empty value) dictionary entries)
                dk_full = jnp.pad(ops.dk, ((0, 0), (0, 0), (0, K4 - Ku4)))
                dv_full = jnp.pad(
                    ops.dv, ((0, 0), (0, 0), (0, VW4 - VWu4))
                )
                dkl = ops.dkl.astype(I32)
                dvl = ops.dvl.astype(I32)
                dr = jnp.arange(D, dtype=I32)[None, :]
                xs = (present, kind_w, ops.idx)

                def wave_step(carry, inp):
                    ok_w, kind_t, idx_w = inp
                    oh = idx_w.astype(I32)[:, None] == dr  # [S, D]
                    ohu = oh.astype(jnp.uint32)[:, :, None]
                    return step_body(
                        carry,
                        ok_w,
                        kind_t,
                        (dkl * oh).sum(1),
                        (dvl * oh).sum(1),
                        (dk_full * ohu).sum(1),
                        (dv_full * ohu).sum(1),
                    )

            new_state, (over_w, gfound, gver, gvlen, gval) = lax.scan(
                wave_step, state, xs
            )
            flags = jnp.stack(
                [
                    all_v1.astype(I32),
                    jnp.any(over_w).astype(I32),
                    jnp.any(
                        new_state[6] >= jnp.int32(2**31 - 2)
                    ).astype(I32),
                ]
            )
            # device-side gather of the GET-bearing waves + two-plane
            # meta pack: [0]=version, [1]=(vlen<<1)|found
            gfound_g = jnp.take(gfound, gidx, axis=0).astype(I32)
            gver_g = jnp.take(gver, gidx, axis=0)
            gvlen_g = jnp.take(gvlen, gidx, axis=0)
            gval_g = jnp.take(gval, gidx, axis=0)
            meta = jnp.stack([gver_g, (gvlen_g << 1) | gfound_g])
            return new_state, flags, meta, gval_g

        return jax.jit(mixed, static_argnames=("W", "max_phases"))

    def mixed_apply(self, alive, base, depth: int, kind: np.ndarray,
                    get_waves: np.ndarray,
                    ops: Union[DeviceWindowOps, DeviceDictOps], W: int,
                    max_phases: int = 4, state=None):
        """Dispatch one mixed decide+apply+lookup window. Returns device
        handles ``(new_state, flags, meta, gval)`` where ``meta`` is
        i32[2, Gp, S] ([0]=version, [1]=(vlen<<1)|found) and ``gval``
        u32[Gp, S, VW4], both gathered to the ``get_waves`` rows (padded
        to a power of two; the caller maps real rows). The caller reads
        the 12-byte flags first and fetches meta/gval only on a clean
        window. ``state`` overrides the table state to run against (the
        pipelined lane chains on the previous in-flight window's
        unresolved output, same as :meth:`decide_apply`)."""
        import jax.numpy as jnp

        is_dict = isinstance(ops, DeviceDictOps)
        if is_dict:
            ops = _pad_dict_idx(ops, W)
        elif ops.klen.shape[0] < W:
            pad = W - ops.klen.shape[0]
            ops = DeviceWindowOps(
                *(
                    np.concatenate(
                        [a, np.zeros((pad,) + a.shape[1:], a.dtype)]
                    )
                    for a in ops
                )
            )
        if kind.shape[0] < W:
            kind = np.concatenate(
                [kind, np.zeros((W - kind.shape[0], kind.shape[1]), kind.dtype)]
            )
        Gp = 1
        while Gp < max(1, len(get_waves)):
            Gp <<= 1
        gidx = np.zeros(Gp, np.int32)
        gidx[: len(get_waves)] = get_waves
        if is_dict:
            D = ops.dkl.shape[1]
            key = ("mixdict", W, ops.dk.shape[2], ops.dv.shape[2], Gp, D)
            build = lambda: self._build_mixed(key[2], key[3], Gp, D)
        else:
            key = ("mix", W, ops.kwin.shape[2], ops.vwin.shape[2], Gp)
            build = lambda: self._build_mixed(key[2], key[3], Gp)
        fn = self._fused_cache.get(key)
        self.compiled_on_last_call = fn is None
        if fn is None:
            fn = build()
            self._fused_cache[key] = fn
        dev_ops = type(ops)(*(jnp.asarray(a) for a in ops))
        return fn(
            self.state if state is None else state,
            self.kernel.place(jnp.asarray(alive)),
            jnp.asarray(base),
            jnp.int32(depth),
            jnp.asarray(kind),
            jnp.asarray(gidx),
            dev_ops,
            W=W,
            max_phases=max_phases,
        )

    def _decide_apply_dict(self, alive, base, depth, ops: DeviceDictOps,
                           W: int, max_phases: int, state=None):
        import jax.numpy as jnp

        ops = _pad_dict_idx(ops, W)
        D = ops.dkl.shape[1]
        key = ("dictset", W, ops.dk.shape[2], ops.dv.shape[2], D)
        fn = self._fused_cache.get(key)
        self.compiled_on_last_call = fn is None
        if fn is None:
            fn = self._build_fused_dict(key[2], key[3], D)
            self._fused_cache[key] = fn
        dev_ops = DeviceDictOps(*(jnp.asarray(a) for a in ops))
        return fn(
            self.state if state is None else state,
            self.kernel.place(jnp.asarray(alive)),
            jnp.asarray(base),
            jnp.int32(depth),
            dev_ops,
            W=W,
            max_phases=max_phases,
        )

    def adopt(self, new_state) -> None:
        self.state = new_state

    # -- sync down (demotion / checkpoint) -----------------------------------

    def dump(self) -> dict:
        """Materialize the table on host: per-entry rows + counters."""
        used, keyw, klen, ver, valw, vlen, sver = (
            # contiguous: a fetched sharded array can come back with a
            # non-contiguous layout, which .view(uint8) rejects
            np.ascontiguousarray(np.asarray(a)) for a in self.state
        )
        key_bytes = keyw.view(np.uint8).reshape(self.S, self.P, self.K)
        val_bytes = valw.view(np.uint8).reshape(self.S, self.P, self.VW)
        rows = []
        s_idx, p_idx = np.nonzero(used[: self.n_shards])
        for s, p in zip(s_idx.tolist(), p_idx.tolist()):
            rows.append(
                (
                    s,
                    key_bytes[s, p, : klen[s, p]].tobytes(),
                    val_bytes[s, p, : vlen[s, p]].tobytes(),
                    int(ver[s, p]),
                )
            )
        return {
            "rows": rows,
            "shard_version": sver[: self.n_shards].astype(np.int64),
        }

    def upload_from(self, sm, seed_cache: Optional[dict] = None) -> bool:
        """Rebuild the device table from one host replica store
        (``dump``'s inverse — the re-promotion path after a demotion).

        ``seed_cache`` (optional): a ``(shard, version) -> value bytes``
        dict populated with every uploaded entry, so the engine's GET
        meta-only read path can resolve pre-promotion versions without
        downloading values (mesh_engine._dev_resolve).

        Returns False, leaving the device state untouched, when the host
        content is outside the lane's envelope: an overflow side-store
        entry, a key over ``K`` bytes, a value over ``VW`` bytes, more
        than ``P`` live entries in one shard, or a version past i32.
        Placement is order-free: the fused program's match compares the
        op key against ALL ``P`` slots of a shard, so any assignment of
        entries to distinct slots is a valid table.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P_

        from rabia_tpu.apps.vector_kv import _USED
        from rabia_tpu.parallel.mesh import SHARD_AXIS

        store = sm.store
        if store._overflow:
            return False  # long keys live outside the inline table
        idx = np.nonzero(store.state == _USED)[0]
        shards = store.shard_col[idx]
        if idx.size:
            if int(store.key_len[idx].max()) > self.K:
                return False
            if int(store.val_len[idx].max()) > self.VW:
                return False
            if int(store.version[idx].max()) >= 2**31 - 2:
                return False
            counts = np.bincount(shards, minlength=self.n_shards)
            if int(counts.max()) > self.P:
                return False
        if int(store.shard_version[: self.n_shards].max(initial=0)) >= (
            2**31 - 2
        ):
            return False

        S, Pc = self.S, self.P
        used = np.zeros((S, Pc), bool)
        keyb = np.zeros((S, Pc, self.K), np.uint8)
        klen = np.zeros((S, Pc), np.int32)
        ver = np.zeros((S, Pc), np.int32)
        valb = np.zeros((S, Pc, self.VW), np.uint8)
        vlen = np.zeros((S, Pc), np.int32)
        # stable per-shard slot assignment: entries sorted by shard, slot
        # p = running index within the shard — columnar scatters for the
        # fixed-width planes; only the ragged value buffers loop
        order = np.argsort(shards, kind="stable")
        if idx.size:
            sh_sorted = shards[order]
            starts = np.searchsorted(sh_sorted, np.arange(self.n_shards))
            pos = np.arange(idx.size) - starts[sh_sorted]
            src = idx[order]
            used[sh_sorted, pos] = True
            kls = store.key_len[src].astype(np.int64)
            klen[sh_sorted, pos] = kls
            ver[sh_sorted, pos] = store.version[src]
            vlen[sh_sorted, pos] = store.val_len[src]
            key_bytes_all = store.key_lanes[src].view(np.uint8)  # [n, L*8]
            kb_w = min(self.K, key_bytes_all.shape[1])
            # zero-padded lanes guarantee zero tails, so one 2-D copy is
            # exact (no per-row tail clearing needed)
            keyb[sh_sorted, pos, :kb_w] = key_bytes_all[:, :kb_w]
            for j in range(idx.size):
                i = src[j]
                buf = store.val_buf[i]
                a = int(store.val_off[i])
                b = a + int(store.val_len[i])
                v = buf[a:b] if buf is not None else b""
                valb[sh_sorted[j], pos[j], : len(v)] = np.frombuffer(
                    v, np.uint8
                )
                if seed_cache is not None:
                    seed_cache[
                        (int(sh_sorted[j]), int(store.version[i]))
                    ] = bytes(v)
        sver = np.zeros(S, np.int32)
        sver[: self.n_shards] = store.shard_version[: self.n_shards]

        shard_sharding = NamedSharding(self.kernel.mesh, P_(SHARD_AXIS))
        put = lambda a: jax.device_put(jnp.asarray(a), shard_sharding)
        self.state = (
            put(used),
            put(np.ascontiguousarray(keyb).view(np.uint32)),
            put(klen),
            put(ver),
            put(np.ascontiguousarray(valb).view(np.uint32)),
            put(vlen),
            put(sver),
        )
        return True

    def sync_into(self, sm, dump: Optional[dict] = None) -> None:
        """Rebuild one host replica store (VectorShardedKV) from the
        device table. The host store is reset first — in device mode the
        host replicas saw none of the device lane's applies. Pass a
        precomputed ``dump()`` when syncing several replicas: the table
        materialization (a device->host transfer) then happens once."""
        from rabia_tpu.apps.vector_kv import VectorKVStore

        d = dump if dump is not None else self.dump()
        store = VectorKVStore(
            self.n_shards, capacity=max(1 << 10, 2 * len(d["rows"]))
        )
        for s, key, val, ver in d["rows"]:
            lanes, klens = store._lanes_from_keys([key])
            shards = np.array([s], np.int64)
            store.bulk_set(shards, lanes, klens, [val])
            # bulk_set assigned a provisional version; pin the real ones
            slot = store._lookup(shards, lanes, klens)[0]
            store.version[slot] = ver
        store.shard_version[:] = 0
        store.shard_version[: self.n_shards] = d["shard_version"]
        sm.store = store
