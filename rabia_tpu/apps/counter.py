"""Counter SMR app: the minimal typed replicated state machine.

Reference parity: examples/counter_smr/src/lib.rs — commands
Increment/Decrement/Set/Get/Reset (:35-47), overflow/underflow-checked
apply logic and an operation counter (:128-207). This is BASELINE config #1's
app and the first end-to-end milestone (SURVEY.md §7.3).
"""

from __future__ import annotations

import enum
import json
from dataclasses import dataclass
from typing import Optional

from rabia_tpu.core.errors import StateMachineError
from rabia_tpu.core.smr import TypedStateMachine

_I64_MAX = (1 << 63) - 1
_I64_MIN = -(1 << 63)


class CounterOp(enum.Enum):
    Increment = "increment"
    Decrement = "decrement"
    Set = "set"
    Get = "get"
    Reset = "reset"


@dataclass(frozen=True)
class CounterCommand:
    """One typed command (counter_smr lib.rs:35-47)."""

    op: CounterOp
    amount: int = 1

    @staticmethod
    def increment(amount: int = 1) -> "CounterCommand":
        return CounterCommand(CounterOp.Increment, amount)

    @staticmethod
    def decrement(amount: int = 1) -> "CounterCommand":
        return CounterCommand(CounterOp.Decrement, amount)

    @staticmethod
    def set(value: int) -> "CounterCommand":
        return CounterCommand(CounterOp.Set, value)

    @staticmethod
    def get() -> "CounterCommand":
        return CounterCommand(CounterOp.Get, 0)

    @staticmethod
    def reset() -> "CounterCommand":
        return CounterCommand(CounterOp.Reset, 0)


@dataclass(frozen=True)
class CounterResponse:
    """Deterministic response: the post-command value (or error text)."""

    value: int
    ok: bool = True
    error: Optional[str] = None


@dataclass
class CounterState:
    value: int = 0
    operations: int = 0


class CounterSMR(TypedStateMachine[CounterCommand, CounterResponse, CounterState]):
    """Overflow-checked counter (counter_smr lib.rs:128-207).

    Saturating errors are *responses*, not exceptions: a rejected overflow
    still advances the op counter deterministically on every replica.
    """

    def __init__(self) -> None:
        self._state = CounterState()

    # -- apply --------------------------------------------------------------

    def apply_command(self, command: CounterCommand) -> CounterResponse:
        st = self._state
        st.operations += 1
        self._bump_version()
        if command.op == CounterOp.Increment:
            nv = st.value + command.amount
            if nv > _I64_MAX or command.amount < 0:
                return CounterResponse(st.value, ok=False, error="overflow")
            st.value = nv
        elif command.op == CounterOp.Decrement:
            nv = st.value - command.amount
            if nv < _I64_MIN or command.amount < 0:
                return CounterResponse(st.value, ok=False, error="underflow")
            st.value = nv
        elif command.op == CounterOp.Set:
            if not (_I64_MIN <= command.amount <= _I64_MAX):
                return CounterResponse(st.value, ok=False, error="out of range")
            st.value = command.amount
        elif command.op == CounterOp.Reset:
            st.value = 0
        elif command.op == CounterOp.Get:
            pass
        else:  # pragma: no cover - enum is closed
            return CounterResponse(st.value, ok=False, error="unknown op")
        return CounterResponse(st.value)

    # -- state --------------------------------------------------------------

    def get_state(self) -> CounterState:
        return CounterState(self._state.value, self._state.operations)

    def set_state(self, state: CounterState) -> None:
        self._state = CounterState(state.value, state.operations)

    @property
    def value(self) -> int:
        return self._state.value

    @property
    def operations(self) -> int:
        return self._state.operations

    # -- codecs (JSON: compact, deterministic, debuggable) -------------------

    def encode_command(self, command: CounterCommand) -> bytes:
        return json.dumps(
            {"op": command.op.value, "amount": command.amount},
            separators=(",", ":"),
        ).encode()

    def decode_command(self, data: bytes) -> CounterCommand:
        try:
            doc = json.loads(data)
            return CounterCommand(CounterOp(doc["op"]), int(doc.get("amount", 0)))
        except (ValueError, KeyError) as e:
            raise StateMachineError(f"bad counter command: {e}") from None

    def encode_response(self, response: CounterResponse) -> bytes:
        return json.dumps(
            {"value": response.value, "ok": response.ok, "error": response.error},
            separators=(",", ":"),
        ).encode()

    def decode_response(self, data: bytes) -> CounterResponse:
        doc = json.loads(data)
        return CounterResponse(int(doc["value"]), bool(doc["ok"]), doc.get("error"))

    def serialize_state(self) -> bytes:
        return json.dumps(
            {"value": self._state.value, "operations": self._state.operations},
            separators=(",", ":"),
        ).encode()

    def deserialize_state(self, data: bytes) -> None:
        doc = json.loads(data)
        self._state = CounterState(int(doc["value"]), int(doc["operations"]))
