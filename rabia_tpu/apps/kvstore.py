"""Production KV store with change notifications and key-range sharding.

Reference parity:
- `KVStore` — rabia-kvstore/src/store.rs: ValueEntry (:44-80), CRUD + batch
  + snapshot (:101-486; `set` :144-188, `apply_batch` :313-348, `snapshot`
  :350-412, checksum :464-475, key/value validation :436-451), stats
  (:82-90), config (:18-42).
- `KVOperation`/`KVResult`/`StoreError` — rabia-kvstore/src/operations.rs
  (:9-51 ops + read/write classes, :54-93 results, :96-167 errors,
  :169-262 OperationBatch/BatchResult).
- `NotificationBus` — rabia-kvstore/src/notifications.rs: change events
  (:14-42), filter algebra All/Key/KeyPrefix/ChangeType/And/Or (:60-89),
  bus with per-subscriber queues + closed-subscriber GC (:106-271,
  `publish` :198-235), stats (:99-104).
- `KVStoreSMR` — examples/kvstore_smr/src/smr_impl.rs:22-100 (with the
  state-transfer accessors of examples/kvstore_smr/src/store.rs:435-455).

TPU-native twist: the store is **sharded by key range** (stable hash →
shard index). Each shard is an independent consensus instance — the shard
axis is exactly the ``S`` axis the device kernel batches over
(SURVEY.md §5.7), so kvstore scale-out IS kernel batch width.
"""

from __future__ import annotations

import enum
import hashlib
import json
import time
import zlib
from dataclasses import dataclass, field
from typing import Callable, Iterable, Optional, Sequence

from rabia_tpu.core.config import KVStoreConfig
from rabia_tpu.core.errors import StateMachineError, ValidationError
from rabia_tpu.core.smr import TypedStateMachine


# ---------------------------------------------------------------------------
# Operations / results / errors (operations.rs)
# ---------------------------------------------------------------------------


class KVOpType(enum.Enum):
    Set = "set"
    Get = "get"
    Delete = "delete"
    Exists = "exists"
    Clear = "clear"
    Cas = "cas"


_WRITE_OPS = {KVOpType.Set, KVOpType.Delete, KVOpType.Clear, KVOpType.Cas}


@dataclass(frozen=True)
class KVOperation:
    """One typed store operation (operations.rs:9-51).

    ``expected_version`` is meaningful for :attr:`KVOpType.Cas` only: the
    entry version the write is conditioned on (0 = create-if-absent).
    """

    op: KVOpType
    key: str = ""
    value: Optional[str] = None
    expected_version: int = 0

    @property
    def is_write(self) -> bool:
        return self.op in _WRITE_OPS

    @property
    def is_read(self) -> bool:
        return not self.is_write

    @staticmethod
    def set(key: str, value: str) -> "KVOperation":
        return KVOperation(KVOpType.Set, key, value)

    @staticmethod
    def get(key: str) -> "KVOperation":
        return KVOperation(KVOpType.Get, key)

    @staticmethod
    def delete(key: str) -> "KVOperation":
        return KVOperation(KVOpType.Delete, key)

    @staticmethod
    def exists(key: str) -> "KVOperation":
        return KVOperation(KVOpType.Exists, key)

    @staticmethod
    def cas(key: str, value: str, expected_version: int) -> "KVOperation":
        return KVOperation(KVOpType.Cas, key, value, expected_version)


class KVResultKind(enum.Enum):
    Success = "success"
    NotFound = "not_found"
    Error = "error"


@dataclass(frozen=True)
class KVResult:
    """Operation outcome (operations.rs:54-93)."""

    kind: KVResultKind
    value: Optional[str] = None
    version: Optional[int] = None
    error: Optional[str] = None

    @staticmethod
    def success(value: Optional[str] = None, version: Optional[int] = None) -> "KVResult":
        return KVResult(KVResultKind.Success, value=value, version=version)

    @staticmethod
    def not_found() -> "KVResult":
        return KVResult(KVResultKind.NotFound)

    @staticmethod
    def err(message: str) -> "KVResult":
        return KVResult(KVResultKind.Error, error=message)

    @property
    def ok(self) -> bool:
        return self.kind == KVResultKind.Success


@dataclass(frozen=True)
class OperationBatch:
    """A group of typed operations executed as one unit
    (operations.rs:169-212)."""

    operations: tuple[KVOperation, ...]
    batch_id: str = ""
    created_at: float = field(default_factory=time.time)

    @staticmethod
    def new(operations: Iterable[KVOperation]) -> "OperationBatch":
        from rabia_tpu.core.types import fast_uuid4

        return OperationBatch(tuple(operations), batch_id=str(fast_uuid4()))

    def size(self) -> int:
        return len(self.operations)

    def has_write_operations(self) -> bool:
        return any(op.is_write for op in self.operations)

    def is_read_only(self) -> bool:
        return not self.has_write_operations()

    def affected_keys(self) -> list[str]:
        return [op.key for op in self.operations]


@dataclass(frozen=True)
class BatchResult:
    """Outcome of one :class:`OperationBatch` (operations.rs:214-262)."""

    batch_id: str
    results: tuple[KVResult, ...]
    success_count: int
    failure_count: int
    execution_time_ms: float

    @staticmethod
    def new(
        batch_id: str,
        results: Iterable[KVResult],
        execution_time_ms: float,
    ) -> "BatchResult":
        rs = tuple(results)
        ok = sum(1 for r in rs if r.ok)
        return BatchResult(batch_id, rs, ok, len(rs) - ok, execution_time_ms)

    def all_succeeded(self) -> bool:
        return self.failure_count == 0

    def has_failures(self) -> bool:
        return self.failure_count > 0

    def success_rate(self) -> float:
        """Percentage of successful operations (0.0 for an empty batch)."""
        if not self.results:
            return 0.0
        return 100.0 * self.success_count / len(self.results)


class StoreErrorKind(enum.Enum):
    """Error taxonomy (operations.rs:96-167)."""

    KeyTooLong = "key_too_long"
    KeyEmpty = "key_empty"
    ValueTooLarge = "value_too_large"
    StoreFull = "store_full"
    KeyNotFound = "key_not_found"
    InvalidOperation = "invalid_operation"
    SnapshotCorrupt = "snapshot_corrupt"
    ChecksumMismatch = "checksum_mismatch"
    VersionConflict = "version_conflict"
    Internal = "internal"

    @property
    def recoverable(self) -> bool:
        return self in (
            StoreErrorKind.KeyNotFound,
            StoreErrorKind.VersionConflict,
            StoreErrorKind.StoreFull,
        )

    @property
    def is_client_error(self) -> bool:
        return self in (
            StoreErrorKind.KeyTooLong,
            StoreErrorKind.KeyEmpty,
            StoreErrorKind.ValueTooLarge,
            StoreErrorKind.InvalidOperation,
            StoreErrorKind.KeyNotFound,
        )


class StoreError(ValidationError):
    def __init__(self, kind: StoreErrorKind, message: str = "") -> None:
        super().__init__(f"{kind.value}: {message}" if message else kind.value)
        self.kind = kind


# ---------------------------------------------------------------------------
# Change notifications (notifications.rs)
# ---------------------------------------------------------------------------


class ChangeType(enum.Enum):
    Created = "created"
    Updated = "updated"
    Deleted = "deleted"
    Cleared = "cleared"


@dataclass(frozen=True)
class ChangeNotification:
    """One change event (notifications.rs:14-42)."""

    key: str
    change: ChangeType
    old_value: Optional[str]
    new_value: Optional[str]
    version: int
    timestamp: float = field(default_factory=time.time)


class NotificationFilter:
    """Filter algebra (notifications.rs:60-89): All / Key / KeyPrefix /
    ChangeType / And / Or, composed as predicate trees."""

    def __init__(self, pred: Callable[[ChangeNotification], bool]) -> None:
        self._pred = pred

    def matches(self, n: ChangeNotification) -> bool:
        return self._pred(n)

    @staticmethod
    def all() -> "NotificationFilter":
        return NotificationFilter(lambda n: True)

    @staticmethod
    def key(key: str) -> "NotificationFilter":
        return NotificationFilter(lambda n: n.key == key)

    @staticmethod
    def key_prefix(prefix: str) -> "NotificationFilter":
        return NotificationFilter(lambda n: n.key.startswith(prefix))

    @staticmethod
    def change_type(ct: ChangeType) -> "NotificationFilter":
        return NotificationFilter(lambda n: n.change == ct)

    def and_(self, other: "NotificationFilter") -> "NotificationFilter":
        return NotificationFilter(lambda n: self.matches(n) and other.matches(n))

    def or_(self, other: "NotificationFilter") -> "NotificationFilter":
        return NotificationFilter(lambda n: self.matches(n) or other.matches(n))


@dataclass
class NotificationStats:
    """Bus counters (notifications.rs:99-104)."""

    published: int = 0
    delivered: int = 0
    dropped: int = 0
    active_subscribers: int = 0


class _Subscription:
    """One subscriber's unbounded queue + filter (notifications.rs:279-314
    NotificationListener analog). Iterate with ``async for`` or ``get()``."""

    _CLOSED = object()  # sentinel: wakes consumers blocked in queue.get()

    def __init__(self, bus: "NotificationBus", flt: NotificationFilter, maxsize: int) -> None:
        import asyncio

        self.bus = bus
        self.filter = flt
        self.queue: "asyncio.Queue" = asyncio.Queue(maxsize)
        self.closed = False

    async def get(self, timeout: Optional[float] = None):
        import asyncio

        item = (
            await self.queue.get()
            if timeout is None
            else await asyncio.wait_for(self.queue.get(), timeout)
        )
        if item is self._CLOSED:
            self.queue.put_nowait(self._CLOSED)  # re-arm for other waiters
            raise StopAsyncIteration
        return item

    def get_nowait(self) -> Optional[ChangeNotification]:
        import asyncio

        try:
            item = self.queue.get_nowait()
        except asyncio.QueueEmpty:
            return None
        if item is self._CLOSED:
            self.queue.put_nowait(self._CLOSED)
            return None
        return item

    def close(self) -> None:
        """Mark closed and wake any consumer parked in get()/async-for."""
        import asyncio

        self.closed = True
        try:
            self.queue.put_nowait(self._CLOSED)
        except asyncio.QueueFull:
            # full queue: the consumer has items to drain and will see the
            # sentinel after them; make room for it deterministically
            try:
                self.queue.get_nowait()
            except asyncio.QueueEmpty:  # pragma: no cover - racy edge
                pass
            self.queue.put_nowait(self._CLOSED)

    def __aiter__(self):
        return self

    async def __anext__(self) -> ChangeNotification:
        if self.closed and self.queue.empty():
            raise StopAsyncIteration
        return await self.get()


class NotificationBus:
    """Filtered pub/sub for store changes (notifications.rs:106-271).

    Synchronous publish into per-subscriber bounded queues (cap mirrors the
    reference's 1000-slot broadcast channel); full queues count drops, and
    closed subscribers are GC'd on the next publish (:237-246 analog).
    """

    def __init__(self, queue_capacity: int = 1000) -> None:
        self._subs: list[_Subscription] = []
        self._capacity = queue_capacity
        self.stats = NotificationStats()

    def subscribe(
        self, flt: Optional[NotificationFilter] = None
    ) -> _Subscription:
        sub = _Subscription(self, flt or NotificationFilter.all(), self._capacity)
        self._subs.append(sub)
        self.stats.active_subscribers = len(self._subs)
        return sub

    def publish(self, n: ChangeNotification) -> int:
        """Deliver to matching subscribers; returns delivery count
        (notifications.rs:198-235)."""
        import asyncio

        self.stats.published += 1
        delivered = 0
        live: list[_Subscription] = []
        for sub in self._subs:
            if sub.closed:
                continue
            live.append(sub)
            if not sub.filter.matches(n):
                continue
            try:
                sub.queue.put_nowait(n)
                delivered += 1
            except asyncio.QueueFull:
                self.stats.dropped += 1
        self._subs = live
        self.stats.active_subscribers = len(live)
        self.stats.delivered += delivered
        return delivered


# ---------------------------------------------------------------------------
# The store (store.rs)
# ---------------------------------------------------------------------------


@dataclass
class ValueEntry:
    """Stored value + metadata (store.rs:44-80)."""

    value: str
    version: int
    created_at: float
    updated_at: float

    @property
    def size(self) -> int:
        return len(self.value.encode())


@dataclass
class StoreStats:
    """Store counters (store.rs:82-90)."""

    total_operations: int = 0
    reads: int = 0
    writes: int = 0
    keys: int = 0
    total_size: int = 0


def shard_for_key(key: str, num_shards: int) -> int:
    """Stable key→shard map (blake2 for cross-process determinism)."""
    if num_shards <= 1:
        return 0
    h = hashlib.blake2s(key.encode(), digest_size=8).digest()
    return int.from_bytes(h, "little") % num_shards


class KVStore:
    """Versioned in-memory KV store with validation, notifications,
    snapshots, and key-range sharding (store.rs:101-486).

    In the SMR deployment every mutation arrives through consensus (one
    consensus instance per shard); direct calls are for local/testing use.
    """

    def __init__(self, config: Optional[KVStoreConfig] = None) -> None:
        self.config = config or KVStoreConfig()
        self._data: dict[str, ValueEntry] = {}
        self._version = 0
        self.stats = StoreStats()
        # bytes->str key intern for the binary fast path: consensus reuses
        # hot keys every slot, and the UTF-8 decode is ~20% of a fused SET
        self._key_cache: dict[bytes, str] = {}
        self.notifications = (
            NotificationBus() if self.config.notifications_enabled else None
        )

    # -- validation (store.rs:436-451) --------------------------------------

    def _validate_key(self, key: str) -> None:
        if not key:
            raise StoreError(StoreErrorKind.KeyEmpty)
        if len(key) > self.config.max_key_length:
            raise StoreError(
                StoreErrorKind.KeyTooLong, f"{len(key)} > {self.config.max_key_length}"
            )

    def _validate_value(self, value: str) -> None:
        if len(value.encode()) > self.config.max_value_size:
            raise StoreError(StoreErrorKind.ValueTooLarge)

    # -- CRUD ---------------------------------------------------------------

    def apply_set_bin_fast(self, b: bytes, now: float) -> Optional[bytes]:
        """Fused fast path for one binary SET (the block lane's per-slot
        common case): same semantics as :meth:`set` minus intermediate
        objects. Returns None when the slow path must run (subscribers
        present, limit checks fail, store full)."""
        bus = self.notifications
        if bus is not None and bus._subs:
            return None
        if len(b) < 3:
            return None
        klen = b[1] | (b[2] << 8)
        vlen = len(b) - 3 - klen
        cfg = self.config
        if not (0 < klen <= cfg.max_key_length) or vlen < 0 or vlen > cfg.max_value_size:
            return None
        cache = self._key_cache
        kb = b[3 : 3 + klen]
        key = cache.get(kb)
        try:
            if key is None:
                key = kb.decode()
                if len(cache) > 65536:  # bound against key-spraying load
                    cache.clear()
                cache[kb] = key
            value = b[3 + klen :].decode()
        except UnicodeDecodeError:
            return None  # slow path reports the malformed op
        data = self._data
        e = data.get(key)
        v = self._version + 1
        if e is None:
            if len(data) >= cfg.max_keys:
                return None
            data[key] = ValueEntry(value, v, now, now)
        else:
            e.value = value
            e.version = v
            e.updated_at = now
        self._version = v
        st = self.stats
        st.total_operations += 1
        st.writes += 1
        return b"\x00" + v.to_bytes(4, "little") + b"\x00"

    def set(self, key: str, value: str) -> KVResult:
        """Insert or update (store.rs:144-188)."""
        self._validate_key(key)
        self._validate_value(value)
        now = time.time()
        self.stats.total_operations += 1
        self.stats.writes += 1
        entry = self._data.get(key)
        if entry is None:
            if len(self._data) >= self.config.max_keys:
                raise StoreError(StoreErrorKind.StoreFull)
            self._version += 1
            self._data[key] = ValueEntry(value, self._version, now, now)
            self._notify(key, ChangeType.Created, None, value)
        else:
            old = entry.value
            self._version += 1
            entry.value = value
            entry.version = self._version
            entry.updated_at = now
            self._notify(key, ChangeType.Updated, old, value)
        return KVResult.success(version=self._version)

    def cas(self, key: str, value: str, expected_version: int) -> KVResult:
        """Compare-and-set: write only when the entry's version equals
        ``expected_version`` (0 = create-if-absent). Deterministic outcomes
        (the replicated-write contract): NotFound for a conditioned write
        on an absent key, ``version_conflict`` (with the CURRENT version in
        the result) on a mismatch — so optimistic-concurrency clients can
        retry off the committed result alone."""
        self._validate_key(key)
        self._validate_value(value)
        now = time.time()
        self.stats.total_operations += 1
        self.stats.writes += 1
        entry = self._data.get(key)
        if entry is None:
            if expected_version != 0:
                return KVResult.not_found()
            if len(self._data) >= self.config.max_keys:
                raise StoreError(StoreErrorKind.StoreFull)
            self._version += 1
            self._data[key] = ValueEntry(value, self._version, now, now)
            self._notify(key, ChangeType.Created, None, value)
            return KVResult.success(version=self._version)
        if entry.version != expected_version:
            return KVResult(
                KVResultKind.Error,
                version=entry.version,
                error="version_conflict",
            )
        old = entry.value
        self._version += 1
        entry.value = value
        entry.version = self._version
        entry.updated_at = now
        self._notify(key, ChangeType.Updated, old, value)
        return KVResult.success(version=self._version)

    def get(self, key: str) -> KVResult:
        self.stats.total_operations += 1
        self.stats.reads += 1
        entry = self._data.get(key)
        if entry is None:
            return KVResult.not_found()
        return KVResult.success(value=entry.value, version=entry.version)

    def get_with_metadata(self, key: str) -> Optional[ValueEntry]:
        entry = self._data.get(key)
        if entry is None:
            return None
        return ValueEntry(entry.value, entry.version, entry.created_at, entry.updated_at)

    def delete(self, key: str) -> KVResult:
        self.stats.total_operations += 1
        self.stats.writes += 1
        entry = self._data.pop(key, None)
        if entry is None:
            return KVResult.not_found()
        self._version += 1
        self._notify(key, ChangeType.Deleted, entry.value, None)
        return KVResult.success(value=entry.value, version=self._version)

    def exists(self, key: str) -> KVResult:
        self.stats.total_operations += 1
        self.stats.reads += 1
        return KVResult.success(value="true" if key in self._data else "false")

    def keys(self, prefix: str = "") -> list[str]:
        """Sorted key listing, optionally prefix-filtered (store.rs keys())."""
        if prefix:
            return sorted(k for k in self._data if k.startswith(prefix))
        return sorted(self._data)

    def clear(self) -> int:
        n = len(self._data)
        self.stats.total_operations += 1
        self.stats.writes += 1
        self._data.clear()
        self._version += 1
        self._notify("", ChangeType.Cleared, None, None)
        return n

    def size(self) -> int:
        return len(self._data)

    def __len__(self) -> int:
        return len(self._data)

    @property
    def version(self) -> int:
        return self._version

    def _notify(
        self, key: str, change: ChangeType, old: Optional[str], new: Optional[str]
    ) -> None:
        if self.notifications is not None:
            self.notifications.publish(
                ChangeNotification(key, change, old, new, self._version)
            )

    # -- batches (store.rs:313-348) ------------------------------------------

    def apply_operations(self, ops: Sequence[KVOperation]) -> list[KVResult]:
        out: list[KVResult] = []
        for op in ops:
            try:
                if op.op == KVOpType.Set:
                    out.append(self.set(op.key, op.value or ""))
                elif op.op == KVOpType.Get:
                    out.append(self.get(op.key))
                elif op.op == KVOpType.Delete:
                    out.append(self.delete(op.key))
                elif op.op == KVOpType.Exists:
                    out.append(self.exists(op.key))
                elif op.op == KVOpType.Cas:
                    out.append(
                        self.cas(op.key, op.value or "", op.expected_version)
                    )
                elif op.op == KVOpType.Clear:
                    self.clear()
                    out.append(KVResult.success())
                else:
                    out.append(KVResult.err("invalid operation"))
            except StoreError as e:
                out.append(KVResult.err(str(e)))
        return out

    def execute_batch(self, batch: OperationBatch) -> BatchResult:
        """Apply a typed :class:`OperationBatch` and report per-op results
        with success counts and execution time (operations.rs:214-262)."""
        t0 = time.perf_counter()
        results = self.apply_operations(batch.operations)
        return BatchResult.new(
            batch.batch_id, results, (time.perf_counter() - t0) * 1000.0
        )

    # -- snapshots (store.rs:350-412) ----------------------------------------

    def snapshot_bytes(self) -> bytes:
        doc = {
            "version": self._version,
            "data": {
                k: [e.value, e.version, e.created_at, e.updated_at]
                for k, e in sorted(self._data.items())
            },
        }
        payload = json.dumps(doc, separators=(",", ":"), sort_keys=True).encode()
        checksum = zlib.crc32(payload) & 0xFFFFFFFF
        return checksum.to_bytes(4, "little") + payload

    def restore_bytes(self, raw: bytes) -> None:
        if len(raw) < 4:
            raise StoreError(StoreErrorKind.SnapshotCorrupt, "too short")
        checksum = int.from_bytes(raw[:4], "little")
        payload = raw[4:]
        if zlib.crc32(payload) & 0xFFFFFFFF != checksum:
            raise StoreError(StoreErrorKind.ChecksumMismatch)
        try:
            doc = json.loads(payload)
            self._data = {
                k: ValueEntry(v[0], int(v[1]), float(v[2]), float(v[3]))
                for k, v in doc["data"].items()
            }
            self._version = int(doc["version"])
        except (ValueError, KeyError, IndexError) as e:
            raise StoreError(StoreErrorKind.SnapshotCorrupt, str(e)) from None

    def checksum(self) -> int:
        """Content hash over sorted (key, value, version) (store.rs:464-475)."""
        h = hashlib.blake2s(digest_size=8)
        for k in sorted(self._data):
            e = self._data[k]
            h.update(k.encode())
            h.update(e.value.encode())
            h.update(e.version.to_bytes(8, "little"))
        return int.from_bytes(h.digest(), "little")


# ---------------------------------------------------------------------------
# Compact binary op codec (the block lane's command format)
# ---------------------------------------------------------------------------
#
# op:     u8 opcode (1=SET 2=GET 3=DEL 4=EXISTS 5=CLEAR 6=CAS)
#         | u16 LE keylen | key utf8
#         | value utf8 (SET: rest of buffer)
#         | u64 LE expected_version | value utf8 (CAS only)
# result: u8 kind (0=success 1=not_found 2=error) | u32 LE version
#         | u8 has_value | value utf8 (rest; error text for kind=2 —
#         the presence byte keeps "" distinct from "no value")
#
# The same records ride the wire (gateway Submit commands), the ledger
# (CommandBatch/PayloadBlock payloads) and the apply plane — the native
# statekernel (native/statekernel.cpp) consumes exactly these bytes and
# must produce byte-identical result frames to apply_op_bin below, which
# stays the semantics owner (RABIA_PY_APPLY=1 forces it).

_OP_SET, _OP_GET, _OP_DEL, _OP_EXISTS, _OP_CLEAR, _OP_CAS = 1, 2, 3, 4, 5, 6


def encode_op_bin(op: KVOperation) -> bytes:
    kb = op.key.encode()
    head = bytes((_OP_CODE[op.op],)) + len(kb).to_bytes(2, "little") + kb
    if op.op == KVOpType.Set:
        return head + (op.value or "").encode()
    if op.op == KVOpType.Cas:
        return (
            head
            + int(op.expected_version).to_bytes(8, "little")
            + (op.value or "").encode()
        )
    return head


_OP_CODE = {
    KVOpType.Set: _OP_SET,
    KVOpType.Get: _OP_GET,
    KVOpType.Delete: _OP_DEL,
    KVOpType.Exists: _OP_EXISTS,
    KVOpType.Clear: _OP_CLEAR,
    KVOpType.Cas: _OP_CAS,
}


def encode_cas_bin(key: str, value: str, expected_version: int) -> bytes:
    kb = key.encode()
    return (
        b"\x06"
        + len(kb).to_bytes(2, "little")
        + kb
        + int(expected_version).to_bytes(8, "little")
        + value.encode()
    )


def encode_set_bin(key: str, value: str) -> bytes:
    kb = key.encode()
    return b"\x01" + len(kb).to_bytes(2, "little") + kb + value.encode()


def _result_bin(kind: int, version: int, value: Optional[str] = None) -> bytes:
    # kind u8 | version u32 LE | has_value u8 | value utf8 — the presence
    # byte keeps "empty string value" distinct from "no value" (JSON parity)
    head = bytes((kind,)) + (version & 0xFFFFFFFF).to_bytes(4, "little")
    if value is None:
        return head + b"\x00"
    return head + b"\x01" + value.encode()


_CODE_OP = {v: k for k, v in _OP_CODE.items()}


def decode_op_bin(data: bytes) -> KVOperation:
    try:
        op = _CODE_OP[data[0]]
        klen = int.from_bytes(data[1:3], "little")
        if 3 + klen > len(data):
            raise KeyError(f"key length {klen} exceeds payload")
        key = data[3 : 3 + klen].decode()
        if op == KVOpType.Set:
            return KVOperation(op, key, data[3 + klen :].decode())
        if op == KVOpType.Cas:
            if 3 + klen + 8 > len(data):
                raise KeyError("cas payload shorter than its version field")
            expected = int.from_bytes(
                data[3 + klen : 3 + klen + 8], "little"
            )
            return KVOperation(
                op, key, data[3 + klen + 8 :].decode(), expected
            )
        return KVOperation(op, key, None)
    except (KeyError, IndexError, UnicodeDecodeError) as e:
        from rabia_tpu.core.errors import StateMachineError

        raise StateMachineError(f"bad binary kv command: {e}") from None


def decode_kv_response(data: bytes) -> KVResult:
    """Decode a committed response frame in EITHER framing: the scalar
    lane's JSON (``KVStoreSMR.encode_response``) or the compact binary
    result (block lane / gateway read path). The client-side twin of
    ``KVStoreSMR.decode_response`` as a free function."""
    if data[:1] == b"{":
        doc = json.loads(data)
        return KVResult(
            KVResultKind(doc["kind"]),
            value=doc.get("value"),
            version=doc.get("version"),
            error=doc.get("error"),
        )
    return decode_result_bin(data)


def decode_result_bin(data: bytes) -> KVResult:
    kind = data[0]
    version = int.from_bytes(data[1:5], "little")
    value = data[6:].decode() if len(data) > 5 and data[5] else None
    if kind == 0:
        return KVResult.success(value=value, version=version or None)
    if kind == 1:
        return KVResult.not_found()
    # error results carry the entry's CURRENT version when known (CAS
    # conflicts report it so optimistic clients can retry without a read)
    return KVResult(
        KVResultKind.Error, error=value or "error", version=version or None
    )


def apply_ops_bin(store: "KVStore", ops, now: Optional[float] = None) -> list[bytes]:
    """Bulk binary apply: semantics identical to the per-op CRUD calls
    (validation, versioning, stats, notifications when subscribed) with the
    per-op overhead amortized — one clock read per wave, notification
    publish skipped when nobody subscribes, no intermediate KVResult
    objects on the SET fast path. Non-SET / limit-violating ops fall back
    to :func:`apply_op_bin` per op.

    Native stores (apps/native_store.NativeKVStore) take the statekernel
    wave path — same records in, byte-identical result frames out (the
    apply-path conformance gate pins this)."""
    if getattr(store, "is_native", False):
        return store.apply_bin_many(ops, now)
    if now is None:
        now = time.time()
    data = store._data
    out: list[bytes] = []
    v = store._version
    bus = store.notifications
    notify = bus is not None and bool(bus._subs)
    cfg = store.config
    max_klen = cfg.max_key_length
    max_val = cfg.max_value_size
    max_keys = cfg.max_keys
    fast_writes = 0
    for b in ops:
        if b[:1] == b"\x01" and len(b) >= 3:
            klen = b[1] | (b[2] << 8)
            vlen = len(b) - 3 - klen
            if 0 < klen <= max_klen and 0 <= vlen <= max_val:
                try:
                    key = b[3 : 3 + klen].decode()
                    value = b[3 + klen :].decode()
                except UnicodeDecodeError:
                    store._version = v
                    out.append(apply_op_bin(store, b))
                    v = store._version
                    continue
                e = data.get(key)
                if e is None:
                    if len(data) >= max_keys:
                        store._version = v
                        out.append(apply_op_bin(store, b))
                        v = store._version
                        continue
                    v += 1
                    data[key] = ValueEntry(value, v, now, now)
                    if notify:
                        store._version = v
                        store._notify(key, ChangeType.Created, None, value)
                else:
                    old = e.value
                    v += 1
                    e.value = value
                    e.version = v
                    e.updated_at = now
                    if notify:
                        store._version = v
                        store._notify(key, ChangeType.Updated, old, value)
                fast_writes += 1
                out.append(b"\x00" + v.to_bytes(4, "little") + b"\x00")
                continue
        store._version = v
        out.append(apply_op_bin(store, b))
        v = store._version
    store._version = v
    store.stats.total_operations += fast_writes
    store.stats.writes += fast_writes
    return out


def apply_op_bin(store: "KVStore", data: bytes) -> bytes:
    """Apply one binary-encoded op against a store; binary result."""
    if getattr(store, "is_native", False):
        return store.apply_bin(data)
    try:
        opcode = data[0]
        klen = int.from_bytes(data[1:3], "little")
        if 3 + klen > len(data):
            return _result_bin(2, 0, f"malformed op: key length {klen} exceeds payload")
        key = data[3 : 3 + klen].decode()
        if opcode == _OP_SET:
            res = store.set(key, data[3 + klen :].decode())
            return _result_bin(0, res.version or 0)
        if opcode == _OP_GET:
            res = store.get(key)
            if res.kind == KVResultKind.NotFound:
                return _result_bin(1, 0)
            return _result_bin(0, res.version or 0, res.value)
        if opcode == _OP_DEL:
            res = store.delete(key)
            if res.kind == KVResultKind.NotFound:
                return _result_bin(1, 0)
            return _result_bin(0, res.version or 0, res.value)
        if opcode == _OP_EXISTS:
            res = store.exists(key)
            return _result_bin(0, 0, res.value or "false")
        if opcode == _OP_CLEAR:
            return _result_bin(0, 0, str(store.clear()))
        if opcode == _OP_CAS:
            if 3 + klen + 8 > len(data):
                return _result_bin(
                    2, 0, "malformed op: cas payload shorter than its "
                    "version field"
                )
            expected = int.from_bytes(
                data[3 + klen : 3 + klen + 8], "little"
            )
            res = store.cas(key, data[3 + klen + 8 :].decode(), expected)
            if res.kind == KVResultKind.NotFound:
                return _result_bin(1, 0)
            if res.kind == KVResultKind.Error:
                return _result_bin(2, res.version or 0, res.error)
            return _result_bin(0, res.version or 0)
        return _result_bin(2, 0, f"unknown opcode {opcode}")
    except StoreError as e:
        return _result_bin(2, 0, str(e))
    except UnicodeDecodeError:
        # canonical text (no codec positions): the native statekernel's
        # validator must produce byte-identical error frames
        return _result_bin(2, 0, "malformed op: invalid utf-8")
    except IndexError as e:
        return _result_bin(2, 0, f"malformed op: {e}")


# ---------------------------------------------------------------------------
# SMR bridge (smr_impl.rs:22-100)
# ---------------------------------------------------------------------------


class KVStoreSMR(TypedStateMachine[KVOperation, KVResult, dict]):
    """Adapts :class:`KVStore` to the typed SMR interface.

    One instance serves ONE shard's consensus log; a sharded deployment runs
    `num_shards` of these behind :class:`ShardedKVService`.

    ``store`` may be a :class:`KVStore` (default) or a
    :class:`~rabia_tpu.apps.native_store.NativeKVStore` view — the typed
    surface and the binary apply path work identically over either (the
    conformance gate pins the equivalence).
    """

    def __init__(
        self, config: Optional[KVStoreConfig] = None, store=None
    ) -> None:
        self.store = store if store is not None else KVStore(config)

    def apply_command(self, command: KVOperation) -> KVResult:
        self._bump_version()
        # apply_operations already folds StoreError into KVResult.err
        return self.store.apply_operations([command])[0]

    def get_state(self) -> dict:
        if getattr(self.store, "is_native", False):
            return self.store.get_state_dict()
        return {k: e.value for k, e in self.store._data.items()}

    def set_state(self, state: dict) -> None:
        if getattr(self.store, "is_native", False):
            self.store.set_state_dict(state)
            return
        self.store._data = {
            k: ValueEntry(v, 0, time.time(), time.time()) for k, v in state.items()
        }

    def encode_command(self, command: KVOperation) -> bytes:
        doc = {
            "op": command.op.value,
            "key": command.key,
            "value": command.value,
        }
        if command.op == KVOpType.Cas:
            doc["expected_version"] = command.expected_version
        return json.dumps(doc, separators=(",", ":")).encode()

    def decode_command(self, data: bytes) -> KVOperation:
        if data[:1] != b"{":
            return decode_op_bin(data)
        try:
            doc = json.loads(data)
            return KVOperation(
                KVOpType(doc["op"]),
                doc.get("key", ""),
                doc.get("value"),
                int(doc.get("expected_version", 0)),
            )
        except (ValueError, KeyError) as e:
            raise StateMachineError(f"bad kv command: {e}") from None

    def encode_response(self, response: KVResult) -> bytes:
        return json.dumps(
            {
                "kind": response.kind.value,
                "value": response.value,
                "version": response.version,
                "error": response.error,
            },
            separators=(",", ":"),
        ).encode()

    def decode_response(self, data: bytes) -> KVResult:
        return decode_kv_response(data)

    def apply_raw(self, data: bytes) -> bytes:
        """Apply one encoded command without the JSON round-trip when it is
        in the compact binary form (the block lane's format); JSON commands
        take the typed path. Response is binary iff the command was."""
        if data[:1] == b"{":
            op = self.decode_command(data)
            return self.encode_response(self.apply_command(op))
        self._bump_version()
        return apply_op_bin(self.store, data)

    def apply_raw_many(self, ops, now: Optional[float] = None) -> list[bytes]:
        """Bulk :meth:`apply_raw` (the block lane's per-shard wave)."""
        if any(b[:1] == b"{" for b in ops):
            return [self.apply_raw(b) for b in ops]
        setattr(
            self,
            "_smr_version",
            getattr(self, "_smr_version", 0) + len(ops),
        )
        return apply_ops_bin(self.store, ops, now)

    def serialize_state(self) -> bytes:
        return self.store.snapshot_bytes()

    def deserialize_state(self, data: bytes) -> None:
        self.store.restore_bytes(data)
