"""Shard-routing state machine + the client-facing sharded KV service.

No direct reference analog: the reference runs ONE consensus instance per
cluster (its kvstore_smr bridges a single store — smr_impl.rs:22-100). Here
the store is partitioned by key range and every shard is an independent
consensus instance — the batched ``S`` axis of the device kernel
(SURVEY.md §5.7, §7.1). This module provides:

- :class:`ShardedStateMachine` — engine-facing bytes SM that routes each
  committed batch to its shard's sub-machine (`CommandBatch.shard` carries
  the index through consensus);
- :class:`ShardedKVService` — the client API: key → shard → engine submit,
  with typed encode/decode via the shard's `KVStoreSMR`.
"""

from __future__ import annotations

import asyncio
import json
from collections import deque
from typing import Callable, Optional, Sequence

import numpy as np

from rabia_tpu.core.batching import ShardedBatcher
from rabia_tpu.core.blocks import build_block
from rabia_tpu.core.config import BatchConfig
from rabia_tpu.core.smr import SMRBridge, TypedStateMachine
from rabia_tpu.core.state_machine import Snapshot, StateMachine, VectorStateMachine
from rabia_tpu.core.types import Command, CommandBatch, ShardId
from rabia_tpu.apps.kvstore import (
    KVOperation,
    KVResult,
    KVStoreConfig,
    KVStoreSMR,
    encode_op_bin,
    encode_set_bin,
    shard_for_key,
)


_BIN_OPCODES = frozenset(
    bytes((c,)) for c in (1, 2, 3, 4, 5, 6)
)  # SET GET DEL EXISTS CLEAR CAS (apps/kvstore.py binary op codec)


class ShardedStateMachine(StateMachine, VectorStateMachine):
    """Routes committed batches to per-shard typed machines by batch.shard.

    The engine applies whole batches (engine.rs:684-706 analog); the shard
    index rides on the batch, so routing is O(1) and the per-shard machines
    stay single-writer (no cross-shard synchronization — matching how the
    kernel treats shards as independent instances).

    Also implements the block lane's :class:`VectorStateMachine`: a whole
    decided wave of per-shard batches applies in one call, each command as
    a byte-slice through the shard machine's ``apply_raw`` fast path (no
    per-command object materialization).
    """

    def __init__(self, machines: Sequence[TypedStateMachine]) -> None:
        self.bridges = [SMRBridge(m) for m in machines]
        self.machines = list(machines)
        self._version = 0
        # native apply plane (apps/native_store): when every shard store
        # is a NativeKVStore view over ONE shared plane, a decided wave
        # applies in a single statekernel call (apply_block below)
        self._native_plane = None
        self._native_stores = [
            getattr(m, "store", None) for m in self.machines
        ]
        stores = self._native_stores
        if stores and all(
            getattr(s, "is_native", False) for s in stores
        ):
            planes = {id(s.plane) for s in stores}
            # exact width match: the native wave routes by
            # shard % n_stores while the Python paths route by
            # shard % len(machines) — any mismatch would silently
            # diverge the two conformance-pinned paths
            if len(planes) == 1 and stores[0].plane.n_stores == len(stores):
                self._native_plane = stores[0].plane

    @property
    def num_shards(self) -> int:
        return len(self.bridges)

    def _bridge_for(self, shard: int) -> SMRBridge:
        return self.bridges[shard % len(self.bridges)]

    def apply_command(self, command: Command) -> bytes:
        # unrouted single commands go to shard 0 (engine tests / smoke)
        return self.bridges[0].apply_command(command)

    def apply_batch(self, batch: CommandBatch) -> list[bytes]:
        shard = int(batch.shard)
        cmds = batch.commands
        m = self.machines[shard % len(self.machines)]
        raw_many = getattr(m, "apply_raw_many", None)
        if (
            raw_many is not None
            and cmds
            and all(c.data[:1] in _BIN_OPCODES for c in cmds)
        ):
            # binary commands skip per-op Command/typed materialization
            # (scalar-lane analog of the block lane's apply_raw path;
            # native stores take the statekernel from here)
            return list(raw_many([c.data for c in cmds]))
        bridge = self._bridge_for(shard)
        return [bridge.apply_command(c) for c in cmds]

    def apply_block(self, block, idxs, want_responses: bool = True):
        """Bulk apply for the engine's block lane (VectorStateMachine).

        One wave-level clock read; array indices are materialized to Python
        ints once so the inner loop is slice + dict work only.
        """
        import time as _time

        now = _time.time()
        n = len(self.machines)
        plane = self._native_plane
        if plane is not None:
            # subscribed stores demote (old-value capture for the
            # notification stream happens per op in the bridge)
            covered = np.asarray(idxs).tolist()
            stores = self._native_stores
            shards_l = block.shards
            if not any(
                stores[int(shards_l[i]) % n]._subscribed() for i in covered
            ):
                res = plane.apply_block_wave(
                    block, covered, now, want_responses
                )
                if res is not NotImplemented:
                    self._version += len(covered)
                    return res
        machines = self.machines
        shards = block.shards.tolist()
        starts = block.shard_starts.tolist()
        offs = block.cmd_offsets.tolist()
        data = block.data
        responses: list[list[bytes]] = []
        applied = 0
        for i in np.asarray(idxs).tolist():
            m = machines[shards[i] % n]
            lo, hi = starts[i], starts[i + 1]
            applied += 1
            if hi - lo == 1:
                b = data[offs[lo] : offs[lo + 1]]
                store = getattr(m, "store", None)
                if store is not None and b[:1] == b"\x01":
                    r = store.apply_set_bin_fast(b, now)
                    if r is not None:
                        if want_responses:
                            responses.append([r])
                        continue
            ops = [data[offs[j] : offs[j + 1]] for j in range(lo, hi)]
            raw_many = getattr(m, "apply_raw_many", None)
            if raw_many is not None:
                rs = raw_many(ops, now)
            else:
                bridge = self._bridge_for(shards[i])
                rs = [bridge.apply_command(Command.new(b)) for b in ops]
            if want_responses:
                responses.append(rs)
        self._version += applied
        return responses if want_responses else None

    def create_snapshot(self) -> Snapshot:
        self._version += 1
        doc = {
            "shards": [
                bridge.create_snapshot().to_bytes().hex() for bridge in self.bridges
            ]
        }
        return Snapshot.create(
            self._version, json.dumps(doc, separators=(",", ":")).encode()
        )

    def restore_snapshot(self, snapshot: Snapshot) -> None:
        snapshot.verify()
        doc = json.loads(snapshot.data)
        for bridge, blob_hex in zip(self.bridges, doc["shards"]):
            bridge.restore_snapshot(Snapshot.from_bytes(bytes.fromhex(blob_hex)))
        self._version = snapshot.version

    def restore_shards(self, snapshot: Snapshot, shard_ids) -> None:
        """Restore ONLY the given shards from the snapshot (sync adoption
        under mixed per-shard progress: the engine adopts a responder's
        state solely for shards where the responder is ahead — wholesale
        restore would regress shards where WE are ahead)."""
        snapshot.verify()
        doc = json.loads(snapshot.data)
        blobs = doc["shards"]
        for s in shard_ids:
            s = int(s)
            # tolerate a responder configured with fewer shards (reconfig
            # skew): indices beyond its snapshot are simply not adopted
            if 0 <= s < len(self.bridges) and s < len(blobs):
                self.bridges[s].restore_snapshot(
                    Snapshot.from_bytes(bytes.fromhex(blobs[s]))
                )
        self._version = max(self._version, snapshot.version)

    def get_state_summary(self) -> str:
        return f"{len(self.bridges)} shards"


def make_sharded_kv(
    num_shards: int,
    config: Optional[KVStoreConfig] = None,
    native: Optional[bool] = None,
) -> tuple[ShardedStateMachine, list[KVStoreSMR]]:
    """Build one `KVStoreSMR` per shard behind a routing SM.

    ``native`` selects the apply plane: True = the statekernel-backed
    :class:`~rabia_tpu.apps.native_store.NativeKVStore` per shard (one
    shared plane; decided waves apply in one C call), False = the Python
    :class:`KVStore` (the semantics owner), None (default) = native when
    the library is available and ``RABIA_PY_APPLY`` != 1."""
    if native is None:
        from rabia_tpu.apps.native_store import native_apply_available

        native = native_apply_available()
    if native:
        from rabia_tpu.apps.native_store import (
            NativeKVStore,
            NativeStorePlane,
        )

        plane = NativeStorePlane(num_shards, config)
        machines = [
            KVStoreSMR(config, store=NativeKVStore(config, plane, s))
            for s in range(num_shards)
        ]
    else:
        machines = [KVStoreSMR(config) for _ in range(num_shards)]
    return ShardedStateMachine(machines), machines


class ShardedKVService:
    """Client facade: key-routed KV operations through consensus.

    `submit` is the engine's `submit_batch`; injected so the service works
    with any engine (or a local loopback in tests). Three submission modes:

    - direct (default): one consensus slot per operation;
    - **adaptive batching** (pass ``batching=BatchConfig(...)``): ops
      accumulate per shard through a :class:`ShardedBatcher` (size+time
      flush, ±10% adaptive sizing — rabia-core/src/batching.rs:150-165) so
      one consensus slot carries ~target_size commands;
    - **block lane** (pass ``submit_block=engine.submit_block``):
      :meth:`set_many` ships a whole columnar
      :class:`~rabia_tpu.core.blocks.PayloadBlock` across shards in one
      submission.
    """

    def __init__(
        self,
        num_shards: int,
        submit: Callable,  # async (CommandBatch, shard) -> Future[list[bytes]]
        machines: Sequence[KVStoreSMR],
        submit_block: Optional[Callable] = None,  # async (PayloadBlock) -> Future
        batching: Optional[BatchConfig] = None,
    ) -> None:
        self.num_shards = num_shards
        self._submit = submit
        self._machines = list(machines)
        self._submit_block = submit_block
        self._batcher = ShardedBatcher(num_shards, batching) if batching else None
        self._op_futures: list[deque] = [deque() for _ in range(num_shards)]
        self._flusher: Optional[asyncio.Task] = None
        self._bg: set = set()

    def shard_of(self, key: str) -> int:
        return shard_for_key(key, self.num_shards)

    @property
    def batch_stats(self):
        """Per-shard BatchStats (adaptive mode only)."""
        return (
            [b.stats for b in self._batcher.batchers] if self._batcher else []
        )

    async def close(self) -> None:
        if self._flusher is not None:
            self._flusher.cancel()
            try:
                await self._flusher
            except asyncio.CancelledError:
                pass
            self._flusher = None
        if self._batcher is not None:
            # drain partial batches so no awaiting caller hangs on an op
            # that never flushed
            for batch in self._batcher.flush_all():
                self._dispatch_batch(int(batch.shard), batch)
        if self._bg:
            await asyncio.gather(*list(self._bg), return_exceptions=True)

    # -- adaptive batching lane ---------------------------------------------

    def _spawn(self, coro) -> None:
        t = asyncio.ensure_future(coro)
        self._bg.add(t)
        t.add_done_callback(self._bg.discard)

    def _dispatch_batch(self, shard: int, batch: CommandBatch) -> None:
        futs = [self._op_futures[shard].popleft() for _ in batch.commands]

        async def run():
            try:
                f = await self._submit(batch, shard)
                responses = await f
                for fu, r in zip(futs, responses):
                    if not fu.done():
                        fu.set_result(r)
            except Exception as e:
                for fu in futs:
                    if not fu.done():
                        fu.set_exception(e)

        self._spawn(run())

    async def _flush_loop(self) -> None:
        delay = max(self._batcher.config.max_batch_delay / 2, 0.001)
        while True:
            await asyncio.sleep(delay)
            for batch in self._batcher.poll_all():
                self._dispatch_batch(int(batch.shard), batch)

    async def _roundtrip_batched(self, op: KVOperation, shard: int) -> KVResult:
        if self._flusher is None:
            self._flusher = asyncio.ensure_future(self._flush_loop())
        codec = self._machines[shard]
        cmd = Command.new(codec.encode_command(op))
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._op_futures[shard].append(fut)
        batch = self._batcher.add(shard, cmd)
        if batch is not None:
            self._dispatch_batch(shard, batch)
        return codec.decode_response(await fut)

    # -- block lane -----------------------------------------------------------

    async def _block_roundtrip(
        self, keyed_ops: Sequence[tuple[str, bytes]]
    ) -> list[KVResult]:
        """Route (key, encoded-op) pairs shard-wise through one columnar
        block submission; results in input order."""
        if not keyed_ops:
            return []
        by_shard: dict[int, list[bytes]] = {}
        positions: dict[int, list[int]] = {}
        for pos, (k, op) in enumerate(keyed_ops):
            s = self.shard_of(k)
            by_shard.setdefault(s, []).append(op)
            positions.setdefault(s, []).append(pos)
        shards = sorted(by_shard)
        block = build_block(shards, [by_shard[s] for s in shards])
        fut = await self._submit_block(block)
        per_shard = await fut
        out: list[KVResult] = [KVResult.err("missing response")] * len(keyed_ops)
        for i, s in enumerate(shards):
            resp = per_shard[i]
            if isinstance(resp, Exception):
                for pos in positions[s]:
                    out[pos] = KVResult.err(str(resp))
            else:
                codec = self._machines[s]
                for pos, raw in zip(positions[s], resp):
                    # decode_response sniffs binary vs JSON — demoted
                    # shards come back through the scalar (JSON) path
                    out[pos] = codec.decode_response(raw)
        return out

    async def set_many(self, pairs: Sequence[tuple[str, str]]) -> list[KVResult]:
        """Write many keys in one columnar block submission (one consensus
        slot per covered shard). Falls back to per-op submission when the
        engine exposes no block lane."""
        if self._submit_block is None:
            return list(
                await asyncio.gather(*[self.set(k, v) for k, v in pairs])
            )
        return await self._block_roundtrip(
            [(k, encode_set_bin(k, v)) for k, v in pairs]
        )

    async def get_many(self, keys: Sequence[str]) -> list[KVResult]:
        """Linearizable bulk reads through consensus (one slot per covered
        shard), mirroring :meth:`set_many`. Falls back to per-op submission
        without a block lane."""
        if self._submit_block is None:
            return list(
                await asyncio.gather(*[self.get(k) for k in keys])
            )
        return await self._block_roundtrip(
            [(k, encode_op_bin(KVOperation.get(k))) for k in keys]
        )

    async def _roundtrip(self, op: KVOperation, shard: int) -> KVResult:
        if self._batcher is not None:
            return await self._roundtrip_batched(op, shard)
        codec = self._machines[shard]
        batch = CommandBatch.new(
            [Command.new(codec.encode_command(op))], shard=ShardId(shard)
        )
        fut = await self._submit(batch, shard)
        responses = await fut
        return codec.decode_response(responses[0])

    async def set(self, key: str, value: str) -> KVResult:
        return await self._roundtrip(KVOperation.set(key, value), self.shard_of(key))

    async def get(self, key: str) -> KVResult:
        return await self._roundtrip(KVOperation.get(key), self.shard_of(key))

    async def delete(self, key: str) -> KVResult:
        return await self._roundtrip(KVOperation.delete(key), self.shard_of(key))

    async def exists(self, key: str) -> bool:
        res = await self._roundtrip(KVOperation.exists(key), self.shard_of(key))
        return res.value == "true"

    def local_store(self, shard: int):
        """Direct access to a shard's local replica store (reads/tests)."""
        return self._machines[shard].store
