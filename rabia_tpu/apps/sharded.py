"""Shard-routing state machine + the client-facing sharded KV service.

No direct reference analog: the reference runs ONE consensus instance per
cluster (its kvstore_smr bridges a single store — smr_impl.rs:22-100). Here
the store is partitioned by key range and every shard is an independent
consensus instance — the batched ``S`` axis of the device kernel
(SURVEY.md §5.7, §7.1). This module provides:

- :class:`ShardedStateMachine` — engine-facing bytes SM that routes each
  committed batch to its shard's sub-machine (`CommandBatch.shard` carries
  the index through consensus);
- :class:`ShardedKVService` — the client API: key → shard → engine submit,
  with typed encode/decode via the shard's `KVStoreSMR`.
"""

from __future__ import annotations

import json
from typing import Callable, Optional, Sequence

from rabia_tpu.core.smr import SMRBridge, TypedStateMachine
from rabia_tpu.core.state_machine import Snapshot, StateMachine
from rabia_tpu.core.types import Command, CommandBatch, ShardId
from rabia_tpu.apps.kvstore import (
    KVOperation,
    KVResult,
    KVStoreConfig,
    KVStoreSMR,
    shard_for_key,
)


class ShardedStateMachine(StateMachine):
    """Routes committed batches to per-shard typed machines by batch.shard.

    The engine applies whole batches (engine.rs:684-706 analog); the shard
    index rides on the batch, so routing is O(1) and the per-shard machines
    stay single-writer (no cross-shard synchronization — matching how the
    kernel treats shards as independent instances).
    """

    def __init__(self, machines: Sequence[TypedStateMachine]) -> None:
        self.bridges = [SMRBridge(m) for m in machines]
        self.machines = list(machines)
        self._version = 0

    @property
    def num_shards(self) -> int:
        return len(self.bridges)

    def _bridge_for(self, shard: int) -> SMRBridge:
        return self.bridges[shard % len(self.bridges)]

    def apply_command(self, command: Command) -> bytes:
        # unrouted single commands go to shard 0 (engine tests / smoke)
        return self.bridges[0].apply_command(command)

    def apply_batch(self, batch: CommandBatch) -> list[bytes]:
        bridge = self._bridge_for(int(batch.shard))
        return [bridge.apply_command(c) for c in batch.commands]

    def create_snapshot(self) -> Snapshot:
        self._version += 1
        doc = {
            "shards": [
                bridge.create_snapshot().to_bytes().hex() for bridge in self.bridges
            ]
        }
        return Snapshot.create(
            self._version, json.dumps(doc, separators=(",", ":")).encode()
        )

    def restore_snapshot(self, snapshot: Snapshot) -> None:
        snapshot.verify()
        doc = json.loads(snapshot.data)
        for bridge, blob_hex in zip(self.bridges, doc["shards"]):
            bridge.restore_snapshot(Snapshot.from_bytes(bytes.fromhex(blob_hex)))
        self._version = snapshot.version

    def get_state_summary(self) -> str:
        return f"{len(self.bridges)} shards"


def make_sharded_kv(
    num_shards: int, config: Optional[KVStoreConfig] = None
) -> tuple[ShardedStateMachine, list[KVStoreSMR]]:
    """Build one `KVStoreSMR` per shard behind a routing SM."""
    machines = [KVStoreSMR(config) for _ in range(num_shards)]
    return ShardedStateMachine(machines), machines


class ShardedKVService:
    """Client facade: key-routed KV operations through consensus.

    `submit` is the engine's `submit_batch`; injected so the service works
    with any engine (or a local loopback in tests).
    """

    def __init__(
        self,
        num_shards: int,
        submit: Callable,  # async (CommandBatch, shard) -> Future[list[bytes]]
        machines: Sequence[KVStoreSMR],
    ) -> None:
        self.num_shards = num_shards
        self._submit = submit
        self._machines = list(machines)

    def shard_of(self, key: str) -> int:
        return shard_for_key(key, self.num_shards)

    async def _roundtrip(self, op: KVOperation, shard: int) -> KVResult:
        codec = self._machines[shard]
        batch = CommandBatch.new(
            [Command.new(codec.encode_command(op))], shard=ShardId(shard)
        )
        fut = await self._submit(batch, shard)
        responses = await fut
        return codec.decode_response(responses[0])

    async def set(self, key: str, value: str) -> KVResult:
        return await self._roundtrip(KVOperation.set(key, value), self.shard_of(key))

    async def get(self, key: str) -> KVResult:
        return await self._roundtrip(KVOperation.get(key), self.shard_of(key))

    async def delete(self, key: str) -> KVResult:
        return await self._roundtrip(KVOperation.delete(key), self.shard_of(key))

    async def exists(self, key: str) -> bool:
        res = await self._roundtrip(KVOperation.exists(key), self.shard_of(key))
        return res.value == "true"

    def local_store(self, shard: int):
        """Direct access to a shard's local replica store (reads/tests)."""
        return self._machines[shard].store
