"""Banking SMR app: validated transfers with a conservation invariant.

Reference parity: examples/banking_smr/src/lib.rs — `Account` in integer
cents (:40-77), commands Deposit/Withdraw/Transfer + account management
(:104-133), validation (positive amounts, per-transaction cap $10M,
balance checks) and state with transaction history + the `total_value`
conservation invariant (:186-261).
"""

from __future__ import annotations

import enum
import json
import time
from dataclasses import dataclass, field
from typing import Optional

from rabia_tpu.core.errors import StateMachineError
from rabia_tpu.core.smr import TypedStateMachine

MAX_TRANSACTION_CENTS = 10_000_000_00  # $10M per transaction (lib.rs cap)
MAX_HISTORY = 10_000


class BankOp(enum.Enum):
    CreateAccount = "create"
    Deposit = "deposit"
    Withdraw = "withdraw"
    Transfer = "transfer"
    GetBalance = "balance"
    ListAccounts = "list"


@dataclass(frozen=True)
class BankCommand:
    """One typed banking command (banking_smr lib.rs:104-133)."""

    op: BankOp
    account: str = ""
    to_account: str = ""
    amount_cents: int = 0

    @staticmethod
    def create(account: str, initial_cents: int = 0) -> "BankCommand":
        return BankCommand(BankOp.CreateAccount, account, amount_cents=initial_cents)

    @staticmethod
    def deposit(account: str, cents: int) -> "BankCommand":
        return BankCommand(BankOp.Deposit, account, amount_cents=cents)

    @staticmethod
    def withdraw(account: str, cents: int) -> "BankCommand":
        return BankCommand(BankOp.Withdraw, account, amount_cents=cents)

    @staticmethod
    def transfer(src: str, dst: str, cents: int) -> "BankCommand":
        return BankCommand(BankOp.Transfer, src, dst, cents)

    @staticmethod
    def balance(account: str) -> "BankCommand":
        return BankCommand(BankOp.GetBalance, account)


@dataclass(frozen=True)
class BankResponse:
    ok: bool
    balance_cents: Optional[int] = None
    accounts: Optional[tuple[str, ...]] = None
    error: Optional[str] = None

    @staticmethod
    def err(message: str) -> "BankResponse":
        return BankResponse(ok=False, error=message)


@dataclass
class Account:
    """Integer-cent account (lib.rs:40-77) — floats never touch money."""

    balance_cents: int = 0
    created_at: float = field(default_factory=time.time)
    transactions: int = 0


@dataclass(frozen=True)
class TransactionRecord:
    op: str
    account: str
    to_account: str
    amount_cents: int
    seq: int


class BankingSMR(TypedStateMachine[BankCommand, BankResponse, dict]):
    """Deterministic bank with validated mutations (lib.rs:186-261).

    Invariant: `total_value()` changes only via Deposit/Withdraw — a
    Transfer conserves the sum exactly (checked by tests and the fault
    harness after every scenario).
    """

    def __init__(self) -> None:
        self._accounts: dict[str, Account] = {}
        self._history: list[TransactionRecord] = []
        self._seq = 0
        self._cmd_cache: dict[bytes, BankCommand] = {}

    # -- invariant ----------------------------------------------------------

    def total_value(self) -> int:
        return sum(a.balance_cents for a in self._accounts.values())

    @property
    def accounts(self) -> dict[str, Account]:
        return self._accounts

    def history(self) -> list[TransactionRecord]:
        return list(self._history)

    # -- validation ---------------------------------------------------------

    @staticmethod
    def _validate_amount(cents: int) -> Optional[str]:
        if cents <= 0:
            return "amount must be positive"
        if cents > MAX_TRANSACTION_CENTS:
            return "amount exceeds per-transaction cap"
        return None

    def _record(self, cmd: BankCommand) -> None:
        self._seq += 1
        self._history.append(
            TransactionRecord(
                cmd.op.value, cmd.account, cmd.to_account, cmd.amount_cents, self._seq
            )
        )
        if len(self._history) > MAX_HISTORY:
            del self._history[: len(self._history) - MAX_HISTORY]

    # -- apply --------------------------------------------------------------

    def apply_command(self, command: BankCommand) -> BankResponse:
        self._bump_version()
        op = command.op
        if op == BankOp.CreateAccount:
            if not command.account:
                return BankResponse.err("account name required")
            if command.account in self._accounts:
                return BankResponse.err("account exists")
            if command.amount_cents < 0:
                return BankResponse.err("negative initial balance")
            self._accounts[command.account] = Account(command.amount_cents)
            self._record(command)
            return BankResponse(ok=True, balance_cents=command.amount_cents)

        if op == BankOp.GetBalance:
            acct = self._accounts.get(command.account)
            if acct is None:
                return BankResponse.err("no such account")
            return BankResponse(ok=True, balance_cents=acct.balance_cents)

        if op == BankOp.ListAccounts:
            return BankResponse(ok=True, accounts=tuple(sorted(self._accounts)))

        if op == BankOp.Deposit:
            err = self._validate_amount(command.amount_cents)
            if err:
                return BankResponse.err(err)
            acct = self._accounts.get(command.account)
            if acct is None:
                return BankResponse.err("no such account")
            acct.balance_cents += command.amount_cents
            acct.transactions += 1
            self._record(command)
            return BankResponse(ok=True, balance_cents=acct.balance_cents)

        if op == BankOp.Withdraw:
            err = self._validate_amount(command.amount_cents)
            if err:
                return BankResponse.err(err)
            acct = self._accounts.get(command.account)
            if acct is None:
                return BankResponse.err("no such account")
            if acct.balance_cents < command.amount_cents:
                return BankResponse.err("insufficient funds")
            acct.balance_cents -= command.amount_cents
            acct.transactions += 1
            self._record(command)
            return BankResponse(ok=True, balance_cents=acct.balance_cents)

        if op == BankOp.Transfer:
            err = self._validate_amount(command.amount_cents)
            if err:
                return BankResponse.err(err)
            src = self._accounts.get(command.account)
            dst = self._accounts.get(command.to_account)
            if src is None or dst is None:
                return BankResponse.err("no such account")
            if command.account == command.to_account:
                return BankResponse.err("self-transfer")
            if src.balance_cents < command.amount_cents:
                return BankResponse.err("insufficient funds")
            src.balance_cents -= command.amount_cents
            dst.balance_cents += command.amount_cents
            src.transactions += 1
            dst.transactions += 1
            self._record(command)
            return BankResponse(ok=True, balance_cents=src.balance_cents)

        return BankResponse.err("unknown op")  # pragma: no cover

    # -- state --------------------------------------------------------------

    def get_state(self) -> dict:
        return {k: a.balance_cents for k, a in self._accounts.items()}

    def set_state(self, state: dict) -> None:
        self._accounts = {k: Account(int(v)) for k, v in state.items()}

    # -- codecs -------------------------------------------------------------

    def encode_command(self, command: BankCommand) -> bytes:
        return json.dumps(
            {
                "op": command.op.value,
                "account": command.account,
                "to": command.to_account,
                "cents": command.amount_cents,
            },
            separators=(",", ":"),
        ).encode()

    def decode_command(self, data: bytes) -> BankCommand:
        # bounded decode cache: commands are immutable and command bytes
        # repeat heavily under hot accounts (a deposit storm decodes ONE
        # JSON doc, not one per committed slot) — the config-4 profile
        # showed per-op json.loads as the largest apply-path cost
        if not isinstance(data, bytes):  # bytearray/memoryview callers
            data = bytes(data)
        cached = self._cmd_cache.get(data)
        if cached is not None:
            return cached
        try:
            doc = json.loads(data.decode())
            cmd = BankCommand(
                BankOp(doc["op"]),
                doc.get("account", ""),
                doc.get("to", ""),
                int(doc.get("cents", 0)),
            )
        except (ValueError, KeyError, UnicodeDecodeError) as e:
            raise StateMachineError(f"bad bank command: {e}") from None
        if len(self._cmd_cache) > 4096:  # bound against command spraying
            self._cmd_cache.clear()
        self._cmd_cache[data] = cmd
        return cmd

    def encode_response(self, response: BankResponse) -> bytes:
        if response.accounts is None and response.error is None:
            # the steady-state shape (ok + balance): hand-framed,
            # byte-identical to the json.dumps output below
            bal = response.balance_cents
            return (
                '{"ok":%s,"balance":%s,"accounts":null,"error":null}'
                % (
                    "true" if response.ok else "false",
                    "null" if bal is None else bal,
                )
            ).encode()
        return json.dumps(
            {
                "ok": response.ok,
                "balance": response.balance_cents,
                "accounts": list(response.accounts) if response.accounts else None,
                "error": response.error,
            },
            separators=(",", ":"),
        ).encode()

    def decode_response(self, data: bytes) -> BankResponse:
        doc = json.loads(data)
        return BankResponse(
            ok=bool(doc["ok"]),
            balance_cents=doc.get("balance"),
            accounts=tuple(doc["accounts"]) if doc.get("accounts") else None,
            error=doc.get("error"),
        )

    def serialize_state(self) -> bytes:
        doc = {
            "seq": self._seq,
            "accounts": {
                k: [a.balance_cents, a.created_at, a.transactions]
                for k, a in sorted(self._accounts.items())
            },
        }
        return json.dumps(doc, separators=(",", ":"), sort_keys=True).encode()

    def deserialize_state(self, data: bytes) -> None:
        doc = json.loads(data)
        self._seq = int(doc["seq"])
        self._accounts = {
            k: Account(int(v[0]), float(v[1]), int(v[2]))
            for k, v in doc["accounts"].items()
        }
        self._history = []
