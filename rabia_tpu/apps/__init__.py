"""Typed SMR applications: counter, sharded KV store, banking.

The reference's example app crates (SURVEY.md §1.6, C27-C29) rebuilt on the
typed SMR API, with the KV store sharded by key range to expose the device
kernel's batch axis.
"""

from rabia_tpu.apps.banking import (
    Account,
    BankCommand,
    BankingSMR,
    BankOp,
    BankResponse,
)
from rabia_tpu.apps.counter import (
    CounterCommand,
    CounterOp,
    CounterResponse,
    CounterSMR,
    CounterState,
)
from rabia_tpu.apps.kvstore import (
    ChangeNotification,
    ChangeType,
    KVOperation,
    KVOpType,
    BatchResult,
    KVResult,
    KVResultKind,
    OperationBatch,
    KVStore,
    KVStoreSMR,
    NotificationBus,
    NotificationFilter,
    StoreError,
    StoreErrorKind,
    shard_for_key,
)
from rabia_tpu.apps.sharded import (
    ShardedKVService,
    ShardedStateMachine,
    make_sharded_kv,
)
from rabia_tpu.apps.vector_kv import (
    VectorKVStore,
    VectorShardedKV,
)

__all__ = [
    "Account",
    "BankCommand",
    "BankOp",
    "BankResponse",
    "BankingSMR",
    "ChangeNotification",
    "ChangeType",
    "CounterCommand",
    "CounterOp",
    "CounterResponse",
    "CounterSMR",
    "CounterState",
    "KVOpType",
    "KVOperation",
    "BatchResult",
    "KVResult",
    "KVResultKind",
    "OperationBatch",
    "KVStore",
    "KVStoreSMR",
    "NotificationBus",
    "NotificationFilter",
    "ShardedKVService",
    "ShardedStateMachine",
    "StoreError",
    "StoreErrorKind",
    "VectorKVStore",
    "VectorShardedKV",
    "make_sharded_kv",
    "shard_for_key",
]
