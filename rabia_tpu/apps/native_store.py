"""ctypes bridge to the native apply plane (native/statekernel.cpp).

The statekernel is an open-addressing byte-key/byte-value KV state
machine in C that consumes decided batches as the SAME binary op records
the wire already carries (apps/kvstore.py encoding: SET/GET/DEL/EXISTS/
CLEAR/CAS) and stages result frames as ``[u32 LE len][payload]`` records
— the ``rt_broadcast_frames`` format — so a whole decided wave applies
in ONE C call with zero per-op Python object materialization.

Two classes:

- :class:`NativeStorePlane` — one replica's plane: owns the C handle for
  ALL shard stores, the SKC_* counter block (zero-copy ndarray view,
  RKC_* conventions) and the FrEvent flight ring (one FRE_APPLY record
  per wave on the C path).
- :class:`NativeKVStore` — the per-shard view implementing the
  :class:`~rabia_tpu.apps.kvstore.KVStore` surface (CRUD, snapshots,
  checksum, stats, notifications) over one store index of a plane.

Semantics owner: the Python binary-op apply in apps/kvstore.py
(``apply_op_bin``/``apply_ops_bin``). ``RABIA_PY_APPLY=1`` forces it;
the conformance gate (testing/conformance.run_ops_on_both_apply_paths +
``fuzz_conformance.py --apply``) pins byte-identical per-op results and
state hashes between the two paths.

Notification semantics: when a store has live subscribers the wave fast
path demotes to a per-op path that fetches the old value before each
mutation and publishes the same :class:`ChangeNotification` stream the
Python store does — correctness over speed on the (rare) subscribed
store; unsubscribed stores never cross into Python per op.
"""

from __future__ import annotations

import ctypes
import hashlib
import json
import time
import zlib
from typing import Optional, Sequence

import numpy as np

from rabia_tpu.core.config import KVStoreConfig
from rabia_tpu.apps.kvstore import (
    ChangeNotification,
    ChangeType,
    KVOperation,
    KVOpType,
    KVResult,
    NotificationBus,
    StoreError,
    StoreErrorKind,
    StoreStats,
    ValueEntry,
    decode_result_bin,
    encode_op_bin,
)

# SKC_* counter block names, in index order (statekernel.cpp). Versioned
# append-only like RK_COUNTER_NAMES: newer libraries may expose more
# (ignored), older fewer (read as 0).
SK_COUNTER_NAMES = (
    "waves",
    "ops",
    "sets",
    "gets",
    "dels",
    "exists",
    "clears",
    "cas_hits",
    "cas_misses",
    "errors",
    "bytes_in",
    "bytes_out",
    "rehashes",
    "delta_snapshots",
    "delta_entries",
)

class NativeResultGroup(Sequence):  # type: ignore[type-arg]
    """One batch's per-op result frames as a LAZY view over a wave's
    copied staging buffer (variable-width records; ``offs`` holds record
    starts, payloads skip the 4-byte length prefix). Result bytes
    materialize only when a client actually reads them — the settle path
    stores the view (the FrameSeq idiom of apps/vector_kv.py)."""

    __slots__ = ("raw", "offs", "lo", "n")

    def __init__(self, raw: bytes, offs: list, lo: int, n: int) -> None:
        self.raw = raw
        self.offs = offs
        self.lo = lo
        self.n = n

    def __len__(self) -> int:
        return self.n

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(self.n))]
        if i < 0:
            i += self.n
        if not (0 <= i < self.n):
            raise IndexError(i)
        j = self.lo + i
        return self.raw[self.offs[j] + 4 : self.offs[j + 1]]

    def __eq__(self, other) -> bool:
        if not isinstance(other, (list, tuple, Sequence)):
            return NotImplemented
        return len(self) == len(other) and all(
            a == b for a, b in zip(self, other)
        )

    def __repr__(self) -> str:
        return f"NativeResultGroup(n={self.n})"


def binary_wave_eligible(
    data, cmd_offsets, shard_starts, n_entries: int, idxs
) -> bool:
    """First-byte binary-op eligibility (opcodes 1..6) over the COVERED
    commands of a wave — the ONE source of the routing rule shared by
    ``NativeStorePlane.apply_block_wave`` and the runtime bridge's wave
    pump (the C runtime mirrors it natively for announces it binds).
    Consensus-critical: proposer and followers must route the same wave
    the same way, so any change here changes the wire-visible behavior.

    A JSON command on a NON-covered index must not demote the wave, and
    zero-length commands are native-eligible (the C kernel emits the
    same "malformed op" frame the Python owner does) — a trailing empty
    command's offset equals ``len(data)``, so they are excluded from the
    first-byte gather."""
    if not len(data):
        return True
    offs = cmd_offsets
    if len(idxs) == n_entries:
        cov = np.arange(len(offs) - 1)
    else:
        cov = np.concatenate(
            [np.arange(shard_starts[i], shard_starts[i + 1]) for i in idxs]
        )
    lens = offs[cov + 1] - offs[cov]
    nonempty = cov[lens > 0]
    first = np.frombuffer(data, np.uint8)[offs[nonempty]]
    return bool(((first >= 1) & (first <= 6)).all())


def native_apply_available() -> bool:
    """True when the statekernel library is loadable and not disabled
    (``RABIA_PY_APPLY=1`` forces the Python apply path)."""
    from rabia_tpu.native.build import load_statekernel

    return load_statekernel() is not None


class NativeStorePlane:
    """One replica's native apply plane: N shard stores behind one C
    handle, applied to with one call per decided wave."""

    def __init__(
        self, n_stores: int, config: Optional[KVStoreConfig] = None
    ) -> None:
        from rabia_tpu.native.build import load_statekernel

        lib = load_statekernel()
        if lib is None:
            raise StoreError(
                StoreErrorKind.Internal, "statekernel unavailable"
            )
        self.lib = lib
        self.config = config or KVStoreConfig()
        self.n_stores = int(n_stores)
        self.handle = lib.sk_plane_create(
            self.n_stores,
            self.config.max_keys,
            self.config.max_key_length,
            self.config.max_value_size,
        )
        if not self.handle:
            raise StoreError(StoreErrorKind.Internal, "sk_plane_create failed")
        # observability: zero-copy view over the C counter block
        n_ctr = int(lib.sk_counters_count())
        self.counters_version = int(lib.sk_counters_version())
        cbuf = (ctypes.c_uint64 * n_ctr).from_address(
            lib.sk_counters(self.handle)
        )
        self.counters = np.frombuffer(cbuf, np.uint64)
        self._stats_buf = np.zeros(3, np.uint64)
        self._stats_ptr = self._stats_buf.ctypes.data
        # flight ring: FrEvent ABI view (obs/flight.FR_DTYPE)
        from rabia_tpu.obs.flight import FR_DTYPE

        self._fr_frozen: Optional[np.ndarray] = None
        if int(lib.sk_flight_record_size()) != FR_DTYPE.itemsize:
            raise StoreError(
                StoreErrorKind.Internal,
                "statekernel flight record ABI mismatch",
            )
        cap = int(lib.sk_flight_cap())
        self.flight_version = int(lib.sk_flight_version())
        fbuf = (ctypes.c_uint8 * (cap * FR_DTYPE.itemsize)).from_address(
            lib.sk_flight(self.handle)
        )
        self._fr_view = np.frombuffer(fbuf, FR_DTYPE)

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        if self.handle:
            self.counters = self.counters.copy()
            self._fr_frozen = self.flight_snapshot()
            h, self.handle = self.handle, None
            self.lib.sk_plane_destroy(h)

    def __del__(self):  # pragma: no cover - GC timing
        try:
            self.close()
        except Exception:
            pass

    # -- observability -------------------------------------------------------

    def counter(self, name: str) -> int:
        try:
            i = SK_COUNTER_NAMES.index(name)
        except ValueError:
            return 0
        return int(self.counters[i]) if i < len(self.counters) else 0

    def counters_dict(self) -> dict[str, int]:
        return {
            n: int(self.counters[i]) if i < len(self.counters) else 0
            for i, n in enumerate(SK_COUNTER_NAMES)
        }

    def flight_head(self) -> int:
        if not self.handle:
            return 0
        return int(self.lib.sk_flight_head(self.handle))

    def flight_snapshot(self) -> np.ndarray:
        """Chronological copy of the live ring window (oldest first)."""
        from rabia_tpu.obs.flight import FR_DTYPE

        if self._fr_frozen is not None:
            return self._fr_frozen
        if not self.handle:
            return np.zeros(0, FR_DTYPE)
        head = self.flight_head()
        cap = len(self._fr_view)
        if head <= cap:
            return self._fr_view[:head].copy()
        i = head % cap
        return np.concatenate([self._fr_view[i:], self._fr_view[:i]])

    # -- the wave apply ------------------------------------------------------

    def _slice_results(
        self, group_bounds: Sequence[tuple[int, int]]
    ) -> list[NativeResultGroup]:
        """Staged result frames as lazy per-group views over ONE copy of
        the staging buffer, grouped by (op_lo, op_hi) process-order
        ranges — per-op bytes materialize only on read."""
        lib = self.lib
        total = int(lib.sk_out_count(self.handle))
        # one copy of the staged buffer + plain-int offsets: per-record
        # numpy scalar indexing costs more than the whole C apply
        offs = np.frombuffer(
            (ctypes.c_int64 * total).from_address(
                lib.sk_out_offs(self.handle)
            ),
            np.int64,
        ).tolist()
        raw = (
            ctypes.string_at(lib.sk_out_buf(self.handle), offs[-1])
            if offs[-1]
            else b""
        )
        return [
            NativeResultGroup(raw, offs, lo, hi - lo)
            for lo, hi in group_bounds
        ]

    def staged_results(self) -> tuple[int, int]:
        """(buffer address, byte length) of the last wave's staged result
        records — ``[u32 LE len][payload]`` framing, directly consumable
        by ``rt_broadcast_frames``-style staging. Valid until the next
        apply call — the borrowed pointer is only sound when no native
        runtime thread shares this plane (it applies concurrently);
        bracket with ``sk_plane_lock``/``sk_plane_unlock`` otherwise."""
        lib = self.lib
        total = int(lib.sk_out_count(self.handle))
        if total == 0:
            return 0, 0
        offs = (ctypes.c_int64 * total).from_address(
            lib.sk_out_offs(self.handle)
        )
        return int(lib.sk_out_buf(self.handle)), int(offs[total - 1])

    def apply_block_wave(self, block, idxs, now: float, want_responses: bool):
        """Apply selected covered-indices of a decided PayloadBlock in one
        C call. Returns grouped responses (or None when not wanted), or
        ``NotImplemented`` when the wave has non-binary commands or a
        subscribed store (caller falls back to the Python path)."""
        data = block.data
        offs = np.ascontiguousarray(block.cmd_offsets, np.int64)
        idxs = np.ascontiguousarray(np.asarray(idxs, np.int64))
        shards = np.ascontiguousarray(block.shards, np.int64)
        starts = np.ascontiguousarray(block.shard_starts, np.int64)
        if len(idxs) == 0:
            return [] if want_responses else None
        if not binary_wave_eligible(data, offs, starts, len(shards), idxs):
            return NotImplemented
        # hold the plane lock across the apply AND the result read-out:
        # with the native runtime active, its io/tick thread applies
        # decided waves on this same plane and clears/regrows out_buf —
        # an unlocked window between our apply returning and the slice
        # copy-out would hand back another wave's (or freed) bytes. The
        # plane mutex is recursive, so bracketing the sk call is safe.
        self.lib.sk_plane_lock(self.handle)
        try:
            rc = self.lib.sk_apply_wave(
                self.handle,
                data,
                offs.ctypes.data,
                shards.ctypes.data,
                starts.ctypes.data,
                idxs.ctypes.data,
                len(idxs),
                now,
                1 if want_responses else 0,
            )
            if rc < 0:
                raise StoreError(
                    StoreErrorKind.Internal, f"sk_apply_wave rc={rc}"
                )
            if not want_responses:
                return None
            bounds = []
            pos = 0
            st = starts
            for i in idxs:
                n = int(st[i + 1] - st[i])
                bounds.append((pos, pos + n))
                pos += n
            return self._slice_results(bounds)
        finally:
            self.lib.sk_plane_unlock(self.handle)

    def apply_ops(
        self, store_idx: int, ops: Sequence[bytes], now: float,
        want_responses: bool = True,
    ) -> Optional[list[bytes]]:
        """Apply a list of binary op records against one store (the
        scalar lane / direct-call path); per-op result frames."""
        n = len(ops)
        if n == 0:
            return [] if want_responses else None
        if n == 1:
            data = ops[0]
            offs = np.asarray([0, len(data)], np.int64)
        else:
            data = b"".join(ops)
            offs = np.zeros(n + 1, np.int64)
            np.cumsum([len(o) for o in ops], out=offs[1:])
        # apply + read-out under one plane-lock bracket (see
        # apply_block_wave: the runtime thread shares out_buf)
        self.lib.sk_plane_lock(self.handle)
        try:
            rc = self.lib.sk_apply_ops(
                self.handle,
                store_idx,
                data,
                offs.ctypes.data,
                n,
                now,
                1 if want_responses else 0,
            )
            if rc < 0:
                raise StoreError(
                    StoreErrorKind.Internal, f"sk_apply_ops rc={rc}"
                )
            if not want_responses:
                return None
            return self._slice_results([(0, n)])[0]
        finally:
            self.lib.sk_plane_unlock(self.handle)

    # -- per-store accessors -------------------------------------------------

    def store_size(self, idx: int) -> int:
        return int(self.lib.sk_store_size(self.handle, idx))

    def store_version(self, idx: int) -> int:
        return int(self.lib.sk_store_version(self.handle, idx))

    def set_store_version(self, idx: int, v: int) -> None:
        self.lib.sk_set_version(self.handle, idx, v)

    def store_stats(self, idx: int) -> tuple[int, int, int]:
        self.lib.sk_store_stats(self.handle, idx, self._stats_ptr)
        b = self._stats_buf
        return int(b[0]), int(b[1]), int(b[2])

    def get(self, idx: int, key: bytes):
        """(value bytes, version) or None.

        Bracketed by the plane lock: ``sk_get`` hands out a BORROWED
        value pointer, and under the native engine runtime a GIL-free
        thread may be applying a wave concurrently — the lock keeps the
        bytes alive across the copy-out (uncontended cost is
        nanoseconds)."""
        val = ctypes.c_void_p()
        ver = ctypes.c_uint64()
        self.lib.sk_plane_lock(self.handle)
        try:
            vlen = self.lib.sk_get(
                self.handle, idx, key, len(key),
                ctypes.byref(val), ctypes.byref(ver),
            )
            if vlen < 0:
                return None
            return (
                ctypes.string_at(val.value, vlen) if vlen else b"",
                int(ver.value),
            )
        finally:
            self.lib.sk_plane_unlock(self.handle)

    def export_entries(self, idx: int) -> list[tuple[bytes, bytes, int, float, float]]:
        """All (key, value, version, created, updated) entries of one
        store (arbitrary order; callers sort)."""
        self.lib.sk_plane_lock(self.handle)
        try:
            need = int(self.lib.sk_export_size(self.handle, idx))
            if need <= 0:
                return []
            buf = np.empty(need, np.uint8)
            got = int(
                self.lib.sk_export(self.handle, idx, buf.ctypes.data, need)
            )
        finally:
            self.lib.sk_plane_unlock(self.handle)
        if got < 0:
            raise StoreError(StoreErrorKind.Internal, "sk_export failed")
        raw = buf.tobytes()
        out = []
        pos = 0
        while pos < got:
            klen = int.from_bytes(raw[pos : pos + 4], "little")
            vlen = int.from_bytes(raw[pos + 4 : pos + 8], "little")
            version = int.from_bytes(raw[pos + 8 : pos + 16], "little")
            created = np.frombuffer(raw, np.float64, 1, pos + 16)[0]
            updated = np.frombuffer(raw, np.float64, 1, pos + 24)[0]
            key = raw[pos + 32 : pos + 32 + klen]
            val = raw[pos + 32 + klen : pos + 32 + klen + vlen]
            out.append((key, val, version, float(created), float(updated)))
            pos += 32 + klen + vlen
        return out

    def snapshot_delta(self, idx: int) -> Optional[bytes]:
        """The store's incremental-snapshot frame: entries mutated since
        the last :meth:`snapshot_mark`, plus the deletion log and clear
        flag (statekernel.cpp delta format). Returns None when only a
        FULL snapshot is faithful (deletion-log overflow) — the caller
        falls back to :meth:`export_entries`. Does NOT advance the mark;
        call :meth:`snapshot_mark` once the frame is durable."""
        self.lib.sk_plane_lock(self.handle)
        try:
            need = int(self.lib.sk_snapshot_delta_size(self.handle, idx))
            if need == -3:
                return None
            if need < 0:
                raise StoreError(
                    StoreErrorKind.Internal, "sk_snapshot_delta_size failed"
                )
            buf = np.empty(max(need, 1), np.uint8)
            got = int(
                self.lib.sk_snapshot_delta(
                    self.handle, idx, buf.ctypes.data, need
                )
            )
        finally:
            self.lib.sk_plane_unlock(self.handle)
        if got == -3:
            return None
        if got < 0:
            raise StoreError(StoreErrorKind.Internal, "sk_snapshot_delta failed")
        return buf[:got].tobytes()

    def snapshot_mark(self, idx: int) -> None:
        self.lib.sk_snapshot_mark(self.handle, idx)

    def clear_store(self, idx: int) -> None:
        self.lib.sk_clear_store(self.handle, idx)

    def delete_raw(self, idx: int, key: bytes) -> bool:
        """Restore-path delete: no stats, no version bump, no deletion
        log (the frame being restored already records it)."""
        return self.lib.sk_delete_raw(self.handle, idx, key, len(key)) == 1

    def insert_raw(
        self, idx: int, key: bytes, val: bytes, version: int,
        created: float, updated: float,
    ) -> None:
        rc = self.lib.sk_insert_raw(
            self.handle, idx, key, len(key), val, len(val),
            version, created, updated,
        )
        if rc != 0:
            raise StoreError(
                StoreErrorKind.Internal, f"sk_insert_raw rc={rc}"
            )

    def add_stats(self, idx: int, ops: int, reads: int, writes: int) -> None:
        self.lib.sk_add_stats(self.handle, idx, ops, reads, writes)


class NativeKVStore:
    """Per-shard view of a :class:`NativeStorePlane` implementing the
    :class:`~rabia_tpu.apps.kvstore.KVStore` surface.

    Construct standalone (owns a 1-store plane) or as a shard view
    (``NativeKVStore(config, plane=plane, idx=s)`` — how
    :func:`~rabia_tpu.apps.sharded.make_sharded_kv` builds them).
    """

    is_native = True

    def __init__(
        self,
        config: Optional[KVStoreConfig] = None,
        plane: Optional[NativeStorePlane] = None,
        idx: int = 0,
    ) -> None:
        self.config = config or KVStoreConfig()
        self.plane = plane or NativeStorePlane(1, self.config)
        self.idx = int(idx)
        self.notifications = (
            NotificationBus() if self.config.notifications_enabled else None
        )

    # -- apply plane ---------------------------------------------------------

    def _subscribed(self) -> bool:
        bus = self.notifications
        return bus is not None and bool(bus._subs)

    def apply_bin_many(
        self, ops: Sequence[bytes], now: Optional[float] = None
    ) -> list[bytes]:
        """Apply binary op records in order; binary result frames —
        byte-identical to :func:`~rabia_tpu.apps.kvstore.apply_ops_bin`
        on the Python store (the conformance-pinned contract)."""
        if now is None:
            now = time.time()
        if not self._subscribed():
            return self.plane.apply_ops(self.idx, list(ops), now)
        # subscribed store: per-op so old values can be captured for the
        # notification stream (the Python store's semantics)
        return [self._apply_one_notify(b, now) for b in ops]

    def apply_bin(self, op: bytes, now: Optional[float] = None) -> bytes:
        return self.apply_bin_many([op], now)[0]

    def apply_set_bin_fast(self, b: bytes, now: float) -> Optional[bytes]:
        """KVStore fast-path API parity: one binary SET. Returns None
        only when the op must take a slow path the caller owns (never
        for the native store — the C kernel IS the fast path)."""
        return self.apply_bin(b, now)

    def _apply_one_notify(self, op: bytes, now: float) -> bytes:
        """One op with notification publication (subscribed stores)."""
        bus = self.notifications
        kind = op[0] if op else 0
        key = b""
        old = None
        if kind in (1, 3, 6) and len(op) >= 3:
            klen = op[1] | (op[2] << 8)
            if 3 + klen <= len(op):
                key = op[3 : 3 + klen]
                got = self.plane.get(self.idx, key)
                old = got[0] if got is not None else None
        prev_size = self.plane.store_size(self.idx)
        res = self.plane.apply_ops(self.idx, [op], now)[0]
        if bus is None or res[:1] != b"\x00":
            return res
        version = self.plane.store_version(self.idx)
        try:
            key_s = key.decode()
            old_s = old.decode() if old is not None else None
        except UnicodeDecodeError:  # pragma: no cover - validated upstream
            return res
        if kind in (1, 6):  # SET / CAS applied
            newv = self.plane.get(self.idx, key)
            new_s = newv[0].decode() if newv else None
            bus.publish(
                ChangeNotification(
                    key_s,
                    ChangeType.Updated if old is not None else ChangeType.Created,
                    old_s,
                    new_s,
                    version,
                )
            )
        elif kind == 3 and old is not None:  # DEL hit
            bus.publish(
                ChangeNotification(
                    key_s, ChangeType.Deleted, old_s, None, version
                )
            )
        elif kind == 5 and prev_size >= 0:  # CLEAR
            bus.publish(
                ChangeNotification("", ChangeType.Cleared, None, None, version)
            )
        return res

    # -- CRUD (KVStore API parity; direct/local use) -------------------------

    def _roundtrip(self, op: KVOperation) -> KVResult:
        res = decode_result_bin(self.apply_bin(encode_op_bin(op)))
        if res.kind.value == "error" and res.error:
            # method-call parity: KVStore raises StoreError for
            # validation failures; map the canonical texts back
            # ("StoreError: <kind>[: detail]" — the apply_op_bin str(e)
            # framing)
            text = res.error
            if text.startswith("StoreError: "):
                text = text[len("StoreError: "):]
            head = text.split(":", 1)[0]
            try:
                kind = StoreErrorKind(head)
            except ValueError:
                return res
            if kind in (
                StoreErrorKind.KeyEmpty,
                StoreErrorKind.KeyTooLong,
                StoreErrorKind.ValueTooLarge,
                StoreErrorKind.StoreFull,
            ):
                raise StoreError(
                    kind, text.split(": ", 1)[1] if ": " in text else ""
                )
        return res

    def set(self, key: str, value: str) -> KVResult:
        return self._roundtrip(KVOperation.set(key, value))

    def cas(self, key: str, value: str, expected_version: int) -> KVResult:
        return self._roundtrip(KVOperation.cas(key, value, expected_version))

    def get(self, key: str) -> KVResult:
        return self._roundtrip(KVOperation.get(key))

    def delete(self, key: str) -> KVResult:
        return self._roundtrip(KVOperation.delete(key))

    def exists(self, key: str) -> KVResult:
        return self._roundtrip(KVOperation.exists(key))

    def clear(self) -> int:
        res = self._roundtrip(KVOperation(KVOpType.Clear))
        return int(res.value or 0)

    def get_with_metadata(self, key: str) -> Optional[ValueEntry]:
        kb = key.encode()
        for k, v, ver, created, updated in self.plane.export_entries(self.idx):
            if k == kb:
                return ValueEntry(v.decode(), ver, created, updated)
        return None

    def keys(self, prefix: str = "") -> list[str]:
        ks = sorted(
            k.decode() for k, *_ in self.plane.export_entries(self.idx)
        )
        if prefix:
            return [k for k in ks if k.startswith(prefix)]
        return ks

    def apply_operations(self, ops: Sequence[KVOperation]) -> list[KVResult]:
        out = []
        for op in ops:
            try:
                out.append(self._roundtrip(op))
            except StoreError as e:
                out.append(KVResult.err(str(e)))
        return out

    def size(self) -> int:
        return self.plane.store_size(self.idx)

    def __len__(self) -> int:
        return self.size()

    @property
    def version(self) -> int:
        return self.plane.store_version(self.idx)

    @property
    def stats(self) -> StoreStats:
        ops, reads, writes = self.plane.store_stats(self.idx)
        return StoreStats(
            total_operations=ops, reads=reads, writes=writes,
            keys=self.size(),
        )

    # -- snapshots / integrity (KVStore wire-format parity) ------------------

    def _sorted_entries(self):
        return sorted(
            self.plane.export_entries(self.idx), key=lambda e: e[0].decode()
        )

    def snapshot_bytes(self) -> bytes:
        doc = {
            "version": self.version,
            "data": {
                k.decode(): [v.decode(), ver, created, updated]
                for k, v, ver, created, updated in self._sorted_entries()
            },
        }
        payload = json.dumps(doc, separators=(",", ":"), sort_keys=True).encode()
        checksum = zlib.crc32(payload) & 0xFFFFFFFF
        return checksum.to_bytes(4, "little") + payload

    def restore_bytes(self, raw: bytes) -> None:
        if len(raw) < 4:
            raise StoreError(StoreErrorKind.SnapshotCorrupt, "too short")
        checksum = int.from_bytes(raw[:4], "little")
        payload = raw[4:]
        if zlib.crc32(payload) & 0xFFFFFFFF != checksum:
            raise StoreError(StoreErrorKind.ChecksumMismatch)
        try:
            doc = json.loads(payload)
            items = [
                (k, v[0], int(v[1]), float(v[2]), float(v[3]))
                for k, v in doc["data"].items()
            ]
            version = int(doc["version"])
        except (ValueError, KeyError, IndexError) as e:
            raise StoreError(StoreErrorKind.SnapshotCorrupt, str(e)) from None
        self.plane.clear_store(self.idx)
        for k, v, ver, created, updated in items:
            self.plane.insert_raw(
                self.idx, k.encode(), v.encode(), ver, created, updated
            )
        self.plane.set_store_version(self.idx, version)

    def checksum(self) -> int:
        """Content hash over sorted (key, value, version) — identical to
        :meth:`KVStore.checksum` for identical logical state (the
        conformance gate's state-hash leg)."""
        h = hashlib.blake2s(digest_size=8)
        for k, v, ver, *_ in self._sorted_entries():
            h.update(k)
            h.update(v)
            h.update(ver.to_bytes(8, "little"))
        return int.from_bytes(h.digest(), "little")

    # -- state dict (KVStoreSMR get_state/set_state parity) ------------------

    def get_state_dict(self) -> dict:
        return {
            k.decode(): v.decode()
            for k, v, *_ in self.plane.export_entries(self.idx)
        }

    def set_state_dict(self, state: dict) -> None:
        self.plane.clear_store(self.idx)
        now = time.time()
        for k, v in state.items():
            self.plane.insert_raw(self.idx, k.encode(), v.encode(), 0, now, now)
