"""Numpy host kernel: the engine's CPU-side twin of :class:`NodeKernel`.

Why this exists: the host engine paces consensus in *rounds* — one
``node_step`` per round per replica. A jitted XLA call on the CPU backend
costs ~1 ms of dispatch at S=4096 (and a tunneled TPU costs a full RTT),
which caps an engine round loop far below the throughput the vectorized
protocol math actually allows. The same int8 array program evaluated with
plain numpy costs ~0.1 ms and its outputs are *already host arrays* (no
device→host mirror transfers), so the engine's hot loop runs on this class
whenever its kernel state lives on host; the JAX :class:`NodeKernel` remains
the device path, where thousands of shards amortize one dispatch
(SURVEY.md §7.4.4).

Layout: ledgers are **replica-major** ``[R, S]`` (the transpose of the JAX
kernel's ``[S, R]``) — vote ingest writes one sender row at a time, and the
quorum tallies become contiguous row sums instead of strided axis-1
reductions (~30× faster in numpy). The engine scatters arriving votes
directly into the ledger rows (:meth:`HostNodeKernel.offer_votes`), so the
hot path has no per-round inbox materialization at all.

Bit-identity contract: every transition here is element-for-element the
same as ``NodeKernel.start_slots`` / ``node_step`` (including the portable
common coin, which was designed to evaluate identically under numpy and
XLA — see ``phase_driver._coin_bits``). ``tests/test_host_kernel.py``
enforces the contract on randomized round sequences.

Reference parity: the per-phase math of rabia-engine/src/engine.rs:424-706
(vote rules, tallies, coin, decision), vectorized over shards.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

from rabia_tpu.core.types import ABSENT, V0, V1, VQUESTION, f_plus_1, quorum_size
from rabia_tpu.kernel.phase_driver import (
    NodeOutbox,
    R1_WAIT,
    R2_WAIT,
    _coin_bits,
    coin_threshold,
)

I8 = np.int8
I32 = np.int32
_ABS = np.int8(ABSENT)


class HostNodeState(NamedTuple):
    """One node's consensus state over its S shards (host arrays).

    Same fields as :class:`~rabia_tpu.kernel.phase_driver.NodeState`, but
    ``led1``/``led2`` are ``[R, S]`` (replica-major; see module doc).
    """

    slot: np.ndarray  # i32[S]
    phase: np.ndarray  # i32[S]
    stage: np.ndarray  # i8[S]
    my_r1: np.ndarray  # i8[S]
    my_r2: np.ndarray  # i8[S]
    led1: np.ndarray  # i8[R,S]
    led2: np.ndarray  # i8[R,S]
    decided: np.ndarray  # i8[S]
    done: np.ndarray  # bool[S]
    active: np.ndarray  # bool[S]


def _rowsum_eq(led: np.ndarray, value: int) -> np.ndarray:
    """Count, per shard, how many sender rows equal ``value``. uint8[S]."""
    eq = (led == value).view(np.uint8)
    if led.shape[0] == 1:
        return eq[0]
    acc = eq[0] + eq[1]
    for i in range(2, led.shape[0]):
        acc += eq[i]
    return acc


# NOTE: quorum presence (`tot`) must count only the valid vote codes
# (V0+V1+V?), exactly like phase_driver._tally — counting "anything
# non-ABSENT" would let garbage codes from a faulty peer fabricate quorum
# presence (and diverge bit-wise from the JAX kernel).


class HostNodeKernel:
    """Numpy twin of :class:`~rabia_tpu.kernel.phase_driver.NodeKernel`.

    Same constructor and step semantics; state arrays are host numpy and
    steps mutate fresh copies (callers may alias the previous state's
    ledgers only until the next ``node_step``). Two ingest styles:

    - functional: pass ``inbox_r1/inbox_r2`` ``[S, R]`` arrays to
      ``node_step`` (drop-in ``NodeKernel`` compatibility);
    - zero-copy: scatter arriving votes with :meth:`offer_votes` as
      messages land, then call ``node_step()`` with no inboxes.
    """

    def __init__(
        self,
        n_shards: int,
        n_replicas: int,
        me: int,
        *,
        coin_p1: float = 0.5,
        seed: int = 0,
    ):
        self.S = int(n_shards)
        self.R = int(n_replicas)
        self.me = int(me)
        self.quorum = quorum_size(self.R)
        self.f1 = f_plus_1(self.R)
        self.coin_p1 = float(coin_p1)
        self.seed = int(seed)
        self._shard_idx = np.arange(self.S, dtype=I32)
        self._coin_threshold = coin_threshold(coin_p1)
        self._native_lib: object = False  # False = not probed yet
        # consensus-health telemetry (chaos plane): common-coin flip
        # outcomes ([V0, V1] counts; the C step accumulates in place via
        # rk_node_step_ex) and the phases-to-decide distribution of
        # locally tally-decided slots (bin p = decisions taking p
        # weak-MVC phases, top bin clamps). Accounting only — no
        # protocol effect, and bit-identity between paths is untouched.
        self.coin_flips = np.zeros(2, np.uint64)
        self.phase_hist = np.zeros(32, np.uint64)
        self.phase_sum = 0

    def init_state(self) -> HostNodeState:
        S, R = self.S, self.R
        return HostNodeState(
            slot=np.zeros((S,), I32),
            phase=np.zeros((S,), I32),
            stage=np.full((S,), R1_WAIT, I8),
            my_r1=np.full((S,), ABSENT, I8),
            my_r2=np.full((S,), ABSENT, I8),
            led1=np.full((R, S), ABSENT, I8),
            led2=np.full((R, S), ABSENT, I8),
            decided=np.full((S,), ABSENT, I8),
            done=np.zeros((S,), bool),
            active=np.zeros((S,), bool),
        )

    # -- zero-copy ingest ----------------------------------------------------

    def offer_votes(
        self,
        state: HostNodeState,
        round_no: int,
        row: int,
        shards: np.ndarray,
        votes: np.ndarray,
    ) -> None:
        """Scatter one sender's votes into the ledger (first write wins
        across calls; the caller routes only votes matching each shard's
        current (slot, phase))."""
        led = state.led1 if round_no == 1 else state.led2
        led_row = led[row]
        writable = led_row[shards] == ABSENT
        if writable.all():
            led_row[shards] = votes
        else:
            led_row[shards[writable]] = votes[writable]

    # -- slot lifecycle -------------------------------------------------------

    def start_slots(
        self,
        state: HostNodeState,
        shard_mask: np.ndarray,  # bool[S]
        slot_index: np.ndarray,  # i32[S]
        initial_votes: np.ndarray,  # i8[S]
    ) -> HostNodeState:
        lib = self._native()
        if lib is not None:
            m = np.ascontiguousarray(shard_mask, bool)
            sl = np.ascontiguousarray(slot_index, I32)
            iv = np.ascontiguousarray(initial_votes, I8)
            st = HostNodeState(*(a.copy() for a in state))
            lib.rk_start_slots(
                self.S, self.R, self.me,
                m.ctypes.data, sl.ctypes.data, iv.ctypes.data,
                st.slot.ctypes.data, st.phase.ctypes.data,
                st.stage.ctypes.data, st.my_r1.ctypes.data,
                st.my_r2.ctypes.data, st.led1.ctypes.data,
                st.led2.ctypes.data, st.decided.ctypes.data,
                st.done.ctypes.data, st.active.ctypes.data,
            )
            return st
        return self._start_slots_np(state, shard_mask, slot_index,
                                    initial_votes)

    def _start_slots_np(
        self,
        state: HostNodeState,
        shard_mask: np.ndarray,
        slot_index: np.ndarray,
        initial_votes: np.ndarray,
    ) -> HostNodeState:
        m = np.asarray(shard_mask, bool)
        slot_index = np.asarray(slot_index)
        initial_votes = np.asarray(initial_votes, I8)
        st = HostNodeState(*(a.copy() for a in state))
        np.copyto(st.slot, slot_index.astype(I32), where=m)
        np.copyto(st.phase, I32(0), where=m)
        np.copyto(st.stage, I8(R1_WAIT), where=m)
        np.copyto(st.my_r1, initial_votes, where=m)
        np.copyto(st.my_r2, _ABS, where=m)
        np.copyto(st.led1, _ABS, where=m[None, :])
        np.copyto(st.led1[self.me], initial_votes, where=m)
        np.copyto(st.led2, _ABS, where=m[None, :])
        np.copyto(st.decided, _ABS, where=m)
        st.done[m] = False
        np.copyto(st.active, True, where=m)
        return st

    # -- the round step --------------------------------------------------------

    def node_step(
        self,
        state: HostNodeState,
        inbox_r1: Optional[np.ndarray] = None,  # i8[S,R] (compat path)
        inbox_r2: Optional[np.ndarray] = None,
        decision_in: Optional[np.ndarray] = None,  # i8[S]
    ) -> tuple[HostNodeState, NodeOutbox]:
        lib = self._native()
        if lib is not None:
            return self._node_step_c(
                lib, state, inbox_r1, inbox_r2, decision_in
            )
        return self._node_step_np(state, inbox_r1, inbox_r2, decision_in)

    def _native(self):
        """The C step library, or None (numpy fallback / forced off)."""
        lib = self._native_lib
        if lib is False:
            from rabia_tpu.native.build import load_hostkernel

            lib = self._native_lib = load_hostkernel()
            if lib is not None:
                self._mk_workspaces()
        return lib

    def _node_step_c(
        self,
        lib,
        state: HostNodeState,
        inbox_r1: Optional[np.ndarray],
        inbox_r2: Optional[np.ndarray],
        decision_in: Optional[np.ndarray],
    ) -> tuple[HostNodeState, NodeOutbox]:
        """One C call instead of ~40 numpy dispatches (the per-activation
        floor under serial commit latency; see native/hostkernel.cpp).

        Output arrays come from two ping-ponged workspaces with cached
        raw pointers — a returned state/outbox stays valid until the
        *second* following ``node_step`` (strictly wider than the
        documented one-step aliasing contract). The C routine mutates the
        workspace in place and fills the outbox extras."""
        ws = self._ws[self._ws_flip]
        self._ws_flip ^= 1
        st_out, out_extra, ptrs = ws
        # copy current state into the workspace (the functional step);
        # np.copyto(a, a) when the caller passes the same workspace back
        # after an offer_votes-only mutation is a safe no-op-by-value
        for dst, src in zip(st_out, state):
            np.copyto(dst, src, casting="unsafe")
        led1, led2 = st_out.led1, st_out.led2
        if inbox_r1 is not None:
            ib = np.asarray(inbox_r1, I8).T
            np.copyto(led1, ib, where=(led1 == ABSENT) & (ib != ABSENT))
        if inbox_r2 is not None:
            ib = np.asarray(inbox_r2, I8).T
            np.copyto(led2, ib, where=(led2 == ABSENT) & (ib != ABSENT))
        if decision_in is None:
            dec_ptr = 0
        else:
            decision_in = np.ascontiguousarray(decision_in, I8)
            dec_ptr = decision_in.ctypes.data
        if self._step_ex:
            lib.rk_node_step_ex(
                *self._const_args, *ptrs[:10], dec_ptr, *ptrs[10:],
                self._coin_ptr,
            )
        else:  # stale prebuilt hostkernel: coin telemetry reads as zeros
            lib.rk_node_step(
                *self._const_args, *ptrs[:10], dec_ptr, *ptrs[10:]
            )
        self._acct_decided(out_extra[3], st_out.phase)
        outbox = NodeOutbox(
            cast_r2=out_extra[0],
            r2_vals=out_extra[1],
            advanced=out_extra[2],
            new_r1=st_out.my_r1,
            new_phase=st_out.phase,
            newly_decided=out_extra[3],
            decided_vals=st_out.decided,
        )
        return st_out, outbox

    def _mk_workspaces(self) -> None:
        """Two ping-ponged output workspaces for the C step: state arrays,
        outbox extras, and their raw pointers precomputed once (ctypes
        marshalling of ``ndarray.ctypes.data`` per call costs more than
        the C step itself at small S)."""
        S, R = self.S, self.R
        self._ws = []
        for _ in range(2):
            st = HostNodeState(
                slot=np.zeros((S,), I32),
                phase=np.zeros((S,), I32),
                stage=np.full((S,), R1_WAIT, I8),
                my_r1=np.full((S,), ABSENT, I8),
                my_r2=np.full((S,), ABSENT, I8),
                led1=np.full((R, S), ABSENT, I8),
                led2=np.full((R, S), ABSENT, I8),
                decided=np.full((S,), ABSENT, I8),
                done=np.zeros((S,), bool),
                active=np.zeros((S,), bool),
            )
            extra = (
                np.empty(S, bool),  # cast_r2
                np.empty(S, I8),  # r2_vals
                np.empty(S, bool),  # advanced
                np.empty(S, bool),  # newly_decided
            )
            ptrs = tuple(a.ctypes.data for a in st) + tuple(
                a.ctypes.data for a in extra
            )
            self._ws.append((st, extra, ptrs))
        self._ws_flip = 0
        self._const_args = (
            S, R, self.me, self.quorum, self.f1,
            self.seed & 0xFFFFFFFF, self._coin_threshold,
        )
        lib = self._native_lib
        self._step_ex = bool(getattr(lib, "rk_node_step_ex", None))
        self._coin_ptr = self.coin_flips.ctypes.data

    def _acct_decided(self, newly, phase) -> None:
        """Fold this step's tally decisions into the phases-to-decide
        telemetry (post-advance phase == phases used)."""
        idx = np.nonzero(newly)[0]
        if len(idx) == 0:
            return
        ph = np.asarray(phase)[idx].astype(np.int64)
        self.phase_sum += int(ph.sum())
        np.add.at(
            self.phase_hist, np.minimum(ph, len(self.phase_hist) - 1), 1
        )

    def _node_step_np(
        self,
        state: HostNodeState,
        inbox_r1: Optional[np.ndarray] = None,  # i8[S,R] (compat path)
        inbox_r2: Optional[np.ndarray] = None,
        decision_in: Optional[np.ndarray] = None,  # i8[S]
    ) -> tuple[HostNodeState, NodeOutbox]:
        Q, F1 = self.quorum, self.f1

        led1 = state.led1.copy()
        led2 = state.led2.copy()
        if inbox_r1 is not None:
            ib = np.asarray(inbox_r1, I8).T
            np.copyto(led1, ib, where=(led1 == ABSENT) & (ib != ABSENT))
        if inbox_r2 is not None:
            ib = np.asarray(inbox_r2, I8).T
            np.copyto(led2, ib, where=(led2 == ABSENT) & (ib != ABSENT))

        enabled = state.active & ~state.done

        c0 = _rowsum_eq(led1, V0)
        c1 = _rowsum_eq(led1, V1)
        tot1 = c0 + c1 + _rowsum_eq(led1, VQUESTION)
        cast_r2 = enabled & (state.stage == R1_WAIT) & (tot1 >= Q)
        r2_val = np.where(
            c1 >= Q, I8(V1), np.where(c0 >= Q, I8(V0), I8(VQUESTION))
        )
        my_r2 = state.my_r2.copy()
        np.copyto(my_r2, r2_val, where=cast_r2)
        stage = state.stage.copy()
        np.copyto(stage, I8(R2_WAIT), where=cast_r2)
        np.copyto(led2[self.me], my_r2, where=cast_r2)

        d0 = _rowsum_eq(led2, V0)
        d1 = _rowsum_eq(led2, V1)
        tot2 = d0 + d1 + _rowsum_eq(led2, VQUESTION)
        advance = enabled & (state.stage == R2_WAIT) & (tot2 >= Q)
        decide1 = d1 >= F1
        decide0 = d0 >= F1
        # next round-1 vote: decided value, else any seen non-? value, else
        # the common coin — computed lazily (the coin hash is the single
        # most expensive op; fault-free traffic never reaches it)
        next_v = np.where(
            decide1,
            I8(V1),
            np.where(
                decide0,
                I8(V0),
                np.where(d1 > 0, I8(V1), I8(V0)),
            ),
        )
        coin_case = advance & ~decide1 & ~decide0 & (d1 == 0) & (d0 == 0)
        if coin_case.any():
            idx = np.nonzero(coin_case)[0]
            bits = _coin_bits(
                self.seed,
                idx.astype(I32),
                state.slot[idx],
                state.phase[idx],
                self.coin_p1,
                xp=np,
            )
            next_v[idx] = bits
            n1 = int((bits == V1).sum())
            self.coin_flips[0] += len(idx) - n1
            self.coin_flips[1] += n1
        newly_decided = advance & (decide1 | decide0)
        dec_val = np.where(decide1, I8(V1), I8(V0))

        adopt = (
            enabled & ~newly_decided & (decision_in != ABSENT)
            if decision_in is not None
            else np.zeros_like(enabled)
        )
        decided = state.decided.copy()
        np.copyto(decided, dec_val, where=newly_decided)
        if decision_in is not None:
            np.copyto(decided, np.asarray(decision_in, I8), where=adopt)
        done = state.done | newly_decided | adopt

        phase = state.phase.copy()
        my_r1 = state.my_r1.copy()
        my_r2_out = my_r2.copy()
        if advance.any():
            np.copyto(phase, state.phase + 1, where=advance)
            np.copyto(my_r1, next_v, where=advance)
            np.copyto(stage, I8(R1_WAIT), where=advance)
            np.copyto(my_r2, _ABS, where=advance)
            np.copyto(led1, _ABS, where=advance[None, :])
            np.copyto(led1[self.me], next_v, where=advance)
            np.copyto(led2, _ABS, where=advance[None, :])

        self._acct_decided(newly_decided, phase)
        new_state = HostNodeState(
            slot=state.slot,
            phase=phase,
            stage=stage,
            my_r1=my_r1,
            my_r2=my_r2,
            led1=led1,
            led2=led2,
            decided=decided,
            done=done,
            active=state.active,
        )
        outbox = NodeOutbox(
            cast_r2=cast_r2,
            r2_vals=my_r2_out,
            advanced=advance,
            new_r1=my_r1,
            new_phase=phase,
            newly_decided=newly_decided,
            decided_vals=decided,
        )
        return new_state, outbox
