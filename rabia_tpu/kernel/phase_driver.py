"""The batched weak-MVC phase driver: consensus as an array program.

This module vectorizes the weak-MVC transition relation (the reference's
formal spec, docs/weak_mvc.ivy:82-186; scalar executable form in
:mod:`rabia_tpu.core.oracle`) over ``S`` independent consensus instances
("shards") × ``R`` replicas:

- vote ledgers are ``int8[S, R, R]`` arrays (receiver-major) instead of the
  reference's per-phase HashMaps (rabia-core/src/messages.rs:138-223);
- the majority tally is a one-hot sum over the sender axis instead of
  ``PhaseData::count_votes`` loops (messages.rs:185-211);
- the round-2 tie-break is a **common coin** — ``fold_in(key, (shard, slot,
  phase))`` — identical on every replica by construction, implementing the
  spec's shared ``coin(P,V)`` relation (weak_mvc.ivy:169-182) rather than the
  reference implementation's per-node RNG (engine.rs:454-481, a documented
  deviation, SURVEY.md §3.1);
- crashes and partitions are boolean masks (``alive[S,R]``,
  ``deliver[S,R,R]``), not control flow.

Two kernels share the transition spec:

:class:`ClusterKernel`
    Whole-cluster simulation: all R replicas' state lives in one set of
    arrays. One ``round_step`` = one synchronous communication round with
    lossy delivery + implicit retransmission — bit-identical in semantics to
    ``WeakMVCOracle.step``. Used by the fault-injection harness and the
    benchmark ``slot_pipeline`` (which runs whole decision slots under
    ``lax.scan`` without host round-trips).

:class:`NodeKernel`
    One node's view (state ``[S]``, inboxes ``[S, R]`` ABSENT-coded): the
    device half of the host engine, which feeds it votes arriving from real
    transports and turns its outboxes into messages. Host-paced rounds
    resolve the async-protocol-on-synchronous-device tension (SURVEY.md
    §7.4.1).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from rabia_tpu.core.types import ABSENT, V0, V1, VQUESTION, f_plus_1, quorum_size

I8 = jnp.int8
I32 = jnp.int32

R1_WAIT = 0
R2_WAIT = 1


# ---------------------------------------------------------------------------
# Common coin
# ---------------------------------------------------------------------------
#
# The coin is a *portable* integer hash — the same uint32 avalanche
# sequence evaluates bit-identically under numpy (the engine's host
# kernel, rabia_tpu/kernel/host_driver.py) and under XLA on any backend.
# This replaces the round-1 design's threefry fold_in chain, which (a) was
# the dominant cost of a node_step dispatch on CPU and (b) could not be
# replayed outside JAX. The spec only requires a *shared* coin(P, V)
# relation (docs/weak_mvc.ivy:169-182): any deterministic function of
# (seed, shard, slot, phase) that every replica evaluates identically
# qualifies; the reference instead flips per-node RNGs
# (engine.rs:454-481), a documented deviation we fix.

_GOLD = 0x9E3779B9  # 2^32 / golden ratio, the hash_combine offset


def _mix32(h):
    """lowbias32 avalanche (a well-mixed uint32 permutation)."""
    h = h ^ (h >> 16)
    h = h * 0x21F0AAAD
    h = h ^ (h >> 15)
    h = h * 0x735A2D97
    h = h ^ (h >> 15)
    return h


def coin_threshold(p1: float) -> int:
    """uint32 acceptance threshold for coin probability ``p1``.

    Bit-identity-critical: every coin implementation (this XLA/numpy
    kernel, the numpy host kernel, and native/hostkernel.cpp) must derive
    the threshold from ``p1`` with EXACTLY this rounding/clamping, or
    replicas on different backends flip different coins."""
    return min(int(p1 * 4294967296.0), 4294967295)


def _coin_bits(seed, shard, slot, phase, p1: float, xp=jnp):
    """Common-coin values for (shard, slot, phase) triples (same shape).

    Depends only on the seed and the triple — never on the replica flipping
    it — so every replica (and every host/device replay) sees the same coin.
    ``xp`` is the array namespace (``jax.numpy`` or ``numpy``); both produce
    identical bits. Returns int8 V0/V1 of the broadcast shape.
    """
    u32 = xp.uint32
    shard, slot, phase = xp.broadcast_arrays(
        xp.asarray(shard), xp.asarray(slot), xp.asarray(phase)
    )
    h = _mix32(xp.full(shard.shape, u32(seed)) ^ u32(_GOLD))
    h = _mix32(h ^ (shard.astype(u32) + u32(_GOLD)))
    h = _mix32(h ^ (slot.astype(u32) + u32(_GOLD)))
    h = _mix32(h ^ (phase.astype(u32) + u32(_GOLD)))
    threshold = u32(coin_threshold(p1))
    return xp.where(h < threshold, xp.int8(V1), xp.int8(V0))


def device_coin(seed: int, shard: int, slot: int, phase: int, p1: float = 0.5) -> int:
    """Scalar host-side view of the common coin (for the oracle/tests)."""
    import numpy as np

    return int(
        _coin_bits(
            seed, np.array([shard]), np.array([slot]), np.array([phase]), p1, xp=np
        )[0]
    )


def _tally(ledger: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Count V0/V1/V? and total present votes over the last (sender) axis.

    The batched form of PhaseData::count_votes (messages.rs:185-211).
    """
    c0 = jnp.sum(ledger == V0, axis=-1, dtype=I32)
    c1 = jnp.sum(ledger == V1, axis=-1, dtype=I32)
    cq = jnp.sum(ledger == VQUESTION, axis=-1, dtype=I32)
    total = c0 + c1 + cq
    return c0, c1, cq, total


# ---------------------------------------------------------------------------
# Cluster-simulation kernel
# ---------------------------------------------------------------------------


class ClusterState(NamedTuple):
    """All-replica consensus state for S shards × R replicas (device)."""

    slot: jnp.ndarray  # i32[S]   decision-slot counter (host-advanced)
    phase: jnp.ndarray  # i32[S,R] weak-MVC phase within the slot
    stage: jnp.ndarray  # i8[S,R]  R1_WAIT | R2_WAIT
    my_r1: jnp.ndarray  # i8[S,R]  this replica's round-1 vote (current phase)
    my_r2: jnp.ndarray  # i8[S,R]  round-2 vote (ABSENT until cast)
    # previous phase's votes, re-offered to stragglers one phase behind:
    # weak MVC assumes reliable broadcast, so under lossy delivery a sender
    # keeps retransmitting the votes of the phase it just left — otherwise a
    # quorum can splinter across adjacent phases and deadlock.
    prev_r1: jnp.ndarray  # i8[S,R]
    prev_r2: jnp.ndarray  # i8[S,R]
    led1: jnp.ndarray  # i8[S,R,R] round-1 ledger [shard, receiver, sender]
    led2: jnp.ndarray  # i8[S,R,R]
    decided: jnp.ndarray  # i8[S]  slot decision (ABSENT until first decider)
    decided_phase: jnp.ndarray  # i32[S] min MVC phase of any decision (or -1)
    done: jnp.ndarray  # bool[S,R] replica knows the decision
    active: jnp.ndarray  # bool[S] shard has a live instance this slot


class ClusterKernel:
    """Factory of jitted cluster-simulation step functions.

    ``n_replicas``, quorum and f+1 are static (baked into the compiled
    program); shard count is dynamic up to the padded shape.
    """

    def __init__(self, n_shards: int, n_replicas: int, *, coin_p1: float = 0.5, seed: int = 0):
        self.S = int(n_shards)
        self.R = int(n_replicas)
        self.quorum = quorum_size(self.R)
        self.f1 = f_plus_1(self.R)
        self.coin_p1 = float(coin_p1)
        self.seed = int(seed)
        self.key = jax.random.key(self.seed)
        self._shard_idx = jnp.arange(self.S, dtype=I32)

    # -- state constructors -------------------------------------------------

    def init_state(self) -> ClusterState:
        S, R = self.S, self.R
        return ClusterState(
            slot=jnp.zeros((S,), I32),
            phase=jnp.zeros((S, R), I32),
            stage=jnp.full((S, R), R1_WAIT, I8),
            my_r1=jnp.full((S, R), ABSENT, I8),
            my_r2=jnp.full((S, R), ABSENT, I8),
            prev_r1=jnp.full((S, R), ABSENT, I8),
            prev_r2=jnp.full((S, R), ABSENT, I8),
            led1=jnp.full((S, R, R), ABSENT, I8),
            led2=jnp.full((S, R, R), ABSENT, I8),
            decided=jnp.full((S,), ABSENT, I8),
            decided_phase=jnp.full((S,), -1, I32),
            done=jnp.zeros((S, R), bool),
            active=jnp.zeros((S,), bool),
        )

    @functools.partial(jax.jit, static_argnums=0)
    def start_slot(
        self, state: ClusterState, shard_mask: jnp.ndarray, initial_votes: jnp.ndarray
    ) -> ClusterState:
        """Begin a new decision slot on masked shards with the given initial
        round-1 votes (V1 where the replica holds the proposal, V0 where it
        gave up waiting — weak_mvc.ivy:113-131)."""
        S, R = self.S, self.R
        m = shard_mask  # bool[S]
        mr = m[:, None]
        eye = jnp.eye(R, dtype=bool)[None, :, :]
        led1_fresh = jnp.where(
            eye, initial_votes[:, :, None].astype(I8), I8(ABSENT)
        )
        return ClusterState(
            slot=jnp.where(m, state.slot + jnp.where(state.active, 1, 0), state.slot),
            phase=jnp.where(mr, 0, state.phase),
            stage=jnp.where(mr, I8(R1_WAIT), state.stage),
            my_r1=jnp.where(mr, initial_votes.astype(I8), state.my_r1),
            my_r2=jnp.where(mr, I8(ABSENT), state.my_r2),
            prev_r1=jnp.where(mr, I8(ABSENT), state.prev_r1),
            prev_r2=jnp.where(mr, I8(ABSENT), state.prev_r2),
            led1=jnp.where(mr[:, :, None], led1_fresh, state.led1),
            led2=jnp.where(mr[:, :, None], I8(ABSENT), state.led2),
            decided=jnp.where(m, I8(ABSENT), state.decided),
            decided_phase=jnp.where(m, -1, state.decided_phase),
            done=jnp.where(mr, False, state.done),
            active=jnp.logical_or(state.active, m),
        )

    # -- the synchronous round step ----------------------------------------

    @functools.partial(jax.jit, static_argnums=0)
    def round_step(
        self,
        state: ClusterState,
        alive: jnp.ndarray,  # bool[S,R] (or broadcastable [R])
        deliver: jnp.ndarray,  # bool[S,R,R]  [shard, sender, receiver]
    ) -> ClusterState:
        """One synchronous communication round for every shard at once.

        No buffer donation here: the simulation kernel's callers (fault
        harness, tests) legitimately hold old states for inspection; the
        hot multi-round drivers (`run_rounds`, `slot_pipeline`) scan on
        device, where XLA reuses the carry buffers anyway. The engine's
        NodeKernel path IS donated — its state is threaded linearly.

        Semantics are element-for-element those of ``WeakMVCOracle.step``:
        (1) deliver outstanding votes under the mask (with retransmission —
        a sender's *current* votes are re-offered every round), (2) run every
        enabled R1→R2 and R2→advance transition, (3) propagate decisions.
        """
        S, R, Q, F1 = self.S, self.R, self.quorum, self.f1
        alive = jnp.broadcast_to(alive, (S, R))
        act = state.active[:, None]

        # ---- 1. delivery ------------------------------------------------
        # link[s,i,j]: sender i's traffic reaches receiver j this round
        link = (
            deliver
            & alive[:, :, None]
            & alive[:, None, :]
            & ~jnp.eye(R, dtype=bool)[None]
        )
        same_phase = state.phase[:, :, None] == state.phase[:, None, :]  # [s,i,j]
        ahead_one = state.phase[:, :, None] == state.phase[:, None, :] + 1
        rcv_open = ~state.done[:, None, :]  # decided receivers stop listening
        offer1 = link & rcv_open & (
            (same_phase & (state.my_r1 != ABSENT)[:, :, None])
            | (ahead_one & (state.prev_r1 != ABSENT)[:, :, None])
        )
        offer2 = link & rcv_open & (
            (
                same_phase
                & (state.stage == R2_WAIT)[:, :, None]
                & (state.my_r2 != ABSENT)[:, :, None]
            )
            | (ahead_one & (state.prev_r2 != ABSENT)[:, :, None])
        )
        val1 = jnp.where(same_phase, state.my_r1[:, :, None], state.prev_r1[:, :, None])
        val2 = jnp.where(same_phase, state.my_r2[:, :, None], state.prev_r2[:, :, None])
        # ledgers are [s, receiver, sender] — transpose the offer/value grids
        o1 = jnp.swapaxes(offer1, 1, 2)
        o2 = jnp.swapaxes(offer2, 1, 2)
        v1 = jnp.swapaxes(jnp.broadcast_to(val1, (S, R, R)), 1, 2)
        v2 = jnp.swapaxes(jnp.broadcast_to(val2, (S, R, R)), 1, 2)
        led1 = jnp.where((state.led1 == ABSENT) & o1, v1, state.led1)
        led2 = jnp.where((state.led2 == ABSENT) & o2, v2, state.led2)

        # ---- 2. transitions (on pre-step stages, like the oracle) --------
        enabled = act & alive & ~state.done
        eye = jnp.eye(R, dtype=bool)[None]

        # R1 -> R2: with a quorum of round-1 votes, vote v on an all-v
        # majority, else V?  (weak_mvc.ivy:133-147)
        c0, c1, _, tot1 = _tally(led1)
        cast_r2 = enabled & (state.stage == R1_WAIT) & (tot1 >= Q)
        r2_val = jnp.where(c1 >= Q, I8(V1), jnp.where(c0 >= Q, I8(V0), I8(VQUESTION)))
        my_r2 = jnp.where(cast_r2, r2_val, state.my_r2)
        stage = jnp.where(cast_r2, I8(R2_WAIT), state.stage)
        led2 = jnp.where(cast_r2[:, :, None] & eye, my_r2[:, :, None], led2)

        # R2 -> advance: decide on f+1 agreeing non-? votes; else adopt any
        # non-? vote; else flip the common coin  (weak_mvc.ivy:149-186)
        d0, d1, _, tot2 = _tally(led2)
        advance = enabled & (state.stage == R2_WAIT) & (tot2 >= Q)
        decide1 = d1 >= F1
        decide0 = d0 >= F1
        coin = _coin_bits(
            self.seed,
            jnp.broadcast_to(self._shard_idx[:, None], (S, R)),
            jnp.broadcast_to(state.slot[:, None], (S, R)),
            state.phase,
            self.coin_p1,
        )
        next_v = jnp.where(
            decide1,
            I8(V1),
            jnp.where(
                decide0,
                I8(V0),
                jnp.where(d1 > 0, I8(V1), jnp.where(d0 > 0, I8(V0), coin)),
            ),
        )
        newly_decided = advance & (decide1 | decide0)
        dec_vals = jnp.where(
            newly_decided, jnp.where(decide1, I8(V1), I8(V0)), I8(-1)
        )
        shard_dec = jnp.max(dec_vals, axis=1)  # -1 if no decider this round
        decided = jnp.where(
            (state.decided == ABSENT) & (shard_dec >= 0),
            shard_dec.astype(I8),
            state.decided,
        )
        # decided_phase = minimum MVC phase at which any replica decided
        intmax = jnp.iinfo(I32).max
        round_min = jnp.min(
            jnp.where(newly_decided, state.phase, intmax), axis=1
        )
        existing = jnp.where(state.decided_phase < 0, intmax, state.decided_phase)
        merged = jnp.minimum(existing, round_min)
        decided_phase = jnp.where(merged == intmax, -1, merged)
        done = state.done | newly_decided

        phase = jnp.where(advance, state.phase + 1, state.phase)
        prev_r1 = jnp.where(advance, state.my_r1, state.prev_r1)
        prev_r2 = jnp.where(advance, my_r2, state.prev_r2)
        my_r1 = jnp.where(advance, next_v, state.my_r1)
        stage = jnp.where(advance, I8(R1_WAIT), stage)
        my_r2 = jnp.where(advance, I8(ABSENT), my_r2)
        adv3 = advance[:, :, None]
        led1 = jnp.where(
            adv3, jnp.where(eye, next_v[:, :, None], I8(ABSENT)), led1
        )
        led2 = jnp.where(adv3, I8(ABSENT), led2)

        # ---- 3. decision propagation ------------------------------------
        # any done replica whose link reaches an undecided one informs it
        informed = jnp.einsum("si,sij->sj", (done & alive).astype(I32), deliver.astype(I32)) > 0
        adopt = state.active[:, None] & alive & ~done & informed & (decided != ABSENT)[:, None]
        done = done | adopt

        return ClusterState(
            slot=state.slot,
            phase=phase,
            stage=stage,
            my_r1=my_r1,
            my_r2=my_r2,
            prev_r1=prev_r1,
            prev_r2=prev_r2,
            led1=led1,
            led2=led2,
            decided=decided,
            decided_phase=decided_phase,
            done=done,
            active=state.active,
        )

    # -- multi-round / multi-slot drivers ----------------------------------

    @functools.partial(
        jax.jit,
        static_argnums=(0, 3, 5),
        static_argnames=("n_rounds", "p_deliver"),
    )
    def run_rounds(
        self,
        state: ClusterState,
        alive: jnp.ndarray,
        n_rounds: int,
        step_key: jnp.ndarray,
        p_deliver: float = 1.0,
        link_mask: Optional[jnp.ndarray] = None,
    ) -> ClusterState:
        """Run ``n_rounds`` round_steps in one dispatch (lax.scan), drawing a
        fresh Bernoulli delivery mask per round ∧ an optional static link
        mask (partitions). ``step_key`` seeds delivery randomness only —
        protocol coins come from the kernel's own key."""
        S, R = self.S, self.R
        base_link = (
            jnp.ones((S, R, R), bool) if link_mask is None else jnp.broadcast_to(link_mask, (S, R, R))
        )

        def body(st, k):
            if p_deliver >= 1.0:
                d = base_link
            else:
                d = base_link & jax.random.bernoulli(k, p_deliver, (S, R, R))
            return self.round_step(st, alive, d), ()

        keys = jax.random.split(step_key, n_rounds)
        state, _ = lax.scan(body, state, keys)
        return state

    @functools.partial(
        jax.jit,
        static_argnums=(0, 3, 4, 5),
        static_argnames=("n_slots", "rounds_per_slot", "start_slot_index"),
    )
    def slot_pipeline(
        self,
        initial_votes: jnp.ndarray,  # i8[T, S, R] per-slot initial R1 votes
        alive: jnp.ndarray,  # bool[S,R]
        n_slots: int,
        rounds_per_slot: int = 2,
        start_slot_index: int = 0,
    ) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Decide ``n_slots`` consecutive slots for all S shards entirely on
        device: scan over slots, ``rounds_per_slot`` full-delivery rounds
        each (2 suffices fault-free: R1 exchange+cast, R2 exchange+decide).

        Returns ``(decided[T, S], decided_phase[T, S])``. This is the
        benchmark hot path — no host round-trips between decisions, which is
        what amortizes dispatch overhead across thousands of shards
        (SURVEY.md §7.4.4).
        """
        S, R = self.S, self.R
        full = jnp.ones((S, R, R), bool)
        every = jnp.ones((S,), bool)

        def per_slot(state, inp):
            slot_votes, slot_idx = inp
            st = self.start_slot(state, every, slot_votes)
            st = st._replace(slot=jnp.full((S,), slot_idx, I32))

            def rd(s, _):
                return self.round_step(s, alive, full), ()

            st, _ = lax.scan(rd, st, None, length=rounds_per_slot)
            return st, (st.decided, st.decided_phase)

        state0 = self.init_state()
        slots = jnp.arange(start_slot_index, start_slot_index + n_slots, dtype=I32)
        _, (decided, dphase) = lax.scan(
            per_slot, state0, (initial_votes, slots)
        )
        return decided, dphase

    @functools.partial(
        jax.jit,
        static_argnums=(0, 3, 4, 5, 6),
        static_argnames=(
            "n_slots", "rounds_per_slot", "start_slot_index", "block"
        ),
    )
    def slot_pipeline_wide(
        self,
        initial_votes: jnp.ndarray,  # i8[T, S, R] per-slot initial R1 votes
        alive: jnp.ndarray,  # bool[S,R]
        n_slots: int,
        rounds_per_slot: int = 2,
        start_slot_index: int = 0,
        block: int = 256,
    ) -> tuple[jnp.ndarray, jnp.ndarray]:
        """:meth:`slot_pipeline` with ``block`` slots evaluated in
        parallel per scan step (vmap over the slot axis).

        Consecutive slots of one shard are independent consensus
        instances (each ``per_slot`` iteration rebuilds its state from
        ``start_slot``), so batching them is semantics-preserving —
        decisions are bit-identical to :meth:`slot_pipeline`
        (conformance-tested). Whether it is FASTER is geometry- and
        backend-dependent: on the tunneled TPU chip the deep sequential
        scan already amortizes its per-step cost, and measured
        throughput favors plain ``slot_pipeline`` at large S — use this
        variant for batch evaluation of many small windows, not as a
        default.

        ``n_slots`` must be a multiple of ``block`` (callers pad votes
        with unanimous-V0 filler slots, which decide in phase 0).
        """
        if n_slots % block:
            raise ValueError(
                f"n_slots {n_slots} not a multiple of block {block}"
            )
        S, R = self.S, self.R
        full = jnp.ones((S, R, R), bool)
        every = jnp.ones((S,), bool)
        state0 = self.init_state()

        def one_slot(slot_votes, slot_idx):
            st = self.start_slot(state0, every, slot_votes)
            st = st._replace(slot=jnp.full((S,), slot_idx, I32))

            def rd(s, _):
                return self.round_step(s, alive, full), ()

            st, _ = lax.scan(rd, st, None, length=rounds_per_slot)
            return st.decided, st.decided_phase

        votes_b = initial_votes.reshape(n_slots // block, block, S, R)
        slots_b = jnp.arange(
            start_slot_index, start_slot_index + n_slots, dtype=I32
        ).reshape(n_slots // block, block)

        def per_chunk(_, inp):
            vb, sb = inp
            return None, jax.vmap(one_slot)(vb, sb)

        _, (decided, dphase) = lax.scan(per_chunk, None, (votes_b, slots_b))
        return (
            decided.reshape(n_slots, S),
            dphase.reshape(n_slots, S),
        )

    def slot_pipeline_fused(
        self,
        initial_votes: jnp.ndarray,  # i8[T, S, R]
        alive: jnp.ndarray,  # bool[S,R] (or broadcastable [R])
        n_slots: int,
        use_pallas: Optional[bool] = None,
        interpret: bool = False,
    ) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Fault-free fast path: bit-identical to
        ``slot_pipeline(votes, alive, T)`` at the default
        ``rounds_per_slot=2`` (full delivery provably collapses to a
        closed-form quorum tally — derivation in
        :mod:`rabia_tpu.kernel.fused_window`), evaluated as ONE fused
        Pallas kernel on TPU, or the same closed form as a plain XLA
        program elsewhere. The scanned :meth:`slot_pipeline` remains the
        semantics owner (and the path for lossy/crash simulation via
        :meth:`run_rounds`)."""
        from rabia_tpu.kernel import fused_window

        if initial_votes.shape[0] != n_slots:
            # slot_pipeline fails loudly on this mismatch (scan length);
            # silent truncation would break the drop-in equivalence
            raise ValueError(
                f"votes carry {initial_votes.shape[0]} slots, "
                f"n_slots={n_slots}"
            )
        alive = jnp.broadcast_to(alive, (self.S, self.R))
        votes = initial_votes
        if use_pallas is None:
            use_pallas = (
                jax.default_backend() == "tpu" and self.S % 128 == 0
            )
        if use_pallas or interpret:
            return fused_window.pallas_window(
                votes, alive, self.quorum, interpret=interpret
            )
        return fused_window.closed_form_window(votes, alive, self.quorum)

    def slot_pipeline_fused_rmajor(
        self,
        votes_rm: jnp.ndarray,  # i8[R, T, S] — replica-major planes
        alive_rm: jnp.ndarray,  # bool[R, S] (or broadcastable [R, 1])
        n_slots: int,
        use_pallas: Optional[bool] = None,
        interpret: bool = False,
        want_phase: bool = True,
    ):
        """:meth:`slot_pipeline_fused` on replica-major votes — the
        bandwidth-shaped entry for producers that build the vote tensor
        themselves (the mesh engine does). Skipping the ``[T,S,R]`` API
        layout avoids an i8 minor-axis relayout; ``want_phase=False``
        additionally skips the redundant i32 phase plane (derivable:
        0 iff decided). Bit-identical to
        ``slot_pipeline(transpose(votes_rm, (1,2,0)), ...)`` — pinned in
        tests/test_kernel.py and scripts/fuzz_conformance.py."""
        from rabia_tpu.kernel import fused_window

        if votes_rm.shape[1] != n_slots:
            raise ValueError(
                f"votes carry {votes_rm.shape[1]} slots, n_slots={n_slots}"
            )
        if votes_rm.shape[0] != self.R or votes_rm.shape[2] != self.S:
            # loud failure on an accidental [T,S,R]-layout pass-through:
            # R binding to T would statically unroll a T-iteration loop
            raise ValueError(
                f"votes_rm is {votes_rm.shape}, expected replica-major "
                f"[R={self.R}, T={n_slots}, S={self.S}]"
            )
        alive_rm = jnp.broadcast_to(alive_rm, (self.R, self.S))
        if use_pallas is None:
            use_pallas = (
                jax.default_backend() == "tpu" and self.S % 128 == 0
            )
        if use_pallas or interpret:
            return fused_window.pallas_window_rmajor(
                votes_rm,
                alive_rm,
                self.quorum,
                interpret=interpret,
                want_phase=want_phase,
            )
        return fused_window.closed_form_window_rmajor(
            votes_rm, alive_rm, self.quorum, want_phase=want_phase
        )

    def slot_pipeline_fused_packed(
        self,
        packed_rm: jnp.ndarray,  # u32[R, T, SW] — 16 votes/word, 2-bit codes
        alive_packed: jnp.ndarray,  # u32[R, SW] — lane-LSB alive bits
        n_slots: int,
    ) -> jnp.ndarray:
        """:meth:`slot_pipeline_fused_rmajor` on word-packed votes — the
        minimum-bytes entry: (2R+2)/8 bytes per decision instead of R+1,
        tallied with word-wise bit arithmetic (kernel/packed_window.py).
        Producers pack with ``packed_window.pack_codes`` /
        ``pack_alive``; returns PACKED decisions u32[T, SW] (decode with
        ``packed_window.unpack_codes``; phase derivable: 0 iff decided).
        Bit-identical to the rmajor entry — pinned in
        tests/test_packed_window.py."""
        from rabia_tpu.kernel import packed_window

        SW = packed_window.packed_width(self.S)
        if packed_rm.shape[1] != n_slots:
            raise ValueError(
                f"votes carry {packed_rm.shape[1]} slots, n_slots={n_slots}"
            )
        if packed_rm.shape[0] != self.R or packed_rm.shape[2] != SW:
            raise ValueError(
                f"packed_rm is {packed_rm.shape}, expected packed "
                f"replica-major [R={self.R}, T={n_slots}, SW={SW}]"
            )
        return packed_window.packed_window_rmajor(
            packed_rm, alive_packed, self.quorum
        )


# ---------------------------------------------------------------------------
# Per-node kernel (the host engine's device half)
# ---------------------------------------------------------------------------


class NodeState(NamedTuple):
    """One node's consensus state over its S shards."""

    slot: jnp.ndarray  # i32[S]
    phase: jnp.ndarray  # i32[S]
    stage: jnp.ndarray  # i8[S]
    my_r1: jnp.ndarray  # i8[S]
    my_r2: jnp.ndarray  # i8[S]
    led1: jnp.ndarray  # i8[S,R]  votes seen for current (slot, phase)
    led2: jnp.ndarray  # i8[S,R]
    decided: jnp.ndarray  # i8[S]
    done: jnp.ndarray  # bool[S]
    active: jnp.ndarray  # bool[S]


class NodeOutbox(NamedTuple):
    """What the host must transmit after a node_step."""

    cast_r2: jnp.ndarray  # bool[S] — broadcast VoteRound2(phase, my_r2)
    r2_vals: jnp.ndarray  # i8[S]
    advanced: jnp.ndarray  # bool[S] — broadcast VoteRound1(phase+1, my_r1)
    new_r1: jnp.ndarray  # i8[S]
    new_phase: jnp.ndarray  # i32[S]
    newly_decided: jnp.ndarray  # bool[S] — broadcast Decision(slot, value)
    decided_vals: jnp.ndarray  # i8[S]


class NodeKernel:
    """Jitted per-node step: ledgers in, transitions out (SURVEY.md §7.1).

    The host engine owns message routing and slot lifecycle; this kernel owns
    every piece of per-phase math the reference computes in
    engine.rs:424-706, for all shards at once.
    """

    def __init__(self, n_shards: int, n_replicas: int, me: int, *, coin_p1: float = 0.5, seed: int = 0):
        self.S = int(n_shards)
        self.R = int(n_replicas)
        self.me = int(me)
        self.quorum = quorum_size(self.R)
        self.f1 = f_plus_1(self.R)
        self.coin_p1 = float(coin_p1)
        self.seed = int(seed)
        self._shard_idx = jnp.arange(self.S, dtype=I32)

    def init_state(self) -> NodeState:
        S, R = self.S, self.R
        return NodeState(
            slot=jnp.zeros((S,), I32),
            phase=jnp.zeros((S,), I32),
            stage=jnp.full((S,), R1_WAIT, I8),
            my_r1=jnp.full((S,), ABSENT, I8),
            my_r2=jnp.full((S,), ABSENT, I8),
            led1=jnp.full((S, R), ABSENT, I8),
            led2=jnp.full((S, R), ABSENT, I8),
            decided=jnp.full((S,), ABSENT, I8),
            done=jnp.zeros((S,), bool),
            active=jnp.zeros((S,), bool),
        )

    @functools.partial(jax.jit, static_argnums=0, donate_argnums=1)
    def start_slots(
        self,
        state: NodeState,
        shard_mask: jnp.ndarray,  # bool[S]
        slot_index: jnp.ndarray,  # i32[S]
        initial_votes: jnp.ndarray,  # i8[S]
    ) -> NodeState:
        return self._start_slots_math(state, shard_mask, slot_index, initial_votes)

    def _start_slots_math(
        self, state, shard_mask, slot_index, initial_votes
    ) -> NodeState:
        R = self.R
        m = shard_mask
        led1 = jnp.where(
            m[:, None],
            jnp.where(
                jnp.arange(R)[None, :] == self.me,
                initial_votes[:, None].astype(I8),
                I8(ABSENT),
            ),
            state.led1,
        )
        return NodeState(
            slot=jnp.where(m, slot_index, state.slot),
            phase=jnp.where(m, 0, state.phase),
            stage=jnp.where(m, I8(R1_WAIT), state.stage),
            my_r1=jnp.where(m, initial_votes.astype(I8), state.my_r1),
            my_r2=jnp.where(m, I8(ABSENT), state.my_r2),
            led1=led1,
            led2=jnp.where(m[:, None], I8(ABSENT), state.led2),
            decided=jnp.where(m, I8(ABSENT), state.decided),
            done=jnp.where(m, False, state.done),
            active=state.active | m,
        )

    @functools.partial(jax.jit, static_argnums=0, donate_argnums=1)
    def node_step(
        self,
        state: NodeState,
        inbox_r1: jnp.ndarray,  # i8[S,R] votes for current (slot, phase); ABSENT elsewhere
        inbox_r2: jnp.ndarray,  # i8[S,R]
        decision_in: jnp.ndarray,  # i8[S] ABSENT or adopted decision value
    ) -> tuple[NodeState, NodeOutbox]:
        """Consume routed inboxes, run enabled transitions on every shard.

        ``state`` is DONATED (device buffers reused in place); do not reuse
        the passed-in state afterwards."""
        return self._node_step_math(state, inbox_r1, inbox_r2, decision_in)

    @functools.partial(
        jax.jit, static_argnums=(0, 8), donate_argnums=1
    )
    def node_cycle(
        self,
        state: NodeState,
        shard_mask: jnp.ndarray,  # bool[S] slots to (re)start this tick
        slot_index: jnp.ndarray,  # i32[S]
        initial_votes: jnp.ndarray,  # i8[S]
        inbox_r1: jnp.ndarray,  # i8[S,R]
        inbox_r2: jnp.ndarray,  # i8[S,R]
        decision_in: jnp.ndarray,  # i8[S]
        n_steps: int,
    ) -> tuple[NodeState, NodeOutbox]:
        """One device dispatch for a whole engine tick: start newly opened
        slots, then chain ``n_steps`` node_steps (inboxes consumed by the
        first; later substeps cascade stage transitions — cast R2, then
        decide — on ledger-resident votes). Returns the final state and a
        NodeOutbox of [n_steps, ...]-stacked transition flags.

        This is the SURVEY.md §7.4.4 dispatch-amortization lever for the
        transport engine: per-round host<->device stepping pays the
        dispatch latency once per STAGE; chaining substeps pays it once
        per tick.
        """
        state = self._start_slots_math(
            state, shard_mask, slot_index, initial_votes
        )
        K = int(n_steps)
        pad1 = jnp.full((K - 1,) + inbox_r1.shape, ABSENT, I8)
        ib1 = jnp.concatenate([inbox_r1[None].astype(I8), pad1])
        ib2 = jnp.concatenate([inbox_r2[None].astype(I8), pad1])
        dec = jnp.concatenate(
            [
                decision_in[None].astype(I8),
                jnp.full((K - 1,) + decision_in.shape, ABSENT, I8),
            ]
        )

        def body(st, xs):
            st, outbox = self._node_step_math(st, xs[0], xs[1], xs[2])
            return st, outbox

        state, outboxes = lax.scan(body, state, (ib1, ib2, dec))
        return state, outboxes

    def _node_step_math(
        self,
        state: NodeState,
        inbox_r1: jnp.ndarray,
        inbox_r2: jnp.ndarray,
        decision_in: jnp.ndarray,
    ) -> tuple[NodeState, NodeOutbox]:
        S, R, Q, F1 = self.S, self.R, self.quorum, self.f1

        led1 = jnp.where((state.led1 == ABSENT) & (inbox_r1 != ABSENT), inbox_r1, state.led1)
        led2 = jnp.where((state.led2 == ABSENT) & (inbox_r2 != ABSENT), inbox_r2, state.led2)

        enabled = state.active & ~state.done

        c0, c1, _, tot1 = _tally(led1)
        cast_r2 = enabled & (state.stage == R1_WAIT) & (tot1 >= Q)
        r2_val = jnp.where(c1 >= Q, I8(V1), jnp.where(c0 >= Q, I8(V0), I8(VQUESTION)))
        my_r2 = jnp.where(cast_r2, r2_val, state.my_r2)
        stage = jnp.where(cast_r2, I8(R2_WAIT), state.stage)
        own = jnp.arange(R)[None, :] == self.me
        led2 = jnp.where(cast_r2[:, None] & own, my_r2[:, None], led2)

        d0, d1, _, tot2 = _tally(led2)
        advance = enabled & (state.stage == R2_WAIT) & (tot2 >= Q)
        decide1 = d1 >= F1
        decide0 = d0 >= F1
        coin = _coin_bits(self.seed, self._shard_idx, state.slot, state.phase, self.coin_p1)
        next_v = jnp.where(
            decide1,
            I8(V1),
            jnp.where(
                decide0,
                I8(V0),
                jnp.where(d1 > 0, I8(V1), jnp.where(d0 > 0, I8(V0), coin)),
            ),
        )
        newly_decided = advance & (decide1 | decide0)
        dec_val = jnp.where(decide1, I8(V1), I8(V0))

        # external decision adoption (Decision broadcast / sync)
        adopt = enabled & ~newly_decided & (decision_in != ABSENT)
        decided = jnp.where(
            newly_decided, dec_val, jnp.where(adopt, decision_in, state.decided)
        )
        done = state.done | newly_decided | adopt

        phase = jnp.where(advance, state.phase + 1, state.phase)
        my_r1 = jnp.where(advance, next_v, state.my_r1)
        stage = jnp.where(advance, I8(R1_WAIT), stage)
        my_r2_out = my_r2
        my_r2 = jnp.where(advance, I8(ABSENT), my_r2)
        led1 = jnp.where(
            advance[:, None],
            jnp.where(own, next_v[:, None], I8(ABSENT)),
            led1,
        )
        led2 = jnp.where(advance[:, None], I8(ABSENT), led2)

        new_state = NodeState(
            slot=state.slot,
            phase=phase,
            stage=stage,
            my_r1=my_r1,
            my_r2=my_r2,
            led1=led1,
            led2=led2,
            decided=decided,
            done=done,
            active=state.active,
        )
        outbox = NodeOutbox(
            cast_r2=cast_r2,
            r2_vals=my_r2_out,
            advanced=advance,
            new_r1=my_r1,
            new_phase=phase,
            newly_decided=newly_decided,
            decided_vals=decided,
        )
        return new_state, outbox


# ---------------------------------------------------------------------------
# Wire phase packing: (slot, mvc_phase) <-> u64 sequence number
# ---------------------------------------------------------------------------

_MVC_BITS = 16


def pack_phase(slot: int, mvc_phase: int) -> int:
    """Encode (decision slot, weak-MVC phase) into a wire sequence number.

    The reference's monotone PhaseId (one per decision) maps to our slot;
    the in-slot MVC phase is new (its engine folds retries into fresh
    PhaseIds instead — SURVEY.md §3.1)."""
    if mvc_phase >= (1 << _MVC_BITS):
        raise ValueError("mvc phase overflow")
    return (slot << _MVC_BITS) | mvc_phase


def unpack_phase(seq: int) -> tuple[int, int]:
    return seq >> _MVC_BITS, seq & ((1 << _MVC_BITS) - 1)
