"""Pallas TPU kernel for the fault-free slot window: one fused pass.

The general :meth:`ClusterKernel.slot_pipeline` runs every slot through the
full weak-MVC machinery — two scanned ``round_step`` dispatches with
``[S, R, R]`` delivery grids — because it must also model loss, partitions
and per-replica divergence. Under the conditions ``slot_pipeline``
actually runs with (FULL delivery, fresh per-slot state, the default
``rounds_per_slot=2``), that machinery provably collapses to a closed
form, which this module evaluates as a single Pallas kernel over the
vote tensor. Measured (not assumed) roofline: the replica-major entry
streams votes at ~60-75% of peak HBM marginal rate once the per-dispatch
tunnel overhead is amortized — see docs/PERFORMANCE.md and
benchmarks/roofline.py for the table and methodology.

Derivation (each step mirrors ``round_step``, phase_driver.py:224-367):

1. With full delivery, every alive receiver's round-1 ledger contains
   exactly the *present* sender set ``{i : alive[i] and vote[i] != ABSENT}``
   (a sender's own diagonal entry from ``start_slot`` coincides with its
   delivered vote), so every alive replica computes the SAME tally
   ``(c0, c1, tot)``.
2. Round 1's transition: if ``tot >= Q`` every alive replica casts the
   same round-2 vote ``r2 = V1 if c1>=Q else V0 if c0>=Q else V?``;
   if ``tot < Q`` nothing ever happens (the ledger cannot grow).
3. Round 2's delivery gives every alive receiver ``n_alive`` copies of
   that same ``r2``; the advance condition ``tot2 >= Q`` holds because
   ``n_alive >= tot >= Q``, and the decide condition ``count >= f+1``
   holds because ``quorum >= f+1`` for every R. So the slot decides
   ``r2`` iff ``r2 != V?`` — at MVC phase 0 — and stays undecided
   otherwise (the coin is never reached within two rounds, so the
   decision is independent of the slot index).

Therefore::

    decided[t, s] = V1      if c1 >= Q
                    V0      elif c0 >= Q
                    ABSENT  else (incl. tot < Q: c0,c1 <= tot)

``tests/test_kernel.py`` pins this bit-identical to ``slot_pipeline``
over random votes (all four codes), random crash masks and odd sizes —
the general kernel remains the semantics owner; this is its proven
fast path. No reference analog: the reference decides one instance at a
time (rabia-core/src/messages.rs:185-211 tallies per phase).

Round 5: the preferred fast path is the PACKED formulation
(`kernel/packed_window.py` — 16 votes per u32 word, bitwise tally),
which moves 4x fewer bytes and streams at the HBM marginal rate; the
i8 entries here remain as the unpacked fallback and the roofline
comparison rows (docs/PERFORMANCE.md).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from rabia_tpu.core.types import ABSENT, V0, V1

I8 = jnp.int8
I32 = jnp.int32


@functools.partial(jax.jit, static_argnames=("quorum",))
def closed_form_window(
    votes: jnp.ndarray,  # i8[T, S, R]
    alive: jnp.ndarray,  # bool[S, R]
    quorum: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The closed form as one jitted XLA program (any backend)."""
    present = (votes != ABSENT) & alive[None, :, :]
    c1 = jnp.sum(present & (votes == V1), axis=-1, dtype=I32)
    c0 = jnp.sum(present & (votes == V0), axis=-1, dtype=I32)
    dec = jnp.where(
        c1 >= quorum, I8(V1), jnp.where(c0 >= quorum, I8(V0), I8(ABSENT))
    )
    ph = jnp.where(dec != ABSENT, I32(0), I32(-1))
    return dec, ph


@functools.partial(jax.jit, static_argnames=("quorum", "want_phase"))
def closed_form_window_rmajor(
    votes_rm: jnp.ndarray,  # i8[R, T, S] — replica-major planes
    alive_rm: jnp.ndarray,  # bool[R, S]
    quorum: int,
    want_phase: bool = True,
) -> tuple[jnp.ndarray, jnp.ndarray] | jnp.ndarray:
    """The closed form on replica-major votes: every operand is a
    well-tiled [T, S] plane, so no i8 minor-axis relayout is needed.
    Bit-identical to ``closed_form_window(transpose(votes_rm,(1,2,0)))``.
    ``want_phase=False`` returns only the decision plane and the i32
    phase plane is never materialized.
    """
    R = votes_rm.shape[0]
    T, S = votes_rm.shape[1], votes_rm.shape[2]
    c1 = jnp.zeros((T, S), I32)
    c0 = jnp.zeros((T, S), I32)
    for r in range(R):  # static unroll: R is tiny
        v = votes_rm[r]
        a = alive_rm[r][None, :]
        c1 = c1 + ((v == V1) & a).astype(I32)
        c0 = c0 + ((v == V0) & a).astype(I32)
    dec = jnp.where(
        c1 >= quorum, I8(V1), jnp.where(c0 >= quorum, I8(V0), I8(ABSENT))
    )
    if not want_phase:
        return dec
    ph = jnp.where(dec != ABSENT, I32(0), I32(-1))
    return dec, ph


def _make_kernel(R: int, quorum: int, want_phase: bool = True):
    """Kernel body closure (R and the quorum are compile-time static)."""

    def kernel(votes_ref, alive_ref, dec_ref, ph_ref=None):
        # votes_ref: i8[R, Tb, S] — replica-major so each plane is a
        # contiguous (Tb, S) tile; alive_ref: i8[R, 1, S]. Integer
        # arithmetic with explicit broadcasts throughout — Mosaic rejects
        # mixed-rank i1 broadcasts ("non-singleton dimension replicated").
        shape = dec_ref.shape
        c1 = jnp.zeros(shape, I32)
        c0 = jnp.zeros(shape, I32)
        for r in range(R):  # static unroll over the replica axis
            v = votes_ref[r].astype(I32)
            a = jnp.broadcast_to(alive_ref[r], shape).astype(I32)
            c1 = c1 + (v == V1).astype(I32) * a
            c0 = c0 + (v == V0).astype(I32) * a
        # stay in i32 until the final store: an i1 mask from an i32
        # compare cannot drive an i8-tiled select (another relayout trap)
        dec = jnp.where(
            c1 >= quorum, I32(V1), jnp.where(c0 >= quorum, I32(V0), I32(ABSENT))
        )
        dec_ref[:] = dec.astype(I8)
        if want_phase:
            ph_ref[:] = jnp.where(dec != ABSENT, I32(0), I32(-1))

    return kernel  # ph_ref defaults to None on the no-phase arity


def _pick_block(T: int, S: int, R: int) -> int:
    # the validated budget point: 64 slots x 4096 shards x 5 replicas of
    # i8 votes + i32 intermediates fits the 16MB VMEM with double
    # buffering — scale the slot tile down as EITHER axis grows so
    # block*S*R stays bounded
    cap = max(1, (64 * 4096 * 5) // max(S * max(R, 1), 1))
    for b in (64, 32, 16, 8, 4, 2, 1):
        if b <= cap and T % b == 0:
            return b
    return 1


@functools.partial(
    jax.jit, static_argnames=("quorum", "interpret", "want_phase")
)
def pallas_window_rmajor(
    votes_rm: jnp.ndarray,  # i8[R, T, S] — replica-major planes
    alive_rm: jnp.ndarray,  # bool[R, S]
    quorum: int,
    interpret: bool = False,
    want_phase: bool = True,
) -> tuple[jnp.ndarray, jnp.ndarray] | jnp.ndarray:
    """The closed form as one Pallas TPU kernel on replica-major votes.

    This is the bandwidth-shaped entry: each replica's votes are a
    contiguous, well-tiled ``[T, S]`` i8 plane, so the kernel streams
    them with no minor-axis relayout (the ``[T, S, R]`` layout puts
    R=5 on the lane axis, and the i8 relayout to fix that dominated
    the round-3 kernel — see docs/PERFORMANCE.md roofline table).

    ``want_phase=False`` skips the i32 phase plane (4 redundant
    bytes/decision: in the fault-free closed form the phase is
    derivable — 0 iff decided) and returns only the decision plane.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, T, S = votes_rm.shape
    block = _pick_block(T, S, R)
    alive_t = alive_rm.astype(I8)[:, None, :]  # [R, 1, S]
    out_specs = [
        pl.BlockSpec((block, S), lambda i: (i, 0), memory_space=pltpu.VMEM)
    ]
    out_shape = [jax.ShapeDtypeStruct((T, S), I8)]
    if want_phase:
        out_specs.append(
            pl.BlockSpec((block, S), lambda i: (i, 0), memory_space=pltpu.VMEM)
        )
        out_shape.append(jax.ShapeDtypeStruct((T, S), I32))
    out = pl.pallas_call(
        _make_kernel(R, quorum, want_phase=want_phase),
        grid=(T // block,),
        in_specs=[
            pl.BlockSpec(
                (R, block, S), lambda i: (0, i, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (R, 1, S), lambda i: (0, 0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(votes_rm, alive_t)
    if want_phase:
        return out[0], out[1]
    return out[0]


@functools.partial(
    jax.jit, static_argnames=("quorum", "interpret")
)
def pallas_window(
    votes: jnp.ndarray,  # i8[T, S, R]
    alive: jnp.ndarray,  # bool[S, R]
    quorum: int,
    interpret: bool = False,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The closed form on the API ``[T, S, R]`` layout: relayouts to
    replica-major, then runs :func:`pallas_window_rmajor`. Producers
    that can build votes replica-major should call the rmajor entry
    directly and skip the relayout."""
    votes_t = jnp.transpose(votes, (2, 0, 1))  # [R, T, S]
    alive_t = jnp.transpose(alive, (1, 0))  # [R, S]
    return pallas_window_rmajor(
        votes_t, alive_t, quorum, interpret=interpret
    )
