"""Device kernels: the batched weak-MVC phase driver and mesh execution.

This package is the TPU-native replacement for the reference's scalar
consensus hot loop (rabia-engine/src/engine.rs:381-746 — vote rules, tally,
coin, decision): thousands of consensus instances evaluated as one array
program over ``[shards, replicas]`` vote matrices.
"""

from rabia_tpu.kernel.host_driver import (  # noqa: F401
    HostNodeKernel,
    HostNodeState,
)
from rabia_tpu.kernel.packed_window import (  # noqa: F401
    pack_alive,
    pack_codes,
    packed_width,
    packed_window_rmajor,
    unpack_codes,
)
from rabia_tpu.kernel.phase_driver import (  # noqa: F401
    ClusterKernel,
    ClusterState,
    NodeKernel,
    NodeState,
    device_coin,
)
