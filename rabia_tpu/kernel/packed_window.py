"""Packed-vote fused window: 16 votes per u32 word, bitwise tally.

The fault-free closed form (`fused_window.closed_form_window_rmajor`)
moves one i8 byte per vote per replica — R+1 bytes per decision — and
its measured roofline sits at ~30% of peak HBM at the production shape
because the i8->i32 unpack arithmetic, not the byte stream, is the
bound (docs/PERFORMANCE.md, roofline_r04). The protocol only has four
vote codes (V0=0, V1=1, V?=2, ABSENT=3 — core/types.py), i.e. 2 bits,
so this module packs 16 votes into each u32 word along the shard axis
and evaluates the SAME closed form with word-wise bit arithmetic:

- per replica word ``w``: the V1 bit-plane is ``lo & ~hi`` and the V0
  plane ``~(lo|hi)`` where ``lo = w & 0x5555…``, ``hi = (w>>1) & 0x5555…``
  (one bit per 2-bit lane, at the lane LSB position);
- replica counts accumulate in BIT-SLICED form with a carry-save ripple
  (`_csa_inc`): ``ceil(log2(R+1))`` u32 planes hold the per-lane count,
  so no lane ever widens past its 2-bit field;
- the quorum test is a static bit-sliced magnitude comparator
  (`_ge_const`): compile-time constant quorum, pure AND/OR/XOR;
- decisions come back packed in the same 2-bit layout
  (V1 / V0 / ABSENT — phase is derivable: 0 iff decided).

Bytes moved per decision drop from R+1 (=6 at R=5) to (2R+2)/8 (=1.5):
a 4x cut, and every op is u32 vector arithmetic Mosaic/XLA handle at
full lane width — this sidesteps the i8 limitation entirely instead of
fighting it. Bit-identical to ``closed_form_window_rmajor`` (pinned in
tests/test_packed_window.py over random codes, crash masks, quorums
and ragged widths).

No reference analog: the reference tallies one instance at a time over
message structs (rabia-core/src/messages.rs:185-211); batching votes
into bit-planes is the TPU-native formulation of the same tally.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from rabia_tpu.core.types import ABSENT

U32 = jnp.uint32
I8 = jnp.int8
LANES = 16  # 2-bit codes per u32 word
_EVEN = 0x55555555  # lane-LSB positions (bits 0,2,…,30)


def packed_width(S: int) -> int:
    """Words per row for S shards (ceil division)."""
    return -(-S // LANES)


@jax.jit
def pack_codes(x: jnp.ndarray) -> jnp.ndarray:
    """Pack 2-bit codes i8[..., S] -> u32[..., ceil(S/16)].

    Ragged widths pad with ABSENT: absent votes never tally, so padding
    lanes decide ABSENT and `unpack_codes` truncates them away.
    """
    S = x.shape[-1]
    SW = packed_width(S)
    pad = SW * LANES - S
    if pad:
        cfg = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
        x = jnp.pad(x, cfg, constant_values=ABSENT)
    w = x.reshape(x.shape[:-1] + (SW, LANES)).astype(U32)
    shifts = jnp.arange(LANES, dtype=U32) * 2
    # disjoint 2-bit fields: sum == bitwise-or
    return jnp.sum(w << shifts, axis=-1, dtype=U32)


@functools.partial(jax.jit, static_argnames=("S",))
def unpack_codes(p: jnp.ndarray, S: int) -> jnp.ndarray:
    """Inverse of :func:`pack_codes`: u32[..., SW] -> i8[..., S]."""
    shifts = jnp.arange(LANES, dtype=U32) * 2
    x = (p[..., None] >> shifts) & U32(3)
    x = x.reshape(p.shape[:-1] + (p.shape[-1] * LANES,)).astype(I8)
    return x[..., :S]


@jax.jit
def pack_alive(alive: jnp.ndarray) -> jnp.ndarray:
    """Pack bool[..., S] -> u32[..., SW] with one bit per lane at the
    lane LSB position (dead/padding lanes are 0)."""
    S = alive.shape[-1]
    SW = packed_width(S)
    pad = SW * LANES - S
    x = alive.astype(U32)
    if pad:
        cfg = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
        x = jnp.pad(x, cfg, constant_values=0)
    w = x.reshape(x.shape[:-1] + (SW, LANES))
    shifts = jnp.arange(LANES, dtype=U32) * 2
    return jnp.sum(w << shifts, axis=-1, dtype=U32)


def _csa_inc(planes: list, b, cap: int):
    """Add bit-plane ``b`` (one bit per lane) into the bit-sliced
    counter ``planes`` (LSB-first). The count after k increments is at
    most k <= R < 2**cap, so the carry out of plane cap-1 is provably
    zero and the counter never grows past ``cap`` planes."""
    carry = b
    out = []
    for p in planes:
        out.append(p ^ carry)
        carry = p & carry
    if len(out) < cap:
        out.append(carry)
    return out


def _ge_const(planes: list, q: int, m):
    """Bit-sliced ``count >= q`` for a compile-time constant q.

    ``planes`` is the LSB-first bit-sliced count (every bit sits at a
    lane LSB position under mask ``m``); returns a mask in the same
    positions. MSB-first magnitude scan: a lane is >= q once a count
    bit exceeds the corresponding q bit on an equal prefix.
    """
    if q <= 0:
        return m
    nbits = len(planes)
    if q > (1 << nbits) - 1:
        return jnp.zeros_like(m)
    ge = jnp.zeros_like(m)
    eq = m
    for bit in reversed(range(nbits)):
        p = planes[bit]
        if (q >> bit) & 1:
            eq = eq & p
        else:
            ge = ge | (eq & p)
    return ge | eq


@functools.partial(jax.jit, static_argnames=("quorum",))
def packed_window_rmajor(
    packed_rm: jnp.ndarray,  # u32[R, T, SW] — replica-major packed planes
    alive_packed: jnp.ndarray,  # u32[R, SW] — lane-LSB alive bits
    quorum: int,
) -> jnp.ndarray:
    """The fault-free closed form on packed votes; returns packed
    decisions u32[T, SW] in the same 2-bit layout (V1/V0/ABSENT).

    Bit-identical to ``pack_codes(closed_form_window_rmajor(unpack)…)``;
    the phase plane is intentionally not produced (derivable: 0 iff
    decided — same contract as ``want_phase=False``).
    """
    R = packed_rm.shape[0]
    cap = R.bit_length()
    m = U32(_EVEN)
    c1: list = []
    c0: list = []
    for r in range(R):  # static unroll: R is tiny
        w = packed_rm[r]
        a = alive_packed[r][None, :]
        lo = w & m
        hi = (w >> 1) & m
        b1 = lo & ~hi & a
        b0 = (lo | hi) ^ m  # ~(lo|hi) confined to lane-LSB bits
        b0 = b0 & a
        c1 = _csa_inc(c1, b1, cap)
        c0 = _csa_inc(c0, b0, cap)
    ge1 = _ge_const(c1, quorum, jnp.broadcast_to(m, packed_rm.shape[1:]))
    ge0 = _ge_const(c0, quorum, jnp.broadcast_to(m, packed_rm.shape[1:]))
    # lane codes: V1=01 where ge1; else V0=00 where ge0; else ABSENT=11
    babs = (ge1 | ge0) ^ jnp.broadcast_to(m, ge1.shape)
    return (ge1 | babs) | (babs << 1)
