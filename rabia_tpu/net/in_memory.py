"""In-process transports: the hub-routed `InMemoryNetwork`.

Reference parity: rabia-testing/src/network/in_memory.rs:9-141 — a central
`InMemoryNetworkSimulator` router plus per-node `InMemoryNetwork` adapters
implementing the transport trait. Here the router is :class:`InMemoryHub`
(asyncio queues instead of tokio channels); the per-node adapter is
:class:`InMemoryNetwork`. Unlike the reference's ``receive()`` — which
errors with "No messages available" after a hard-coded 10ms
(in_memory.rs:73-82) — receive takes an explicit timeout and raises
:class:`~rabia_tpu.core.errors.TimeoutError_` only when it expires.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Optional

from rabia_tpu.core.errors import NetworkError, TimeoutError_
from rabia_tpu.core.network import NetworkTransport
from rabia_tpu.core.types import NodeId


@dataclass
class HubStats:
    """Delivery counters for the whole hub."""

    sent: int = 0
    delivered: int = 0
    dropped: int = 0
    total_bytes: int = 0


class InMemoryHub:
    """Central router: one unbounded queue per registered node.

    Reference: the `InMemoryNetworkSimulator` bus (in_memory.rs:106-141).
    Supports administrative disconnection (drops traffic to/from a node) so
    harnesses can crash nodes without tearing down objects.
    """

    def __init__(self) -> None:
        self._queues: dict[NodeId, asyncio.Queue[tuple[NodeId, bytes]]] = {}
        self._disconnected: set[NodeId] = set()
        self._notify: dict[NodeId, object] = {}  # node -> zero-arg callable
        # membership epoch: bumped on register/connect/notify changes so
        # per-sender broadcast fan-out caches can invalidate
        self._epoch = 0
        self.stats = HubStats()

    def register(self, node: NodeId) -> "InMemoryNetwork":
        if node in self._queues:
            raise NetworkError(f"node {node} already registered")
        self._queues[node] = asyncio.Queue()
        self._epoch += 1
        return InMemoryNetwork(node, self)

    def nodes(self) -> set[NodeId]:
        return set(self._queues) - self._disconnected

    def set_connected(self, node: NodeId, connected: bool) -> None:
        if connected:
            self._disconnected.discard(node)
        else:
            self._disconnected.add(node)
        self._epoch += 1

    def is_connected(self, node: NodeId) -> bool:
        return node in self._queues and node not in self._disconnected

    def route(self, sender: NodeId, target: NodeId, data: bytes) -> None:
        self.stats.sent += 1
        if sender in self._disconnected or target in self._disconnected:
            self.stats.dropped += 1
            return
        q = self._queues.get(target)
        if q is None:
            self.stats.dropped += 1
            return
        q.put_nowait((sender, data))
        self.stats.delivered += 1
        self.stats.total_bytes += len(data)
        cb = self._notify.get(target)
        if cb is not None:
            cb()

    def set_notify(self, node: NodeId, callback) -> None:
        """Wake-on-inbox hook: `callback` runs (on the loop thread, from
        route()) whenever a message lands in `node`'s queue."""
        self._notify[node] = callback
        self._epoch += 1

    def queue_of(self, node: NodeId) -> asyncio.Queue:
        return self._queues[node]


class InMemoryNetwork(NetworkTransport):
    """Per-node transport adapter over an :class:`InMemoryHub`."""

    def __init__(self, node_id: NodeId, hub: InMemoryHub) -> None:
        self.node_id = node_id
        self.hub = hub
        self._bcast_epoch = -1
        self._bcast_targets: list = []  # [(queue, notify-or-None)]

    async def send_to(self, target: NodeId, data: bytes) -> None:
        self.send_to_nowait(target, data)

    async def broadcast(self, data: bytes) -> None:
        self.broadcast_nowait(data)

    def send_to_nowait(self, target: NodeId, data: bytes) -> bool:
        self.hub.route(self.node_id, target, data)
        return True

    def broadcast_nowait(self, data: bytes) -> bool:
        hub = self.hub
        if hub._epoch != self._bcast_epoch:
            # rebuild the fan-out on membership/notify change: rebuilding
            # the recipient set per broadcast (NodeId set algebra + dict
            # walks) measurably taxed the serial engine shape
            self._bcast_epoch = hub._epoch
            self._bcast_targets = (
                []
                if self.node_id in hub._disconnected
                else [
                    (hub._queues[n], hub._notify.get(n))
                    for n in hub._queues
                    if n != self.node_id and n not in hub._disconnected
                ]
            )
        if not self._bcast_targets:
            if self.node_id in hub._disconnected:
                # stat parity with the uncached path: route() counted one
                # attempted+dropped send per LIVE peer for a disconnected
                # sender
                n_live = sum(
                    1
                    for n in hub._queues
                    if n != self.node_id and n not in hub._disconnected
                )
                hub.stats.sent += n_live
                hub.stats.dropped += n_live
            return True
        me = self.node_id
        stats = hub.stats
        nbytes = len(data)
        for q, cb in self._bcast_targets:
            stats.sent += 1
            q.put_nowait((me, data))
            stats.delivered += 1
            stats.total_bytes += nbytes
            if cb is not None:
                cb()
        return True

    async def receive(self, timeout: Optional[float] = None) -> tuple[NodeId, bytes]:
        q = self.hub.queue_of(self.node_id)
        if timeout is None:
            return await q.get()
        try:
            return await asyncio.wait_for(q.get(), timeout)
        except asyncio.TimeoutError:
            raise TimeoutError_("receive", timeout) from None

    def receive_nowait(self) -> Optional[tuple[NodeId, bytes]]:
        """Non-blocking drain helper for the engine's round loop."""
        q = self.hub.queue_of(self.node_id)
        try:
            return q.get_nowait()
        except asyncio.QueueEmpty:
            return None

    def set_receive_notify(self, callback) -> bool:
        self.hub.set_notify(self.node_id, callback)
        return True

    async def get_connected_nodes(self) -> set[NodeId]:
        if not self.hub.is_connected(self.node_id):
            return set()
        return self.hub.nodes() - {self.node_id}

    async def disconnect(self, node: NodeId) -> None:
        self.hub.set_connected(node, False)

    async def reconnect(self) -> None:
        self.hub.set_connected(self.node_id, True)
