"""Network simulator: latency, loss, partitions, bandwidth — in-process.

Reference parity: rabia-testing/src/network_sim.rs — `NetworkConditions`
(:13-32), `NetworkStats` (:60-85), the simulator with timed partitions and a
delayed-delivery queue (:50-333; `send_message` :138-186 applies loss and
partition checks, `run_simulation` :248-272 is the 1ms delivery tick,
`deliver_message` :274-301), and the per-node `SimulatedNetwork` transport
adapter (:335-406).

Implementation notes (asyncio instead of tokio): instead of a 1ms polling
tick, delivery uses a heap of (due_time, message) serviced by a single
driver task that sleeps exactly until the next due message — same observable
behavior, no busy loop. Partitions use the reference's one-sided membership
semantics (network_sim.rs:188-204): traffic is blocked iff exactly one
endpoint is inside the partitioned group.
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
import random
import time
from dataclasses import dataclass, field
from typing import Optional

from rabia_tpu.core.errors import NetworkError, TimeoutError_
from rabia_tpu.core.network import NetworkTransport
from rabia_tpu.core.types import NodeId


@dataclass
class NetworkConditions:
    """Tunable impairments (network_sim.rs:13-32)."""

    latency_min: float = 0.0  # seconds
    latency_max: float = 0.0
    packet_loss_rate: float = 0.0  # [0,1]
    partition_probability: float = 0.0  # spontaneous partition chance per send
    bandwidth_limit: Optional[int] = None  # bytes/sec; None = unlimited

    @staticmethod
    def perfect() -> "NetworkConditions":
        return NetworkConditions()

    @staticmethod
    def lossy(rate: float) -> "NetworkConditions":
        return NetworkConditions(packet_loss_rate=rate)

    @staticmethod
    def wan(latency_ms: float = 50.0, jitter_ms: float = 20.0) -> "NetworkConditions":
        base = latency_ms / 1000.0
        return NetworkConditions(
            latency_min=max(0.0, base - jitter_ms / 2000.0),
            latency_max=base + jitter_ms / 2000.0,
        )


@dataclass
class NetworkStats:
    """Aggregate delivery counters (network_sim.rs:60-85)."""

    messages_sent: int = 0
    messages_delivered: int = 0
    messages_dropped: int = 0
    total_latency: float = 0.0
    total_bytes: int = 0

    @property
    def average_latency(self) -> float:
        if self.messages_delivered == 0:
            return 0.0
        return self.total_latency / self.messages_delivered

    @property
    def delivery_rate(self) -> float:
        if self.messages_sent == 0:
            return 1.0
        return self.messages_delivered / self.messages_sent

    def throughput_mbps(self, elapsed: float) -> float:
        if elapsed <= 0:
            return 0.0
        return self.total_bytes * 8 / elapsed / 1e6


@dataclass(order=True)
class _Pending:
    due: float
    seq: int
    sender: NodeId = field(compare=False)
    target: NodeId = field(compare=False)
    data: bytes = field(compare=False)
    sent_at: float = field(compare=False, default=0.0)


class NetworkSimulator:
    """Central simulated fabric all `SimulatedNetwork` adapters share.

    Crash/partition model:
      - `crash(node)` / `recover(node)`: node neither sends nor receives.
      - `partition(group, duration)`: one-sided membership test — a message
        is blocked iff exactly one endpoint is in `group`
        (network_sim.rs:188-204).
    """

    def __init__(
        self,
        conditions: Optional[NetworkConditions] = None,
        seed: Optional[int] = None,
    ) -> None:
        self.conditions = conditions or NetworkConditions.perfect()
        self.stats = NetworkStats()
        self._rng = random.Random(seed)
        self._queues: dict[NodeId, asyncio.Queue[tuple[NodeId, bytes]]] = {}
        self._notify: dict[NodeId, object] = {}  # node -> zero-arg callable
        self._crashed: set[NodeId] = set()
        self._node_delay: dict[NodeId, float] = {}  # SlowNode fault support
        self._partition: set[NodeId] = set()
        self._partition_until: float = 0.0
        # chaos plane: per-DIRECTED-link impairments (asymmetric loss /
        # extra one-way delay) and a scheduled flapping partition
        self._link_loss: dict[tuple[NodeId, NodeId], float] = {}
        self._link_delay: dict[tuple[NodeId, NodeId], float] = {}
        self._flap_group: set[NodeId] = set()
        self._flap_period: float = 1.0
        self._flap_duty: float = 0.5
        self._flap_t0: float = 0.0
        self._flap_until: float = 0.0
        self._heap: list[_Pending] = []
        self._seq = itertools.count()
        self._wakeup: Optional[asyncio.Event] = None
        self._driver: Optional[asyncio.Task] = None
        self._closed = False
        # token-bucket state for bandwidth_limit
        self._bucket_tokens: float = 0.0
        self._bucket_at: float = time.monotonic()

    # -- registration -------------------------------------------------------

    def register(self, node: NodeId) -> "SimulatedNetwork":
        if node in self._queues:
            raise NetworkError(f"node {node} already registered")
        self._queues[node] = asyncio.Queue()
        return SimulatedNetwork(node, self)

    def nodes(self) -> set[NodeId]:
        return set(self._queues)

    # -- fault injection ----------------------------------------------------

    def crash(self, node: NodeId) -> None:
        self._crashed.add(node)

    def recover(self, node: NodeId) -> None:
        self._crashed.discard(node)

    def is_crashed(self, node: NodeId) -> bool:
        return node in self._crashed

    def set_node_delay(self, node: NodeId, delay: float) -> None:
        """SlowNode fault: extra delay on all of `node`'s traffic (the
        reference stubs this — fault_injection.rs:267-288)."""
        if delay <= 0:
            self._node_delay.pop(node, None)
        else:
            self._node_delay[node] = delay

    def set_link_loss(self, src: NodeId, dst: NodeId, rate: float) -> None:
        """ASYMMETRIC loss: drop `rate` of messages on the DIRECTED link
        src->dst only (the reverse direction is untouched — the
        sustained-asymmetric-loss chaos profile; wireless-BFT's lossy
        uplink shape). rate <= 0 clears the link."""
        if rate <= 0:
            self._link_loss.pop((src, dst), None)
        else:
            self._link_loss[(src, dst)] = min(1.0, float(rate))

    def set_link_delay(self, src: NodeId, dst: NodeId, delay: float) -> None:
        """Extra one-way delay on the DIRECTED link src->dst (seconds);
        composes with global conditions and node delays. <= 0 clears."""
        if delay <= 0:
            self._link_delay.pop((src, dst), None)
        else:
            self._link_delay[(src, dst)] = float(delay)

    def clear_link_faults(self) -> None:
        self._link_loss.clear()
        self._link_delay.clear()

    def set_flap(
        self,
        group: set[NodeId],
        period: float,
        duty: float = 0.5,
        duration: Optional[float] = None,
    ) -> None:
        """Scheduled flapping partition: `group` is isolated (one-sided
        membership semantics, like :meth:`partition`) for the first
        ``duty`` fraction of every ``period`` seconds, healed for the
        rest — evaluated lazily at send/delivery time, so the schedule
        is exact with no timer tasks. ``duration`` bounds the whole
        flapping episode (None = until :meth:`clear_flap`)."""
        if period <= 0:
            raise ValueError("flap period must be positive")
        self._flap_group = set(group)
        self._flap_period = float(period)
        self._flap_duty = min(1.0, max(0.0, float(duty)))
        self._flap_t0 = time.monotonic()
        self._flap_until = (
            self._flap_t0 + duration if duration is not None else float("inf")
        )

    def clear_flap(self) -> None:
        self._flap_group = set()
        self._flap_until = 0.0

    def _flap_active(self) -> bool:
        if not self._flap_group:
            return False
        now = time.monotonic()
        if now >= self._flap_until:
            self._flap_group = set()
            return False
        phase = ((now - self._flap_t0) % self._flap_period) / self._flap_period
        return phase < self._flap_duty

    def partition(self, group: set[NodeId], duration: Optional[float] = None) -> None:
        """Isolate `group` from the rest for `duration` seconds (None = until
        healed explicitly)."""
        self._partition = set(group)
        self._partition_until = (
            time.monotonic() + duration if duration is not None else float("inf")
        )

    def heal_partition(self) -> None:
        self._partition = set()
        self._partition_until = 0.0

    def _partition_active(self) -> bool:
        if not self._partition:
            return False
        if time.monotonic() >= self._partition_until:
            self._partition = set()
            return False
        return True

    def _blocked_by_partition(self, a: NodeId, b: NodeId) -> bool:
        if self._partition_active() and (
            (a in self._partition) != (b in self._partition)
        ):
            return True
        if self._flap_active() and (
            (a in self._flap_group) != (b in self._flap_group)
        ):
            return True
        return False

    # -- the send path (network_sim.rs:138-186) -----------------------------

    def send(self, sender: NodeId, target: NodeId, data: bytes) -> None:
        self.stats.messages_sent += 1
        c = self.conditions
        if sender in self._crashed or target in self._crashed:
            self.stats.messages_dropped += 1
            return
        if target not in self._queues:
            self.stats.messages_dropped += 1
            return
        if self._blocked_by_partition(sender, target):
            self.stats.messages_dropped += 1
            return
        if c.packet_loss_rate > 0 and self._rng.random() < c.packet_loss_rate:
            self.stats.messages_dropped += 1
            return
        link_loss = self._link_loss.get((sender, target))
        if link_loss and self._rng.random() < link_loss:
            self.stats.messages_dropped += 1
            return
        if c.partition_probability > 0 and self._rng.random() < c.partition_probability:
            self.stats.messages_dropped += 1
            return

        delay = 0.0
        if c.latency_max > 0:
            delay = self._rng.uniform(c.latency_min, c.latency_max)
        delay += self._node_delay.get(sender, 0.0) + self._node_delay.get(target, 0.0)
        delay += self._link_delay.get((sender, target), 0.0)
        if c.bandwidth_limit:
            delay += self._bandwidth_delay(len(data), c.bandwidth_limit)

        if delay <= 0:
            self._deliver(sender, target, data, 0.0)
            return
        now = time.monotonic()
        heapq.heappush(
            self._heap,
            _Pending(now + delay, next(self._seq), sender, target, data, now),
        )
        self._ensure_driver()
        if self._wakeup is not None:
            self._wakeup.set()

    def _bandwidth_delay(self, nbytes: int, limit: int) -> float:
        """Token-bucket serialization delay for a message of nbytes."""
        now = time.monotonic()
        self._bucket_tokens = min(
            float(limit), self._bucket_tokens + (now - self._bucket_at) * limit
        )
        self._bucket_at = now
        self._bucket_tokens -= nbytes
        if self._bucket_tokens >= 0:
            return 0.0
        return -self._bucket_tokens / limit

    def _deliver(self, sender: NodeId, target: NodeId, data: bytes, latency: float) -> None:
        if target in self._crashed or target not in self._queues:
            self.stats.messages_dropped += 1
            return
        if self._blocked_by_partition(sender, target):
            self.stats.messages_dropped += 1
            return
        self._queues[target].put_nowait((sender, data))
        self.stats.messages_delivered += 1
        self.stats.total_latency += latency
        self.stats.total_bytes += len(data)
        cb = self._notify.get(target)
        if cb is not None:
            cb()

    # -- delayed-delivery driver (replaces the 1ms tick loop) ---------------

    def _ensure_driver(self) -> None:
        if self._driver is None or self._driver.done():
            self._wakeup = asyncio.Event()
            self._driver = asyncio.get_event_loop().create_task(self._drive())

    async def _drive(self) -> None:
        while not self._closed:
            now = time.monotonic()
            while self._heap and self._heap[0].due <= now:
                p = heapq.heappop(self._heap)
                self._deliver(p.sender, p.target, p.data, now - p.sent_at)
            if self._heap:
                try:
                    await asyncio.wait_for(
                        self._wakeup.wait(), self._heap[0].due - now
                    )
                except asyncio.TimeoutError:
                    pass
                self._wakeup.clear()
            else:
                try:
                    await asyncio.wait_for(self._wakeup.wait(), 0.25)
                except asyncio.TimeoutError:
                    # a send() may have raced the timeout and pushed onto the
                    # heap while we were suspended (it saw the driver not
                    # done, so it won't restart us) — only exit truly idle
                    if self._heap:
                        continue
                    self._driver = None
                    return
                self._wakeup.clear()

    async def close(self) -> None:
        self._closed = True
        if self._wakeup is not None:
            self._wakeup.set()
        if self._driver is not None:
            try:
                await self._driver
            except asyncio.CancelledError:
                pass

    def set_notify(self, node: NodeId, callback) -> None:
        """Wake-on-inbox hook: `callback` runs at actual delivery time
        (after simulated latency), on the loop thread."""
        self._notify[node] = callback

    def queue_of(self, node: NodeId) -> asyncio.Queue:
        return self._queues[node]


class SimulatedNetwork(NetworkTransport):
    """Per-node transport over a shared :class:`NetworkSimulator`
    (network_sim.rs:335-406)."""

    def __init__(self, node_id: NodeId, sim: NetworkSimulator) -> None:
        self.node_id = node_id
        self.sim = sim

    async def send_to(self, target: NodeId, data: bytes) -> None:
        self.sim.send(self.node_id, target, data)

    async def broadcast(self, data: bytes) -> None:
        for n in self.sim.nodes():
            if n != self.node_id:
                self.sim.send(self.node_id, n, data)

    async def receive(self, timeout: Optional[float] = None) -> tuple[NodeId, bytes]:
        q = self.sim.queue_of(self.node_id)
        if timeout is None:
            return await q.get()
        try:
            return await asyncio.wait_for(q.get(), timeout)
        except asyncio.TimeoutError:
            raise TimeoutError_("receive", timeout) from None

    def receive_nowait(self) -> Optional[tuple[NodeId, bytes]]:
        try:
            return self.sim.queue_of(self.node_id).get_nowait()
        except asyncio.QueueEmpty:
            return None

    def set_receive_notify(self, callback) -> bool:
        self.sim.set_notify(self.node_id, callback)
        return True

    async def get_connected_nodes(self) -> set[NodeId]:
        if self.sim.is_crashed(self.node_id):
            return set()
        out = set()
        for n in self.sim.nodes():
            if n == self.node_id or self.sim.is_crashed(n):
                continue
            if self.sim._blocked_by_partition(self.node_id, n):
                continue
            out.add(n)
        return out

    async def disconnect(self, node: NodeId) -> None:
        self.sim.crash(node)

    async def reconnect(self) -> None:
        self.sim.recover(self.node_id)
