"""Transports: in-memory hub, fault-injecting simulator, (C++) TCP.

The communication planes of SURVEY.md §5.8 — all behind
:class:`rabia_tpu.core.network.NetworkTransport`.
"""

from rabia_tpu.net.in_memory import HubStats, InMemoryHub, InMemoryNetwork
from rabia_tpu.net.simulator import (
    NetworkConditions,
    NetworkSimulator,
    NetworkStats,
    SimulatedNetwork,
)

__all__ = [
    "HubStats",
    "InMemoryHub",
    "InMemoryNetwork",
    "NetworkConditions",
    "NetworkSimulator",
    "NetworkStats",
    "SimulatedNetwork",
]
