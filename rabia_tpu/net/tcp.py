"""TcpNetwork: the production transport over the C++ epoll data plane.

Reference parity: rabia-engine/src/network/tcp.rs (C17) — but the
framing/handshake/reconnect machinery lives in native code
(rabia_tpu/native/transport.cpp) with zero Python in the io path; this
module is the asyncio bridge implementing
:class:`~rabia_tpu.core.network.NetworkTransport`:

- a reader thread blocks in the native `rt_recv` and pushes frames into a
  plain deque (the engine's hot drain is ``receive_nowait``); the asyncio
  loop is woken via ``call_soon_threadsafe`` at most ONCE per pending
  batch, not once per frame — per-frame wakeups write the loop's self-pipe
  and measurably dominate a 16384-shard profile;
- sends/broadcasts frame once into the native outbound staging queue —
  the returned awaitables complete immediately (the reference's unbounded
  outbound queues, tcp.rs:559-643, behave the same way) and never contend
  with the io thread's syscalls.
"""

from __future__ import annotations

import asyncio
import collections
import ctypes
import os
import threading
from typing import Optional

import numpy as np

from rabia_tpu.core.config import TcpNetworkConfig
from rabia_tpu.core.errors import NetworkError, TimeoutError_
from rabia_tpu.core.network import NetworkTransport
from rabia_tpu.core.types import NodeId
from rabia_tpu.native import load_library

_RECV_BUF_CAP = 16 * 1024 * 1024  # matches the native 16MiB frame cap

# Session-multiplex handshake id (transport.cpp kMuxMagic): a connection
# that handshakes with this 16-byte id carries MANY sessions — every
# frame is prefixed with a 16-byte session id inside the payload
# (inbound: the prefix becomes the sender; outbound: rt_send to a bound
# session id wraps the frame with it). Client-side speakers (the loadgen
# mux lane) dial plain TCP, send MUX_MAGIC, then frame as
# [u32 LE 16+len][session id][payload].
MUX_MAGIC = bytes([0xF5]) + b"RABIA-MUX" + bytes([0xF5] * 6)
assert len(MUX_MAGIC) == 16

# Names of the native transport's observability counter block, in RTC_*
# index order (transport.cpp). Versioned append-only: a newer library may
# expose more (ignored here), an older one fewer (read as 0).
RT_COUNTER_NAMES = (
    "frames_in",
    "bytes_in",
    "frames_out",
    "bytes_out",
    "inbox_dropped",
    "out_pool_hits",
    "out_pool_misses",
    "in_pool_hits",
    "in_pool_misses",
    "arena_borrows",
    "dials",
    "conns_established",
    "conns_closed",
    # chaos shaping layer (RTC v2)
    "shape_dropped",
    "shape_delayed",
    # thread-per-shard-group inbox routing (RTC v3)
    "group_frames",
    "group_copies",
)


def _id_bytes(node: NodeId) -> bytes:
    return node.value.bytes


class _BorrowedFrame:
    """Zero-copy view over an inbound frame still owned by the native
    transport's buffer arena (SURVEY §7.4.7 handoff, step 0: no memcpy
    between the io thread's landing buffer and the codec/jax.dlpack
    consumer). ``view`` aliases C memory — it is only valid until
    ``release()``, which returns the buffer to the arena (idempotent;
    also safe after transport close, where it is a no-op)."""

    __slots__ = ("_owner", "_token", "addr", "view")

    def __init__(self, owner: "TcpNetwork", token: int, addr: int, n: int):
        self._owner = owner
        self._token = token
        self.addr = addr  # the arena address the C side reported
        self.view = memoryview(
            (ctypes.c_uint8 * n).from_address(addr)
        ).cast("B") if n else memoryview(b"")

    def release(self) -> None:
        tok, self._token = self._token, 0
        if tok:
            self.view = memoryview(b"")  # drop the alias before the free
            self._owner._release_token(tok)

    def to_bytes(self) -> bytes:
        data = bytes(self.view)
        self.release()
        return data

    def __del__(self):  # leak guard: a dropped frame must not pin its arena
        try:
            self.release()
        except Exception:
            pass


class TcpNetwork(NetworkTransport):
    """Async transport facade over the native epoll loop."""

    def __init__(
        self,
        node_id: NodeId,
        config: Optional[TcpNetworkConfig] = None,
    ) -> None:
        self.node_id = node_id
        self.config = config or TcpNetworkConfig()
        self._lib = load_library()
        actual = ctypes.c_uint16(0)
        self_id = (ctypes.c_uint8 * 16).from_buffer_copy(_id_bytes(node_id))
        self._handle = self._lib.rt_create(
            self_id,
            self.config.bind_host.encode(),
            self.config.bind_port,
            ctypes.byref(actual),
        )
        if not self._handle:
            raise NetworkError(
                f"cannot bind {self.config.bind_host}:{self.config.bind_port}"
            )
        self.port: int = actual.value
        # frame handoff: deque appends are GIL-atomic, so the engine's
        # receive_nowait drain never crosses the asyncio machinery at all;
        # _data_ready only serves the blocking receive() path
        self._pending: collections.deque[tuple[NodeId, bytes]] = (
            collections.deque()
        )
        self._data_ready = asyncio.Event()
        self._wake_scheduled = False
        self._recv_notify = None  # wake-on-inbox hook (set_receive_notify)
        # must be the RUNNING loop: the reader thread posts into it with
        # call_soon_threadsafe; a get_event_loop()-created orphan loop would
        # swallow frames forever. Constructing outside async context is an
        # error (RuntimeError), not a silent hang.
        self._loop = asyncio.get_running_loop()
        self._closed = False
        # counter state frozen at close (late scrapes read these instead
        # of the freed native Transport)
        self._final_ctrs: dict[str, int] = {}
        self._final_out_pool: tuple[int, int] = (0, 0)
        from rabia_tpu.obs.flight import TF_DTYPE

        self._final_flight = np.zeros(0, TF_DTYPE)
        self._recv_buf = (ctypes.c_uint8 * _RECV_BUF_CAP)()
        self._sender_buf = (ctypes.c_uint8 * 16)()
        # zero-copy recv engages when the native library exports the
        # borrow API (a prebuilt RABIA_NATIVE_LIB may predate it) and is
        # not explicitly disabled
        self._zero_copy = bool(
            getattr(self._lib, "rt_recv_borrow", None)
        ) and not os.environ.get("RABIA_NO_ZERO_COPY_RECV")
        self._reader_detached = False
        self._reader = threading.Thread(target=self._reader_loop, daemon=True)
        self._reader.start()

    # -- peers --------------------------------------------------------------

    def add_peer(self, peer: NodeId, host: str, port: int) -> None:
        pid = (ctypes.c_uint8 * 16).from_buffer_copy(_id_bytes(peer))
        self._lib.rt_add_peer(self._handle, pid, host.encode(), port)

    def remove_peer(self, peer: NodeId) -> None:
        pid = (ctypes.c_uint8 * 16).from_buffer_copy(_id_bytes(peer))
        self._lib.rt_remove_peer(self._handle, pid)

    # -- chaos shaping (adverse-network scenario engine) --------------------

    def set_peer_shaping(
        self,
        peer: NodeId,
        delay_ms: float = 0.0,
        jitter_ms: float = 0.0,
        drop_rate: float = 0.0,
        seed: int = 0,
    ) -> None:
        """Inject outbound delay (+/- jitter) and drop probability on
        THIS transport's link to ``peer``, applied inside the native io
        loop — the real epoll/TCP path carries the shaped traffic, so
        chaos profiles exercise the production C runtime. Asymmetric by
        construction: shape one endpoint to impair one direction. All
        zeros clears the peer's shaping."""
        if not hasattr(self._lib, "rt_set_shaping"):
            raise NetworkError(
                "native transport library predates rt_set_shaping; "
                "rebuild it from transport.cpp"
            )
        h = self._handle
        if not h:
            return
        pid = (ctypes.c_uint8 * 16).from_buffer_copy(_id_bytes(peer))
        self._lib.rt_set_shaping(
            h, pid,
            int(max(0.0, delay_ms) * 1000),
            int(max(0.0, jitter_ms) * 1000),
            float(drop_rate),
            seed & 0xFFFFFFFFFFFFFFFF,
        )

    def clear_shaping(self) -> None:
        """Remove every per-peer shaping entry (already-delayed frames
        still deliver at their due times)."""
        h = self._handle
        if h and hasattr(self._lib, "rt_clear_shaping"):
            self._lib.rt_clear_shaping(h)

    # -- reader bridge ------------------------------------------------------

    def detach_reader(self) -> None:
        """Hand exclusive inbox ownership to a native consumer (the
        engine's GIL-free runtime thread, engine/runtime_bridge.py):
        stop the Python reader thread so the two never steal each
        other's frames. Frames it already pulled into the pending queue
        stay drainable through the receive_* surface; the caller drains
        them before the native consumer starts."""
        self._reader_detached = True
        if self._handle and hasattr(self._lib, "rt_inbox_kick"):
            self._lib.rt_inbox_kick(self._handle)
        if self._reader.is_alive():
            self._reader.join(timeout=2.0)
            if self._reader.is_alive():
                # a reader that outlives the join would keep pulling
                # frames into the pending queue nothing drains once the
                # native consumer starts — silently losing votes. The
                # caller treats runtime start failure as fatal; failing
                # here is strictly better than racing the inbox.
                self._reader_detached = False
                raise RuntimeError(
                    "transport reader thread did not stop within 2s; "
                    "refusing to hand the inbox to a native consumer"
                )

    def _reader_loop(self) -> None:
        import uuid

        ptr = ctypes.c_void_p()
        ln = ctypes.c_uint32()
        while not self._closed and not self._reader_detached:
            if self._zero_copy:
                tok = self._lib.rt_recv_borrow(
                    self._handle,
                    self._sender_buf,
                    ctypes.byref(ptr),
                    ctypes.byref(ln),
                    100,
                )
                if tok == -3:
                    continue  # timeout tick
                if tok < 0:
                    return  # transport closing
                sender = NodeId(uuid.UUID(bytes=bytes(self._sender_buf)))
                data = _BorrowedFrame(self, tok, ptr.value or 0, ln.value)
            else:
                n = self._lib.rt_recv(
                    self._handle,
                    self._sender_buf,
                    self._recv_buf,
                    _RECV_BUF_CAP,
                    100,
                )
                if n == -3:
                    continue  # timeout tick; 0 is a valid empty frame
                if n < 0:
                    return  # transport closing
                sender = NodeId(uuid.UUID(bytes=bytes(self._sender_buf)))
                # one C-level memcpy; slicing the ctypes array instead
                # would build n Python ints and burn the GIL the sender
                # needs
                data = ctypes.string_at(self._recv_buf, n)
            self._pending.append((sender, data))
            if not self._wake_scheduled:
                # one loop wakeup per pending BATCH: further appends ride
                # the already-scheduled callback. (A spurious extra wake
                # after a drain is harmless; a missed one is impossible —
                # the flag only resets inside the loop-thread callback.)
                self._wake_scheduled = True
                try:
                    self._loop.call_soon_threadsafe(self._on_frames)
                except RuntimeError:
                    return  # loop closed

    # -- NetworkTransport ---------------------------------------------------

    async def send_to(self, target: NodeId, data: bytes) -> None:
        self.send_to_nowait(target, data)

    async def broadcast(self, data: bytes) -> None:
        self.broadcast_nowait(data)

    def send_to_nowait(self, target: NodeId, data: bytes) -> bool:
        pid = (ctypes.c_uint8 * 16).from_buffer_copy(_id_bytes(target))
        rc = self._lib.rt_send(self._handle, pid, data, len(data))
        if rc == -2:
            raise NetworkError("frame exceeds 16MiB cap")
        # rc == -1 (not connected) is a silent drop, like the reference's
        # best-effort sends to disconnected peers
        return True

    def broadcast_nowait(self, data: bytes) -> bool:
        rc = self._lib.rt_broadcast(self._handle, data, len(data))
        if rc == -2:
            raise NetworkError("frame exceeds 16MiB cap")
        return True

    def _on_frames(self) -> None:
        self._wake_scheduled = False
        self._data_ready.set()
        if self._recv_notify is not None:
            self._recv_notify()

    def _release_token(self, token: int) -> None:
        # close() nulls the handle only after the reader joined and the
        # pending queue was drained — a late release then no-ops here
        h = self._handle
        if h:
            self._lib.rt_recv_release(h, token)

    @staticmethod
    def _as_bytes(data) -> bytes:
        return data.to_bytes() if isinstance(data, _BorrowedFrame) else data

    async def receive(self, timeout: Optional[float] = None) -> tuple[NodeId, bytes]:
        deadline = (
            None
            if timeout is None
            else asyncio.get_running_loop().time() + timeout
        )
        while True:
            try:
                sender, data = self._pending.popleft()
                return sender, self._as_bytes(data)
            except IndexError:
                pass
            self._data_ready.clear()
            if self._pending:  # appended between popleft and clear
                continue
            if deadline is None:
                await self._data_ready.wait()
                continue
            left = deadline - asyncio.get_running_loop().time()
            if left <= 0:
                raise TimeoutError_("receive", timeout) from None
            try:
                await asyncio.wait_for(self._data_ready.wait(), left)
            except asyncio.TimeoutError:
                raise TimeoutError_("receive", timeout) from None

    def receive_nowait(self) -> Optional[tuple[NodeId, bytes]]:
        try:
            sender, data = self._pending.popleft()
        except IndexError:
            return None
        return sender, self._as_bytes(data)

    def receive_borrowed_nowait(self):
        """Zero-copy drain: ``(sender, buffer, release)`` where ``buffer``
        aliases the native frame arena (a memoryview) until ``release()``
        is called — the engine decodes straight out of the io thread's
        landing buffer (SURVEY §7.4.7). Falls back to a plain bytes
        frame (with a no-op release) when zero-copy recv is off."""
        try:
            sender, data = self._pending.popleft()
        except IndexError:
            return None
        if isinstance(data, _BorrowedFrame):
            return sender, data.view, data.release
        return sender, data, lambda: None

    def receive_raw_nowait(self):
        """Address-level drain for the engine's native tick ingest:
        ``(sender, data, addr, length, release)``. For frames still owned
        by the native arena, ``addr`` is the raw frame address (``data``
        is None) — the C ingest reads it with zero Python buffer
        wrapping; otherwise ``data`` is a bytes object and ``addr`` is 0.
        ``release`` is None for bytes frames."""
        try:
            sender, data = self._pending.popleft()
        except IndexError:
            return None
        if isinstance(data, _BorrowedFrame):
            return sender, None, data.addr, len(data.view), data.release
        return sender, data, 0, len(data), None

    def set_receive_notify(self, callback) -> bool:
        # invoked from _on_frames, which already runs on the loop thread
        # (the reader thread posts it via call_soon_threadsafe)
        self._recv_notify = callback
        return True

    async def get_connected_nodes(self) -> set[NodeId]:
        import uuid

        if not self._handle:  # closed (or close in progress): no peers
            return set()
        cap = 1024
        buf = (ctypes.c_uint8 * (16 * cap))()
        n = self._lib.rt_connected(self._handle, buf, cap)
        out = set()
        for i in range(n):
            out.add(NodeId(uuid.UUID(bytes=bytes(buf[16 * i : 16 * (i + 1)]))))
        return out

    @property
    def dropped_frames(self) -> int:
        """Inbound frames dropped by the native bounded inbox (oldest-first
        beyond 64Ki queued frames)."""
        if not self._handle:
            return 0
        return int(self._lib.rt_dropped(self._handle))

    @property
    def pool_stats(self) -> tuple[int, int]:
        """(hits, misses) of the native buffer arena (C10 PoolStats).

        Merged view: inbound landing buffers + the outbound frame arena.
        Use :attr:`out_pool_stats` for the outbound arena alone."""
        if not self._handle:
            return (0, 0)
        hits = ctypes.c_uint64()
        misses = ctypes.c_uint64()
        self._lib.rt_pool_stats(
            self._handle, ctypes.byref(hits), ctypes.byref(misses)
        )
        return int(hits.value), int(misses.value)

    @property
    def out_pool_stats(self) -> tuple[int, int]:
        """(hits, misses) of the OUTBOUND frame arena alone — the
        rt_send/rt_broadcast staging buffers transport.cpp recycles
        (previously collected natively but unreadable from Python).
        After close, reports the values frozen at teardown."""
        h = self._handle  # read ONCE: close() swaps it to None
        if not h or not hasattr(self._lib, "rt_out_pool_stats"):
            return self._final_out_pool
        hits = ctypes.c_uint64()
        misses = ctypes.c_uint64()
        self._lib.rt_out_pool_stats(
            h, ctypes.byref(hits), ctypes.byref(misses)
        )
        return int(hits.value), int(misses.value)

    def flight_snapshot(self, max_records: int = 4096) -> np.ndarray:
        """Chronological copy of the native frame in/out flight ring
        (transport.cpp TfEvent records — :data:`rabia_tpu.obs.flight.
        TF_DTYPE`), taken consistently under the io mutex. After close,
        reports the ring frozen at teardown."""
        from rabia_tpu.obs.flight import TF_DTYPE

        h = self._handle  # read ONCE: close() swaps it to None
        if not h or not hasattr(self._lib, "rt_flight_copy"):
            return self._final_flight
        buf = np.zeros(max_records, TF_DTYPE)
        n = int(self._lib.rt_flight_copy(h, buf.ctypes.data, max_records))
        return buf[:n]

    def transport_counters(self) -> dict[str, int]:
        """The native observability counter block as ``{name: value}``
        (RT_COUNTER_NAMES order; see docs/OBSERVABILITY.md). Values are
        relaxed-atomic reads — monotonic, not a consistent snapshot.
        After close, reports the block frozen at teardown. A scrape
        thread must not race ``close()`` itself (the gateway closes its
        HTTP shim before its transport for exactly that reason)."""
        h = self._handle  # read ONCE: close() swaps it to None
        if not h:
            return dict(self._final_ctrs)
        if not hasattr(self._lib, "rt_counters"):
            return {}
        n = int(self._lib.rt_counters_count())
        addr = self._lib.rt_counters(h)
        if not addr:
            return {}
        cells = (ctypes.c_uint64 * n).from_address(addr)
        return {
            name: int(cells[i])
            for i, name in enumerate(RT_COUNTER_NAMES)
            if i < n
        }

    async def disconnect(self, node: NodeId) -> None:
        self.remove_peer(node)

    async def reconnect(self) -> None:
        # dialing is continuous in the native loop; nothing to kick
        return

    async def close(self) -> None:
        if self._closed:
            return
        # order matters: stop the reader FIRST (it polls _closed every
        # <=100ms inside rt_recv), and only then destroy the native handle —
        # rt_close deletes the Transport, so a reader still inside rt_recv
        # would be a use-after-free
        self._closed = True
        # freeze the final counter state while the native handle is still
        # valid — post-close scrapes read these copies
        self._final_ctrs = self.transport_counters()
        self._final_out_pool = self.out_pool_stats
        self._final_flight = self.flight_snapshot()
        loop = asyncio.get_running_loop()
        # stop the native io loop first: this makes any in-flight rt_recv
        # return immediately (-1), so the reader exits promptly
        if self._handle:
            self._lib.rt_stop(self._handle)
        if self._reader.is_alive():
            await loop.run_in_executor(None, self._reader.join, 2.0)
        if self._reader.is_alive():
            # the join timed out: the reader may still be inside rt_recv, so
            # rt_close (which deletes the Transport) would be a use-after-
            # free. The io loop is already stopped (no accepts/redials), so
            # leak the inert handle — process teardown reclaims it — and
            # say so.
            import logging

            logging.getLogger("rabia_tpu.net").warning(
                "tcp close: reader thread still alive after join timeout; "
                "leaking stopped native transport handle"
            )
            self._handle = None
            return
        # materialize any zero-copy frames still pending: their buffers
        # live in the native arena rt_close is about to free (to_bytes
        # releases each token while the handle is still valid)
        for i, (sender, data) in enumerate(self._pending):
            if isinstance(data, _BorrowedFrame):
                self._pending[i] = (sender, data.to_bytes())
        handle, self._handle = self._handle, None
        if handle:
            await loop.run_in_executor(None, self._lib.rt_close, handle)
