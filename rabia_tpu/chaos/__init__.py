"""Chaos plane: adverse-network scenario engine + elastic membership.

Rabia's pitch is randomized termination without a leader; this package
proves it where it is hard. A declarative profile matrix
(:mod:`~rabia_tpu.chaos.profiles`) drives full clusters — the in-process
simulator fabric AND real-TCP clusters shaped inside the C transport —
through WAN jitter, sustained asymmetric loss, flapping partitions,
lagging replicas, crash/recover churn and elastic-membership transitions
under sustained open-loop load, while the runner
(:mod:`~rabia_tpu.chaos.runner`) continuously records commit
availability and the consensus-health evidence the paper's claim needs:
the phases-to-decide distribution and coin-flip tallies.

Entry points: ``python benchmarks/scenario_matrix.py`` (the CI smoke
cell and the standing ``scenario_matrix_r12`` baseline), or
:func:`run_profile` / :func:`run_matrix` programmatically.
See docs/SCENARIOS.md.
"""

from rabia_tpu.chaos.profiles import (
    ChaosEvent,
    ChaosProfile,
    default_profiles,
    get_profile,
    smoke_profiles,
)
from rabia_tpu.chaos.runner import (
    MATRIX_KEY,
    collect_evidence,
    record_matrix,
    render_matrix,
    run_matrix,
    run_profile,
)

__all__ = [
    "ChaosEvent",
    "ChaosProfile",
    "default_profiles",
    "smoke_profiles",
    "get_profile",
    "run_profile",
    "run_matrix",
    "render_matrix",
    "record_matrix",
    "collect_evidence",
    "MATRIX_KEY",
]
