"""Chaos scenario runner: drive a full cluster through adverse-network /
elastic-membership profiles under open-loop load, continuously recording
consensus-health telemetry.

One :func:`run_profile` call executes one :class:`~rabia_tpu.chaos.
profiles.ChaosProfile`:

1. build the profile's fabric — an in-process simulator cluster
   (``fabric="sim"``) or a real-TCP gateway + native-runtime + WAL
   cluster (``fabric="tcp"``);
2. start an **open-loop Poisson load** (arrivals keep firing whether or
   not the system keeps up — a partition shows up as failed windows, not
   as a silently reduced offered rate; the r09 loadgen methodology);
3. fire the profile's timed :class:`ChaosEvent` injections;
4. sample a **continuous timeline** (~8 Hz): per-window commit
   availability (ok arrivals / offered arrivals, scored at the arrival's
   window) and per-replica decided counters — the dip during the fault
   IS the datum;
5. score the run: availability floors, a wedge check on the final
   quarter, end-state convergence, and the consensus-health evidence the
   paper's claim needs — the **phases-to-decide distribution** and
   **coin-flip tallies** pulled from the engines' telemetry (C counter
   blocks + host bins feeding the ``rabia_phases_to_decide`` /
   ``rabia_coin_flips_total`` families).

:func:`run_matrix` runs a profile dict and merges everything into the
``scenario_matrix_r12`` report recorded in benchmarks/results.json — the
standing robustness baseline later PRs report against (schema in
docs/SCENARIOS.md).
"""

from __future__ import annotations

import asyncio
import math
import random
import sys
import time
from pathlib import Path
from typing import Optional

import numpy as np

from rabia_tpu.chaos.profiles import ChaosProfile
from rabia_tpu.core.errors import QuorumNotAvailableError, RabiaError
from rabia_tpu.core.messages import ResultStatus
from rabia_tpu.core.types import CommandBatch
from rabia_tpu.testing.loadsession import LoadSession

MATRIX_VERSION = 1
MATRIX_KEY = "scenario_matrix_r12"

_OUTCOMES = ("ok", "shed", "error", "timeout", "overflow")


class _Arrivals:
    """Per-arrival score sheet -> post-hoc windowed availability curve."""

    def __init__(self) -> None:
        self.rows: list[tuple[float, str, float]] = []  # (t, outcome, ms)

    def score(self, t: float, outcome: str, ms: float = 0.0) -> None:
        self.rows.append((t, outcome, ms))

    def timeline(
        self, t0: float, duration: float, window: float
    ) -> list[dict]:
        n_win = max(1, int(math.ceil(duration / window)))
        wins = [
            {"t": round(k * window, 3), "attempts": 0, "ok": 0,
             "failed": 0, "lat_ms": []}
            for k in range(n_win)
        ]
        for t, outcome, ms in self.rows:
            k = int((t - t0) / window)
            if k < 0 or k >= n_win:
                continue
            w = wins[k]
            w["attempts"] += 1
            if outcome == "ok":
                w["ok"] += 1
                w["lat_ms"].append(ms)
            else:
                w["failed"] += 1
        out = []
        for w in wins:
            lat = sorted(w.pop("lat_ms"))
            w["availability"] = (
                round(w["ok"] / w["attempts"], 4) if w["attempts"] else None
            )
            w["p99_ms"] = (
                round(lat[min(len(lat) - 1, int(0.99 * len(lat)))], 2)
                if lat
                else None
            )
            out.append(w)
        return out


# ---------------------------------------------------------------------------
# Fabrics
# ---------------------------------------------------------------------------


class _SimFabric:
    """TestCluster over the NetworkSimulator; events map to simulator
    fault-injection calls; load submits straight to the engines."""

    name = "sim"

    def __init__(self, profile: ChaosProfile) -> None:
        from rabia_tpu.testing.cluster import TestCluster, default_test_config

        self.profile = profile
        self.cluster = TestCluster(
            profile.n_replicas,
            config=default_test_config(profile.n_shards),
            seed=profile.seed,
        )
        self._crashed: set[int] = set()

    async def start(self) -> None:
        await self.cluster.start()

    async def stop(self) -> None:
        await self.cluster.stop()

    def _node(self, i: int):
        return self.cluster.nodes[i]

    def apply_event(self, action: str, args: dict) -> None:
        sim = self.cluster.sim
        if action == "wan":
            base = args.get("latency_ms", 0.0) / 1000.0
            jit = args.get("jitter_ms", 0.0) / 2000.0
            sim.conditions.latency_min = max(0.0, base - jit)
            sim.conditions.latency_max = base + jit
        elif action == "link_loss":
            sim.set_link_loss(
                self._node(args["src"]), self._node(args["dst"]),
                args["rate"],
            )
        elif action == "flap":
            sim.set_flap(
                {self._node(i) for i in args["group"]},
                period=args["period"],
                duty=args.get("duty", 0.5),
                duration=args.get("duration"),
            )
        elif action == "partition":
            sim.partition(
                {self._node(i) for i in args["group"]},
                duration=args.get("duration"),
            )
        elif action == "heal":
            sim.heal_partition()
            sim.clear_flap()
        elif action == "slow":
            sim.set_node_delay(
                self._node(args["node"]), args.get("delay_ms", 0.0) / 1000.0
            )
        elif action == "crash":
            sim.crash(self._node(args["node"]))
            self._crashed.add(args["node"])
        elif action == "recover":
            sim.recover(self._node(args["node"]))
            self._crashed.discard(args["node"])
        elif action == "clear":
            sim.clear_link_faults()
            sim.conditions.latency_min = 0.0
            sim.conditions.latency_max = 0.0
        else:
            raise ValueError(f"sim fabric: unknown action {action!r}")

    def clear_faults(self) -> None:
        sim = self.cluster.sim
        sim.heal_partition()
        sim.clear_flap()
        sim.clear_link_faults()
        for i in list(self._crashed):
            sim.recover(self._node(i))
        self._crashed.clear()
        for node in self.cluster.nodes:
            sim.set_node_delay(node, 0.0)

    async def submit(self, i: int, pairs: list, timeout: float) -> str:
        """One open-loop arrival routed like an honest client: round-robin
        over replicas not currently crashed."""
        live = [
            j for j in range(self.profile.n_replicas)
            if j not in self._crashed
        ]
        if not live:
            return "shed"
        eng = self.cluster.engines[live[i % len(live)]]
        shard = i % self.profile.n_shards
        cmds = [f"SET {k} {v}" for k, v in pairs]
        try:
            fut = await eng.submit_batch(CommandBatch.new(cmds), shard=shard)
            await asyncio.wait_for(fut, timeout)
            return "ok"
        except QuorumNotAvailableError:
            return "shed"
        except asyncio.TimeoutError:
            return "timeout"
        except (RabiaError, Exception):
            return "error"

    def engines(self) -> list:
        return [e for e in self.cluster.engines if e is not None]

    def decided_totals(self) -> list[Optional[int]]:
        return [
            int(e.rt.decided_v1 + e.rt.decided_v0)
            for e in self.cluster.engines
        ]

    def watchdog_sample(self) -> dict:
        n = self.profile.n_replicas
        return {
            "members_alive": n - len(self._crashed),
            "members_total": n,
        }

    async def converged(self, timeout: float) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            datas = [
                getattr(sm, "_data", None) for sm in self.cluster.sms
            ]
            if all(d is not None for d in datas) and all(
                d == datas[0] for d in datas[1:]
            ):
                return True
            await asyncio.sleep(0.05)
        return False


class _TcpFabric:
    """GatewayCluster (real TCP, gateway + native runtime + WAL
    durability) driven through protocol-faithful LoadSessions; events map
    to the C transport's shaping layer and the elastic-membership
    surface."""

    name = "tcp"

    SESSIONS_PER_GW = 8

    def __init__(self, profile: ChaosProfile) -> None:
        from rabia_tpu.gateway import GatewayConfig
        from rabia_tpu.testing.gateway_cluster import GatewayCluster

        self.profile = profile
        # profile-pinned gateway knobs (e.g. the coalescing lane's
        # window for the coalesce_flap_restart scenario)
        gw_cfg = (
            GatewayConfig(**dict(profile.gateway_overrides))
            if profile.gateway_overrides
            else None
        )
        self.cluster = GatewayCluster(
            n_replicas=profile.n_replicas,
            n_shards=profile.n_shards,
            gateway_config=gw_cfg,
            persistence="wal",
        )
        self._ser = None
        self._sessions: dict[int, list] = {}  # gw index -> LoadSession pool
        self._down: set[int] = set()
        self._redials: set[asyncio.Task] = set()

    async def start(self) -> None:
        from rabia_tpu.core.serialization import Serializer

        await self.cluster.start()
        self._ser = Serializer()
        for i in range(self.profile.n_replicas):
            self._sessions[i] = await self._dial_pool(i)

    async def _dial_pool(self, i: int) -> list:
        out = []
        gw = self.cluster.gateways[i]
        if gw is None:
            return out
        for _ in range(self.SESSIONS_PER_GW):
            s = LoadSession(self._ser)
            try:
                await s.connect("127.0.0.1", gw.port)
                out.append(s)
            except Exception:
                await s.close()
        return out

    async def stop(self) -> None:
        for t in list(self._redials):
            t.cancel()
        await asyncio.gather(*self._redials, return_exceptions=True)
        for pool in self._sessions.values():
            await asyncio.gather(
                *(s.close() for s in pool), return_exceptions=True
            )
        self._sessions.clear()
        await self.cluster.stop()
        # the fabric owns the cluster's implicit mkdtemp WAL dir: remove
        # it, or every matrix/CI run litters /tmp with full WAL chains
        if self.cluster.wal_dir:
            import shutil

            shutil.rmtree(self.cluster.wal_dir, ignore_errors=True)

    # -- events -------------------------------------------------------------

    def _shape(self, src: int, dst: int, **kw) -> None:
        net = self.cluster.nets[src]
        if net is not None:
            net.set_peer_shaping(self.cluster.ids[dst], **kw)

    def apply_event(self, action: str, args: dict) -> None:
        n = self.profile.n_replicas
        if action == "wan":
            # symmetric one-way delay on every replica-to-replica link.
            # jitter_ms is the TOTAL spread on both fabrics (latency
            # +/- jitter/2 — the NetworkConditions.wan convention);
            # rt_set_shaping takes the half-amplitude, so halve here to
            # keep sim and tcp matrix cells comparable
            delay = args.get("latency_ms", 0.0)
            jit = args.get("jitter_ms", 0.0) / 2.0
            for i in range(n):
                for j in range(n):
                    if i != j:
                        self._shape(
                            i, j, delay_ms=delay, jitter_ms=jit,
                            seed=self.profile.seed + i * n + j,
                        )
        elif action == "link_loss":
            self._shape(
                args["src"], args["dst"], drop_rate=args["rate"],
                seed=self.profile.seed + 7,
            )
        elif action == "slow":
            d = args.get("delay_ms", 0.0)
            for j in range(n):
                if j != args["node"]:
                    self._shape(args["node"], j, delay_ms=d)
        elif action == "clear":
            for net in self.cluster.nets:
                if net is not None:
                    net.clear_shaping()
        elif action in ("stop_replica", "start_replica", "restart_replica"):
            # handled asynchronously by the runner (they await)
            raise RuntimeError("membership events are async — runner bug")
        else:
            raise ValueError(f"tcp fabric: unknown action {action!r}")

    async def apply_event_async(self, action: str, args: dict) -> None:
        if action == "stop_replica":
            i = args["node"]
            self._down.add(i)
            pool = self._sessions.pop(i, [])
            await asyncio.gather(
                *(s.close() for s in pool), return_exceptions=True
            )
            await self.cluster.stop_replica(i)
        elif action == "start_replica":
            i = args["node"]
            await self.cluster.start_replica(i)
            self._down.discard(i)
            self._spawn_redial(i)
        elif action == "restart_replica":
            i = args["node"]
            self._down.add(i)
            pool = self._sessions.pop(i, [])
            await asyncio.gather(
                *(s.close() for s in pool), return_exceptions=True
            )
            await self.cluster.restart_replica(i)
            self._down.discard(i)
            self._spawn_redial(i)
        else:
            self.apply_event(action, args)

    def _spawn_redial(self, i: int) -> None:
        async def redial():
            self._sessions[i] = await self._dial_pool(i)

        t = asyncio.ensure_future(redial())
        self._redials.add(t)
        t.add_done_callback(self._redials.discard)

    def clear_faults(self) -> None:
        for net in self.cluster.nets:
            if net is not None:
                net.clear_shaping()

    # -- load ---------------------------------------------------------------

    async def submit(self, i: int, pairs: list, timeout: float) -> str:
        from rabia_tpu.apps.kvstore import encode_set_bin

        live = [
            j for j in range(self.profile.n_replicas)
            if j not in self._down and self._sessions.get(j)
        ]
        if not live:
            return "shed"
        pool = self._sessions[live[i % len(live)]]
        sess = pool[i % len(pool)]
        shard = i % self.profile.n_shards
        cmds = [encode_set_bin(k, v) for k, v in pairs]
        try:
            res = await sess.submit(shard, cmds, timeout)
            if res.status in (ResultStatus.OK, ResultStatus.CACHED):
                return "ok"
            if res.status == ResultStatus.RETRY:
                return "shed"
            return "error"
        except asyncio.TimeoutError:
            return "timeout"
        except Exception:
            return "error"

    def engines(self) -> list:
        return [e for e in self.cluster.engines if e is not None]

    def decided_totals(self) -> list[Optional[int]]:
        return [
            int(e.rt.decided_v1 + e.rt.decided_v0) if e is not None else None
            for e in self.cluster.engines
        ]

    def watchdog_sample(self) -> dict:
        # membership from the fabric's OWN knowledge of what it stopped
        # (deterministic — no scrape race), coalesce counters from the
        # live gateways' per-shard stats (cumulative; a restarted
        # gateway's counters reset, which a delta window just skips)
        n = self.profile.n_replicas
        waves = covered = 0
        for g in self.cluster.gateways:
            if g is None:
                continue
            for cs in getattr(g, "coal_shard_stats", {}).values():
                waves += cs["waves"]
                covered += cs["covered"]
        return {
            "members_alive": n - len(self._down),
            "members_total": n,
            "waves": waves,
            "covered": covered,
        }

    def critpath_sample(
        self, pool: int = 8, max_age_s: Optional[float] = None
    ) -> Optional[dict]:
        """Decompose the live gateways' slow exemplars in-process (zero
        alignment error) into one attribution sample: which critical-
        path segment the tail's wall time sits in RIGHT NOW. The runner
        records these at the health cadence for profiles with
        ``expect_critpath`` — the watchdog pattern applied to
        attribution instead of burn rate.

        Decomposes up to ``pool`` exemplars across the gateways (not
        just the global slowest few: a multi-second straggler whose
        ring has wrapped is honestly excluded from the aggregate, and
        taking only the top walls would leave such samples empty).

        ``max_age_s`` keeps only exemplars whose completion is at most
        that many seconds old — the recovery sample uses it so fault-era
        stragglers that legitimately finish (and therefore pin the
        slowest-first reservoir) cannot mask a healthy post-fault
        tail."""
        from rabia_tpu.obs.critpath import (
            decompose,
            inprocess_exemplar_timeline,
        )

        exemplars = []
        for g in self.cluster.gateways:
            if g is None or getattr(g, "slowlog", None) is None:
                continue
            # age-filtered samples read the FULL reservoir before the
            # filter: a recovering cluster's last fault-era stragglers
            # are the slowest entries, and cutting to the per-gateway
            # top few first would evict the young exemplars the filter
            # is there to isolate
            doc = g.slowlog.document(None if max_age_s is not None
                                     else 4)
            exemplars.extend(doc.get("exemplars", []))
        if max_age_s is not None:
            exemplars = [
                e for e in exemplars
                if float(e.get("age_s", 0.0)) <= max_age_s
            ]
        if not exemplars:
            return None
        exemplars.sort(key=lambda e: -float(e.get("wall_s", 0.0)))
        exemplars = exemplars[:pool]
        engines = [e for e in self.cluster.engines if e is not None]
        seg_tot: dict[str, float] = {}
        n_ok = n_trunc = n_bad = 0
        for ex in exemplars:
            try:
                merged = inprocess_exemplar_timeline(engines, ex)
                d = decompose(
                    merged,
                    coalesced=ex.get("coalesced"),
                    wall_s=ex.get("wall_s"),
                )
            except Exception:
                n_bad += 1
                continue
            if not d["ok"]:
                n_bad += 1
                continue
            if d["truncated"]:
                n_trunc += 1
                continue
            n_ok += 1
            for k, v in d["segments"].items():
                seg_tot[k] = seg_tot.get(k, 0.0) + v
            seg_tot["unattributed"] = (
                seg_tot.get("unattributed", 0.0) + d["unattributed_s"]
            )
        out = {
            "exemplars": n_ok,
            "truncated": n_trunc,
            "unanchored": n_bad,
            "worst_ms": round(
                float(exemplars[0].get("wall_s", 0.0)) * 1e3, 3
            ),
        }
        if n_ok:
            out["dominant"] = max(
                seg_tot.items(), key=lambda kv: kv[1]
            )[0]
            out["segments_ms"] = {
                k: round(v / n_ok * 1e3, 3)
                for k, v in sorted(seg_tot.items())
            }
        return out

    async def converged(self, timeout: float) -> bool:
        try:
            await self.cluster.wait_converged(timeout)
            return True
        except Exception as e:
            # the divergence detail (per-replica checksums/versions/
            # frontiers) is the evidence a failing matrix row needs
            print(f"# convergence failure: {e}", file=sys.stderr)
            return False


class _FleetFabric:
    """Routed fleet (round 16): a real-TCP replica cluster behind
    consistent-hash-routed fleet gateways
    (:class:`~rabia_tpu.fleet.harness.FleetHarness`), driven by
    MOVED-following :class:`~rabia_tpu.fleet.harness.FleetSession`
    clients over shared mux connections. Events add ``kill_gateway``
    (abrupt death, no handoff — survivors adopt the shrunken ring) and
    ``rebalance`` (planned drain with session handoff). The post-run
    :meth:`verify` hook is the scenario's exactly-once gate: every
    session's last ACKED result must replay byte-identical wherever
    the ring routes it now, with zero store mutation."""

    name = "fleet"

    N_SESSIONS = 24

    def __init__(self, profile: ChaosProfile) -> None:
        from rabia_tpu.fleet.harness import FleetHarness
        from rabia_tpu.gateway import GatewayConfig

        self.profile = profile
        gw_cfg = (
            GatewayConfig(**dict(profile.gateway_overrides))
            if profile.gateway_overrides
            else None
        )
        self.harness = FleetHarness(
            n_gateways=profile.n_gateways,
            n_replicas=profile.n_replicas,
            n_shards=profile.n_shards,
            persistence="wal",
            gateway_config=gw_cfg,
        )
        self._sessions: list = []
        self._pool = None
        # per session: (seq, shard, payload bytes) of the LAST acked
        # submit — the verify() replay sample (newest seq per session
        # is never GC-eligible under its own ack frontier)
        self._last_acked: dict[int, tuple] = {}

    async def start(self) -> None:
        from rabia_tpu.fleet.harness import FleetConnPool, FleetSession

        await self.harness.start()
        self._pool = FleetConnPool(self.harness.ser)
        resolver = self.harness.resolver()
        self._sessions = [
            FleetSession(
                self.harness.ser, resolver, pool=self._pool,
                call_timeout=min(5.0, self.profile.call_timeout),
            )
            for _ in range(self.N_SESSIONS)
        ]

    async def stop(self) -> None:
        for s in self._sessions:
            await s.close()
        self._sessions = []
        if self._pool is not None:
            await self._pool.close()
        await self.harness.stop()
        if self.harness.cluster.wal_dir:
            import shutil

            shutil.rmtree(self.harness.cluster.wal_dir, ignore_errors=True)

    # -- events -------------------------------------------------------------

    def apply_event(self, action: str, args: dict) -> None:
        if action == "clear":
            return
        if action in ("kill_gateway", "rebalance"):
            raise RuntimeError("fleet events are async — runner bug")
        raise ValueError(f"fleet fabric: unknown action {action!r}")

    async def apply_event_async(self, action: str, args: dict) -> None:
        if action == "kill_gateway":
            await self.harness.kill_gateway(args["gw"])
        elif action == "rebalance":
            await self.harness.rebalance(args["members"])
        else:
            self.apply_event(action, args)

    def clear_faults(self) -> None:
        pass

    # -- load ---------------------------------------------------------------

    async def submit(self, i: int, pairs: list, timeout: float) -> str:
        from rabia_tpu.apps.kvstore import encode_set_bin

        si = i % len(self._sessions)
        sess = self._sessions[si]
        shard = i % self.profile.n_shards
        cmds = [encode_set_bin(k, v) for k, v in pairs]
        try:
            res = await sess.submit(shard, cmds, timeout=timeout)
        except (asyncio.TimeoutError, TimeoutError):
            return "timeout"
        except Exception:
            return "error"
        if res.status in (ResultStatus.OK, ResultStatus.CACHED):
            self._last_acked[si] = (
                res.seq, shard, tuple(bytes(p) for p in res.payload)
            )
            return "ok"
        if res.status == ResultStatus.RETRY:
            return "shed"
        return "error"

    # -- scoring ------------------------------------------------------------

    async def verify(self) -> list[str]:
        """The routed-failover acceptance gates: zero lost acked
        Results (byte-identical replays through the post-fault ring)
        and zero double-applies (store mutation parity across the
        replay sweep)."""
        from rabia_tpu.apps.kvstore import encode_set_bin

        problems: list[str] = []
        if not self._last_acked:
            return ["fleet verify: no acked submits to replay"]

        def versions():
            return [
                [
                    self.harness.cluster.store(r, s).version
                    for s in range(self.profile.n_shards)
                ]
                for r in range(self.profile.n_replicas)
            ]

        before = versions()
        lost = 0
        for si in sorted(self._last_acked):
            seq, shard, want = self._last_acked[si]
            try:
                res = await self._sessions[si].submit_seq(
                    seq, shard,
                    [encode_set_bin("verify-replay", "X")],
                    timeout=15.0,
                )
            except Exception as e:
                problems.append(
                    f"fleet verify: replay session {si} seq {seq} "
                    f"failed: {e}"
                )
                continue
            if tuple(bytes(p) for p in res.payload) != want:
                lost += 1
        if lost:
            problems.append(
                f"fleet verify: {lost} acked result(s) replayed "
                "non-identical — exactly-once broken"
            )
        await asyncio.sleep(0.3)
        if versions() != before:
            problems.append(
                "fleet verify: replays mutated replica state — "
                "double apply"
            )
        return problems

    def engines(self) -> list:
        return [
            e for e in self.harness.cluster.engines if e is not None
        ]

    def decided_totals(self) -> list[Optional[int]]:
        return [
            int(e.rt.decided_v1 + e.rt.decided_v0) if e is not None else None
            for e in self.harness.cluster.engines
        ]

    def watchdog_sample(self) -> dict:
        # members = the ROUTING tier (a killed fleet gateway is what
        # ring_stale names here); coalesce counters come from the
        # replica-cluster gateways that actually pack the waves
        waves = covered = 0
        for g in self.harness.cluster.gateways:
            if g is None:
                continue
            for cs in getattr(g, "coal_shard_stats", {}).values():
                waves += cs["waves"]
                covered += cs["covered"]
        return {
            "members_alive": len(self.harness.live_indices()),
            "members_total": self.profile.n_gateways,
            "waves": waves,
            "covered": covered,
        }

    async def converged(self, timeout: float) -> bool:
        try:
            await self.harness.cluster.wait_converged(timeout)
            return True
        except Exception as e:
            print(f"# convergence failure: {e}", file=sys.stderr)
            return False


class _MeshFabric:
    """MeshEngine colocated-lockstep fabric: the whole cluster is ONE
    in-process device-mesh engine (``n_replicas`` lockstep replicas over
    the JAX mesh) with the device-resident KV table AND the read-index
    lane on. Load is full-width PayloadBlocks — a SET wave or (every
    third arrival) a GET wave that the read lane must serve from
    consensus-free ``lookup_only`` probe windows. Events map to the
    alive-mask (``crash``/``recover``) and to forced device-lane
    demotion (``demote_device``): parked probe reads must flush to the
    consensus path, and the auto-repromote must re-engage the lane —
    with correct write barriers — while arrivals keep firing."""

    name = "mesh"

    def __init__(self, profile: ChaosProfile) -> None:
        from rabia_tpu.apps.vector_kv import VectorShardedKV
        from rabia_tpu.parallel import MeshEngine, make_mesh

        self.profile = profile
        n_shards = profile.n_shards
        self.eng = MeshEngine(
            lambda: VectorShardedKV(n_shards, capacity=1 << 14),
            n_shards=n_shards,
            n_replicas=profile.n_replicas,
            mesh=make_mesh(),
            window=8,
            device_store=True,
            device_read_lane=True,
            # small repromote horizon so a mid-run demote_device event
            # re-engages the lane INSIDE the measure window (the
            # barrier-reset path is part of what this fabric scores)
            device_store_repromote=24,
        )
        self._crashed: set[int] = set()
        self._pump_task: Optional[asyncio.Task] = None
        self._running = False

    def _blocks(self, idx: int, pairs: list):
        """One full-width wave per arrival: SET waves carry the runner's
        key/value pairs fanned across shards; every third arrival is a
        GET wave on the j=0 keys an earlier same-slot SET wrote."""
        from rabia_tpu.apps.kvstore import (
            KVOperation,
            KVOpType,
            encode_op_bin,
            encode_set_bin,
        )
        from rabia_tpu.core.blocks import build_block

        shards = list(range(self.profile.n_shards))
        if idx % 3 == 0:
            cmds = [
                [encode_op_bin(
                    KVOperation(KVOpType.Get, f"k{idx % 512}-0-{s}")
                )]
                for s in shards
            ]
        else:
            cmds = [
                [encode_set_bin(f"{k}-{s}", v) for k, v in pairs]
                for s in shards
            ]
        return build_block(shards, cmds)

    async def start(self) -> None:
        # pin every program compile (SET wave, GET wave, lookup_only
        # probe) OUTSIDE the measured window
        self.eng.submit_block(self._blocks(1, [("warm", "w")]))
        self.eng.submit_block(self._blocks(0, [("warm", "w")]))
        self.eng.flush(max_cycles=400)
        self._running = True
        self._pump_task = asyncio.ensure_future(self._pump())

    async def _pump(self) -> None:
        # the engine is synchronous: one background task turns cycles
        # whenever work is pending, yielding between cycles so the
        # arrival generator and event injections interleave honestly
        while self._running:
            got = self.eng.run_cycle()
            await asyncio.sleep(0.0 if got else 0.002)

    async def stop(self) -> None:
        self._running = False
        if self._pump_task is not None:
            self._pump_task.cancel()
            try:
                await self._pump_task
            except (asyncio.CancelledError, Exception):
                pass
        self.eng.close()

    def apply_event(self, action: str, args: dict) -> None:
        eng = self.eng
        if action == "crash":
            eng.crash_replica(args["node"])
            self._crashed.add(args["node"])
        elif action == "recover":
            eng.heal_replica(args["node"])
            self._crashed.discard(args["node"])
        elif action == "demote_device":
            # forced mid-window demotion: the device table syncs to the
            # host replica stores, parked probe reads flush back into
            # the consensus stream, and (repromote horizon permitting)
            # the lane re-engages with reset write barriers
            if eng._dev_active:
                eng._demote_device_store()
        elif action == "clear":
            for r in list(self._crashed):
                eng.heal_replica(r)
            self._crashed.clear()
        else:
            raise ValueError(f"mesh fabric: unknown action {action!r}")

    def clear_faults(self) -> None:
        for r in list(self._crashed):
            self.eng.heal_replica(r)
        self._crashed.clear()

    async def submit(self, i: int, pairs: list, timeout: float) -> str:
        eng = self.eng
        if not eng.has_quorum:
            return "shed"
        try:
            bfut = eng.submit_block(self._blocks(i, pairs))
        except Exception:
            return "error"
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        while not bfut.done():
            if loop.time() >= deadline:
                return "timeout"
            await asyncio.sleep(0.001)
        return "ok"

    async def verify(self) -> list[str]:
        """Mesh acceptance gates: the read lane actually engaged (probe
        reads > 0 — a run whose GETs all fell back to consensus slots
        is a silent regression of the tier under test) and the lockstep
        replicas never diverged on an apply outcome."""
        problems: list[str] = []
        rl = self.eng.read_lane_stats()
        if rl["probe"] <= 0:
            problems.append(
                "mesh verify: read lane served zero off-consensus "
                f"probe reads (stats {rl})"
            )
        if int(self.eng.divergences) != 0:
            problems.append(
                f"mesh verify: {self.eng.divergences} lockstep apply "
                "divergences"
            )
        return problems

    def engines(self) -> list:
        return [self.eng]

    def decided_totals(self) -> list[Optional[int]]:
        return [int(self.eng.decided_v1 + self.eng.decided_v0)]

    def watchdog_sample(self) -> dict:
        n = self.profile.n_replicas
        return {
            "members_alive": n - len(self._crashed),
            "members_total": n,
        }

    async def converged(self, timeout: float) -> bool:
        eng = self.eng
        deadline = time.time() + timeout
        while time.time() < deadline:
            if not eng._has_pending():
                break
            await asyncio.sleep(0.02)
        else:
            return False
        if eng._dev_active:
            eng.sync_to_host()  # device table down into every replica
        from rabia_tpu.apps.vector_kv import VectorKVStore

        def canon(sm):
            # logical content only: snapshot bytes embed per-store
            # wall-clock created/updated stamps that legitimately differ
            sv, rows, over = VectorKVStore._parse_snapshot(
                sm.store.snapshot_bytes()
            )
            shards, keys, vals, vers, _cr, _up = rows
            return (
                sv.tolist(), shards, keys, vals, vers,
                sorted(
                    (d["shard"], d["key"], d["value"], d["version"])
                    for d in over
                ),
            )

        snaps = [canon(sm) for sm in eng.sms]
        return all(s == snaps[0] for s in snaps[1:])


class _GroupFabric:
    """Partitioned shard-group fabric (round 20, fleet/groups.py): N
    independent consensus groups, each a real OS-process replica set
    (:class:`~rabia_tpu.fleet.groups.GroupProcHarness` — durable WAL
    children, SIGKILL-able) under its own WAL subtree. Sessions are
    group-routed client-side: every arrival's home shard maps through
    the :class:`~rabia_tpu.fleet.groups.GroupMap` to its owning group,
    and the session dials that group's preferred ("proposer") replica
    gateway, failing over INSIDE the group when it dies. Events add
    ``kill_group_proposer`` (SIGKILL — no graceful anything) and
    ``restart_group_proposer`` (respawn + WAL recovery). Scoring adds
    the blast-radius gate: the NON-killed groups' goodput during the
    kill window must hold against their own healthy control band, and
    the post-run :meth:`verify` replays every session's last acked seq
    through a DIFFERENT replica gateway of its group (byte-identical,
    zero applied-frontier movement = exactly-once held per group).

    Everything observed cross-process comes from the replica gateways'
    admin plane (METRICS scrape) — there are no in-process engines, so
    the fabric carries its own scrape-based evidence collector."""

    name = "groups"

    SESSIONS_PER_LANE = 6

    def __init__(self, profile: ChaosProfile) -> None:
        from rabia_tpu.fleet.groups import GroupMap, GroupProcHarness

        self.profile = profile
        self.group_map = GroupMap.initial(
            profile.n_shards, profile.n_groups
        )
        self.harness = GroupProcHarness(
            self.group_map, n_replicas=profile.n_replicas
        )
        self._ser = None
        # (group, replica) -> LoadSession pool; sessions prefer the
        # lowest live replica index of their group (the "proposer")
        self._sessions: dict[tuple[int, int], list] = {}
        self._down: set[tuple[int, int]] = set()
        self._redials: set[asyncio.Task] = set()
        # last acked submit per session: client_id -> (group, replica,
        # seq, shard, payload) — the verify() replay sample
        self._last_acked: dict = {}
        # per-group goodput rows (arrival wall time, group, outcome)
        # and the kill/restart edges, for the blast-radius gate
        self._group_rows: list[tuple[float, int, str]] = []
        self._kill_edges: dict[int, list[float]] = {}
        self._scrape_task: Optional[asyncio.Task] = None
        self._decided_cache: dict[tuple[int, int], Optional[int]] = {}
        self._running = False

    async def start(self) -> None:
        from rabia_tpu.core.serialization import Serializer

        loop = asyncio.get_event_loop()
        await loop.run_in_executor(None, self.harness.start)
        self._ser = Serializer()
        for g in self.group_map.groups():
            for r in range(self.profile.n_replicas):
                self._sessions[(g, r)] = await self._dial_pool(g, r)
        self._running = True
        self._scrape_task = asyncio.ensure_future(self._scrape_loop())

    async def _dial_pool(self, g: int, r: int) -> list:
        port = self.harness.harnesses[g].gw_ports[r]
        out = []
        for _ in range(self.SESSIONS_PER_LANE):
            s = LoadSession(self._ser)
            try:
                await s.connect("127.0.0.1", port)
                out.append(s)
            except Exception:
                await s.close()
        return out

    async def stop(self) -> None:
        self._running = False
        if self._scrape_task is not None:
            self._scrape_task.cancel()
            try:
                await self._scrape_task
            except (asyncio.CancelledError, Exception):
                pass
        for t in list(self._redials):
            t.cancel()
        await asyncio.gather(*self._redials, return_exceptions=True)
        for pool in self._sessions.values():
            await asyncio.gather(
                *(s.close() for s in pool), return_exceptions=True
            )
        self._sessions.clear()
        self.harness.stop()
        import shutil

        shutil.rmtree(self.harness.wal_root, ignore_errors=True)

    # -- admin-plane scraping ----------------------------------------------

    def _live(self, g: int, r: int) -> bool:
        rp = self.harness.harnesses[g].procs[r]
        return (
            (g, r) not in self._down
            and rp is not None
            and rp.proc.poll() is None
        )

    async def _scrape_metrics(
        self, g: int, r: int, timeout: float = 3.0
    ) -> Optional[dict]:
        from rabia_tpu.core.messages import AdminKind
        from rabia_tpu.gateway.client import admin_fetch
        from rabia_tpu.obs.registry import parse_prometheus_text

        if not self._live(g, r):
            return None
        port = self.harness.harnesses[g].gw_ports[r]
        try:
            body = await admin_fetch(
                "127.0.0.1", port, kind=int(AdminKind.METRICS),
                timeout=timeout,
            )
            return parse_prometheus_text(body.decode(errors="replace"))
        except Exception:
            return None

    async def _scrape_loop(self) -> None:
        """Background decided-counter cache: ``decided_totals`` is
        called synchronously at the health cadence, and a cross-process
        fabric cannot afford a blocking scrape there."""
        keys = [
            (g, r)
            for g in self.group_map.groups()
            for r in range(self.profile.n_replicas)
        ]
        while self._running:
            for g, r in keys:
                mm = await self._scrape_metrics(g, r, timeout=2.0)
                if mm is None:
                    self._decided_cache[(g, r)] = None
                    continue
                self._decided_cache[(g, r)] = int(
                    mm.get('rabia_engine_decided_total{value="v0"}', 0)
                    + mm.get('rabia_engine_decided_total{value="v1"}', 0)
                )
            await asyncio.sleep(0.3)

    # -- events -------------------------------------------------------------

    def apply_event(self, action: str, args: dict) -> None:
        if action == "clear":
            return
        if action in ("kill_group_proposer", "restart_group_proposer"):
            raise RuntimeError("group events are async — runner bug")
        raise ValueError(f"groups fabric: unknown action {action!r}")

    async def apply_event_async(self, action: str, args: dict) -> None:
        loop = asyncio.get_event_loop()
        if action == "kill_group_proposer":
            g = args["group"]
            self._down.add((g, 0))
            self._kill_edges.setdefault(g, []).append(loop.time())
            pool = self._sessions.pop((g, 0), [])
            await loop.run_in_executor(
                None, self.harness.kill9, g, 0
            )
            await asyncio.gather(
                *(s.close() for s in pool), return_exceptions=True
            )
        elif action == "restart_group_proposer":
            g = args["group"]
            await loop.run_in_executor(
                None, self.harness.restart, g, 0
            )
            self._down.discard((g, 0))
            self._kill_edges.setdefault(g, []).append(loop.time())

            async def redial(g=g):
                self._sessions[(g, 0)] = await self._dial_pool(g, 0)

            t = asyncio.ensure_future(redial())
            self._redials.add(t)
            t.add_done_callback(self._redials.discard)
        else:
            self.apply_event(action, args)

    def clear_faults(self) -> None:
        pass

    # -- load ---------------------------------------------------------------

    async def submit(self, i: int, pairs: list, timeout: float) -> str:
        from rabia_tpu.apps.kvstore import encode_set_bin

        shard = i % self.profile.n_shards
        g = self.group_map.group_of(shard)
        arrived = asyncio.get_event_loop().time()
        live = [
            r for r in range(self.profile.n_replicas)
            if self._live(g, r) and self._sessions.get((g, r))
        ]
        if not live:
            self._group_rows.append((arrived, g, "shed"))
            return "shed"
        pool = self._sessions[(g, live[0])]
        sess = pool[i % len(pool)]
        cmds = [encode_set_bin(k, v) for k, v in pairs]
        try:
            res = await sess.submit(shard, cmds, timeout)
        except asyncio.TimeoutError:
            self._group_rows.append((arrived, g, "timeout"))
            return "timeout"
        except Exception:
            self._group_rows.append((arrived, g, "error"))
            return "error"
        if res.status in (ResultStatus.OK, ResultStatus.CACHED):
            self._last_acked[sess.client_id] = (
                g, live[0], res.seq, shard,
                tuple(bytes(p) for p in res.payload),
            )
            self._group_rows.append((arrived, g, "ok"))
            return "ok"
        self._group_rows.append((arrived, g, "shed"))
        if res.status == ResultStatus.RETRY:
            return "shed"
        return "error"

    # -- scoring ------------------------------------------------------------

    def _blast_radius_problems(self) -> list[str]:
        """The isolation gate: for every group that was NOT killed, its
        goodput during another group's kill window must hold against
        its OWN healthy control (the equal-length window just before
        the kill). Allows a 50% dip — a 1-core host legitimately bleeds
        some CPU into the victim's WAL recovery — but a partitioned
        tier whose healthy groups halt with the victim is a failed
        isolation story."""
        problems: list[str] = []
        for victim, edges in self._kill_edges.items():
            kill_t = edges[0]
            end_t = edges[1] if len(edges) > 1 else max(
                (t for t, _g, _o in self._group_rows), default=kill_t
            )
            span = end_t - kill_t
            if span <= 0:
                continue
            for g in self.group_map.groups():
                if g == victim:
                    continue

                def avail(lo: float, hi: float, g=g) -> tuple:
                    att = ok = 0
                    for t, gg, o in self._group_rows:
                        if gg == g and lo <= t < hi:
                            att += 1
                            ok += o == "ok"
                    return (ok / att if att else None), att

                ctrl, ctrl_n = avail(kill_t - span, kill_t)
                fault, fault_n = avail(kill_t, end_t)
                if ctrl is None or fault is None:
                    problems.append(
                        f"blast radius: group {g} has no arrivals to "
                        f"score around group {victim}'s kill window"
                    )
                    continue
                if fault < 0.5 * ctrl:
                    problems.append(
                        f"blast radius: group {g} goodput fell to "
                        f"{fault:.3f} (n={fault_n}) during group "
                        f"{victim}'s kill window vs healthy control "
                        f"{ctrl:.3f} (n={ctrl_n}) — isolation broken"
                    )
        return problems

    async def verify(self) -> list[str]:
        """Per-group exactly-once sweep: every session's last ACKED seq
        re-submitted through a DIFFERENT replica gateway of the SAME
        group must answer byte-identical (the engine ledger's replay
        lane), and the sweep must move no group's applied frontier
        (a moved frontier = a replay consumed a real slot = double
        apply)."""
        problems = self._blast_radius_problems()
        if not self._last_acked:
            return problems + ["groups verify: no acked submits to replay"]
        from rabia_tpu.apps.kvstore import encode_set_bin

        async def frontier() -> dict:
            out = {}
            for g in self.group_map.groups():
                for r in range(self.profile.n_replicas):
                    mm = await self._scrape_metrics(g, r)
                    out[(g, r)] = (
                        None if mm is None else
                        int(mm.get("rabia_engine_applied_slots_total", 0))
                    )
            return out

        before = await frontier()
        lost = 0
        identical = 0
        aged = 0
        for cid, (g, r_used, seq, shard, want) in sorted(
            self._last_acked.items(), key=lambda kv: str(kv[0])
        ):
            others = [
                r for r in range(self.profile.n_replicas)
                if r != r_used and self._live(g, r)
            ]
            if not others:
                problems.append(
                    f"groups verify: group {g} has no OTHER live "
                    "replica to replay against"
                )
                continue
            s = LoadSession(self._ser, client_id=cid)
            try:
                await s.connect(
                    "127.0.0.1",
                    self.harness.harnesses[g].gw_ports[others[0]],
                )
                # replay at the ORIGINAL arity: the ledger-replay lane
                # narrows an over-long recorded response list to the
                # replayed command count (it must never widen), so a
                # 1-command probe of a 4-command batch would read as a
                # truncated — hence "lost" — payload
                res = await s.submit_seq(
                    seq, shard,
                    [encode_set_bin("verify-replay", "X")] * len(want),
                    timeout=15.0,
                )
                got = tuple(bytes(p) for p in res.payload)
                if res.status in (
                    ResultStatus.OK, ResultStatus.CACHED
                ) and got == want:
                    identical += 1
                elif (
                    res.status == ResultStatus.ERROR
                    and got
                    and b"committed but responses unavailable" in got[0]
                ):
                    # the HONEST terminal for an aged replay: the engine
                    # dedups forever on applied_ids/alias_ledger, but
                    # applied_results is a BOUNDED response cache — an
                    # old seq's recorded slice can evict cluster-wide,
                    # and the replay then gets this marker instead of a
                    # fabricated answer. Exactly-once still holds: the
                    # frontier check below proves no slot was consumed.
                    aged += 1
                else:
                    lost += 1
                    if lost <= 4:
                        problems.append(
                            f"groups verify detail: group {g} shard "
                            f"{shard} seq {seq} via r{others[0]} "
                            f"status={ResultStatus(res.status).name} "
                            f"want={len(want)}x{[w[:24] for w in want[:2]]}"
                            f" got={len(got)}x{[b[:24] for b in got[:2]]}"
                        )
            except Exception as e:
                problems.append(
                    f"groups verify: replay of group {g} session "
                    f"seq {seq} failed: {e}"
                )
            finally:
                await s.close()
        if lost:
            problems.append(
                f"groups verify: {lost} acked result(s) replayed "
                "non-identical — exactly-once broken"
            )
        if not identical:
            # all-aged (or all-errored) would make the byte-identity leg
            # vacuous: demand at least one replay actually round-tripped
            problems.append(
                "groups verify: no replay came back byte-identical "
                f"(identical=0 aged={aged} lost={lost})"
            )
        await asyncio.sleep(0.3)
        after = await frontier()
        moved = {
            k: (before[k], after[k])
            for k in before
            if before[k] is not None
            and after[k] is not None
            and after[k] != before[k]
        }
        if moved:
            problems.append(
                "groups verify: replay sweep moved applied frontiers "
                f"{moved} — double apply"
            )
        return problems

    def engines(self) -> list:
        return []  # cross-process: evidence comes from collect_evidence

    async def collect_evidence(self) -> dict:
        """Scrape-based termination evidence: rebuild the per-replica
        phases-to-decide bucket counts from the Prometheus exposition
        (cumulative ``le`` buckets diffed back to per-phase bins) and
        the coin tallies, then fold into the shared report schema."""
        hist = np.zeros(32, np.int64)
        total = 0
        ssum = 0.0
        coins = {"v0": 0, "v1": 0}
        pref = 'rabia_phases_to_decide_bucket{le="'
        for g in self.group_map.groups():
            for r in range(self.profile.n_replicas):
                mm = await self._scrape_metrics(g, r)
                if mm is None:
                    continue
                rows = []
                for k, v in mm.items():
                    if k.startswith(pref) and not k.endswith('+Inf"}'):
                        rows.append((float(k[len(pref):-2]), v))
                rows.sort()
                prev = 0.0
                for le, cum in rows:
                    c = int(cum - prev)
                    prev = cum
                    if c > 0:
                        hist[min(int(le), 31)] += c
                total += int(mm.get("rabia_phases_to_decide_count", 0))
                ssum += float(mm.get("rabia_phases_to_decide_sum", 0.0))
                for k in ("v0", "v1"):
                    coins[k] += int(
                        mm.get(
                            f'rabia_coin_flips_total{{outcome="{k}"}}', 0
                        )
                    )
        return _evidence_report(hist, total, ssum, coins)

    def decided_totals(self) -> list[Optional[int]]:
        return [
            self._decided_cache.get((g, r))
            for g in self.group_map.groups()
            for r in range(self.profile.n_replicas)
        ]

    def watchdog_sample(self) -> dict:
        alive = self.harness.alive()
        return {
            "members_alive": sum(alive.values()),
            "members_total": (
                self.profile.n_groups * self.profile.n_replicas
            ),
        }

    async def converged(self, timeout: float) -> bool:
        """Frontier convergence per group: every live replica of a
        group reports the SAME applied-slot frontier, stable across two
        scrapes. (Byte-level store parity is out of reach across
        process boundaries — the verify() replay sweep is what gates
        payload correctness.)"""
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        last = None
        while loop.time() < deadline:
            snap = {}
            flat = True
            for g in self.group_map.groups():
                vals = []
                for r in range(self.profile.n_replicas):
                    mm = await self._scrape_metrics(g, r)
                    if mm is not None:
                        vals.append(
                            int(mm.get(
                                "rabia_engine_applied_slots_total", 0
                            ))
                        )
                snap[g] = vals
                if len(vals) < 2 or len(set(vals)) != 1:
                    flat = False
            if flat and snap == last:
                return True
            last = snap if flat else None
            await asyncio.sleep(0.4)
        print(
            f"# groups convergence failure: frontiers {last}",
            file=sys.stderr,
        )
        return False


# ---------------------------------------------------------------------------
# Consensus-health evidence
# ---------------------------------------------------------------------------


def _evidence_report(
    hist: np.ndarray, total: int, ssum: float, coins: dict
) -> dict:
    """Fold an aggregated phases-to-decide histogram into the matrix
    evidence schema (shared by the in-process and scrape-based
    collectors)."""
    nz = np.nonzero(hist)[0]
    dist = {str(int(p)): int(hist[p]) for p in nz}
    cum = np.cumsum(hist)

    def pct(q: float) -> Optional[int]:
        if total == 0:
            return None
        tgt = q * total
        for p in range(len(hist)):
            if cum[p] >= tgt:
                return int(p)
        return int(len(hist) - 1)

    return {
        "decisions": total,
        "hist": dist,
        "mean_phases": round(ssum / total, 4) if total else None,
        "p50_phases": pct(0.50),
        "p99_phases": pct(0.99),
        "max_phases": int(nz[-1]) if len(nz) else None,
        "coin_flips": coins,
    }


def collect_evidence(engines: list) -> dict:
    """Aggregate the termination-analysis evidence across replicas: the
    phases-to-decide distribution (rabia_phases_to_decide sources — C
    tick-context bins + host kernel bins + device-window bins) and the
    common-coin outcome tallies."""
    hist = np.zeros(32, np.int64)
    total = 0
    ssum = 0.0
    coins = {"v0": 0, "v1": 0}
    for eng in engines:
        try:
            h = eng.metrics.histogram("phases_to_decide")
            counts, count, s = h.merged()
            for j, c in enumerate(counts):
                hist[min(j + 1, 31)] += int(c)
            total += int(count)
            ssum += float(s)
            for k in ("v0", "v1"):
                coins[k] += int(
                    eng.metrics.counter(
                        "coin_flips_total", labels={"outcome": k}
                    ).value()
                )
        except Exception:
            continue
    return _evidence_report(hist, total, ssum, coins)


# ---------------------------------------------------------------------------
# The runner
# ---------------------------------------------------------------------------


async def run_profile(profile: ChaosProfile, verbose: bool = True) -> dict:
    """Execute one profile end-to-end; returns its scenario report (the
    matrix entry — schema in docs/SCENARIOS.md)."""

    def log(msg: str) -> None:
        if verbose:
            print(f"# [{profile.name}] {msg}", file=sys.stderr)

    fabric = {
        "sim": _SimFabric, "tcp": _TcpFabric, "fleet": _FleetFabric,
        "mesh": _MeshFabric, "groups": _GroupFabric,
    }[profile.fabric](profile)
    log(f"starting {profile.fabric} cluster "
        f"({profile.n_replicas} replicas, {profile.n_shards} shards)")
    await fabric.start()
    arrivals = _Arrivals()
    health_rows: list[dict] = []
    rng = random.Random(profile.seed)
    loop = asyncio.get_event_loop()
    fires: set[asyncio.Task] = set()
    inflight = 0
    inflight_cap = max(64, int(profile.rate * profile.call_timeout * 2))
    window = max(0.2, profile.duration / 32.0)

    # SLO burn-rate watchdog (obs/fleet_obs.py): fed cumulative counters
    # at the health cadence — outcome totals from the arrival score
    # sheet, membership + coalesce counters from the fabric's own
    # knowledge (no scrape race). Profiles with ``expect_watchdog`` gate
    # on the verdict; everyone else carries it as report evidence.
    from rabia_tpu.obs.fleet_obs import BurnRateWatchdog, SLOPolicy

    watchdog = BurnRateWatchdog(
        SLOPolicy(
            fast_window_s=2.0 * window,
            slow_window_s=8.0 * window,
        )
    )

    def wd_observe(rel_t: float) -> None:
        ok = errors = 0
        for _t, outcome, _ms in arrivals.rows:
            if outcome == "ok":
                ok += 1
            else:
                errors += 1
        sample = {"ok": ok, "errors": errors}
        if hasattr(fabric, "watchdog_sample"):
            sample.update(fabric.watchdog_sample())
        for kind in watchdog.observe(rel_t, sample):
            log(f"t={rel_t:.1f}s watchdog fired {kind}")

    # slow-exemplar attribution samples (profiles with expect_critpath
    # only — the in-process trace scan is not free at the health
    # cadence, so nobody else pays for it)
    critpath_rows: list[dict] = []

    def cp_observe(
        rel_t: float, max_age_s: Optional[float] = None
    ) -> None:
        if not profile.expect_critpath or not hasattr(
            fabric, "critpath_sample"
        ):
            return
        try:
            sample = fabric.critpath_sample(max_age_s=max_age_s)
        except Exception:  # noqa: BLE001 — evidence, never the run
            return
        if sample is not None:
            sample["t"] = round(rel_t, 3)
            if max_age_s is not None:
                sample["max_age_s"] = max_age_s
            critpath_rows.append(sample)

    try:
        # warmup: light load so the pipeline is hot before t0
        warm_end = loop.time() + profile.warmup
        while loop.time() < warm_end:
            t = asyncio.ensure_future(
                fabric.submit(
                    rng.randrange(1 << 20),
                    [(f"warm{rng.randrange(64)}", "w")] * profile.batch,
                    profile.call_timeout,
                )
            )
            fires.add(t)
            t.add_done_callback(fires.discard)
            await asyncio.sleep(max(0.005, 2.0 / profile.rate))

        t0 = loop.time()
        t_end = t0 + profile.duration
        events = sorted(profile.events, key=lambda e: e.at)
        ev_idx = 0
        next_arrival = t0
        next_sample = t0
        i = 0
        membership_pending: Optional[asyncio.Task] = None

        async def fire(idx: int, arrived: float) -> None:
            nonlocal inflight
            key = f"k{idx % 512}"
            pairs = [
                (f"{key}-{j}", f"v{idx}") for j in range(profile.batch)
            ]
            try:
                outcome = await fabric.submit(
                    idx, pairs, profile.call_timeout
                )
            except Exception:
                outcome = "error"
            finally:
                inflight -= 1
            arrivals.score(
                arrived, outcome, (loop.time() - arrived) * 1e3
            )

        while True:
            now = loop.time()
            # timed fault injections (membership events run async but
            # sequentially — one transition at a time, like a real
            # operator; load keeps firing while they run)
            while ev_idx < len(events) and now - t0 >= events[ev_idx].at:
                ev = events[ev_idx]
                ev_idx += 1
                log(f"t={now - t0:.1f}s event {ev.action} {ev.args}")
                if hasattr(fabric, "apply_event_async"):
                    # one transition at a time, like a real operator;
                    # load keeps firing while the transition runs
                    if membership_pending is not None:
                        await membership_pending
                    membership_pending = asyncio.ensure_future(
                        fabric.apply_event_async(ev.action, ev.args)
                    )
                    # let the transition coroutine reach its first await
                    # (membership bookkeeping is its first statement),
                    # then sample the watchdog on the event edge — a
                    # sub-window outage must not dodge detection by
                    # falling between cadence samples
                    await asyncio.sleep(0)
                    wd_observe(loop.time() - t0)
                else:
                    fabric.apply_event(ev.action, ev.args)
            # health sample (~per window)
            if now >= next_sample:
                health_rows.append(
                    {
                        "t": round(now - t0, 3),
                        "decided": fabric.decided_totals(),
                    }
                )
                # fresh clock read: an event-edge sample earlier in this
                # iteration may already have stamped a later t than the
                # loop-top `now`, and the watchdog windows assume
                # monotone sample times
                wd_observe(loop.time() - t0)
                cp_observe(loop.time() - t0)
                next_sample = now + window
            if now >= t_end:
                break
            # open-loop Poisson arrivals
            if now >= next_arrival:
                arrived = next_arrival
                next_arrival += rng.expovariate(profile.rate)
                if inflight >= inflight_cap:
                    arrivals.score(arrived, "overflow")
                else:
                    inflight += 1
                    t = asyncio.ensure_future(fire(i, arrived))
                    fires.add(t)
                    t.add_done_callback(fires.discard)
                i += 1
                continue
            await asyncio.sleep(
                max(0.001, min(next_arrival, next_sample, t_end) - now)
            )

        if membership_pending is not None:
            await membership_pending
        # drain stragglers (bounded), then score
        log("draining in-flight arrivals")
        if fires:
            await asyncio.wait(fires, timeout=profile.call_timeout + 1.0)
        for t in list(fires):
            t.cancel()
        if fires:
            await asyncio.gather(*fires, return_exceptions=True)
        fabric.clear_faults()

        # end-state convergence (faults cleared first)
        converged = True
        if profile.require_convergence:
            converged = await fabric.converged(timeout=10.0)
        # recovered-state attribution sample. The slowest-first
        # reservoir is honest but unforgiving here: fault-era stragglers
        # complete LATE, so they legitimately top the post-fault windows
        # and can mask the recovered tail. Drive a short healthy probe
        # load, then sample only exemplars younger than the probe phase —
        # the recovered tail, not the funeral of the faulted one.
        if profile.expect_critpath:
            # quiesce: gateway-side waves from the fault era complete on
            # their own schedule (client cancellation does not unwind
            # them) — let them land BEFORE the probe window opens so the
            # age filter below can tell the two populations apart
            await asyncio.sleep(2.0)
            probe_t0 = loop.time()
            for j in range(0, 40, 8):
                burst = [
                    fabric.submit(
                        1_000_000 + j + k,
                        [(f"probe-{j + k}", "v")],
                        profile.call_timeout,
                    )
                    for k in range(8)
                ]
                await asyncio.gather(*burst, return_exceptions=True)
                if j == 16:
                    # the first bursts absorb post-restart cold-start
                    # latency (session redial, first slot open); the
                    # verdict should judge the WARM recovered path, so
                    # age-scope the sample to the trailing bursts
                    probe_t0 = loop.time()
            cp_observe(
                loop.time() - t0,
                max_age_s=loop.time() - probe_t0 + 0.5,
            )
        else:
            cp_observe(loop.time() - t0)
        # fabric-specific end-state gates (the fleet fabric's
        # exactly-once replay sweep) — run before teardown
        fabric_problems: list = []
        if hasattr(fabric, "verify"):
            log("running fabric verify sweep")
            fabric_problems = await fabric.verify()
        # cross-process fabrics have no in-process engines: they carry
        # their own (scrape-based) evidence collector
        if hasattr(fabric, "collect_evidence"):
            evidence = await fabric.collect_evidence()
        else:
            evidence = collect_evidence(fabric.engines())
    finally:
        await fabric.stop()

    timeline = arrivals.timeline(t0, profile.duration, window)
    n_ok = sum(w["ok"] for w in timeline)
    n_att = sum(w["attempts"] for w in timeline)
    avail = n_ok / n_att if n_att else 0.0
    q_len = max(1, len(timeline) // 4)
    tail = timeline[-q_len:]
    tail_ok = sum(w["ok"] for w in tail)
    tail_att = sum(w["attempts"] for w in tail)
    tail_avail = tail_ok / tail_att if tail_att else 0.0
    lat = sorted(
        ms for t, o, ms in arrivals.rows if o == "ok"
    )

    def lpct(q: float) -> Optional[float]:
        if not lat:
            return None
        return round(lat[min(len(lat) - 1, int(q * len(lat)))], 2)

    counts = {k: 0 for k in _OUTCOMES}
    for _t, o, _ms in arrivals.rows:
        counts[o] = counts.get(o, 0) + 1

    problems = []
    if n_att == 0:
        problems.append("no measured arrivals")
    if avail < profile.min_availability:
        problems.append(
            f"availability {avail:.3f} < floor {profile.min_availability}"
        )
    if tail_avail < profile.min_final_availability:
        problems.append(
            f"wedged: final-quarter availability {tail_avail:.3f} < "
            f"{profile.min_final_availability}"
        )
    if profile.require_convergence and not converged:
        problems.append("replicas did not converge after fault clearing")
    if not evidence["decisions"]:
        problems.append("no phases-to-decide evidence recorded")
    problems.extend(fabric_problems)

    # SLO watchdog verdict: profiles that declare expectations gate on
    # (a) every expected kind fired inside the fault window and (b) the
    # healthy control — NOTHING fired before the first fault event
    verdict = watchdog.verdict()
    if profile.expect_watchdog:
        first_event_at = min(
            (e.at for e in profile.events), default=0.0
        )
        for kind in profile.expect_watchdog:
            hits = [
                ep for ep in verdict["episodes"]
                if ep["kind"] == kind and ep["t"] >= first_event_at
            ]
            if not hits:
                problems.append(
                    f"watchdog: expected {kind!r} to fire during the "
                    f"fault window (fired: {verdict['fired'] or 'nothing'})"
                )
        early = [
            ep["kind"] for ep in verdict["episodes"]
            if ep["t"] < first_event_at
        ]
        if early:
            problems.append(
                "watchdog: fired on the healthy control (before "
                f"t={first_event_at}s): {sorted(set(early))}"
            )

    # critical-path attribution verdict — the watchdog's burn-rate
    # pattern applied to attribution: the expected segments' tail
    # milliseconds must BURN far above their healthy-control band
    # during the fault window, and return inside it after the faults
    # clear. (A label-argmax gate would be dishonest here: on a durable
    # profile fsync_barrier legitimately dominates the HEALTHY tail at
    # tens of ms — the fault signature is its explosion by an order of
    # magnitude, not its first appearance.)
    critpath_doc = None
    if profile.expect_critpath:
        first_event_at = min((e.at for e in profile.events), default=0.0)
        expected = set(profile.expect_critpath)

        def expected_ms(r: dict) -> float:
            segs = r.get("segments_ms", {})
            return sum(segs.get(s, 0.0) for s in expected)

        # The reservoir observes COMPLETIONS, so attribution lags the
        # fault: a wave stalled by the restart finishes (and is
        # decomposed) well after the last clear event. The burn window
        # is therefore everything from the first event through the
        # drain — while recovery is proven ONLY by the age-filtered
        # probe samples (max_age_s set), which see exclusively
        # post-quiesce traffic.
        control = [
            r for r in critpath_rows
            if r["t"] < first_event_at and r.get("exemplars")
            and "max_age_s" not in r
        ]
        fault = [
            r for r in critpath_rows
            if r["t"] >= first_event_at and r.get("exemplars")
            and "max_age_s" not in r
        ]
        post = [
            r for r in critpath_rows
            if "max_age_s" in r and r.get("exemplars")
        ]
        c_ms = max((expected_ms(r) for r in control), default=0.0)
        f_ms = max((expected_ms(r) for r in fault), default=0.0)
        r_ms = min((expected_ms(r) for r in post), default=None)
        # shift threshold: well clear of the control band (3x) with an
        # absolute floor so a near-zero control cannot make scheduler
        # noise look like a fault signature
        threshold = max(3.0 * c_ms, c_ms + 250.0)
        critpath_doc = {
            "expected": sorted(expected),
            "samples": len(critpath_rows),
            "control_ms": round(c_ms, 3),
            "fault_ms": round(f_ms, 3),
            "recovered_ms": (
                round(r_ms, 3) if r_ms is not None else None
            ),
            "threshold_ms": round(threshold, 3),
            "control_dominants": sorted(
                {r["dominant"] for r in control}
            ),
            "fault_dominants": sorted({r["dominant"] for r in fault}),
            "post_dominants": sorted({r["dominant"] for r in post}),
            "rows": critpath_rows,
        }
        if not control:
            problems.append(
                "critpath: no decomposable healthy-control sample "
                f"before t={first_event_at}s"
            )
        if f_ms < threshold:
            problems.append(
                f"critpath: {sorted(expected)} never burned above the "
                f"control band between the first event and the drain "
                f"(fault {f_ms:.0f}ms < threshold {threshold:.0f}ms, "
                f"control {c_ms:.0f}ms)"
            )
        if r_ms is None:
            problems.append(
                "critpath: no decomposable recovery-probe sample "
                "(recovery unproven)"
            )
        elif r_ms >= threshold:
            problems.append(
                f"critpath: {sorted(expected)} did not return to the "
                f"control band on the post-quiesce probe load "
                f"(best probe {r_ms:.0f}ms >= threshold "
                f"{threshold:.0f}ms)"
            )

    report = {
        "profile": profile.name,
        "fabric": profile.fabric,
        "description": profile.description,
        "duration_s": profile.duration,
        "offered_rps": profile.rate,
        "replicas": profile.n_replicas,
        "shards": profile.n_shards,
        "events": [
            {"at": e.at, "action": e.action, **e.args} for e in profile.events
        ],
        "arrivals": n_att,
        "outcomes": counts,
        "availability": round(avail, 4),
        "final_quarter_availability": round(tail_avail, 4),
        "min_window_availability": min(
            (w["availability"] for w in timeline
             if w["availability"] is not None),
            default=None,
        ),
        "settle_ms": {"p50": lpct(0.5), "p99": lpct(0.99),
                      "max": lpct(1.0)},
        "phases_to_decide": evidence,
        "timeline": timeline,
        "health": health_rows,
        "watchdog": verdict,
        "critpath": critpath_doc,
        "converged": converged,
        "pass": not problems,
        "problems": problems,
    }
    log(
        f"done: avail={avail:.3f} tail={tail_avail:.3f} "
        f"decisions={evidence['decisions']} "
        f"mean_phases={evidence['mean_phases']} "
        f"coins={evidence['coin_flips']} "
        f"{'PASS' if not problems else 'FAIL ' + '; '.join(problems)}"
    )
    return report


async def run_matrix(
    profiles: dict[str, ChaosProfile], verbose: bool = True
) -> dict:
    """Run every profile sequentially and assemble the matrix report."""
    entries = {}
    for name, prof in profiles.items():
        entries[name] = await run_profile(prof, verbose=verbose)
    return {
        "version": MATRIX_VERSION,
        "benchmark": "scenario_matrix",
        "ts": time.time(),
        "profiles": entries,
        "pass": all(e["pass"] for e in entries.values()),
        "problems": {
            n: e["problems"] for n, e in entries.items() if e["problems"]
        },
    }


def render_matrix(report: dict) -> str:
    head = (
        f"{'profile':<22} {'fabric':<6} {'avail':>6} {'tail':>6} "
        f"{'p50ms':>7} {'p99ms':>8} {'decided':>8} {'phases':>11} "
        f"{'coins v0/v1':>12} {'ok?':>4}"
    )
    lines = [head, "-" * len(head)]
    for name, e in report["profiles"].items():
        ph = e["phases_to_decide"]
        s = e["settle_ms"]
        lines.append(
            f"{name:<22} {e['fabric']:<6} {e['availability']:>6.3f} "
            f"{e['final_quarter_availability']:>6.3f} "
            f"{s['p50'] if s['p50'] is not None else float('nan'):>7.1f} "
            f"{s['p99'] if s['p99'] is not None else float('nan'):>8.1f} "
            f"{ph['decisions']:>8d} "
            f"{(str(ph['mean_phases']) + '/' + str(ph['max_phases'])):>11} "
            f"{(str(ph['coin_flips']['v0']) + '/' + str(ph['coin_flips']['v1'])):>12} "
            f"{'yes' if e['pass'] else 'NO':>4}"
        )
    return "\n".join(lines)


def record_matrix(report: dict, key: str = MATRIX_KEY) -> None:
    """Merge the matrix (timelines trimmed) into benchmarks/results.json
    under ``key`` (latest run per key, the sweep_metrics convention)."""
    import json

    path = (
        Path(__file__).resolve().parents[2] / "benchmarks" / "results.json"
    )
    doc = {}
    if path.exists():
        try:
            doc = json.loads(path.read_text())
        except ValueError:
            doc = {}
    slim = {**report, "profiles": {}}
    for name, e in report["profiles"].items():
        slim["profiles"][name] = {
            k: v for k, v in e.items() if k not in ("health",)
        }
    doc[key] = slim
    path.write_text(json.dumps(doc, indent=1))
