"""Adverse-network / elastic-membership scenario profiles (the chaos DSL).

A :class:`ChaosProfile` is a declarative description of ONE scenario: a
load shape (open-loop Poisson arrivals, never waiting on the system), a
timed list of :class:`ChaosEvent` fault injections, and acceptance floors
the matrix gate scores against. Profiles are data, not code — the runner
(:mod:`rabia_tpu.chaos.runner`) interprets the events against whichever
fabric the profile targets:

- ``fabric="sim"``  — an in-process :class:`~rabia_tpu.net.NetworkSimulator`
  cluster (deterministic impairments: per-link asymmetric loss, scheduled
  flapping, timed partitions, slow nodes, crash/recover);
- ``fabric="tcp"``  — a real-TCP :class:`~rabia_tpu.testing.gateway_cluster.
  GatewayCluster` (gateway + native engine runtime + WAL durability plane),
  impaired through the C transport's shaping layer (``rt_set_shaping``)
  and the elastic-membership surface (stop/start/rolling-restart) — the
  PRODUCTION commit path carries the shaped traffic, not a stand-in.

Event vocabulary (``ChaosEvent.action``):

====================  =======  ====================================================
action                fabrics  args
====================  =======  ====================================================
``wan``               both     ``latency_ms``, ``jitter_ms`` = TOTAL spread (all links)
``link_loss``         both     ``src``, ``dst`` (replica indices), ``rate``
``flap``              sim      ``group`` (indices), ``period``, ``duty``, ``duration``
``partition``         sim      ``group``, ``duration`` (None = until ``heal``)
``heal``              sim      — (heals partition AND flapping)
``slow``              both     ``node``, ``delay_ms`` (0 clears)
``crash``             sim/mesh ``node``
``recover``           sim/mesh ``node``
``demote_device``     mesh     — (force device-lane demotion mid-window)
``stop_replica``      tcp      ``node``
``start_replica``     tcp      ``node``
``restart_replica``   tcp      ``node``
``kill_gateway``      fleet    ``gw`` (fleet gateway index; abrupt, no handoff)
``rebalance``         fleet    ``members`` (surviving gateway indices; handoff runs)
``kill_group_proposer`` groups ``group`` (SIGKILL that group's proposer replica)
``restart_group_proposer`` groups ``group`` (restart the killed proposer)
``clear``             both     — (clears link faults / shaping)
====================  =======  ====================================================

``fabric="mesh"`` (round 17) is the device-plane tier: one colocated
lockstep :class:`~rabia_tpu.parallel.MeshEngine` with the
device-resident KV table AND the consensus-free read-index lane on —
full-width SET waves interleave with GET waves the lane must serve off
consensus, while replicas drop out of the alive mask and the device
store is force-demoted mid-window; the post-run verify gates on the
lane having actually engaged (probe reads > 0) and on zero lockstep
apply divergences.

``fabric="groups"`` (round 20) is the partitioned tier: N independent
consensus groups, each its own OS-process replica set with its own WAL
root (``fleet/groups.py``), loaded through group-routed sessions; the
scenario SIGKILLs one group's proposer mid-wave and gates on the OTHER
groups' goodput holding inside the healthy control band (blast-radius
isolation) plus a post-run per-group exactly-once replay sweep.

``fabric="fleet"`` (round 16) is the routed tier: the same real-TCP
replica cluster behind consistent-hash-routed fleet gateways
(docs/FLEET.md), loaded through MOVED-following sessions; its runs add
a post-run exactly-once replay sweep (every session's last acked
Result must replay byte-identical through the post-fault ring with
zero store mutation).

Every profile measures the same consensus-health evidence regardless of
fabric: the per-decision **phases-to-decide distribution** and
**coin-flip tallies** (the paper's randomized-termination analysis), and
a **continuous commit-availability timeline** (per-window goodput over
offered arrivals — the dip during the partition is the datum, not the
end-of-run average). docs/SCENARIOS.md documents the schema.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class ChaosEvent:
    """One timed fault injection: run ``action(args)`` at ``at`` seconds
    after the measure window opens."""

    at: float
    action: str
    args: dict = field(default_factory=dict)


@dataclass(frozen=True)
class ChaosProfile:
    """One named scenario (see module doc for the event vocabulary)."""

    name: str
    fabric: str  # "sim" | "tcp" | "fleet" | "mesh" | "groups"
    description: str
    duration: float  # measure window, seconds
    events: tuple[ChaosEvent, ...] = ()
    # open-loop load shape
    rate: float = 120.0  # offered ops/s (Poisson)
    warmup: float = 1.0
    batch: int = 4  # commands per submit
    call_timeout: float = 8.0
    n_replicas: int = 3
    n_shards: int = 4
    n_gateways: int = 2  # fleet fabric only: routing-tier size
    n_groups: int = 2  # groups fabric only: partitioned consensus groups
    # acceptance floors (the matrix gate)
    min_availability: float = 0.5  # mean over the whole run
    min_final_availability: float = 0.05  # last-quarter mean: wedge guard
    require_convergence: bool = True
    seed: int = 20260803
    # tcp fabric only: GatewayConfig field overrides as a (key, value)
    # tuple-of-pairs (profiles are frozen/hashable — no dict field)
    gateway_overrides: tuple = ()
    # SLO burn-rate watchdog (obs/fleet_obs.py, round 18): journal kinds
    # the watchdog MUST record during the fault window — and before the
    # first fault event it must stay quiet (the healthy control). Empty
    # tuple = watchdog runs but is not asserted on.
    expect_watchdog: tuple = ()
    # Critical-path attribution gate (obs/critpath.py, round 19):
    # segment names whose combined slow-exemplar milliseconds must burn
    # far above their healthy-control band during the fault window and
    # return inside it after the faults clear (the burn-rate watchdog
    # pattern applied to attribution — on a durable profile
    # fsync_barrier dominates the HEALTHY tail too, so the signature is
    # magnitude, not first appearance). Empty tuple = no exemplar
    # sampling (the decomposition scan is not free).
    expect_critpath: tuple = ()

    def scaled(self, factor: float) -> "ChaosProfile":
        """Time-scaled copy (the CI smoke cell runs factor < 1)."""
        if factor == 1.0:
            return self
        ev = tuple(
            ChaosEvent(
                at=e.at * factor,
                action=e.action,
                args={
                    k: (v * factor if k in ("duration", "period") else v)
                    for k, v in e.args.items()
                },
            )
            for e in self.events
        )
        return ChaosProfile(
            **{
                **self.__dict__,
                "duration": self.duration * factor,
                "warmup": max(0.5, self.warmup * factor),
                "events": ev,
            }
        )


def _p(name, fabric, desc, duration, events, **kw) -> ChaosProfile:
    return ChaosProfile(
        name=name,
        fabric=fabric,
        description=desc,
        duration=duration,
        events=tuple(events),
        **kw,
    )


def default_profiles() -> dict[str, ChaosProfile]:
    """The standing scenario matrix (``scenario_matrix_r12``): ≥6 named
    profiles, at least one real-TCP shaped and at least one elastic
    membership change under sustained load."""
    profiles = [
        # -- simulator fabric -------------------------------------------
        _p(
            "wan_jitter",
            "sim",
            "WAN latency with heavy jitter on every link (25ms one-way, "
            "20ms total spread): decisions must keep terminating in few phases "
            "with round trips two orders slower than LAN",
            duration=8.0,
            events=[
                ChaosEvent(0.0, "wan", {"latency_ms": 25.0, "jitter_ms": 20.0}),
            ],
            # WAN round trips serialize slot progress: offer well under
            # the ~shards/RTT capacity so the curve scores the network,
            # not a queueing collapse of the generator's own making
            rate=36.0,
            min_availability=0.8,
        ),
        _p(
            "asymmetric_loss",
            "sim",
            "Sustained asymmetric loss: replica 0's OUTBOUND links drop "
            "30%, then 60% mid-run, while its inbound stays clean (the "
            "wireless-BFT lossy-uplink shape); retransmission must keep "
            "the phase-count tail bounded",
            duration=10.0,
            events=[
                ChaosEvent(0.0, "link_loss", {"src": 0, "dst": 1, "rate": 0.3}),
                ChaosEvent(0.0, "link_loss", {"src": 0, "dst": 2, "rate": 0.3}),
                ChaosEvent(4.0, "link_loss", {"src": 0, "dst": 1, "rate": 0.6}),
                ChaosEvent(4.0, "link_loss", {"src": 0, "dst": 2, "rate": 0.6}),
                ChaosEvent(8.0, "clear", {}),
            ],
            min_availability=0.55,
        ),
        _p(
            "flapping_partition",
            "sim",
            "A minority replica flaps in and out of a partition every "
            "1.2s (40% down duty): the cluster must ride through every "
            "flap without wedging on stale votes",
            duration=10.0,
            events=[
                ChaosEvent(
                    1.0,
                    "flap",
                    {"group": [2], "period": 1.2, "duty": 0.4,
                     "duration": 7.0},
                ),
                ChaosEvent(8.5, "heal", {}),
            ],
            min_availability=0.6,
        ),
        _p(
            "slow_replica",
            "sim",
            "One chronically lagging replica (35ms extra on all its "
            "traffic): the quorum path must route around it, and its "
            "stale votes must not poison phase counts",
            duration=8.0,
            events=[
                ChaosEvent(0.5, "slow", {"node": 1, "delay_ms": 35.0}),
                ChaosEvent(6.5, "slow", {"node": 1, "delay_ms": 0.0}),
            ],
            rate=90.0,
            min_availability=0.7,
        ),
        _p(
            "crash_recover_churn",
            "sim",
            "Minority crash/recover churn: each replica in turn crashes "
            "for ~1.5s and recovers; availability must hold through "
            "every single-replica outage",
            duration=10.0,
            events=[
                ChaosEvent(1.0, "crash", {"node": 2}),
                ChaosEvent(2.5, "recover", {"node": 2}),
                ChaosEvent(4.0, "crash", {"node": 1}),
                ChaosEvent(5.5, "recover", {"node": 1}),
                ChaosEvent(7.0, "crash", {"node": 0}),
                ChaosEvent(8.5, "recover", {"node": 0}),
            ],
            min_availability=0.5,
        ),
        # -- real-TCP fabric (gateway + native runtime + durability) ----
        _p(
            "tcp_shaped_wan",
            "tcp",
            "Real-TCP cluster under C-transport shaping: every "
            "replica-to-replica link carries 10ms (6ms jitter spread) injected "
            "one-way delay inside the native io loop — the production "
            "epoll path, not a simulator",
            duration=8.0,
            events=[
                ChaosEvent(0.0, "wan", {"latency_ms": 10.0, "jitter_ms": 6.0}),
            ],
            rate=80.0,
            min_availability=0.7,
        ),
        _p(
            "tcp_asymmetric_loss",
            "tcp",
            "Real-TCP asymmetric drop: replica 0's outbound consensus "
            "frames drop 25% in the C transport while everything else "
            "flows clean; vote retransmission must carry the slack",
            duration=8.0,
            events=[
                ChaosEvent(
                    0.5, "link_loss", {"src": 0, "dst": 1, "rate": 0.25}
                ),
                ChaosEvent(
                    0.5, "link_loss", {"src": 0, "dst": 2, "rate": 0.25}
                ),
                ChaosEvent(6.5, "clear", {}),
            ],
            rate=80.0,
            min_availability=0.6,
        ),
        _p(
            "membership_elastic",
            "tcp",
            "Elastic membership under sustained load: a replica is "
            "DECOMMISSIONED mid-run (gateway, engine and transport down),"
            " the remaining quorum keeps committing, then it REJOINS "
            "(WAL recovery + tail catch-up) — commit availability and "
            "settle latency are scored CONTINUOUSLY through both "
            "transitions, not just at end-state convergence",
            duration=12.0,
            events=[
                ChaosEvent(3.0, "stop_replica", {"node": 2}),
                ChaosEvent(7.0, "start_replica", {"node": 2}),
            ],
            rate=80.0,
            min_availability=0.55,
        ),
        _p(
            "coalesce_flap_restart",
            "tcp",
            "Cross-session coalescing lane under compound adversity: a "
            "flapping partial partition (replica 2's links drop 100% in "
            "1s bursts) while a proposer restarts mid-run with decided "
            "coalesced waves whose durability barrier is still pending "
            "— parked windows must shed retryable (never duplicate-"
            "apply), multi-client waves must keep packing between "
            "flaps, and every covered session's Result must stay "
            "exactly-once through the WAL recovery",
            duration=12.0,
            events=[
                # flapping partial partition: 1s on / 1s off bursts
                ChaosEvent(1.0, "link_loss", {"src": 2, "dst": 0, "rate": 1.0}),
                ChaosEvent(1.0, "link_loss", {"src": 2, "dst": 1, "rate": 1.0}),
                ChaosEvent(2.0, "clear", {}),
                ChaosEvent(3.0, "link_loss", {"src": 2, "dst": 0, "rate": 1.0}),
                ChaosEvent(3.0, "link_loss", {"src": 2, "dst": 1, "rate": 1.0}),
                ChaosEvent(4.0, "clear", {}),
                # proposer restart mid-load: decided-but-barrier-pending
                # coalesced waves ride the WAL recovery
                ChaosEvent(6.0, "restart_replica", {"node": 0}),
                ChaosEvent(8.0, "link_loss", {"src": 2, "dst": 0, "rate": 1.0}),
                ChaosEvent(8.0, "link_loss", {"src": 2, "dst": 1, "rate": 1.0}),
                # cleared well before run end: the flapped replica's
                # catch-up sync must fit the convergence window even on
                # a loaded CI host
                ChaosEvent(9.0, "clear", {}),
            ],
            rate=80.0,
            min_availability=0.45,
            # pinned coalescing windows so multi-client packing is the
            # shape under test, not an arrival-rate accident
            gateway_overrides=(
                ("coalesce", True),
                ("coalesce_window", 0.02),
                ("coalesce_window_min", 0.02),
                # fast slowlog rotation so each attribution sample sees
                # only the last ~2s of exemplars (current + previous
                # window), not the whole run's tail
                ("slowlog_window", 1.0),
            ),
            # the proposer restart takes a member out of the watchdog's
            # alive set mid-run: ring_stale must fire in the fault
            # window and nothing may fire before the first event
            expect_watchdog=("ring_stale",),
            # attribution gate: while the faults are live the slow
            # tail's time must pile into the stall legs — proposals
            # waiting for a slot to open while the flapped/restarted
            # proposer recovers (propose_to_open), the WAL barrier
            # (fsync_barrier), and coalesce parking (coalesce_park).
            # WHICH of the three absorbs a given straggler depends on
            # where its wave was when the fault landed, so the gate
            # sums the set rather than asserting one label; after the
            # faults clear the sum must drop back inside the control
            # band
            expect_critpath=(
                "propose_to_open",
                "fsync_barrier",
                "coalesce_park",
            ),
        ),
        # -- device-mesh fabric (round 17: device KV + read-index lane) -
        _p(
            "mesh_device_read_lane",
            "mesh",
            "Device-plane read lane under replica loss and forced "
            "demotion: a colocated MeshEngine serves full-width SET "
            "waves plus GET waves off-consensus (zero slots) while a "
            "minority replica crashes out of the alive mask and "
            "recovers, then the device store is force-DEMOTED "
            "mid-window — parked probe reads must flush to the "
            "consensus path, the auto-repromote must re-engage the "
            "lane with reset write barriers, and the verify sweep "
            "gates on probe reads > 0 and zero lockstep divergences",
            duration=10.0,
            events=[
                ChaosEvent(2.0, "crash", {"node": 2}),
                ChaosEvent(4.0, "recover", {"node": 2}),
                ChaosEvent(6.0, "demote_device", {}),
            ],
            rate=60.0,
            batch=1,
            n_replicas=3,
            n_shards=4,
            min_availability=0.6,
        ),
        # -- routed fleet fabric (round 16: gateway tier + hash ring) ---
        _p(
            "routed_gateway_failover",
            "fleet",
            "Kill a fleet gateway mid-wave: clients follow MOVED / ring "
            "failover to the successor, whose replicated dedup ledger "
            "answers every redirected replay byte-identically — zero "
            "double-applies, zero lost acked Results (the post-run "
            "replay sweep is the gate), and goodput recovers once the "
            "survivors adopt the shrunken ring",
            duration=10.0,
            events=[
                ChaosEvent(4.0, "kill_gateway", {"gw": 0}),
            ],
            rate=80.0,
            n_gateways=2,
            min_availability=0.5,
            # the killed fleet gateway leaves the watchdog's alive set
            # for the rest of the run: ring_stale is the asserted kind
            expect_watchdog=("ring_stale",),
        ),
        # -- partitioned shard-group fabric (round 20: fleet/groups.py) -
        _p(
            "group_proposer_kill",
            "groups",
            "SIGKILL one consensus group's proposer replica mid-wave in "
            "a 2-group partitioned fleet: the victim group rides through "
            "on its surviving quorum while the OTHER group's goodput "
            "must hold inside the healthy control band (blast-radius "
            "isolation is the datum) — then the proposer restarts (WAL "
            "recovery) and a per-group exactly-once replay sweep "
            "re-submits every session's last acked seq through a "
            "DIFFERENT replica gateway of its group, expecting CACHED "
            "byte-identical answers and zero store mutation",
            duration=12.0,
            events=[
                ChaosEvent(4.0, "kill_group_proposer", {"group": 0}),
                ChaosEvent(8.0, "restart_group_proposer", {"group": 0}),
            ],
            # 2 groups x 3 replicas = 6 OS processes sharing whatever
            # cores the host has: offer modestly so the curve scores the
            # kill, not CPU starvation of the generator's own making
            rate=60.0,
            n_groups=2,
            min_availability=0.5,
            # the SIGKILLed proposer leaves the watchdog's per-process
            # alive set for the kill window: ring_stale is the asserted
            # kind, and nothing may fire in the healthy control prefix
            expect_watchdog=("ring_stale",),
        ),
        _p(
            "rolling_restart",
            "tcp",
            "Rolling restart under load: each replica in turn restarts "
            "(WAL recovery, port rebind, peer redial) while clients keep "
            "submitting — the zero-downtime-deploy drill",
            duration=12.0,
            events=[
                ChaosEvent(2.0, "restart_replica", {"node": 0}),
                ChaosEvent(6.0, "restart_replica", {"node": 1}),
                ChaosEvent(10.0, "restart_replica", {"node": 2}),
            ],
            rate=80.0,
            min_availability=0.5,
        ),
    ]
    return {p.name: p for p in profiles}


def smoke_profiles() -> dict[str, ChaosProfile]:
    """The CI smoke subset: 7 short profiles — one simulator adverse-net,
    one real-TCP shaped, one membership change under load, one routed
    gateway failover, the device-mesh read-lane drill, and the
    partitioned-group proposer kill — time-scaled to keep the cell
    under a couple of minutes."""
    all_p = default_profiles()
    out = {}
    for name, factor in (
        ("flapping_partition", 0.6),
        ("tcp_shaped_wan", 0.6),
        ("membership_elastic", 0.7),
        ("coalesce_flap_restart", 0.7),
        ("routed_gateway_failover", 0.7),
        ("mesh_device_read_lane", 0.6),
        ("group_proposer_kill", 0.7),
    ):
        out[name] = all_p[name].scaled(factor)
    return out


def get_profile(name: str) -> Optional[ChaosProfile]:
    return default_profiles().get(name)
