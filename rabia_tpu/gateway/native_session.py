"""NativeSessionTable: ctypes bridge to the C gateway session plane.

Wraps native/sessionkernel.cpp behind the SAME op-level API as the
Python :class:`~rabia_tpu.gateway.session.SessionTable` (the semantics
owner; ``RABIA_PY_GATEWAY=1`` forces it), so
:class:`~rabia_tpu.gateway.server.GatewayServer` is table-agnostic:

- the submit hot path (ensure + ack advance + dedup classify + window
  check + reservation) is ONE C call; cached dedup payloads come back
  as borrowed views unpacked into the exact ``tuple[bytes, ...]`` the
  Python table would return (byte parity is the conformance contract);
- the per-second GC sweep over every session runs in C — at 10^5
  sessions the Python loop's sweep alone cost tens of ms of asyncio
  loop stall per interval;
- the GWC_* counter block is exposed zero-copy for the metrics
  registry (``rabia_gateway_plane_*`` families).

Payload blob ABI (shared with the kernel):
``[u32 nparts][u32 len_i]*nparts [concatenated part bytes]``.
"""

from __future__ import annotations

import ctypes
import struct
import time
import uuid
from typing import Optional

from rabia_tpu.gateway.session import (
    SUBMIT_DUP_CACHED,
    CachedResult,
    SessionStats,
)

# GWC_* counter names in index order (sessionkernel.cpp); versioned
# append-only like RKC_*/SKC_*
GWC_COUNTER_NAMES = (
    "hellos",
    "submits",
    "dedup_cached",
    "dedup_inflight",
    "shed_window",
    "fresh",
    "completes",
    "aborts",
    "gc_runs",
    "sessions_opened",
    "sessions_expired",
    "leases_expired",
    "results_cached",
    "results_evicted",
    "result_bytes",
    "rehashes",
)

GWS_COUNTERS_VERSION = 1


def pack_payload(payload) -> bytes:
    """Pack a result payload (sequence of bytes-likes) into the kernel's
    blob ABI. Accepts memoryviews — the lazy result views the native
    apply plane stages — without materializing intermediate objects
    beyond this one blob."""
    parts = [bytes(p) for p in payload]
    head = struct.pack("<I", len(parts)) + b"".join(
        struct.pack("<I", len(p)) for p in parts
    )
    return head + b"".join(parts)


def unpack_payload(blob: bytes) -> tuple[bytes, ...]:
    n = struct.unpack_from("<I", blob, 0)[0]
    lens = struct.unpack_from(f"<{n}I", blob, 4)
    off = 4 + 4 * n
    out = []
    for ln in lens:
        out.append(blob[off:off + ln])
        off += ln
    return tuple(out)


class _NativeResultsView:
    """Dict-ish view of one session's cached results (test surface:
    ``seq in sess.results``, ``len``, ``get``)."""

    def __init__(self, table: "NativeSessionTable", cid: uuid.UUID) -> None:
        self._t = table
        self._cid = cid

    def __contains__(self, seq: int) -> bool:
        return self._t.cached_result(self._cid, seq) is not None

    def get(self, seq: int) -> Optional[CachedResult]:
        return self._t.cached_result(self._cid, seq)

    def __len__(self) -> int:
        info = self._t._info(self._cid)
        return 0 if info is None else info[4]

    def keys(self) -> list[int]:
        return self._t.result_seqs(self._cid)


class _NativeSessionView:
    """Read-only session facade matching the GatewaySession attributes
    tests and repair paths consult."""

    __slots__ = ("_t", "client_id")

    def __init__(self, table: "NativeSessionTable", cid: uuid.UUID) -> None:
        self._t = table
        self.client_id = cid

    @property
    def results(self) -> _NativeResultsView:
        return _NativeResultsView(self._t, self.client_id)

    def _field(self, idx: int):
        info = self._t._info(self.client_id)
        return None if info is None else info[idx]

    @property
    def window(self):
        return self._field(0)

    @property
    def ack_upto(self):
        return self._field(1)

    @property
    def highest_completed(self):
        return self._field(2)

    @property
    def inflight(self) -> dict:
        return {q: None for q in self._t.inflight_seqs(self.client_id)}


class _NativeSessionsFacade:
    """The ``table.sessions`` mapping surface (tests wipe it to simulate
    session-state loss; health counts it)."""

    def __init__(self, table: "NativeSessionTable") -> None:
        self._t = table

    def clear(self) -> None:
        self._t.clear()

    def __contains__(self, cid: uuid.UUID) -> bool:
        return self._t._info(cid) is not None

    def __len__(self) -> int:
        return len(self._t)

    def keys(self) -> list[uuid.UUID]:
        return self._t.session_ids()


class NativeSessionTable:
    """C-resident session/dedup table (see module doc)."""

    is_native = True

    def __init__(
        self,
        lib,
        default_window: int = 64,
        session_ttl: float = 600.0,
        result_cache_cap: int = 4096,
        lease_ttl: Optional[float] = None,
    ) -> None:
        self._lib = lib
        self.default_window = max(1, default_window)
        self.session_ttl = session_ttl
        self.result_cache_cap = max(1, result_cache_cap)
        self.lease_ttl = (
            lease_ttl if lease_ttl is not None else 4.0 * session_ttl
        )
        self._h = lib.gws_create(
            self.default_window,
            float(session_ttl),
            self.result_cache_cap,
            float(self.lease_ttl),
        )
        if not self._h:
            raise MemoryError("sessionkernel plane allocation failed")
        n = lib.gws_counters_count()
        addr = lib.gws_counters(self._h)
        self._ctr = (ctypes.c_uint64 * n).from_address(addr)
        self.sessions = _NativeSessionsFacade(self)

    def close(self) -> None:
        h = self._h
        if h:
            # freeze a final counter copy for late scrapes. Publish the
            # frozen copy and null the handle BEFORE freeing: /metrics
            # renders on the HTTP shim's handler threads, and a scrape
            # racing close() must land on the frozen copy (counters) or
            # the nulled handle (gws_len/gws_stats), never freed heap.
            n = len(self._ctr)
            frozen = (ctypes.c_uint64 * n)(*self._ctr)
            self._ctr = frozen
            self._h = None
            self._lib.gws_destroy(h)

    # -- op-level API (mirrors SessionTable) --------------------------------

    def hello(
        self,
        client_id: uuid.UUID,
        requested_window: int = 0,
        now: Optional[float] = None,
    ) -> tuple[int, int]:
        last = ctypes.c_uint64()
        window = self._lib.gws_hello(
            self._h, client_id.bytes, int(requested_window),
            time.time() if now is None else now, ctypes.byref(last),
        )
        return int(window), int(last.value)

    def submit_check(
        self,
        client_id: uuid.UUID,
        seq: int,
        ack_upto: int = 0,
        now: Optional[float] = None,
    ) -> tuple[int, int, tuple[bytes, ...]]:
        status = ctypes.c_int32()
        blob_p = ctypes.c_void_p()
        blob_len = ctypes.c_int64()
        dec = self._lib.gws_submit(
            self._h, client_id.bytes, seq, int(ack_upto),
            time.time() if now is None else now,
            ctypes.byref(status), ctypes.byref(blob_p),
            ctypes.byref(blob_len),
        )
        if dec == SUBMIT_DUP_CACHED:
            blob = ctypes.string_at(blob_p.value, blob_len.value)
            return int(dec), int(status.value), unpack_payload(blob)
        return int(dec), 0, ()

    def complete_op(
        self,
        client_id: uuid.UUID,
        seq: int,
        status: int,
        payload,
        frontier_mark: int,
        now: Optional[float] = None,
    ) -> bool:
        blob = pack_payload(payload)
        return bool(
            self._lib.gws_complete(
                self._h, client_id.bytes, seq, int(status),
                int(frontier_mark), blob, len(blob),
                time.time() if now is None else now,
            )
        )

    def abort(self, client_id: uuid.UUID, seq: int) -> None:
        self._lib.gws_abort(self._h, client_id.bytes, seq)

    def cached_result(
        self, client_id: uuid.UUID, seq: int
    ) -> Optional[CachedResult]:
        status = ctypes.c_int32()
        frontier = ctypes.c_uint64()
        blob_p = ctypes.c_void_p()
        blob_len = ctypes.c_int64()
        ok = self._lib.gws_get_result(
            self._h, client_id.bytes, seq, ctypes.byref(status),
            ctypes.byref(frontier), ctypes.byref(blob_p),
            ctypes.byref(blob_len),
        )
        if not ok:
            return None
        blob = ctypes.string_at(blob_p.value, blob_len.value)
        return CachedResult(
            status=int(status.value),
            payload=unpack_payload(blob),
            frontier_mark=int(frontier.value),
        )

    def gc(self, state_version: int, now: Optional[float] = None) -> int:
        return int(
            self._lib.gws_gc(
                self._h, int(state_version),
                time.time() if now is None else now,
            )
        )

    # -- facades / introspection --------------------------------------------

    def ensure(
        self,
        client_id: uuid.UUID,
        requested_window: int = 0,
        now: Optional[float] = None,
    ) -> _NativeSessionView:
        self.hello(client_id, requested_window, now=now)
        return _NativeSessionView(self, client_id)

    def get(self, client_id: uuid.UUID) -> Optional[_NativeSessionView]:
        if self._info(client_id) is None:
            return None
        return _NativeSessionView(self, client_id)

    def clear(self) -> None:
        self._lib.gws_clear(self._h)

    def _info(self, client_id: uuid.UUID):
        window = ctypes.c_int64()
        ack = ctypes.c_uint64()
        highest = ctypes.c_uint64()
        n_inflight = ctypes.c_int64()
        n_results = ctypes.c_int64()
        ok = self._lib.gws_session_info(
            self._h, client_id.bytes, ctypes.byref(window),
            ctypes.byref(ack), ctypes.byref(highest),
            ctypes.byref(n_inflight), ctypes.byref(n_results),
        )
        if not ok:
            return None
        return (
            int(window.value), int(ack.value), int(highest.value),
            int(n_inflight.value), int(n_results.value),
        )

    def session_ids(self) -> list[uuid.UUID]:
        cap = max(16, len(self) + 8)
        buf = (ctypes.c_uint8 * (16 * cap))()
        n = self._lib.gws_session_ids(self._h, buf, cap)
        raw = bytes(buf)
        return [
            uuid.UUID(bytes=raw[16 * i:16 * i + 16]) for i in range(n)
        ]

    def result_seqs(self, client_id: uuid.UUID) -> list[int]:
        info = self._info(client_id)
        if info is None:
            return []
        cap = max(1, info[4])
        out = (ctypes.c_uint64 * cap)()
        n = self._lib.gws_result_seqs(self._h, client_id.bytes, out, cap)
        return [int(out[i]) for i in range(max(0, n))]

    def inflight_seqs(self, client_id: uuid.UUID) -> list[int]:
        info = self._info(client_id)
        if info is None:
            return []
        cap = max(1, info[3])
        out = (ctypes.c_uint64 * cap)()
        n = self._lib.gws_inflight_seqs(self._h, client_id.bytes, out, cap)
        return [int(out[i]) for i in range(max(0, n))]

    def counters_dict(self) -> dict[str, int]:
        return {
            name: int(self._ctr[i]) if i < len(self._ctr) else 0
            for i, name in enumerate(GWC_COUNTER_NAMES)
        }

    @property
    def stats(self) -> SessionStats:
        """SessionStats parity view (computed from the counter block)."""
        out = (ctypes.c_uint64 * 6)()
        h = self._h  # local: close() nulls the handle before freeing
        if h:
            self._lib.gws_stats(h, out)
            vals = [int(v) for v in out]
        else:
            c = self.counters_dict()
            vals = [
                c["sessions_opened"],
                c["dedup_cached"] + c["dedup_inflight"],
                c["results_cached"],
                c["results_evicted"],
                c["sessions_expired"],
                c["leases_expired"],
            ]
        return SessionStats(
            sessions_opened=vals[0],
            duplicate_submits=vals[1],
            results_cached=vals[2],
            results_evicted=vals[3],
            sessions_expired=vals[4],
            leases_expired=vals[5],
        )

    def __len__(self) -> int:
        h = self._h  # local: close() nulls the handle before freeing
        return int(self._lib.gws_len(h)) if h else 0


def make_session_table(
    default_window: int = 64,
    session_ttl: float = 600.0,
    result_cache_cap: int = 4096,
    lease_ttl: Optional[float] = None,
):
    """The gateway's table factory: the native plane when the kernel
    builds and ``RABIA_PY_GATEWAY`` does not force Python, else the
    Python semantics owner."""
    from rabia_tpu.gateway.session import SessionTable
    from rabia_tpu.native.build import load_sessionkernel

    lib = load_sessionkernel()
    if lib is not None:
        return NativeSessionTable(
            lib,
            default_window=default_window,
            session_ttl=session_ttl,
            result_cache_cap=result_cache_cap,
            lease_ttl=lease_ttl,
        )
    return SessionTable(
        default_window=default_window,
        session_ttl=session_ttl,
        result_cache_cap=result_cache_cap,
        lease_ttl=lease_ttl,
    )
