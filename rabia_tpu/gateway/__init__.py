"""Client gateway subsystem: the cluster's front door.

Exactly-once client sessions, linearizable read-index reads, and
admission control over the native transport — see
:mod:`rabia_tpu.gateway.server` for the service and
:mod:`rabia_tpu.gateway.client` for the client library.
"""

from rabia_tpu.gateway.client import (
    BackpressureError,
    GatewayError,
    RabiaClient,
    admin_fetch,
)
from rabia_tpu.gateway.server import (
    GatewayConfig,
    GatewayEndpoint,
    GatewayServer,
    GatewayStats,
    devkv_read_handler,
    kv_read_handler,
)
from rabia_tpu.gateway.session import (
    CachedResult,
    GatewaySession,
    SessionTable,
)

__all__ = [
    "BackpressureError",
    "CachedResult",
    "GatewayConfig",
    "GatewayEndpoint",
    "GatewayError",
    "GatewayServer",
    "GatewaySession",
    "GatewayStats",
    "RabiaClient",
    "SessionTable",
    "admin_fetch",
    "devkv_read_handler",
    "kv_read_handler",
]
