"""GatewayServer: the replica's client-facing front door.

One gateway runs next to each :class:`~rabia_tpu.engine.RabiaEngine`
replica, on its OWN native transport instance (own node id, own port) —
client traffic never rides the consensus plane's broadcast fan-out and
the engine's message loop never sees a client frame. The gateway talks
to its engine in-process and to peer gateways over the wire (read-index
frontier probes).

Three request paths:

- **Submit** — exactly-once writes. The session table answers duplicate
  ``(client_id, seq)`` submissions from cache (or attaches them to the
  in-flight proposal); fresh seqs go through admission control and then
  ``engine.submit_batch``.
- **ReadIndex (READ)** — linearizable GETs with no consensus slot
  consumed. The gateway probes a quorum of gateways for their potential
  decided frontiers (:meth:`RabiaEngine.decided_frontier`), takes the
  per-shard max as the read index, waits until the local applied
  frontier covers it, and serves the value from the local state machine.
  Quorum intersection makes this linearizable: every write committed
  before the probe has a round-2 quorum, and any probed quorum shares a
  member with it that reports a frontier above the write's slot.
  Probe rounds are shared by every read that arrived before the round
  started — read throughput is decoupled from the probe RTT.
- **Admission control** — a bounded per-session inflight window plus an
  engine queue-depth ceiling; both shed load with a retryable
  ``ResultStatus.RETRY`` before the engine inbox saturates.
"""

from __future__ import annotations

import asyncio
import logging
import time
import uuid
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from rabia_tpu.core.config import TcpNetworkConfig
from rabia_tpu.core.errors import (
    RabiaError,
    ResponsesUnavailableError,
    TimeoutError_,
)
from rabia_tpu.core.messages import (
    AdminKind,
    AdminRequest,
    AdminResponse,
    ClientHello,
    ProtocolMessage,
    ReadIndex,
    ReadIndexMode,
    Result,
    ResultStatus,
    Submit,
)
from rabia_tpu.core.serialization import Serializer
from rabia_tpu.core.types import (
    BatchId,
    Command,
    CommandBatch,
    NodeId,
    ShardId,
)
from rabia_tpu.gateway.session import (
    SUBMIT_DUP_CACHED,
    SUBMIT_DUP_INFLIGHT,
    SUBMIT_FRESH,
    SUBMIT_SHED_WINDOW,
)
from rabia_tpu.obs.flight import (
    FRE_BARRIER,
    FRE_GW_RECV,
    FRE_RESULT,
    batch_id_for,
    fr_hash,
)

logger = logging.getLogger("rabia_tpu.gateway")

# reader: (shard, key bytes) -> encoded result bytes (the host store's
# binary result framing — byte-identical to a committed GET's response)
ReadHandler = Callable[[int, bytes], bytes]


@dataclass
class GatewayConfig:
    bind_host: str = "127.0.0.1"
    bind_port: int = 0  # ephemeral
    max_inflight_per_session: int = 64
    # shed Submits once the engine's local submission queues hold this
    # many batches (well under the native transport's 64Ki-frame inbox)
    max_queue_depth: int = 1024
    session_ttl: float = 600.0
    result_cache_cap: int = 4096
    # hard session lease (seconds): a session silent this long is dropped
    # by GC even with in-flight seqs, so a stalled frontier / wedged
    # engine cannot pin dead sessions forever. None = 4 x session_ttl.
    session_lease: Optional[float] = None
    # one probe round answers every read that arrived before it started;
    # a round that cannot assemble a quorum of frontiers by this deadline
    # fails those reads with a retryable RETRY
    probe_timeout: float = 2.0
    # how long a read may wait for the local applied frontier to cover
    # its read index before failing retryable
    read_timeout: float = 5.0
    gc_interval: float = 1.0
    # observability HTTP shim (obs/http.py): None = no HTTP listener
    # (the admin FRAMES on the native transport are always served);
    # 0 = bind an ephemeral port, exposed as GatewayServer.http_port
    http_port: Optional[int] = None
    http_host: str = "127.0.0.1"
    # per-second telemetry ring (obs/telemetry.TelemetrySampler): one
    # registry snapshot per interval into a bounded ring, served as
    # AdminKind.TIMELINE and /timeline. 0 disables the sampler.
    telemetry_interval: float = 1.0
    telemetry_cap: int = 900
    # thread-per-shard-group native runtime workers for engines built by
    # GatewayCluster from this config (None = the engine default /
    # RabiaConfig.runtime_workers / RABIA_RT_WORKERS — see
    # docs/PERFORMANCE.md "Thread-per-shard-group runtime")
    runtime_workers: Optional[int] = None
    # -- cross-session submit coalescing (docs/PERFORMANCE.md
    # "Coalescing tier"): eligible fresh binary-op Submits arriving
    # within a short adaptive window pack into ONE multi-client
    # PayloadBlock entry per shard — one consensus slot, one
    # sk_apply_wave, one durability-barrier wait for MANY sessions.
    # False = the per-submit lane only (the round-10 shape).
    coalesce: bool = True
    # latency budget: the LONGEST a parked Submit waits for its window
    # to fill; the adaptive window (sized from the eligible-arrival
    # rate EWMA) never exceeds it. None = auto: 2ms, raised to 8ms on
    # durable clusters (results there cannot leave before the fsync
    # barrier anyway, so a longer window is nearly free and buys
    # cross-session batching)
    coalesce_window: Optional[float] = None
    # adaptive floor: under dense arrivals the window shrinks toward
    # this instead of zero (a too-small window degenerates to solo)
    coalesce_window_min: float = 0.0005
    # ops budget per packed entry (clamped further to the engine's
    # max-batch validation limits at flush time)
    coalesce_max_ops: int = 128
    # bytes budget for a packed entry's command payloads
    coalesce_max_bytes: int = 256 * 1024
    # -- tail-exemplar slowlog (obs/critpath.py; AdminKind.SLOWLOG):
    # the gateway keeps a bounded reservoir of the SLOWEST fresh-Submit
    # completions per rotation window (batch id + wall time + outcome),
    # so p99 exemplars are capturable with no operator foreknowledge of
    # batch ids. Serving merges the live and previous windows — a fresh
    # rotation never empties the reply. 0 exemplars disables capture.
    slowlog_cap: int = 8
    slowlog_window: float = 10.0
    # -- shard-group scale-out (fleet/groups.py): when this replica
    # belongs to one consensus group of a partitioned deployment,
    # group_id names the group and group_shards lists the half-open
    # [lo, hi) global shard ranges the group owns. Submits outside the
    # owned ranges shed retryable (reason "group_range") — the router's
    # map flip re-aims the client's retry — and the coalescing lane
    # asserts every flushed window is group-local. The owned ranges are
    # RUNTIME-UPDATABLE via the AdminKind.RING {"op": "set_group"}
    # frame (rebalance widens the new owner before the route flips).
    # None = ungrouped (the classic whole-shard-space replica).
    group_id: Optional[int] = None
    group_shards: Optional[tuple[tuple[int, int], ...]] = None


class _SlowlogReservoir:
    """Per-window bounded reservoir of the slowest completions.

    ``observe`` is the hot call: one comparison against the window's
    current floor in the common case (a completion faster than every
    kept exemplar). The reservoir keeps the ``cap`` slowest entries of
    the live window and rotates on a wall cadence, retaining exactly one
    previous window so a scrape right after rotation still sees the
    recent tail. Exemplar documents are JSON-ready plain dicts."""

    __slots__ = (
        "cap", "window", "_cur", "_prev", "_floor", "_window_start",
        "observed", "rotations",
    )

    def __init__(self, cap: int, window: float) -> None:
        self.cap = cap
        self.window = window
        self._cur: list[tuple[float, dict, float]] = []
        self._prev: list[tuple[float, dict, float]] = []
        self._floor = 0.0
        self._window_start = time.monotonic()
        self.observed = 0
        self.rotations = 0

    def _rotate_if_due(self, now: float) -> None:
        if now - self._window_start < self.window:
            return
        self._prev = self._cur
        self._cur = []
        self._floor = 0.0
        self._window_start = now
        self.rotations += 1

    def observe(self, wall_s: float, exemplar: dict) -> None:
        if self.cap <= 0:
            return
        self.observed += 1
        now = time.monotonic()
        self._rotate_if_due(now)
        cur = self._cur
        if len(cur) >= self.cap:
            if wall_s <= self._floor:
                return
            # evict the fastest kept exemplar (linear over a tiny cap)
            cur.pop(min(range(len(cur)), key=lambda i: cur[i][0]))
        cur.append((wall_s, exemplar, now))
        self._floor = min(w for w, _, _ in cur) if len(cur) >= self.cap \
            else 0.0

    def document(self, last: Optional[int] = None) -> dict:
        """The AdminKind.SLOWLOG reply body: live + previous windows,
        slowest first, with the serve-time clock pair the collector
        aligns with (the TraceSlice convention)."""
        now = time.monotonic()
        self._rotate_if_due(now)
        ex = sorted(
            self._cur + self._prev, key=lambda e: -e[0]
        )
        if last is not None:
            ex = ex[: max(0, last)]
        return {
            "version": 1,
            "cap": self.cap,
            "window_s": self.window,
            "observed": self.observed,
            "rotations": self.rotations,
            "wall": time.time(),
            "mono_ns": time.monotonic_ns(),
            "exemplars": [
                dict(e, wall_s=w, age_s=round(now - at, 3))
                for w, e, at in ex
            ],
        }


@dataclass
class GatewayStats:
    submits: int = 0
    submits_deduped: int = 0
    submits_shed: int = 0
    reads: int = 0
    reads_failed: int = 0
    probe_rounds: int = 0
    results_sent: int = 0
    results_repaired: int = 0  # fetched from a peer after a sync overtake
    submits_coalesced: int = 0  # submits that rode a multi-client wave
    coalesce_waves: int = 0  # multi-client waves proposed
    reads_batched: int = 0  # reads served via the handler's read_many batch


@dataclass
class GatewayEndpoint:
    """Address card for one gateway (what a client/peer needs to dial)."""

    node_id: NodeId
    host: str
    port: int


def kv_read_handler(sm) -> ReadHandler:
    """Default read handler over a sharded KV state machine
    (:class:`~rabia_tpu.apps.sharded.ShardedStateMachine` of
    ``KVStoreSMR`` shards): serve GETs straight from the shard's host
    store, framed byte-identically to a committed GET response. A
    device-lane deployment (apps/device_kv) plugs in a handler backed by
    the device table's GET lane here instead — the gateway only needs
    the ``(shard, key) -> result bytes`` seam."""
    from rabia_tpu.apps.kvstore import KVResultKind, _result_bin

    machines = getattr(sm, "machines", None)
    if machines is None:
        raise TypeError(
            "kv_read_handler needs a sharded state machine with .machines"
        )

    def read(shard: int, key: bytes) -> bytes:
        store = machines[shard % len(machines)].store
        try:
            k = key.decode()
        except UnicodeDecodeError:
            return _result_bin(2, 0, "malformed key")
        if getattr(store, "is_native", False):
            # native apply plane: one borrowed C lookup, result framed
            # directly — no op encode/apply/decode round trip. Stats
            # are counted like KVStore.get so the two store paths stay
            # parity-comparable.
            plane, idx = store.plane, store.idx
            got = plane.get(idx, key)
            plane.add_stats(idx, 1, 1, 0)
            if got is None:
                return _result_bin(1, 0)
            val, ver = got
            return (
                b"\x00"
                + (ver & 0xFFFFFFFF).to_bytes(4, "little")
                + b"\x01"
                + val
            )
        res = store.get(k)
        if res.kind == KVResultKind.NotFound:
            return _result_bin(1, 0)
        return _result_bin(0, res.version or 0, res.value)

    return read


def devkv_read_handler(engine) -> ReadHandler:
    """Read handler over a device-store MeshEngine
    (:class:`~rabia_tpu.parallel.mesh_engine.MeshEngine` with
    ``device_store=True``): probe-covered GETs are answered by a
    consensus-free ``lookup_only`` dispatch against the device-resident
    table — zero consensus slots, zero collectives in the program —
    with meta-only readback (~5 bytes/op) and host-segment value
    resolution (value planes download only on the eviction edge).

    The handler also exposes ``read_many`` so the gateway batches ALL
    reads covered by one probe round into a SINGLE device dispatch: one
    plane fetch per probe window instead of one per read. With the
    device lane demoted, reads fall back to the host replica store (the
    semantics owner, synced at demotion). Drive the engine and this
    handler from one thread — the device table is not locked."""
    from rabia_tpu.apps.device_kv import _bucket, _get_frame

    def _host_one(shard: int, key: bytes) -> bytes:
        got = engine.sms[0].store.get(shard, key)
        if got is None:
            return _get_frame(False, 0, b"")
        val, ver = got
        return _get_frame(True, ver, val)

    def read_many(items: list) -> list:
        if engine._dev is None or not engine._dev_active:
            return [_host_one(s, k) for s, k in items]
        dev = engine._dev
        W = engine.window
        out: list = [None] * len(items)
        sel = []
        for i, (s, k) in enumerate(items):
            if len(k) > dev.K or not (0 <= s < engine.n_shards):
                # a key wider than the table's lanes cannot have been
                # SET while the lane is active (it would have demoted):
                # not-found by construction, no dispatch needed
                out[i] = _get_frame(False, 0, b"")
            else:
                sel.append(i)
        if not sel:
            return out
        # wave-pack: at most one key per shard per wave (the lookup
        # program's shape); duplicate-shard reads spill to later waves
        waves: list[dict] = []
        for i in sel:
            s = items[i][0]
            for used in waves:
                if s not in used:
                    used[s] = i
                    break
            else:
                waves.append({s: i})
        Ku = min(
            _bucket(max(len(items[i][1]) for i in sel)), dev.K
        )
        for c0 in range(0, len(waves), W):
            chunk = waves[c0 : c0 + W]
            depth = len(chunk)
            klen = np.zeros((depth, dev.S), np.int16)
            kwin = np.zeros((depth, dev.S, Ku), np.uint8)
            for t, wv in enumerate(chunk):
                for s, i in wv.items():
                    k = items[i][1]
                    klen[t, s] = len(k)
                    kwin[t, s, : len(k)] = np.frombuffer(k, np.uint8)
            found_d, ver_d, vlen_d, valw_d = dev.lookup_only(
                (klen, np.ascontiguousarray(kwin).view(np.uint32)),
                W=W,
                state=engine._dev_chain_base(),
            )
            found = np.asarray(found_d)
            ver = np.asarray(ver_d)
            n_ops = sum(len(wv) for wv in chunk)
            engine._read_stats["probe"] += n_ops
            engine._read_stats["probe_windows"] += 1
            engine._h_read_batch.observe(float(depth))
            if engine._dev_unresolvable(found[:depth], ver[:depth]):
                # eviction edge: this window downloads the value planes
                engine._read_stats["fallback"] += n_ops
                resolver = None
                vlen = np.asarray(vlen_d)
                valb = np.ascontiguousarray(np.asarray(valw_d)).view(
                    np.uint8
                )
            else:
                resolver = engine._dev_make_resolver()
            for t, wv in enumerate(chunk):
                for s, i in wv.items():
                    f, v = bool(found[t, s]), int(ver[t, s])
                    if not f:
                        out[i] = _get_frame(False, 0, b"")
                    elif resolver is not None:
                        out[i] = _get_frame(True, v, resolver(s, v))
                    else:
                        out[i] = _get_frame(
                            True, v,
                            valb[t, s, : int(vlen[t, s])].tobytes(),
                        )
        return out

    def read(shard: int, key: bytes) -> bytes:
        return read_many([(shard, key)])[0]

    read.read_many = read_many  # the gateway's batched probe-round seam
    return read


_COAL_SHARD_FIELDS = ("waves", "covered", "solo", "scalar", "results_ok")
_COAL_SHARD_ZERO = {f: 0 for f in _COAL_SHARD_FIELDS}


class _CoalesceWindow:
    """One shard's open coalescing window: parked FRESH submits (their
    session reservations held), running op/byte totals, and the armed
    flush timer."""

    __slots__ = ("entries", "ops", "size", "timer")

    def __init__(self) -> None:
        self.entries: list = []  # (sender NodeId, Submit, t0 perf_counter)
        self.ops = 0
        self.size = 0
        self.timer = None  # asyncio.TimerHandle while armed


class _ProbeRound:
    """One in-flight frontier probe: nonce, collected reply vectors, and
    the waiters served by this round."""

    __slots__ = ("nonce", "replies", "done", "waiters", "started_at")

    def __init__(self, nonce: int, waiters: list) -> None:
        self.nonce = nonce
        self.replies: dict[NodeId, np.ndarray] = {}
        self.done = asyncio.Event()
        self.waiters = waiters
        self.started_at = time.time()


class GatewayServer:
    """Client-facing service over one engine replica (see module doc)."""

    def __init__(
        self,
        engine,
        reader: Optional[ReadHandler] = None,
        config: Optional[GatewayConfig] = None,
        node_id: Optional[NodeId] = None,
    ) -> None:
        self.engine = engine
        self.config = config or GatewayConfig()
        self.node_id = node_id or NodeId.new()
        self.reader = reader if reader is not None else kv_read_handler(
            engine.sm
        )
        self.serializer = Serializer(engine.config.serialization)
        # the session/dedup table: the native C plane (sessionkernel.cpp)
        # when it builds, else the Python semantics owner
        # (RABIA_PY_GATEWAY=1 forces the latter)
        from rabia_tpu.gateway.native_session import make_session_table

        self.sessions = make_session_table(
            default_window=self.config.max_inflight_per_session,
            session_ttl=self.config.session_ttl,
            result_cache_cap=self.config.result_cache_cap,
            lease_ttl=self.config.session_lease,
        )
        self.stats = GatewayStats()
        self._net = None
        self._peer_gateways: dict[NodeId, tuple[str, int]] = {}
        self._frontier_event = asyncio.Event()
        self._round: Optional[_ProbeRound] = None
        self._round_waiters: list[asyncio.Future] = []
        self._probe_kick = asyncio.Event()
        self._nonce = 0
        self._fetches: dict[int, asyncio.Future] = {}
        self._fetch_nonce = 0
        # reads in flight by (client_id, seq): client retransmits of a
        # slow read must attach to the original, not spawn parallel
        # probe rounds + reader calls (the read twin of sess.inflight)
        self._reads_inflight: set[tuple[uuid.UUID, int]] = set()
        # reads waiting for the next shared probe round: every GET that
        # arrived before the round starts is served by THAT round (one
        # quorum probe amortized over the whole window, Velos-style one-
        # sided reads) — no per-read driver task, no per-read future
        self._pending_reads: list[tuple[NodeId, ReadIndex]] = []
        # cross-session coalescing lane: per-shard open windows of
        # parked FRESH submits + per-shard eligible-arrival-rate EWMAs
        # that size the adaptive flush window and gate parking — sparse
        # lanes skip the window entirely on EVERY cluster flavor (no
        # batching chance means only the latency tax, and a parked solo
        # submit can miss its proposer-eligibility instant; see
        # _coal_add). Durable clusters merely get a LONGER default
        # window (below), engaged only once traffic is dense.
        self._coal: dict[int, _CoalesceWindow] = {}
        self._coal_rate: dict[int, float] = {}
        self._coal_last_arrival: dict[int, float] = {}
        self._coal_window_cfg = (
            self.config.coalesce_window
            if self.config.coalesce_window is not None
            else (0.008 if getattr(engine, "_wal", None) is not None
                  else 0.002)
        )
        # ops budget clamped to what submit_block will accept, so a
        # packed entry can never bounce off the engine's validators
        self._coal_max_ops = max(1, min(
            self.config.coalesce_max_ops,
            engine.config.max_batch_size,
            engine.config.validation.max_commands_per_batch,
        ))
        # an over-limit command must fail ITS OWN submit on the classic
        # lane, not poison window-mates with a batch-level rejection
        self._coal_max_cmd = engine.config.validation.max_command_size
        self.coalesce_outcomes: dict[str, int] = {
            "coalesced": 0,  # submits that rode a multi-client wave
            "solo": 0,       # windows that flushed with one submit
            "bypass": 0,     # eligible lane on, submit not packable
            "sparse": 0,     # density gate: parking would not batch
        }
        # per-SHARD coalescing/commit counters (fleet observability):
        # the fleet aggregator groups these by ring shard ownership to
        # attribute coalesce density and slots/op to the fleet gateway
        # that concentrated the traffic. "waves"+"scalar" is the
        # slots-proposed proxy for the shard (each wave and each
        # per-submit drive proposes exactly one consensus entry);
        # "covered" counts submits riding waves, "results_ok" the OK
        # results fanned out.
        self.coal_shard_stats: dict[int, dict[str, int]] = {}
        # serialization ns credited inside the current gateway stage
        # bracket (carved out so the two stages never double-count)
        self._ser_carve = 0
        # tail-exemplar slowlog: the slowest fresh-Submit completions
        # per rotation window, served over AdminKind.SLOWLOG so the
        # critpath decomposer (obs/critpath.py) can pick p99 exemplars
        # without knowing any batch id in advance
        self.slowlog = _SlowlogReservoir(
            self.config.slowlog_cap, self.config.slowlog_window
        )
        self._tasks: set = set()
        self._running = False
        self._run_task = None
        self._probe_task = None
        self._http = None
        self._telemetry = None
        # admission-control outcomes by reason (exported as
        # rabia_gateway_shed_total{reason=...} — today's sheds were only
        # visible to the shedding client as RETRY)
        self.shed_reasons: dict[str, int] = {
            "session_window": 0,
            "queue_depth": 0,
            "no_quorum": 0,
            "engine_reject": 0,
            "group_range": 0,
        }
        # shard-group locality enforcement (fleet/groups.py): the
        # half-open global shard ranges this replica's group owns.
        # None = ungrouped. Mutable at runtime (set_group admin) so a
        # rebalance can widen the new owner BEFORE the route flips.
        self._group_ranges: Optional[list[tuple[int, int]]] = (
            [(int(lo), int(hi)) for lo, hi in self.config.group_shards]
            if self.config.group_shards is not None
            else None
        )
        # observability: the gateway registers into ITS ENGINE's registry
        # so one scrape covers the whole replica (engine + transport
        # counter block + gateway). Registration is idempotent by metric
        # identity, so a gateway restart on the same engine re-binds.
        self.metrics = engine.metrics
        self._register_metrics()

    def _register_metrics(self) -> None:
        m = self.metrics
        st = self.stats
        for name, help_ in (
            ("submits", "Submit frames received"),
            ("submits_deduped", "Duplicate (client_id, seq) submits"),
            ("submits_shed", "Submits shed by admission control"),
            ("reads", "Linearizable READ requests"),
            ("reads_failed", "READs failed (retryable or terminal)"),
            ("reads_batched", "READs served via the reader's read_many "
             "batch (one device-plane dispatch per probe round)"),
            ("probe_rounds", "Read-index frontier probe rounds"),
            ("results_sent", "Result frames sent to clients"),
            ("results_repaired", "Results repaired from peer gateways"),
            ("submits_coalesced", "Submits committed via multi-client waves"),
            ("coalesce_waves", "Multi-client coalesced waves proposed"),
        ):
            m.counter(
                f"gateway_{name}_total", help_,
                fn=lambda n=name: getattr(st, n),
            )
        m.gauge(
            "gateway_sessions", "Live client sessions",
            fn=lambda: len(self.sessions),
        )
        m.gauge(
            "gateway_reads_inflight", "READs currently being driven",
            fn=lambda: len(self._reads_inflight),
        )
        # admission-control outcomes, by reason (stats.submits_shed stays
        # the total; the labeled family makes shed behavior scrapeable)
        for reason in self.shed_reasons:
            m.counter(
                "gateway_shed_total",
                "Submits shed by admission control, by reason",
                {"reason": reason},
                fn=lambda r=reason: self.shed_reasons[r],
            )
        # native session plane: the GWC_* counter block (sessionkernel.cpp)
        # read zero-copy at scrape time, one family per counter — absent
        # entirely when the Python table owns the plane (scrapes tell the
        # active plane from rabia_gateway_plane_native too)
        m.gauge(
            "gateway_plane_native",
            "1 when the C session/dedup table owns the gateway plane",
            fn=lambda: 1.0 if self.sessions.is_native else 0.0,
        )
        # shard-group membership (fleet/groups.py): exported only on
        # grouped replicas so every series scraped from this process
        # attributes to its group (fleet-top / burn-rate labels join on
        # it); ungrouped deployments keep their metric surface unchanged
        if self.config.group_id is not None:
            m.gauge(
                "gateway_group",
                "Shard-group id this replica's consensus group serves",
                fn=lambda: float(self.config.group_id),
            )
            m.gauge(
                "gateway_group_shards",
                "Global shards currently owned by this replica's group",
                fn=lambda: float(sum(
                    hi - lo for lo, hi in (self._group_ranges or [])
                )),
            )
        if self.sessions.is_native:
            from rabia_tpu.gateway.native_session import GWC_COUNTER_NAMES

            for cname in GWC_COUNTER_NAMES:
                m.counter(
                    f"gateway_plane_{cname}_total",
                    "Native gateway session plane counter "
                    "(sessionkernel.cpp GWC block)",
                    fn=lambda c=cname: self.sessions.counters_dict().get(
                        c, 0
                    ),
                )
        # client-observed submit→result latency: the SLO evidence
        # plane's top stage (rabia_slo_seconds{stage="submit_result"}),
        # observed for every freshly driven submit — dedup cache hits
        # and sheds answer in microseconds and are counted by their own
        # families instead of skewing the commit-latency curve
        from rabia_tpu.obs.registry import SLO_BUCKETS

        self._h_submit_result = m.histogram(
            "slo_seconds",
            "SLO latency histograms by pipeline stage "
            "(log-bucketed; native RTH block + Python observes)",
            {"stage": "submit_result"},
            buckets=SLO_BUCKETS,
        )
        # cross-session coalescing lane (docs/OBSERVABILITY.md):
        # per-outcome submit counts and the submits-per-flush size
        # distribution. Slots-per-committed-op derives from these plus
        # the runtime's decided counters (rabia_engine_* / RKC block):
        # slots/op = Δdecided_v1 / Δ(ok results).
        for oc in self.coalesce_outcomes:
            m.counter(
                "coalesce_total",
                "Coalescing-lane submit outcomes "
                "(coalesced=rode a multi-client wave, solo=window of "
                "one, bypass=not packable)",
                {"outcome": oc},
                fn=lambda o=oc: self.coalesce_outcomes[o],
            )
        self._h_coal = m.histogram(
            "coalesce_batch_size",
            "Submits per coalescing-window flush (1 = solo)",
            buckets=[1, 2, 4, 8, 16, 32, 64, 128, 256],
        )
        # per-shard coalescing/commit counters, one series per (shard,
        # field) — the fleet aggregator's per-gateway attribution input
        # (see coal_shard_stats above). Registered for every shard up
        # front so scrapes see zeros, not absences, before traffic.
        for s in range(self.engine.n_shards):
            for fld in _COAL_SHARD_FIELDS:
                m.counter(
                    "coalesce_shard_total",
                    "Per-shard coalescing-lane counters "
                    "(waves=multi-client flushes, covered=submits "
                    "riding waves, solo=windows of one, scalar="
                    "per-submit proposals, results_ok=OK results)",
                    {"shard": str(s), "field": fld},
                    fn=lambda s=s, f=fld: self.coal_shard_stats.get(
                        s, _COAL_SHARD_ZERO
                    )[f],
                )

    # -- observability surface ----------------------------------------------

    def health(self) -> dict:
        """The /healthz document: the engine's health plus the gateway's
        client-facing view."""
        doc = self.engine.health()
        # the gateway plane joins the engine's plane ground truth (an
        # env toggle or a silent sessionkernel build failure both read
        # as "python" here — the loadgen CI gate checks this key)
        doc.setdefault("planes", {})["gateway"] = (
            "native" if self.sessions.is_native else "python"
        )
        doc["gateway"] = {
            "node": str(self.node_id.value),
            "port": self.port,
            "sessions": len(self.sessions),
            "peer_gateways": len(self._peer_gateways),
            "submits": self.stats.submits,
            "reads": self.stats.reads,
            "reads_batched": self.stats.reads_batched,
        }
        if self.config.group_id is not None:
            doc["gateway"]["group"] = {
                "id": self.config.group_id,
                "shards": [
                    [lo, hi] for lo, hi in (self._group_ranges or [])
                ],
            }
        return doc

    def _group_owns(self, shard: int) -> bool:
        if self._group_ranges is None:
            return True
        return any(lo <= shard < hi for lo, hi in self._group_ranges)

    def _admin_body(self, kind: int, query: bytes = b"") -> tuple[int, bytes]:
        import json

        if kind == AdminKind.METRICS:
            return 0, self.metrics.render_prometheus().encode()
        if kind == AdminKind.HEALTH:
            return 0, json.dumps(self.health()).encode()
        if kind == AdminKind.JOURNAL:
            jkind, last = None, 64
            if query:
                try:
                    q = json.loads(query)
                    jkind = q.get("kind")
                    last = max(0, int(q.get("last", 64)))
                except (ValueError, TypeError, AttributeError):
                    return 1, b"malformed journal query"
            return 0, json.dumps(
                {
                    "anomalies": self.engine.journal.snapshot(
                        limit=last, kind=jkind
                    )
                }
            ).encode()
        if kind == AdminKind.TRACE:
            # TraceQuery -> TraceSlice (obs/flight): the query names a
            # batch by session coordinates (ids derive deterministically,
            # so any replica can compute the hash) or by batch id hex
            from rabia_tpu.obs.flight import (
                batch_id_for,
                build_trace_slice,
            )

            try:
                q = json.loads(query) if query else {}
                if "batch" in q:
                    bid = uuid.UUID(hex=q["batch"])
                else:
                    bid = batch_id_for(
                        uuid.UUID(hex=q["client"]), int(q["seq"])
                    )
            except (ValueError, TypeError, KeyError):
                return 1, b"malformed trace query"
            doc = build_trace_slice(self.engine, fr_hash(bid))
            doc["gateway"] = str(self.node_id.value)
            doc["batch_id"] = bid.hex
            return 0, json.dumps(doc).encode()
        if kind == AdminKind.TIMELINE:
            if self._telemetry is None:
                return 1, b"telemetry sampler disabled"
            last = None
            if query:
                try:
                    last = json.loads(query).get("last")
                    # "last" is optional: {} serves the full ring, same
                    # as an empty query
                    if last is not None:
                        last = int(last)
                except (ValueError, TypeError, AttributeError):
                    return 1, b"malformed timeline query"
            return 0, json.dumps(self._telemetry.document(last)).encode()
        if kind == AdminKind.SLOWLOG:
            last = None
            if query:
                try:
                    last = json.loads(query).get("last")
                    if last is not None:
                        last = int(last)
                except (ValueError, TypeError, AttributeError):
                    return 1, b"malformed slowlog query"
            doc = self.slowlog.document(last)
            doc["node"] = str(self.node_id.value)
            return 0, json.dumps(doc).encode()
        if kind == AdminKind.RING:
            # the replica-side slice of the shard-group plane: a plain
            # get answers the group card; {"op": "set_group"} adopts
            # new owned ranges — the widen-the-new-owner-first step of
            # a group rebalance (fleet/groups.py), pushed BEFORE the
            # routing tier flips its GroupMap
            try:
                q = json.loads(query) if query else {}
            except (ValueError, TypeError):
                return 1, b"malformed ring query"
            if q.get("op") == "set_group":
                if self.config.group_id is None:
                    return 1, b"replica is not grouped"
                try:
                    ranges = [
                        (int(lo), int(hi)) for lo, hi in q["shards"]
                    ]
                except (ValueError, TypeError, KeyError):
                    return 1, b"malformed set_group ranges"
                for lo, hi in ranges:
                    if not (0 <= lo < hi <= self.engine.n_shards):
                        return 1, b"set_group range out of shard space"
                self._group_ranges = ranges
            return 0, json.dumps({
                "group": self.config.group_id,
                "shards": (
                    [[lo, hi] for lo, hi in self._group_ranges]
                    if self._group_ranges is not None
                    else None
                ),
                "n_shards": self.engine.n_shards,
                "node": str(self.node_id.value),
            }).encode()
        return 1, f"unknown admin kind {kind}".encode()

    def _on_admin(self, sender: NodeId, p: AdminRequest) -> None:
        """Serve one admin document as a framed response. Read-only and
        unauthenticated by design (same trust domain as the scrape shim);
        anything beyond the known kinds answers status=1."""
        if p.kind == AdminKind.TIMELINE and self._telemetry is not None:
            # an unbounded ring is up to cap (900) registry snapshots —
            # multi-MB of dict building + json.dumps; done inline it
            # stalls the loop driving submits/results and perturbs the
            # very curves the timeline measures. The document build only
            # touches the sampler's deque (already read from a foreign
            # thread by the sampler contract), so serve it off-loop.
            self._spawn(self._serve_admin_offloop(sender, p))
            return
        try:
            status, body = self._admin_body(p.kind, p.query)
        except Exception as e:  # a broken provider must still answer
            logger.exception("admin request failed")
            status, body = 1, f"admin handler failed: {e}".encode()
        self._send(
            AdminResponse(nonce=p.nonce, status=status, body=body), sender
        )

    async def _serve_admin_offloop(
        self, sender: NodeId, p: AdminRequest
    ) -> None:
        loop = asyncio.get_running_loop()
        try:
            status, body = await loop.run_in_executor(
                None, self._admin_body, p.kind, p.query
            )
        except Exception as e:  # a broken provider must still answer
            logger.exception("admin request failed")
            status, body = 1, f"admin handler failed: {e}".encode()
        self._send(
            AdminResponse(nonce=p.nonce, status=status, body=body), sender
        )

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        from rabia_tpu.net.tcp import TcpNetwork

        self._net = TcpNetwork(
            self.node_id,
            TcpNetworkConfig(
                bind_host=self.config.bind_host,
                bind_port=self.config.bind_port,
            ),
        )
        self.engine.add_frontier_listener(self._frontier_event.set)
        if self.config.telemetry_interval > 0 and self._telemetry is None:
            from rabia_tpu.obs import TelemetrySampler

            self._telemetry = TelemetrySampler(
                self.metrics,
                node=str(self.engine.node_id.value),
                interval=self.config.telemetry_interval,
                cap=self.config.telemetry_cap,
            ).start()
        if self.config.http_port is not None and self._http is None:
            from rabia_tpu.obs import AdminHTTPServer

            self._http = AdminHTTPServer(
                self.metrics,
                health_fn=self.health,
                journal=self.engine.journal,
                host=self.config.http_host,
                port=self.config.http_port,
                timeline_fn=(
                    (lambda last: self._telemetry.document(last))
                    if self._telemetry is not None
                    else None
                ),
            )
        self._running = True
        self._run_task = asyncio.ensure_future(self._run())
        self._probe_task = asyncio.ensure_future(self._probe_loop())

    @property
    def http_port(self) -> int:
        """Bound port of the observability HTTP shim (0 when disabled)."""
        return self._http.port if self._http is not None else 0

    @property
    def port(self) -> int:
        return self._net.port if self._net is not None else 0

    @property
    def endpoint(self) -> GatewayEndpoint:
        return GatewayEndpoint(
            self.node_id, self.config.bind_host, self.port
        )

    def add_peer_gateway(
        self, node_id: NodeId, host: str, port: int
    ) -> None:
        """Register a peer replica's gateway (read-index probe quorum)."""
        self._peer_gateways[node_id] = (host, port)
        self._net.add_peer(node_id, host, port)

    async def close(self) -> None:
        self._running = False
        # open coalescing windows: nothing in them was proposed — shed
        # the parked submits retryable while the transport still sends
        self._coal_abort_all()
        if self._http is not None:
            self._http.close()
            self._http = None
        if self._telemetry is not None:
            # final flush so the ring covers the run's last instant even
            # when the gateway closes between 1 Hz samples
            self._telemetry.sample()
            self._telemetry.close()
            self._telemetry = None
        self.engine.remove_frontier_listener(self._frontier_event.set)
        for t in (self._run_task, self._probe_task, *self._tasks):
            if t is not None:
                t.cancel()
        await asyncio.gather(
            *(t for t in (self._run_task, self._probe_task, *self._tasks) if t),
            return_exceptions=True,
        )
        self._tasks.clear()
        if self._net is not None:
            await self._net.close()
            self._net = None
        closer = getattr(self.sessions, "close", None)
        if closer is not None:
            # native plane: freeze the GWC counter block for late scrapes
            # and free the C table
            closer()

    def _spawn(self, coro) -> None:
        task = asyncio.ensure_future(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    # -- receive loop -------------------------------------------------------

    async def _run(self) -> None:
        pcns = time.perf_counter_ns
        last_gc = time.time()
        while self._running:
            try:
                sender, data = await self._net.receive(
                    timeout=self.config.gc_interval
                )
            except TimeoutError_:
                sender = None
            except asyncio.CancelledError:
                return
            if sender is not None:
                # stage profiler: the control-plane work the r09 profile
                # buried in `other` — codec time as "serialization",
                # dispatch + session/table work as "gateway"
                t0 = pcns()
                try:
                    msg = self.serializer.deserialize(data)
                except RabiaError as e:
                    self._stg_ser(pcns() - t0)
                    logger.warning(
                        "gateway %s: dropping bad frame from %s: %s",
                        self.node_id.short(),
                        sender,
                        e,
                    )
                else:
                    self._stg_ser(pcns() - t0)
                    t1 = pcns()
                    self._ser_carve = 0
                    self._handle(sender, msg)
                    self._stg_gw(pcns() - t1)
            now = time.time()
            if now - last_gc >= self.config.gc_interval:
                last_gc = now
                t0 = pcns()
                self.sessions.gc(self.engine.rt.state_version, now)
                self._ser_carve = 0
                self._stg_gw(pcns() - t0)

    # -- stage accounting (asyncio-owner control plane) ---------------------
    #
    # The gateway shares the engine's asyncio loop; its work used to land
    # in the runtime stage profiler's `other` remainder. These helpers
    # credit the named "serialization"/"gateway" stages on the ENGINE's
    # accounting (engine._stg_ext excludes the ns from `other`), with
    # serialization carved out of enclosing gateway brackets so nested
    # _send() serializes never double-count.

    def _stg_ser(self, ns: int) -> None:
        f = getattr(self.engine, "_stg_ext", None)
        if f is not None:
            self._ser_carve += ns
            f("serialization", ns)

    def _stg_gw(self, ns: int) -> None:
        f = getattr(self.engine, "_stg_ext", None)
        if f is not None:
            ns -= self._ser_carve
            self._ser_carve = 0
            if ns > 0:
                f("gateway", ns)

    def _stg_rp(self, ns: int) -> None:
        # "read_probe": serving probe-covered reads through the read
        # handler (the device read-index lane's host-side cost); nested
        # _send_result serializes carve out like the gateway bracket
        f = getattr(self.engine, "_stg_ext", None)
        if f is not None:
            ns -= self._ser_carve
            self._ser_carve = 0
            if ns > 0:
                f("read_probe", ns)

    def _handle(self, sender: NodeId, msg: ProtocolMessage) -> None:
        p = msg.payload
        if isinstance(p, (ClientHello, Submit)) or (
            isinstance(p, ReadIndex) and p.mode == ReadIndexMode.READ
        ):
            # a client's transport identity IS its session id (the client
            # library dials with NodeId(client_id)); mismatches would let
            # one client replay into another's session
            if sender.value != p.client_id:
                logger.warning(
                    "gateway %s: client frame session/transport mismatch "
                    "(%s via %s)",
                    self.node_id.short(),
                    p.client_id,
                    sender,
                )
                return
        if isinstance(p, ClientHello):
            self._on_hello(sender, p)
        elif isinstance(p, Submit):
            self._on_submit(sender, p)
        elif isinstance(p, ReadIndex):
            if p.mode == ReadIndexMode.READ:
                self._on_read(sender, p)
            elif p.mode == ReadIndexMode.PROBE:
                self._on_probe(sender, p)
            elif p.mode == ReadIndexMode.REPLY:
                self._on_probe_reply(sender, p)
            elif p.mode == ReadIndexMode.FETCH_RESULT:
                self._on_fetch_result(sender, p)
        elif isinstance(p, Result):
            # a peer gateway answering one of our result-repair fetches
            self._on_peer_result(sender, p)
        elif isinstance(p, AdminRequest):
            self._on_admin(sender, p)
        # anything else on the gateway port is noise; ignore

    def _send(self, payload, recipient: NodeId) -> None:
        msg = ProtocolMessage.new(self.node_id, payload, recipient)
        t0 = time.perf_counter_ns()
        data = self.serializer.serialize(msg)
        self._stg_ser(time.perf_counter_ns() - t0)
        try:
            self._net.send_to_nowait(recipient, data)
        except RabiaError:
            logger.warning(
                "gateway %s: send of %s to %s failed",
                self.node_id.short(),
                type(payload).__name__,
                recipient.short(),
            )

    def _send_result(
        self,
        recipient: NodeId,
        client_id: uuid.UUID,
        seq: int,
        status: int,
        payload: tuple[bytes, ...],
    ) -> None:
        self.stats.results_sent += 1
        self._send(
            Result(
                client_id=client_id, seq=seq, status=int(status),
                payload=payload,
            ),
            recipient,
        )

    # -- session / submit path ---------------------------------------------

    def _on_hello(self, sender: NodeId, p: ClientHello) -> None:
        window, last_seq = self.sessions.hello(p.client_id, p.max_inflight)
        self._send(
            ClientHello(
                client_id=p.client_id,
                ack=True,
                last_seq=last_seq,
                max_inflight=window,
            ),
            sender,
        )

    def _on_submit(self, sender: NodeId, p: Submit) -> None:
        self.stats.submits += 1
        # the submit hot path in ONE table op (native: one C call):
        # ensure/touch + ack advance + dedup classify + window check +
        # FRESH reservation
        decision, cstatus, cpayload = self.sessions.submit_check(
            p.client_id, p.seq, p.ack_upto
        )
        if decision == SUBMIT_DUP_CACHED:
            # exactly-once: a completed seq is answered from cache, never
            # re-proposed. OK results resend as CACHED so tests/clients
            # can observe the dedup; terminal errors resend as-is.
            self.stats.submits_deduped += 1
            status = (
                ResultStatus.CACHED
                if cstatus == ResultStatus.OK
                else cstatus
            )
            self._send_result(sender, p.client_id, p.seq, status, cpayload)
            return
        if decision == SUBMIT_DUP_INFLIGHT:
            # concurrent duplicate: the original proposal's completion
            # answers it (same commit, one apply)
            self.stats.submits_deduped += 1
            return
        if decision == SUBMIT_SHED_WINDOW:
            self.stats.submits_shed += 1
            self.shed_reasons["session_window"] += 1
            self._send_result(
                sender, p.client_id, p.seq, ResultStatus.RETRY,
                (b"backpressure: session window full",),
            )
            return
        assert decision == SUBMIT_FRESH
        if not (0 <= p.shard < self.engine.n_shards):
            # shard validation FIRST: the ledger lookup below indexes
            # rt.shards, and a malformed frame must answer (and release
            # its reservation), not raise out of the receive loop
            self.sessions.abort(p.client_id, p.seq)
            self._send_result(
                sender, p.client_id, p.seq, ResultStatus.ERROR,
                (b"shard out of range",),
            )
            return
        if not self._group_owns(p.shard):
            # group-locality fence (fleet/groups.py): RETRYABLE, not an
            # error — mid-rebalance a router's stale map can land one
            # in-flight submit here after this group shrank; the retry
            # re-resolves against the flipped map and reaches the new
            # owner, where the deterministic batch id dedups any replay
            self.sessions.abort(p.client_id, p.seq)
            self.stats.submits_shed += 1
            self.shed_reasons["group_range"] += 1
            self._send_result(
                sender, p.client_id, p.seq, ResultStatus.RETRY,
                (b"shard not owned by this group",),
            )
            return
        if not p.commands:
            # validate BEFORE the ledger dedup: an empty replay of an
            # applied seq must stay an error, not an OK with a
            # zero-truncated payload cached in the session table
            self.sessions.abort(p.client_id, p.seq)
            self._send_result(
                sender, p.client_id, p.seq, ResultStatus.ERROR,
                (b"empty submit",),
            )
            return
        # engine-ledger dedup BEFORE any proposal: a seq whose result
        # was evicted from the session cache (ack + GC, lease expiry,
        # session loss) re-arrives FRESH, but its deterministic batch id
        # may already be known applied — scalar commits and wave-lane
        # entry ids in applied_ids, coalesced-wave per-client ALIASES in
        # the proposer-local alias_ledger (kept out of applied_ids so
        # the apply-path dedup stays symmetric across replicas). Answer
        # from the ledger instead of burning a slot (and instead of
        # re-applying through the wave lane, which applies decided
        # waves unconditionally).
        bid = BatchId(batch_id_for(p.client_id, p.seq))
        sh = self.engine.rt.shards[p.shard]
        if bid in sh.applied_ids or bid in sh.alias_ledger:
            self.stats.submits_deduped += 1
            self._spawn(self._drive_ledger_replay(sender, p, bid, sh))
            return
        # -- admission control (shed BEFORE the engine sees the batch;
        # the FRESH reservation is released on every shed path) --
        if self.engine.pending_queue_depth() >= self.config.max_queue_depth:
            self.sessions.abort(p.client_id, p.seq)
            self.stats.submits_shed += 1
            self.shed_reasons["queue_depth"] += 1
            self._send_result(
                sender, p.client_id, p.seq, ResultStatus.RETRY,
                (b"backpressure: engine queue saturated",),
            )
            return
        if not self.engine.rt.has_quorum:
            self.sessions.abort(p.client_id, p.seq)
            self.stats.submits_shed += 1
            self.shed_reasons["no_quorum"] += 1
            self._send_result(
                sender, p.client_id, p.seq, ResultStatus.RETRY,
                (b"no quorum",),
            )
            return
        t0 = time.perf_counter()
        coal = self.config.coalesce and self._coal_eligible(p)
        # flight: the gateway-accept stamp (critpath's gateway_queue /
        # coalesce_park boundary; arg records the park decision)
        self.engine.flight.record(
            FRE_GW_RECV, shard=p.shard, arg=1 if coal else 0,
            batch=fr_hash(bid),
        )
        if coal:
            self._coal_add(sender, p, t0)
            return
        if self.config.coalesce:
            self.coalesce_outcomes["bypass"] += 1
        self._spawn(self._drive_submit(sender, p, t0))

    @staticmethod
    def _deterministic_batch(p: Submit) -> CommandBatch:
        """Build the consensus batch with ids derived from
        ``(client_id, seq)`` instead of fresh uuid4s. A replay of the
        same Submit — even after the gateway lost its session state
        (restart, cache eviction, session expiry) — therefore produces
        a byte-identical batch with the SAME batch id, and the engine's
        ``applied_ids`` dedup ledger blocks the double apply that a
        random id would slip past. The derivation lives in
        :func:`rabia_tpu.obs.flight.batch_id_for` (the trace collector
        names batches from session coordinates the same way)."""
        import hashlib


        seed = p.client_id.bytes + p.seq.to_bytes(8, "little")
        bid = batch_id_for(p.client_id, p.seq)
        cmds = [
            Command(
                id=uuid.UUID(
                    bytes=hashlib.blake2s(
                        seed + i.to_bytes(4, "little"), digest_size=16
                    ).digest()
                ),
                data=c,
            )
            for i, c in enumerate(p.commands)
        ]
        return CommandBatch(
            id=BatchId(bid), commands=tuple(cmds), shard=ShardId(p.shard)
        )

    def _wave_block(self, p: Submit):
        """Build the one-shard :class:`PayloadBlock` that routes this
        submit through the zero-handoff wave lane, or None when it must
        ride the scalar lane. Eligible when the native runtime owns the
        commit path, this replica is the rotation proposer at the
        shard's head RIGHT NOW, and every command is a binary op (the
        consensus wave-routing rule) — then decide→apply→result runs
        end-to-end in C (``waves_native`` grows, ``gil_handoffs`` stays
        flat), where the scalar lane pays one designed GIL handoff per
        decide. The block id is derived so the entry commits under the
        SAME deterministic ``(client_id, seq)`` batch id the scalar lane
        would use: replays dedup in ``applied_ids`` regardless of lane,
        even if the entry demotes mid-flight."""
        e = self.engine
        if getattr(e, "_rtm", None) is None:
            return None
        if not bool((e.proposer_eligible_shards() == p.shard).any()):
            return None
        from rabia_tpu.apps.native_store import binary_wave_eligible
        from rabia_tpu.core.blocks import block_id_for_batch, build_block

        blk = build_block(
            [p.shard], [list(p.commands)],
            block_id=block_id_for_batch(
                batch_id_for(p.client_id, p.seq), p.shard
            ),
        )
        if not binary_wave_eligible(
            blk.data, blk.cmd_offsets, blk.shard_starts, 1,
            np.arange(1),
        ):
            return None
        # self-alias: the per-submit wave registers its own (client_id,
        # seq)-derived id + responses in the applied ledger exactly like
        # a coalesced wave's covered clients — a replay after session-
        # state loss answers from the ledger instead of re-applying
        # through the wave lane (which never consults applied_ids)
        blk.aliases = {
            0: (
                (
                    batch_id_for(p.client_id, p.seq).bytes,
                    0, len(p.commands),
                ),
            )
        }
        return blk

    async def _drive_ledger_replay(
        self, sender: NodeId, p: Submit, bid, sh
    ) -> None:
        """Answer a FRESH submit whose batch id is already in the
        engine's applied ledger: the commit happened in an earlier life
        of this session. Serve the recorded responses (or repair them
        from a peer) — NEVER re-propose."""
        responses = sh.applied_results.get(bid)
        if bid in sh.applied_results:
            if responses is None:
                # applied but deterministically rejected: that failure
                # is the true outcome of this seq
                status, payload = ResultStatus.ERROR, (b"apply failed",)
            else:
                status, payload = ResultStatus.OK, tuple(responses)
        else:
            # committed, responses not recorded here (a C-applied wave
            # on a ledger-recovered replica): try the peer repair lane
            # (which is terminal — it returns OK or ERROR, never RETRY)
            status, payload = await self._repair_result(bid, p.shard)
        if status == ResultStatus.OK:
            # the LEAD client of a coalesced entry replays under the
            # entry's own id, whose recorded/repaired responses may be
            # the FULL entry list (the scalar-demoted lane records the
            # whole entry under that id, and entry-level repair/settle
            # need it intact) — the lead's ops are the entry's PREFIX
            # by construction, so its own answers are the first
            # `count` responses. The count comes from the alias ledger
            # (recorded at apply time), NEVER from the replayed
            # Submit's arity: a replay with inflated command count must
            # not receive other covered clients' response slices. Post-
            # crash the recorded count is gone (K_LEDGER has no op
            # ranges) — fall back to the replayed arity, which can only
            # NARROW an over-long list, never widen the slice.
            count = sh.alias_ledger.get(bid)
            if count is None:
                count = len(p.commands)
            if len(payload) > count:
                payload = payload[:count]
        if status == ResultStatus.OK:
            wal = getattr(self.engine, "_wal", None)
            if wal is not None:
                # the ledger entry was written at APPLY time, possibly
                # ahead of the wave's fsync — an OK replay answer must
                # honor the same durability fence as every other OK
                # Result on a durable cluster
                try:
                    await wal.durability_barrier()
                except Exception as e:
                    status, payload = ResultStatus.ERROR, (
                        f"durability barrier failed: {e}".encode(),
                    )
        self.sessions.complete_op(
            p.client_id, p.seq, int(status), payload,
            self.engine.rt.state_version,
        )
        # a replayed commit resends as CACHED (the dedup observable),
        # matching the session-cache path's wire behavior
        wire_status = (
            ResultStatus.CACHED if status == ResultStatus.OK else status
        )
        self._send_result(sender, p.client_id, p.seq, wire_status, payload)

    # -- cross-session coalescing lane (docs/PERFORMANCE.md) ----------------
    #
    # Many sessions' FRESH binary-op Submits to one shard pack into ONE
    # PayloadBlock entry under the lead client's deterministic batch id:
    # one consensus slot, one apply, one result-staging pass, and on
    # durable clusters ONE durability-barrier wait for every covered
    # session. Every covered client's (client_id, seq)-derived id rides
    # the block as an ALIAS (core/blocks.py) so dedup/replay/K_LEDGER
    # stay exactly-once PER CLIENT with zero new wire semantics.

    def _coal_eligible(self, p: Submit) -> bool:
        """Packable: every command a binary KV op (opcodes 1..6 — the
        wave-routing rule) and the submit alone within the ops budget.
        Everything else rides the classic per-submit lane."""
        cmds = p.commands
        if not cmds or len(cmds) > self._coal_max_ops:
            return False
        for c in cmds:
            if not c or not (1 <= c[0] <= 6) or len(c) > self._coal_max_cmd:
                return False
        return True

    def _coal_window_s(self, shard: int) -> float:
        """Adaptive flush window: aim to collect several submits at the
        shard's eligible-arrival rate, floored and capped by config (the
        cap IS the per-submit latency budget)."""
        cfg = self.config
        rate = self._coal_rate.get(shard, 0.0)
        if rate <= 1.0:
            return self._coal_window_cfg
        return min(
            self._coal_window_cfg,
            max(cfg.coalesce_window_min, 8.0 / rate),
        )

    def _coal_add(self, sender: NodeId, p: Submit, t0: float) -> None:
        """Park one FRESH eligible submit in its shard's window (the
        session reservation from submit_check is HELD while parked, so
        client retransmits attach as DUP_INFLIGHT). Sparse lanes drive
        straight through instead: with no realistic chance of a window
        companion, parking buys nothing and can cost a lot more than
        the window itself — a parked submit can MISS its shard's
        proposer-eligibility instant and demote to the forwarded scalar
        path (measured: +50ms p50 at 30/s on a 3-gateway cluster)."""
        # per-shard arrival-rate EWMA (adaptive window + density gate)
        s = p.shard
        last = self._coal_last_arrival.get(s, 0.0)
        self._coal_last_arrival[s] = t0
        rate = self._coal_rate.get(s, 0.0)
        dt = t0 - last
        if 0.0 < dt < 1.0:
            rate += 0.2 * ((1.0 / dt) - rate)
        else:
            rate *= 0.5
        self._coal_rate[s] = rate
        w = self._coal.get(s)
        if w is None and rate * self._coal_window_cfg < 0.5:
            self.coalesce_outcomes["sparse"] += 1
            self._spawn(self._drive_submit(sender, p, t0))
            return
        n_ops = len(p.commands)
        n_bytes = sum(len(c) for c in p.commands)
        if w is not None and w.entries and (
            w.ops + n_ops > self._coal_max_ops
            or w.size + n_bytes > self.config.coalesce_max_bytes
        ):
            # budget would overflow: flush what is parked, start fresh
            self._coal_flush(s)
            w = None
        if w is None:
            w = self._coal[s] = _CoalesceWindow()
        w.entries.append((sender, p, t0))
        w.ops += n_ops
        w.size += n_bytes
        if (
            w.ops >= self._coal_max_ops
            or w.size >= self.config.coalesce_max_bytes
        ):
            self._coal_flush(s)
            return
        if w.timer is None:
            w.timer = asyncio.get_event_loop().call_later(
                self._coal_window_s(s), self._coal_flush_timed, s
            )

    def _coal_flush_timed(self, shard: int) -> None:
        """Timer-fired flush: bracket the assembly work for the stage
        profiler (the _on_submit path is already inside a bracket)."""
        t0 = time.perf_counter_ns()
        self._ser_carve = 0
        self._coal_flush(shard)
        self._stg_gw(time.perf_counter_ns() - t0)

    def _coal_flush(self, shard: int) -> None:
        w = self._coal.pop(shard, None)
        if w is None:
            return
        # a coalesced PayloadBlock must NEVER span groups: windows key
        # per shard (structural), and on a grouped replica the flushed
        # shard must sit inside the owned ranges — asserted, not
        # assumed (admission fences every parked submit, and set_group
        # only ever WIDENS before routing flips toward a group)
        assert self._group_owns(shard), (
            f"coalesce window for shard {shard} outside group "
            f"{self.config.group_id} ranges {self._group_ranges}"
        )
        if w.timer is not None:
            w.timer.cancel()
            w.timer = None
        entries = w.entries
        self._h_coal.observe(len(entries))
        cs = self._coal_shard(shard)
        if len(entries) == 1:
            # window of one: the classic lane is strictly cheaper (and
            # keeps the zero-handoff per-submit wave path hot)
            self.coalesce_outcomes["solo"] += 1
            cs["solo"] += 1
            sender, p, t0 = entries[0]
            self._spawn(self._drive_submit(sender, p, t0))
            return
        self.coalesce_outcomes["coalesced"] += len(entries)
        self.stats.submits_coalesced += len(entries)
        self.stats.coalesce_waves += 1
        cs["waves"] += 1
        cs["covered"] += len(entries)
        self._spawn(self._drive_coalesced(shard, entries))

    def _coal_shard(self, shard: int) -> dict:
        cs = self.coal_shard_stats.get(shard)
        if cs is None:
            cs = self.coal_shard_stats[shard] = dict(_COAL_SHARD_ZERO)
        return cs

    def _coal_abort_all(self, notify: bool = True) -> None:
        """Tear down every open window (gateway close): release the
        session reservations and shed the parked submits retryable —
        nothing was proposed, so a client retry is FRESH everywhere."""
        for s in list(self._coal):
            w = self._coal.pop(s)
            if w.timer is not None:
                w.timer.cancel()
            for sender, p, _t0 in w.entries:
                self.sessions.abort(p.client_id, p.seq)
                if notify:
                    self._send_result(
                        sender, p.client_id, p.seq, ResultStatus.RETRY,
                        (b"gateway closing",),
                    )

    async def _drive_coalesced(self, shard: int, entries: list) -> None:
        """Commit ONE multi-client wave and fan its Result slices out to
        every covered session (the coalescing twin of _drive_submit)."""
        pcns = time.perf_counter_ns
        tb = pcns()
        from rabia_tpu.core.blocks import block_id_for_batch, build_block

        flat: list[bytes] = []
        ranges: list[tuple[int, int]] = []
        for _sender, p, _t0 in entries:
            lo = len(flat)
            flat.extend(p.commands)
            ranges.append((lo, len(flat)))
        lead = entries[0][1]
        lead_bid = batch_id_for(lead.client_id, lead.seq)
        blk = build_block(
            [shard], [flat],
            block_id=block_id_for_batch(lead_bid, shard),
        )
        # EVERY covered client (lead included) aliases the entry with
        # its deterministic id + op range: the apply paths register
        # them in alias_ledger/applied_results/K_LEDGER
        blk.aliases = {
            0: tuple(
                (batch_id_for(p.client_id, p.seq).bytes, lo, hi)
                for (_s, p, _t), (lo, hi) in zip(entries, ranges)
            )
        }
        batch_id = blk.batch_id_for(0)  # == lead_bid by construction
        self._ser_carve = 0
        self._stg_gw(pcns() - tb)
        proposed = False
        status: int = ResultStatus.OK
        responses: Optional[list] = None
        payload_all: tuple[bytes, ...] = ()
        try:
            fut = await self.engine.submit_block(blk)
            proposed = True
            entry = (await fut)[0]
            if isinstance(entry, Exception):
                raise entry
            responses = list(entry)
        except asyncio.CancelledError:
            for _sender, p, _t0 in entries:
                self.sessions.abort(p.client_id, p.seq)
            raise
        except ResponsesUnavailableError:
            # committed, responses adopted away by a sync overtake:
            # repair the ENTRY once by its lead id, slice per client
            status, payload = await self._repair_result(batch_id, shard)
            if status == ResultStatus.OK:
                responses = list(payload)
                if len(responses) != len(flat):
                    status = ResultStatus.ERROR
                    payload_all = (
                        b"repaired responses misaligned with wave",
                    )
            else:
                payload_all = payload
        except RabiaError as e:
            if not proposed and e.is_retryable():
                # rejected before any proposal reached consensus: shed
                # every covered submit retryable
                for sender, p, _t0 in entries:
                    self.sessions.abort(p.client_id, p.seq)
                    self.stats.submits_shed += 1
                    self.shed_reasons["engine_reject"] += 1
                    self._send_result(
                        sender, p.client_id, p.seq, ResultStatus.RETRY,
                        (str(e).encode(),),
                    )
                return
            # post-proposal failures are terminal for every covered seq
            # (cached; clients retry under new seqs) — same contract as
            # the scalar lane
            status = ResultStatus.ERROR
            payload_all = (str(e).encode(),)
        # cross-session durability-barrier batching: the wave staged its
        # WAL record at apply, so ONE watermark wait here releases EVERY
        # covered session's Result frame
        wal = getattr(self.engine, "_wal", None)
        if wal is not None and status == ResultStatus.OK:
            try:
                await wal.durability_barrier(covered=len(entries))
                # flight: one barrier stamp per wave, keyed by the LEAD
                # batch hash (covered entries' traces merge the wave's
                # trace in — obs/critpath fetches both hashes)
                self.engine.flight.record(
                    FRE_BARRIER, shard=shard, batch=fr_hash(batch_id),
                )
            except Exception as e:
                status = ResultStatus.ERROR
                payload_all = (
                    f"durability barrier failed: {e}".encode(),
                )
        tc = pcns()
        self._ser_carve = 0
        if status == ResultStatus.OK:
            self._coal_shard(shard)["results_ok"] += len(entries)
        sv = self.engine.rt.state_version
        now = time.perf_counter()
        for (sender, p, t0), (lo, hi) in zip(entries, ranges):
            pay = (
                tuple(responses[lo:hi])
                if status == ResultStatus.OK and responses is not None
                else payload_all
            )
            self.sessions.complete_op(
                p.client_id, p.seq, int(status), pay, sv
            )
            self.engine.flight.record(
                FRE_RESULT, shard=shard, arg=int(status),
                batch=fr_hash(batch_id_for(p.client_id, p.seq)),
            )
            if t0:
                wall = now - t0
                self._h_submit_result.observe(wall)
                self.slowlog.observe(
                    wall,
                    {
                        "client": p.client_id.hex,
                        "seq": int(p.seq),
                        "batch": batch_id_for(p.client_id, p.seq).hex,
                        "wave": getattr(
                            batch_id, "value", batch_id
                        ).hex,
                        "shard": int(shard),
                        "status": int(status),
                        "coalesced": True,
                    },
                )
            self._send_result(sender, p.client_id, p.seq, status, pay)
        self._stg_gw(pcns() - tc)

    async def _drive_submit(
        self, sender: NodeId, p: Submit, t0: float = 0.0
    ) -> None:
        pcns = time.perf_counter_ns
        tb = pcns()
        # per-shard slots proxy: one per-submit drive = one proposal
        # attempt (engine-reject sheds inflate this by the shed count —
        # zero on a healthy run, and the aggregator's tolerance absorbs
        # fault-window noise)
        cs = self._coal_shard(p.shard)
        cs["scalar"] += 1
        blk = self._wave_block(p)
        if blk is None:
            batch = self._deterministic_batch(p)
            batch_id = batch.id
        else:
            batch = None
            batch_id = blk.batch_id_for(0)
        self._ser_carve = 0
        self._stg_gw(pcns() - tb)
        proposed = False
        try:
            if blk is not None:
                fut = await self.engine.submit_block(blk)
                proposed = True
                entry = (await fut)[0]
                if isinstance(entry, Exception):
                    # per-entry failures surface as values on the block
                    # future; re-raise into the scalar lane's handlers
                    # (sync overtake -> ResponsesUnavailableError ->
                    # peer repair, like the scalar path)
                    raise entry
                responses = entry
            else:
                fut = await self.engine.submit_batch(batch, p.shard)
                proposed = True
                responses = await fut
            status: int = ResultStatus.OK
            payload = tuple(responses)
        except asyncio.CancelledError:
            self.sessions.abort(p.client_id, p.seq)
            raise
        except ResponsesUnavailableError:
            # the batch COMMITTED but this replica adopted its slots via
            # snapshot sync — the responses exist on peers that applied
            # normally. Repair from a peer gateway; never re-propose.
            status, payload = await self._repair_result(batch_id, p.shard)
        except RabiaError as e:
            if not proposed and e.is_retryable():
                # rejected before any proposal reached consensus: shed
                # retryable, nothing to dedup against
                self.sessions.abort(p.client_id, p.seq)
                self.stats.submits_shed += 1
                self.shed_reasons["engine_reject"] += 1
                self._send_result(
                    sender, p.client_id, p.seq, ResultStatus.RETRY,
                    (str(e).encode(),),
                )
                return
            # post-proposal failures are terminal for this seq: the batch
            # MAY have committed (e.g. applied via snapshot sync with
            # responses unavailable) — a silent retry under the same seq
            # could double-apply under a fresh batch id, so the error is
            # cached and the client must use a new seq to retry
            status = ResultStatus.ERROR
            payload = (str(e).encode(),)
        # durability barrier (docs/DURABILITY.md): on a WAL cluster the
        # decided wave's record must survive an fsync BEFORE this seq's
        # result frame leaves the replica. The wave was staged at apply
        # (before the submit future settled, on both runtime paths), so
        # one group-amortized wait on the current watermark covers it.
        wal = getattr(self.engine, "_wal", None)
        if wal is not None and status == ResultStatus.OK:
            try:
                await wal.durability_barrier()
                self.engine.flight.record(
                    FRE_BARRIER, shard=p.shard, batch=fr_hash(batch_id),
                )
            except Exception as e:
                # lost durability must not ack: terminal for this seq
                # (cached; the client retries under a new seq)
                status = ResultStatus.ERROR
                payload = (f"durability barrier failed: {e}".encode(),)
        # result staging to the session plane: one table op drops the
        # inflight reservation and caches (status, payload, frontier) —
        # on the native plane the payload views (the apply plane's lazy
        # result frames) are packed once into the C-resident blob the
        # dedup path answers from, with no per-part Python bytes kept
        tc = pcns()
        self._ser_carve = 0
        if status == ResultStatus.OK:
            cs["results_ok"] += 1
        self.sessions.complete_op(
            p.client_id, p.seq, int(status), payload,
            self.engine.rt.state_version,
        )
        # flight: the commit timeline's terminal stage (the batch hash
        # ties it back to submit/propose/decide/apply)
        self.engine.flight.record(
            FRE_RESULT, shard=p.shard, arg=int(status),
            batch=fr_hash(batch_id),
        )
        if t0:
            wall = time.perf_counter() - t0
            self._h_submit_result.observe(wall)
            self.slowlog.observe(
                wall,
                {
                    "client": p.client_id.hex,
                    "seq": int(p.seq),
                    "batch": getattr(batch_id, "value", batch_id).hex,
                    "wave": getattr(batch_id, "value", batch_id).hex,
                    "shard": int(p.shard),
                    "status": int(status),
                    "coalesced": False,
                },
            )
        self._send_result(sender, p.client_id, p.seq, status, payload)
        self._stg_gw(pcns() - tc)

    # -- linearizable read path ---------------------------------------------

    def _on_read(self, sender: NodeId, p: ReadIndex) -> None:
        self.stats.reads += 1
        if not self.engine.rt.has_quorum:
            self.stats.reads_failed += 1
            self._send_result(
                sender, p.client_id, p.seq, ResultStatus.RETRY,
                (b"no quorum",),
            )
            return
        if not (0 <= p.shard < self.engine.n_shards):
            self.stats.reads_failed += 1
            self._send_result(
                sender, p.client_id, p.seq, ResultStatus.ERROR,
                (b"shard out of range",),
            )
            return
        key = (p.client_id, p.seq)
        if key in self._reads_inflight:
            return  # retransmit of a slow read: the original answers
        self._reads_inflight.add(key)
        # queue for the NEXT shared probe round (a round already in
        # flight started before this read arrived, so its frontiers may
        # predate writes the read must observe). No per-read task, no
        # per-read future: the round serves the whole window.
        self._pending_reads.append((sender, p))
        self._probe_kick.set()

    def _fail_read(self, sender: NodeId, p: ReadIndex, status: int,
                   text: bytes) -> None:
        self.stats.reads_failed += 1
        self._reads_inflight.discard((p.client_id, p.seq))
        self._send_result(sender, p.client_id, p.seq, status, (text,))

    def _serve_read(self, sender: NodeId, p: ReadIndex) -> None:
        """Serve one read whose read index the applied frontier already
        covers (synchronous: one reader call, one result frame)."""
        try:
            data = self.reader(p.shard, p.key)
        except Exception as e:
            # the reader is a pluggable seam (device-KV handlers can
            # fail transiently): the client must get a frame, never
            # silence — a dropped read would make it retransmit forever
            logger.warning(
                "gateway %s: read handler failed for shard %d: %s",
                self.node_id.short(), p.shard, e,
            )
            self._fail_read(
                sender, p, ResultStatus.ERROR,
                f"read handler failed: {e}".encode(),
            )
            return
        self._reads_inflight.discard((p.client_id, p.seq))
        self._send_result(
            sender, p.client_id, p.seq, ResultStatus.OK, (data,)
        )

    async def _acquire_read_index(self) -> np.ndarray:
        """Join the NEXT probe round as a bare frontier waiter (non-read
        callers, tests)."""
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._round_waiters.append(fut)
        self._probe_kick.set()
        return await fut

    async def _probe_loop(self) -> None:
        while self._running:
            try:
                await self._probe_kick.wait()
            except asyncio.CancelledError:
                return
            self._probe_kick.clear()
            if not self._round_waiters and not self._pending_reads:
                continue
            waiters, self._round_waiters = self._round_waiters, []
            reads, self._pending_reads = self._pending_reads, []
            try:
                frontier = await self._run_probe_round(waiters)
            except asyncio.CancelledError:
                for w in waiters:
                    if not w.done():
                        w.set_exception(
                            TimeoutError_("read-index probe cancelled")
                        )
                for sender, p in reads:
                    self._fail_read(
                        sender, p, ResultStatus.RETRY,
                        b"read-index probe cancelled",
                    )
                return
            except RabiaError as e:
                for w in waiters:
                    if not w.done():
                        w.set_exception(e)
                for sender, p in reads:
                    self._fail_read(
                        sender, p, ResultStatus.RETRY, str(e).encode()
                    )
                continue
            for w in waiters:
                if not w.done():
                    w.set_result(frontier)
            if reads:
                self._finish_reads(reads, frontier)

    def _finish_reads(self, reads: list, frontier: np.ndarray) -> None:
        """Serve every read of one probe round: reads whose shard's
        applied frontier already covers its read index answer inline
        (zero additional tasks — the common case on a healthy replica);
        the rest group into ONE waiter task per shard."""
        rt = self.engine.rt
        inline: list = []
        deferred: dict[int, list] = {}
        for sender, p in reads:
            target = int(frontier[p.shard])
            if rt.applied_upto[p.shard] >= target:
                inline.append((sender, p))
            else:
                deferred.setdefault(p.shard, []).append(
                    (sender, p, target)
                )
        if inline:
            self._serve_reads_batch(inline)
        for shard, items in deferred.items():
            self._spawn(self._serve_deferred_reads(shard, items))

    def _serve_reads_batch(self, pairs: list) -> None:
        """Serve every probe-covered read of one round in ONE handler
        call when the reader exposes ``read_many`` (the device-plane
        batched seam: all GETs of the probe window become a single
        ``lookup_only`` dispatch — one plane fetch per probe round
        instead of one per read). Handlers without the batch seam fall
        back to per-read serving."""
        rm = getattr(self.reader, "read_many", None)
        if rm is None or len(pairs) == 1:
            t0 = time.perf_counter_ns()
            for sender, p in pairs:
                self._serve_read(sender, p)
            self._stg_rp(time.perf_counter_ns() - t0)
            return
        t0 = time.perf_counter_ns()
        try:
            frames = rm([(p.shard, p.key) for _, p in pairs])
        except Exception as e:
            logger.warning(
                "gateway %s: batched read handler failed: %s",
                self.node_id.short(), e,
            )
            for sender, p in pairs:
                self._fail_read(
                    sender, p, ResultStatus.ERROR,
                    f"read handler failed: {e}".encode(),
                )
            self._stg_rp(time.perf_counter_ns() - t0)
            return
        self.stats.reads_batched += len(pairs)
        for (sender, p), data in zip(pairs, frames):
            self._reads_inflight.discard((p.client_id, p.seq))
            self._send_result(
                sender, p.client_id, p.seq, ResultStatus.OK, (data,)
            )
        self._stg_rp(time.perf_counter_ns() - t0)

    async def _serve_deferred_reads(self, shard: int, items: list) -> None:
        """One apply-frontier wait covers every deferred read of the
        round on this shard (targets share the round's frontier, so the
        max dominates)."""
        target = max(t for _, _, t in items)
        try:
            await self._await_applied(shard, target)
        except RabiaError as e:
            for sender, p, _ in items:
                self._fail_read(
                    sender, p, ResultStatus.RETRY, str(e).encode()
                )
            return
        except asyncio.CancelledError:
            for sender, p, _ in items:
                self._reads_inflight.discard((p.client_id, p.seq))
            raise
        self._serve_reads_batch([(sender, p) for sender, p, _ in items])

    async def _run_probe_round(self, waiters: list) -> np.ndarray:
        self.stats.probe_rounds += 1
        frontier = self.engine.decided_frontier().astype(np.int64)
        need = self.engine.cluster.quorum_size - 1
        if need <= 0:
            return frontier  # single-replica cluster: self IS a quorum
        if len(self._peer_gateways) < need:
            raise TimeoutError_("read-index: not enough peer gateways")
        self._nonce += 1
        round_ = _ProbeRound(self._nonce, waiters)
        self._round = round_
        probe = ReadIndex(
            mode=int(ReadIndexMode.PROBE),
            client_id=self.node_id.value,
            seq=round_.nonce,
        )
        for peer in self._peer_gateways:
            self._send(probe, peer)
        try:
            await asyncio.wait_for(
                round_.done.wait(), self.config.probe_timeout
            )
        except asyncio.TimeoutError:
            raise TimeoutError_(
                "read-index probe", self.config.probe_timeout
            ) from None
        finally:
            self._round = None
        for vec in round_.replies.values():
            m = min(len(vec), len(frontier))
            np.maximum(frontier[:m], vec[:m], out=frontier[:m])
        return frontier

    def _on_probe(self, sender: NodeId, p: ReadIndex) -> None:
        # answer only known peer gateways: the frontier is engine state
        if sender not in self._peer_gateways:
            return
        self._send(
            ReadIndex(
                mode=int(ReadIndexMode.REPLY),
                client_id=self.node_id.value,
                seq=p.seq,
                frontier=tuple(
                    int(x) for x in self.engine.decided_frontier()
                ),
            ),
            sender,
        )

    def _on_probe_reply(self, sender: NodeId, p: ReadIndex) -> None:
        if sender not in self._peer_gateways:
            return
        round_ = self._round
        if round_ is None or p.seq != round_.nonce:
            return  # stale reply from an expired round
        round_.replies[sender] = np.asarray(p.frontier, np.int64)
        if len(round_.replies) >= self.engine.cluster.quorum_size - 1:
            round_.done.set()

    # -- result repair (committed, responses lost to a sync overtake) -------

    def _on_fetch_result(self, sender: NodeId, p: ReadIndex) -> None:
        """A peer gateway asks for a committed batch's applied responses
        (its replica adopted the slots via snapshot sync and never ran
        the apply). ``key`` is the 16-byte batch id."""
        if sender not in self._peer_gateways:
            return
        status, payload = ResultStatus.RETRY, ()  # unknown here
        if len(p.key) == 16 and 0 <= p.shard < self.engine.n_shards:
            sh = self.engine.rt.shards[p.shard]
            bid = BatchId(uuid.UUID(bytes=p.key))
            if bid in sh.applied_results:
                responses = sh.applied_results[bid]
                if responses is None:
                    # applied here too, but the state machine rejected it
                    # deterministically: the failure is the true outcome
                    status, payload = ResultStatus.ERROR, (b"apply failed",)
                else:
                    status, payload = ResultStatus.OK, tuple(responses)
        self._send_result(sender, self.node_id.value, p.seq, status, payload)

    def _on_peer_result(self, sender: NodeId, p: Result) -> None:
        if sender not in self._peer_gateways:
            return
        fut = self._fetches.get(p.seq)
        if fut is not None and not fut.done():
            fut.set_result(p)

    async def _repair_result(
        self, batch_id, shard: int
    ) -> tuple[int, tuple[bytes, ...]]:
        """Fetch a committed batch's responses from peer gateways — never
        re-proposes, so exactly-once is preserved. Returns (status,
        payload); ERROR with a diagnostic when no peer holds them."""
        for peer in list(self._peer_gateways):
            self._fetch_nonce += 1
            nonce = self._fetch_nonce
            fut: asyncio.Future = asyncio.get_event_loop().create_future()
            self._fetches[nonce] = fut
            try:
                self._send(
                    ReadIndex(
                        mode=int(ReadIndexMode.FETCH_RESULT),
                        client_id=self.node_id.value,
                        seq=nonce,
                        shard=shard,
                        key=batch_id.value.bytes,
                    ),
                    peer,
                )
                res = await asyncio.wait_for(
                    fut, self.config.probe_timeout
                )
            except asyncio.TimeoutError:
                continue
            finally:
                self._fetches.pop(nonce, None)
            if res.status == ResultStatus.OK:
                self.stats.results_repaired += 1
                return ResultStatus.OK, tuple(res.payload)
            if res.status == ResultStatus.ERROR:
                return ResultStatus.ERROR, tuple(res.payload)
            # RETRY: this peer doesn't hold it either; try the next
        return ResultStatus.ERROR, (
            b"committed but responses unavailable cluster-wide",
        )

    async def _await_applied(self, shard: int, target: int) -> None:
        """Block until the local applied frontier covers ``target`` on
        ``shard`` (event-driven via the engine's frontier hook, with a
        coarse poll guard)."""
        rt = self.engine.rt
        if rt.applied_upto[shard] >= target:
            return
        loop = asyncio.get_event_loop()
        deadline = loop.time() + self.config.read_timeout
        while rt.applied_upto[shard] < target:
            left = deadline - loop.time()
            if left <= 0:
                raise TimeoutError_(
                    "read-index apply wait", self.config.read_timeout
                )
            self._frontier_event.clear()
            if rt.applied_upto[shard] >= target:
                return
            try:
                await asyncio.wait_for(
                    self._frontier_event.wait(), min(left, 0.05)
                )
            except asyncio.TimeoutError:
                pass
