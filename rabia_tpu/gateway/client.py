"""RabiaClient: asyncio client library for the gateway protocol.

Talks to a :class:`~rabia_tpu.gateway.server.GatewayServer` over the
native transport. The client's transport node id IS ``NodeId(client_id)``
— the gateway authenticates every frame's session against the
transport-level sender, and replies route back on the same identity
across reconnects.

Reliability model:

- every command gets a session-unique monotonically increasing ``seq``;
- unanswered frames are re-sent every ``retry_interval`` (the gateway's
  session table dedups, so re-sending is always safe);
- a lost connection is redialed transparently (rotating through the
  configured endpoints) and every pending seq is replayed after the
  hello handshake — committed commands come back from the session cache
  (``CACHED``) instead of re-applying;
- ``RETRY`` results (admission control) surface as
  :class:`BackpressureError` — a retryable ``StoreError`` — or are
  retried internally with backoff when ``retry_backpressure`` is on.
"""

from __future__ import annotations

import asyncio
import logging
import struct
import uuid
from typing import Iterable, Optional, Sequence

from rabia_tpu.apps.kvstore import StoreError, StoreErrorKind
from rabia_tpu.core.config import TcpNetworkConfig
from rabia_tpu.core.errors import NetworkError, RabiaError, TimeoutError_
from rabia_tpu.core.messages import (
    ClientHello,
    ProtocolMessage,
    ReadIndex,
    ReadIndexMode,
    Result,
    ResultStatus,
    Submit,
)
from rabia_tpu.core.serialization import Serializer
from rabia_tpu.core.types import NodeId, fast_uuid4
from rabia_tpu.gateway.server import GatewayEndpoint

logger = logging.getLogger("rabia_tpu.gateway.client")


class BackpressureError(StoreError):
    """The gateway shed this request (admission control). Retryable —
    back off and resubmit (same seq is safe; the session dedups)."""

    retryable = True

    def __init__(self, message: str = "") -> None:
        super().__init__(
            StoreErrorKind.StoreFull, message or "gateway backpressure"
        )


class GatewayError(RabiaError):
    """Terminal (non-retryable under the same seq) gateway-reported
    failure; retry semantically with a fresh command if appropriate."""


class _MuxLink:
    """Client side of the C transport's session-multiplex lane
    (net/tcp.MUX_MAGIC): one plain TCP connection handshaken with the
    mux magic id, every frame ``[u32 LE 16+len][16B session id]
    [payload]`` both ways. Duck-types the slice of the TcpNetwork
    surface RabiaClient uses (add_peer / send_to_nowait / receive /
    get_connected_nodes / close), so the client's retry/redial machinery
    is transport-agnostic. The session id IS the client's node id — the
    gateway authenticates every frame against it, and the transport
    rebinds the session to the NEWEST connection carrying it (latest
    binding wins), which is exactly what makes redial rebinding work:
    a reconnected client's first frame reroutes all replies here.

    The wire contract (MUX_MAGIC handshake + per-frame session-id
    prefix) is owned by transport.cpp; the OTHER client-side speaker is
    :class:`rabia_tpu.testing.loadsession.MuxConn` (a shared-connection
    pool for thousands of loadgen sessions — a different shape from this
    single-session link, hence two speakers of one 3-line framing)."""

    def __init__(self, node_id: NodeId) -> None:
        self.node_id = node_id
        self._sid = node_id.value.bytes
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self._gateway: Optional[NodeId] = None
        self._q: asyncio.Queue = asyncio.Queue()
        self._dial_task: Optional[asyncio.Task] = None
        self._read_task: Optional[asyncio.Task] = None
        self._target: Optional[tuple[str, int]] = None
        self._dead = False

    def add_peer(self, peer: NodeId, host: str, port: int) -> None:
        # `peer` is advisory (the endpoint's configured id): the live
        # identity comes back in the handshake
        self._target = (host, port)
        self._dial_task = asyncio.ensure_future(self._dial())

    async def _dial(self) -> None:
        from rabia_tpu.net.tcp import MUX_MAGIC

        try:
            host, port = self._target  # type: ignore[misc]
            reader, writer = await asyncio.open_connection(host, port)
            writer.write(MUX_MAGIC)
            gw = await reader.readexactly(16)
            self._gateway = NodeId(uuid.UUID(bytes=gw))
            self.reader, self.writer = reader, writer
            self._read_task = asyncio.ensure_future(self._read_loop())
        except Exception:
            self._dead = True

    async def _read_loop(self) -> None:
        try:
            while True:
                hdr = await self.reader.readexactly(4)
                (ln,) = struct.unpack("<I", hdr)
                data = await self.reader.readexactly(ln)
                if ln < 16 or data[:16] != self._sid:
                    continue  # another session's frame (never ours to see)
                self._q.put_nowait((self._gateway, data[16:]))
        except (asyncio.IncompleteReadError, asyncio.CancelledError,
                ConnectionError, OSError):
            self._dead = True

    def send_to_nowait(self, recipient: NodeId, data: bytes) -> bool:
        w = self.writer
        if w is None or self._dead:
            return False  # the hello/retry loops re-send after connect
        try:
            w.write(struct.pack("<I", 16 + len(data)) + self._sid + data)
        except Exception:
            self._dead = True
            return False
        return True

    async def receive(self, timeout: Optional[float] = None):
        if timeout is None:
            return await self._q.get()
        try:
            return await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            raise TimeoutError_("mux receive", timeout) from None

    async def get_connected_nodes(self) -> set[NodeId]:
        if self._dead or self._gateway is None or self.writer is None:
            return set()
        if self.writer.is_closing():
            return set()
        return {self._gateway}

    async def close(self) -> None:
        for t in (self._dial_task, self._read_task):
            if t is not None:
                t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass
        if self.writer is not None:
            try:
                self.writer.close()
                await self.writer.wait_closed()
            except Exception:
                pass
        self.writer = None


class RabiaClient:
    """Exactly-once client over the gateway protocol (see module doc)."""

    def __init__(
        self,
        endpoints: Sequence[GatewayEndpoint],
        client_id: Optional[uuid.UUID] = None,
        max_inflight: int = 0,
        call_timeout: float = 15.0,
        retry_interval: float = 0.5,
        retry_backpressure: bool = True,
        backpressure_base_delay: float = 0.02,
        max_backpressure_retries: int = 200,
        mux: bool = False,
    ) -> None:
        if not endpoints:
            raise ValueError("at least one gateway endpoint required")
        self.endpoints = list(endpoints)
        # opt-in session-mux lane: ride the C transport's multiplexed
        # connection class (one plain socket + the MUX_MAGIC handshake)
        # instead of a private native transport instance per client —
        # the 10^4-clients-per-host deployment shape. Exactly-once
        # semantics are unchanged: the session id stays the client id,
        # redials rebind the session to the newest connection.
        self.mux = bool(mux)
        self.client_id = client_id or fast_uuid4()
        self.node_id = NodeId(self.client_id)
        self.call_timeout = call_timeout
        self.retry_interval = retry_interval
        self.retry_backpressure = retry_backpressure
        self.backpressure_base_delay = backpressure_base_delay
        self.max_backpressure_retries = max_backpressure_retries
        self.max_inflight = max_inflight
        self.serializer = Serializer()
        self._net = None
        self._recv_task = None
        self._endpoint_idx = 0
        self._gateway: Optional[GatewayEndpoint] = None
        self._seq = 0
        self._ack_upto = 0  # highest contiguously acknowledged seq
        self._acked: set[int] = set()
        self._pending: dict[int, tuple[asyncio.Future, object]] = {}
        self._hello_fut: Optional[asyncio.Future] = None
        self.server_window = 0
        self.server_last_seq = 0
        self.reconnects = 0
        self.cached_replies = 0  # results answered from the session cache
        self.moved_redirects = 0  # fleet MOVED redirects followed
        self._conn_lock = asyncio.Lock()

    # -- connection management ---------------------------------------------

    async def connect(self, timeout: float = 5.0) -> None:
        """Dial a gateway and complete the session handshake, rotating
        through the configured endpoints until one answers. A no-op when
        the current link is already live (so N concurrent calls that all
        noticed the same dead link trigger ONE redial, not N)."""
        async with self._conn_lock:
            if await self._link_alive():
                return
            await self._connect_locked(timeout)

    async def _connect_locked(self, timeout: float) -> None:
        from rabia_tpu.net.tcp import TcpNetwork

        last_err: Optional[Exception] = None
        for _ in range(len(self.endpoints)):
            ep = self.endpoints[self._endpoint_idx % len(self.endpoints)]
            self._endpoint_idx += 1
            await self._teardown_net()
            try:
                if self.mux:
                    self._net = _MuxLink(self.node_id)
                else:
                    self._net = TcpNetwork(
                        self.node_id, TcpNetworkConfig(bind_port=0)
                    )
                self._net.add_peer(ep.node_id, ep.host, ep.port)
                self._recv_task = asyncio.ensure_future(self._recv_loop())
                self._hello_fut = asyncio.get_event_loop().create_future()
                deadline = asyncio.get_event_loop().time() + timeout
                # re-send the hello until the ack lands (the dial itself
                # is async inside the native transport)
                while True:
                    self._send(
                        ClientHello(
                            client_id=self.client_id,
                            max_inflight=self.max_inflight,
                        ),
                        ep.node_id,
                    )
                    left = deadline - asyncio.get_event_loop().time()
                    if left <= 0:
                        raise TimeoutError_("gateway hello", timeout)
                    try:
                        await asyncio.wait_for(
                            asyncio.shield(self._hello_fut),
                            min(left, 0.25),
                        )
                        break
                    except asyncio.TimeoutError:
                        continue
                self._gateway = ep
                # replay everything unanswered, in seq order — the
                # gateway session dedups anything that already committed
                for seq in sorted(self._pending):
                    self._send_pending(seq)
                return
            except (RabiaError, OSError) as e:
                last_err = e
                continue
        await self._teardown_net()
        raise NetworkError(f"no gateway reachable: {last_err}")

    async def _teardown_net(self) -> None:
        if self._recv_task is not None:
            self._recv_task.cancel()
            try:
                await self._recv_task
            except (asyncio.CancelledError, Exception):
                pass
            self._recv_task = None
        if self._net is not None:
            try:
                await self._net.close()
            except Exception:
                pass
            self._net = None

    async def close(self) -> None:
        async with self._conn_lock:
            await self._teardown_net()
            for fut, _ in self._pending.values():
                if not fut.done():
                    fut.cancel()
            self._pending.clear()

    async def _reconnect(self) -> None:
        self.reconnects += 1
        await self.connect()

    def _connected(self) -> bool:
        return self._net is not None and self._gateway is not None

    # -- wire ---------------------------------------------------------------

    def _send(self, payload, recipient: NodeId) -> None:
        if self._net is None:
            return
        msg = ProtocolMessage.new(self.node_id, payload, recipient)
        try:
            self._net.send_to_nowait(
                recipient, self.serializer.serialize(msg)
            )
        except RabiaError:
            pass  # best-effort; the retry loop re-sends

    def _send_pending(self, seq: int) -> None:
        entry = self._pending.get(seq)
        if entry is not None and self._gateway is not None:
            self._send(entry[1], self._gateway.node_id)

    async def _recv_loop(self) -> None:
        net = self._net
        while True:
            try:
                sender, data = await net.receive()
            except asyncio.CancelledError:
                return
            except RabiaError:
                return  # transport closed under us; reconnect handles it
            try:
                msg = self.serializer.deserialize(data)
            except RabiaError:
                continue
            p = msg.payload
            if isinstance(p, ClientHello) and p.ack:
                self.server_window = p.max_inflight
                self.server_last_seq = p.last_seq
                if self._hello_fut is not None and not self._hello_fut.done():
                    self._hello_fut.set_result(p)
            elif isinstance(p, Result):
                if p.status == ResultStatus.CACHED:
                    self.cached_replies += 1
                entry = self._pending.get(p.seq)
                if entry is not None and not entry[0].done():
                    entry[0].set_result(p)

    # -- request machinery --------------------------------------------------

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    def _ack(self, seq: int) -> None:
        """Advance the contiguous ack frontier (the gateway GC hint)."""
        self._acked.add(seq)
        while (self._ack_upto + 1) in self._acked:
            self._ack_upto += 1
            self._acked.discard(self._ack_upto)

    async def _call(self, seq: int, frame) -> Result:
        """Send, await the Result, re-send on silence, reconnect on a
        dead link — until the call timeout."""
        loop = asyncio.get_event_loop()
        fut: asyncio.Future = loop.create_future()
        self._pending[seq] = (fut, frame)
        deadline = loop.time() + self.call_timeout
        try:
            self._send_pending(seq)
            while True:
                left = deadline - loop.time()
                if left <= 0:
                    raise TimeoutError_(f"gateway call seq={seq}",
                                        self.call_timeout)
                try:
                    return await asyncio.wait_for(
                        asyncio.shield(fut), min(left, self.retry_interval)
                    )
                except asyncio.TimeoutError:
                    if fut.done():
                        return fut.result()
                    # silence: maybe a lost frame, maybe a dead link
                    if not await self._link_alive():
                        try:
                            await self._reconnect()  # replays all pending
                        except NetworkError:
                            continue  # next cycle tries again
                    else:
                        self._send_pending(seq)
        finally:
            self._pending.pop(seq, None)

    async def _redirect(self, res: Result) -> None:
        """Follow a fleet-tier ``MOVED`` redirect: the payload names the
        shard's owning gateway (``b"host:port"``, 16-byte node id). The
        owner moves to the front of the endpoint rotation and the link
        redials it; the caller then re-sends the SAME seq there —
        exactly-once holds because the redirecting gateway reserved and
        proposed nothing (docs/FLEET.md)."""
        host, _, port = res.payload[0].decode().rpartition(":")
        node = (
            NodeId(uuid.UUID(bytes=bytes(res.payload[1])))
            if len(res.payload) > 1 and len(res.payload[1]) == 16
            else NodeId(fast_uuid4())  # transport learns the real id
        )
        ep = GatewayEndpoint(node_id=node, host=host, port=int(port))
        self.moved_redirects += 1
        async with self._conn_lock:
            self.endpoints = [ep] + [
                e for e in self.endpoints
                if (e.host, e.port) != (ep.host, ep.port)
            ]
            self._endpoint_idx = 0
            self._gateway = None
            await self._teardown_net()
            await self._connect_locked(5.0)

    async def _link_alive(self) -> bool:
        if self._net is None or self._gateway is None:
            return False
        try:
            connected = await self._net.get_connected_nodes()
        except Exception:
            return False
        return self._gateway.node_id in connected

    # -- public API ---------------------------------------------------------

    async def submit(
        self, shard: int, commands: Iterable[bytes]
    ) -> list[bytes]:
        """Propose a command batch on ``shard`` with exactly-once
        semantics; returns the committed per-command responses."""
        seq = self._next_seq()
        cmds = tuple(
            c if isinstance(c, bytes) else bytes(c) for c in commands
        )
        attempts = 0
        redirects = 0
        while True:
            frame = Submit(
                client_id=self.client_id,
                seq=seq,
                shard=shard,
                commands=cmds,
                ack_upto=self._ack_upto,
            )
            res = await self._call(seq, frame)
            if res.status in (ResultStatus.OK, ResultStatus.CACHED):
                self._ack(seq)
                return list(res.payload)
            if res.status == ResultStatus.MOVED:
                redirects += 1
                if redirects > 8:
                    raise GatewayError(
                        f"shard {shard}: MOVED redirect loop"
                    )
                await self._redirect(res)
                continue  # same seq to the named owner
            if res.status == ResultStatus.RETRY:
                attempts += 1
                if (
                    not self.retry_backpressure
                    or attempts > self.max_backpressure_retries
                ):
                    raise BackpressureError(
                        res.payload[0].decode() if res.payload else ""
                    )
                await asyncio.sleep(
                    min(1.0, self.backpressure_base_delay * attempts)
                )
                continue
            self._ack(seq)
            raise GatewayError(
                res.payload[0].decode() if res.payload else "gateway error"
            )

    async def get(self, shard: int, key: bytes | str) -> bytes:
        """Linearizable read: the gateway serves it via read-index against
        the decided frontier — no consensus slot is consumed. Returns the
        store's encoded result frame (see
        :func:`rabia_tpu.apps.kvstore.decode_result_bin`)."""
        seq = self._next_seq()
        kb = key.encode() if isinstance(key, str) else bytes(key)
        attempts = 0
        redirects = 0
        while True:
            frame = ReadIndex(
                mode=int(ReadIndexMode.READ),
                client_id=self.client_id,
                seq=seq,
                shard=shard,
                key=kb,
            )
            res = await self._call(seq, frame)
            if res.status in (ResultStatus.OK, ResultStatus.CACHED):
                # reads are not cached gateway-side, but their seqs share
                # the session counter: ack them too or the contiguous ack
                # frontier (the gateway's GC hint) stalls at the first
                # read forever
                self._ack(seq)
                return res.payload[0] if res.payload else b""
            if res.status == ResultStatus.MOVED:
                redirects += 1
                if redirects > 8:
                    raise GatewayError(
                        f"shard {shard}: MOVED redirect loop"
                    )
                await self._redirect(res)
                continue  # same seq to the named owner
            if res.status == ResultStatus.RETRY:
                attempts += 1
                if (
                    not self.retry_backpressure
                    or attempts > self.max_backpressure_retries
                ):
                    raise BackpressureError(
                        res.payload[0].decode() if res.payload else ""
                    )
                await asyncio.sleep(
                    min(1.0, self.backpressure_base_delay * attempts)
                )
                continue
            self._ack(seq)
            raise GatewayError(
                res.payload[0].decode() if res.payload else "gateway error"
            )


# ---------------------------------------------------------------------------
# Ops tooling: framed admin fetch (the `python -m rabia_tpu stats` path)
# ---------------------------------------------------------------------------


async def admin_fetch_timed(
    host: str,
    port: int,
    kind: int = 0,
    timeout: float = 10.0,
    query: bytes = b"",
) -> tuple[bytes, float, float]:
    """Fetch one admin document (metrics / health / journal / trace —
    see :class:`~rabia_tpu.core.messages.AdminKind`) from a gateway's
    native transport, knowing only ``host:port``. Returns
    ``(body, send_wall, recv_wall)`` where the wall times bracket the
    answered request round trip on THIS process's clock — the trace
    collector's clock-alignment input (offset = RTT midpoint, error
    bound ±RTT/2; see obs/flight.align_slice).

    The framed transport normally needs the peer's node id up front; ops
    tooling has only an address. The trick: dial under a PLACEHOLDER peer
    id — the handshake exchanges real 16-byte ids regardless, so the
    established connection comes up keyed by the gateway's actual id,
    which ``get_connected_nodes`` then reveals. The placeholder peer
    entry is removed right after (stopping its redial scan) and the
    request rides the discovered identity.
    """
    import time as _time

    from rabia_tpu.core.messages import AdminRequest, AdminResponse
    from rabia_tpu.net.tcp import TcpNetwork

    net = TcpNetwork(NodeId(fast_uuid4()), TcpNetworkConfig(bind_port=0))
    try:
        placeholder = NodeId(fast_uuid4())
        net.add_peer(placeholder, host, port)
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        gw: Optional[NodeId] = None
        while loop.time() < deadline:
            conn = await net.get_connected_nodes()
            if conn:
                gw = next(iter(conn))
                break
            await asyncio.sleep(0.02)
        if gw is None:
            raise TimeoutError_("admin fetch: gateway handshake", timeout)
        net.remove_peer(placeholder)  # our live conn is keyed by gw's id
        ser = Serializer()
        nonce = 1
        req = ser.serialize(
            ProtocolMessage.new(
                net.node_id,
                AdminRequest(kind=int(kind), nonce=nonce, query=query),
                gw,
            )
        )
        last_send = 0.0
        send_wall = 0.0
        while True:
            now = loop.time()
            if now >= deadline:
                raise TimeoutError_("admin fetch: response", timeout)
            if now - last_send >= 1.0:  # re-send over a racing establish
                if not send_wall:
                    # bracket from the FIRST send: a late response to an
                    # earlier send must widen err_s (conservative), never
                    # tighten it around the wrong serve time
                    send_wall = _time.time()
                net.send_to_nowait(gw, req)
                last_send = now
            try:
                sender, data = await net.receive(
                    timeout=min(0.25, deadline - now)
                )
            except (TimeoutError_, NetworkError):
                continue
            try:
                msg = ser.deserialize(data)
            except RabiaError:
                continue
            p = msg.payload
            if isinstance(p, AdminResponse) and p.nonce == nonce:
                if p.status != 0:
                    raise GatewayError(
                        p.body.decode(errors="replace") or "admin error"
                    )
                return p.body, send_wall, _time.time()
    finally:
        await net.close()


async def admin_fetch(
    host: str,
    port: int,
    kind: int = 0,
    timeout: float = 10.0,
    query: bytes = b"",
) -> bytes:
    """:func:`admin_fetch_timed` without the RTT bracket (the
    `python -m rabia_tpu stats` path)."""
    body, _, _ = await admin_fetch_timed(
        host, port, kind, timeout=timeout, query=query
    )
    return body
