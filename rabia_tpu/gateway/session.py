"""Per-replica client session table: exactly-once command semantics.

Velos (arXiv:2106.08676) carves the client-facing path — session state,
retry dedup, read leases — out of the consensus core as its own
subsystem; this module is that state. Every client command carries a
``(client_id, seq)`` pair; a session keeps the results of completed
seqs so a duplicate submission (client retry, reconnect replay) is
answered from cache instead of re-proposed, and tracks in-flight seqs
so concurrent duplicates attach to the original proposal.

GC is tied to the engine's decided frontier: a cached result becomes
evictable only once (a) the client acknowledged receiving it
(``ack_upto``) AND (b) the engine's state version moved past the
version recorded at completion — the decided frontier has provably
advanced beyond the command's slot, so no in-flight consensus path can
re-surface it. Idle sessions age out whole after ``session_ttl``; a
hard ``lease_ttl`` (default 4x the idle ttl) drops a session even with
in-flight seqs, so a stalled frontier (no quorum, wedged engine) cannot
pin dead sessions forever — a replay of a lease-dropped seq re-proposes
under the SAME deterministic batch id and the engine's ``applied_ids``
ledger still blocks the double apply.

This table is the SEMANTICS OWNER of the gateway session plane: the C
twin (native/sessionkernel.cpp via gateway/native_session.py) mirrors
every decision and cached byte here, ``RABIA_PY_GATEWAY=1`` forces this
table, and ``testing.conformance.run_gateway_ops_on_both_tables`` pins
the two byte-identical. The op-level API (:meth:`hello`,
:meth:`submit_check`, :meth:`complete_op`, :meth:`abort`, :meth:`gc`)
is the conformance surface — the gateway server calls only these.
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Optional

# submit_check decisions (shared with the native kernel's gws_submit)
SUBMIT_FRESH = 0  # reserved in the inflight window; caller drives it
SUBMIT_DUP_CACHED = 1  # completed seq: answer from cache
SUBMIT_DUP_INFLIGHT = 2  # in-flight duplicate: the original answers
SUBMIT_SHED_WINDOW = 3  # session inflight window full: shed retryable


@dataclass(frozen=True)
class CachedResult:
    """One completed seq's outcome, replayable to duplicate submits."""

    status: int
    payload: tuple[bytes, ...]
    frontier_mark: int  # engine state_version when the result completed


@dataclass
class SessionStats:
    sessions_opened: int = 0
    duplicate_submits: int = 0
    results_cached: int = 0
    results_evicted: int = 0
    sessions_expired: int = 0
    leases_expired: int = 0  # hard-lease drops (inflight notwithstanding)


@dataclass
class GatewaySession:
    """One client's gateway-side state."""

    client_id: uuid.UUID
    window: int
    inflight: dict = field(default_factory=dict)  # seq -> opaque
    results: dict = field(default_factory=dict)  # seq -> CachedResult
    ack_upto: int = 0
    highest_completed: int = 0
    last_active: float = field(default_factory=time.time)

    def touch(self, now: Optional[float] = None) -> None:
        self.last_active = time.time() if now is None else now

    def complete(self, seq: int, result: CachedResult) -> None:
        self.results[seq] = result
        if seq > self.highest_completed:
            self.highest_completed = seq


class SessionTable:
    """client_id -> :class:`GatewaySession`, with frontier-tied GC."""

    is_native = False

    def __init__(
        self,
        default_window: int = 64,
        session_ttl: float = 600.0,
        result_cache_cap: int = 4096,
        lease_ttl: Optional[float] = None,
    ) -> None:
        self.default_window = max(1, default_window)
        self.session_ttl = session_ttl
        self.result_cache_cap = max(1, result_cache_cap)
        # the hard lease: even a session with in-flight seqs is dropped
        # once it has been silent this long (see module doc)
        self.lease_ttl = (
            lease_ttl if lease_ttl is not None else 4.0 * session_ttl
        )
        self.sessions: dict[uuid.UUID, GatewaySession] = {}
        self.stats = SessionStats()

    # -- op-level API (the conformance surface; server.py calls these) ------

    def hello(
        self,
        client_id: uuid.UUID,
        requested_window: int = 0,
        now: Optional[float] = None,
    ) -> tuple[int, int]:
        """Open or resume the session; returns ``(window, last_seq)``
        for the hello ack."""
        sess = self.ensure(client_id, requested_window, now=now)
        return sess.window, sess.highest_completed

    def submit_check(
        self,
        client_id: uuid.UUID,
        seq: int,
        ack_upto: int = 0,
        now: Optional[float] = None,
    ) -> tuple[int, int, tuple[bytes, ...]]:
        """The submit hot path in ONE table operation: ensure/touch the
        session, advance its ack frontier, and classify the seq.
        Returns ``(decision, status, payload)`` — status/payload are
        meaningful only for ``SUBMIT_DUP_CACHED`` (the RAW cached
        status; the server maps OK to CACHED on the wire). A ``FRESH``
        decision RESERVES the seq in the inflight window; the caller
        must end it with :meth:`complete_op` or :meth:`abort`."""
        sess = self.ensure(client_id, now=now)
        if ack_upto > sess.ack_upto:
            sess.ack_upto = ack_upto
        cached = sess.results.get(seq)
        if cached is not None:
            self.stats.duplicate_submits += 1
            return SUBMIT_DUP_CACHED, cached.status, cached.payload
        if seq in sess.inflight:
            self.stats.duplicate_submits += 1
            return SUBMIT_DUP_INFLIGHT, 0, ()
        if len(sess.inflight) >= sess.window:
            return SUBMIT_SHED_WINDOW, 0, ()
        sess.inflight[seq] = None  # reserved synchronously (dedup window)
        return SUBMIT_FRESH, 0, ()

    def complete_op(
        self,
        client_id: uuid.UUID,
        seq: int,
        status: int,
        payload: tuple[bytes, ...],
        frontier_mark: int,
        now: Optional[float] = None,
    ) -> bool:
        """Finish a FRESH seq: drop the inflight reservation and cache
        the result. Returns False (a no-op) when the session is gone —
        lease-expired mid-flight; the client's replay re-opens it."""
        sess = self.sessions.get(client_id)
        if sess is None:
            return False
        sess.inflight.pop(seq, None)
        sess.complete(
            seq,
            CachedResult(
                status=int(status),
                payload=tuple(bytes(p) for p in payload),
                frontier_mark=int(frontier_mark),
            ),
        )
        self.stats.results_cached += 1
        sess.touch(now)
        return True

    def abort(self, client_id: uuid.UUID, seq: int) -> None:
        """Release a FRESH reservation without caching anything (the
        submit was shed/rejected before any proposal committed)."""
        sess = self.sessions.get(client_id)
        if sess is not None:
            sess.inflight.pop(seq, None)

    def cached_result(
        self, client_id: uuid.UUID, seq: int
    ) -> Optional[CachedResult]:
        sess = self.sessions.get(client_id)
        if sess is None:
            return None
        return sess.results.get(seq)

    # -- session objects (tests, repair paths) ------------------------------

    def ensure(
        self,
        client_id: uuid.UUID,
        requested_window: int = 0,
        now: Optional[float] = None,
    ) -> GatewaySession:
        """Open or resume the client's session. The granted window is the
        gateway's default capped further by the client's request (a
        client may shrink its window, never grow past the gateway's)."""
        sess = self.sessions.get(client_id)
        if sess is None:
            sess = GatewaySession(
                client_id=client_id, window=self.default_window
            )
            self.sessions[client_id] = sess
            self.stats.sessions_opened += 1
        if requested_window > 0:
            # renegotiable on resume too — a reconnecting client may ask
            # for a stricter window than its previous session had
            sess.window = min(self.default_window, requested_window)
        sess.touch(now)
        return sess

    def get(self, client_id: uuid.UUID) -> Optional[GatewaySession]:
        return self.sessions.get(client_id)

    def gc(self, state_version: int, now: Optional[float] = None) -> int:
        """Evict acknowledged results the decided frontier has moved past,
        cap runaway per-session caches, expire idle sessions, and sweep
        hard-expired leases (a stalled frontier must not pin dead
        sessions — the lease sweep is frontier-INDEPENDENT by design).
        Returns the number of evicted results."""
        now = time.time() if now is None else now
        evicted = 0
        dead: list[uuid.UUID] = []
        for cid, sess in self.sessions.items():
            if sess.results:
                gone = [
                    seq
                    for seq, r in sess.results.items()
                    if seq <= sess.ack_upto and r.frontier_mark < state_version
                ]
                for seq in gone:
                    del sess.results[seq]
                evicted += len(gone)
                # hard cap against a client that never acks: evict oldest
                # seqs first. A replay of an evicted seq re-proposes, but
                # under the SAME deterministic batch id (server.
                # _deterministic_batch), so the engine's applied_ids
                # ledger still blocks a double apply — this cache only
                # saves the round trip and the burned slot
                if len(sess.results) > self.result_cache_cap:
                    for seq in sorted(sess.results)[
                        : len(sess.results) - self.result_cache_cap
                    ]:
                        del sess.results[seq]
                        evicted += 1
            idle = now - sess.last_active
            if idle > self.lease_ttl:
                # hard lease: expired regardless of inflight seqs — a
                # wedged engine keeping futures pending forever must not
                # make the session immortal (GC-under-frontier-stall)
                dead.append(cid)
                self.stats.leases_expired += 1
            elif not sess.inflight and idle > self.session_ttl:
                dead.append(cid)
        for cid in dead:
            sess = self.sessions.pop(cid)
            evicted += len(sess.results)
            self.stats.sessions_expired += 1
        self.stats.results_evicted += evicted
        return evicted

    def __len__(self) -> int:
        return len(self.sessions)
