"""Per-replica client session table: exactly-once command semantics.

Velos (arXiv:2106.08676) carves the client-facing path — session state,
retry dedup, read leases — out of the consensus core as its own
subsystem; this module is that state. Every client command carries a
``(client_id, seq)`` pair; a session keeps the results of completed
seqs so a duplicate submission (client retry, reconnect replay) is
answered from cache instead of re-proposed, and tracks in-flight seqs
so concurrent duplicates attach to the original proposal.

GC is tied to the engine's decided frontier: a cached result becomes
evictable only once (a) the client acknowledged receiving it
(``ack_upto``) AND (b) the engine's state version moved past the
version recorded at completion — the decided frontier has provably
advanced beyond the command's slot, so no in-flight consensus path can
re-surface it. Idle sessions age out whole after ``session_ttl``.
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Optional


@dataclass(frozen=True)
class CachedResult:
    """One completed seq's outcome, replayable to duplicate submits."""

    status: int
    payload: tuple[bytes, ...]
    frontier_mark: int  # engine state_version when the result completed


@dataclass
class SessionStats:
    sessions_opened: int = 0
    duplicate_submits: int = 0
    results_cached: int = 0
    results_evicted: int = 0
    sessions_expired: int = 0


@dataclass
class GatewaySession:
    """One client's gateway-side state."""

    client_id: uuid.UUID
    window: int
    inflight: dict = field(default_factory=dict)  # seq -> asyncio.Future
    results: dict = field(default_factory=dict)  # seq -> CachedResult
    ack_upto: int = 0
    highest_completed: int = 0
    last_active: float = field(default_factory=time.time)

    def touch(self) -> None:
        self.last_active = time.time()

    def complete(self, seq: int, result: CachedResult) -> None:
        self.results[seq] = result
        if seq > self.highest_completed:
            self.highest_completed = seq


class SessionTable:
    """client_id -> :class:`GatewaySession`, with frontier-tied GC."""

    def __init__(
        self,
        default_window: int = 64,
        session_ttl: float = 600.0,
        result_cache_cap: int = 4096,
    ) -> None:
        self.default_window = max(1, default_window)
        self.session_ttl = session_ttl
        self.result_cache_cap = max(1, result_cache_cap)
        self.sessions: dict[uuid.UUID, GatewaySession] = {}
        self.stats = SessionStats()

    def ensure(
        self, client_id: uuid.UUID, requested_window: int = 0
    ) -> GatewaySession:
        """Open or resume the client's session. The granted window is the
        gateway's default capped further by the client's request (a
        client may shrink its window, never grow past the gateway's)."""
        sess = self.sessions.get(client_id)
        if sess is None:
            sess = GatewaySession(
                client_id=client_id, window=self.default_window
            )
            self.sessions[client_id] = sess
            self.stats.sessions_opened += 1
        if requested_window > 0:
            # renegotiable on resume too — a reconnecting client may ask
            # for a stricter window than its previous session had
            sess.window = min(self.default_window, requested_window)
        sess.touch()
        return sess

    def get(self, client_id: uuid.UUID) -> Optional[GatewaySession]:
        return self.sessions.get(client_id)

    def gc(self, state_version: int, now: Optional[float] = None) -> int:
        """Evict acknowledged results the decided frontier has moved past,
        cap runaway per-session caches, and expire idle sessions.
        Returns the number of evicted results."""
        now = time.time() if now is None else now
        evicted = 0
        dead: list[uuid.UUID] = []
        for cid, sess in self.sessions.items():
            if sess.results:
                gone = [
                    seq
                    for seq, r in sess.results.items()
                    if seq <= sess.ack_upto and r.frontier_mark < state_version
                ]
                for seq in gone:
                    del sess.results[seq]
                evicted += len(gone)
                # hard cap against a client that never acks: evict oldest
                # seqs first. A replay of an evicted seq re-proposes, but
                # under the SAME deterministic batch id (server.
                # _deterministic_batch), so the engine's applied_ids
                # ledger still blocks a double apply — this cache only
                # saves the round trip and the burned slot
                if len(sess.results) > self.result_cache_cap:
                    for seq in sorted(sess.results)[
                        : len(sess.results) - self.result_cache_cap
                    ]:
                        del sess.results[seq]
                        evicted += 1
            if (
                not sess.inflight
                and now - sess.last_active > self.session_ttl
            ):
                dead.append(cid)
        for cid in dead:
            del self.sessions[cid]
            self.stats.sessions_expired += 1
        self.stats.results_evicted += evicted
        return evicted

    def __len__(self) -> int:
        return len(self.sessions)
