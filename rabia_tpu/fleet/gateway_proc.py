"""FleetGateway: a standalone session-holding gateway behind the ring.

The routed fleet decouples the session/dedup tier from the replica
count: many of these processes (not one per replica) each own a slice
of the shard space (:mod:`rabia_tpu.fleet.ring`), hold the client
sessions homed there, and proxy fresh Submits to the replica cluster's
gateways over the session-mux transport lane — forwarding UNDER THE
CLIENT'S OWN 16-byte session id, so the replica gateway's session table
keeps the end-to-end ``(client_id, seq)`` exactly-once key. A fleet
gateway for a shard it does not own answers ``ResultStatus.MOVED`` with
the owner's address and the client re-sends the same seq there.

Why double session tables are safe: the fleet table is a *cache tier*
(answers replays without a replica round-trip, sheds per-session window
overflow at the edge); the replica-tier table remains authoritative.
Any seq the fleet tier forwards twice (lost ledger record, expired
waiter, crashed fleet gateway) dedups upstream — and even past a
replica session lease, the engine's deterministic batch ids
(``batch_id_for(client_id, seq)``) block a double apply. Nothing in
this tier is a correctness dependency; it is all fast-path.

Upstream routing concentrates a shard on ONE replica gateway
(``shard % len(upstreams)``) so the round-15 cross-session coalescing
tier sees the same arrival density it was scored at.

Failover story (scored by the ``routed_gateway_failover`` chaos
scenario): completed results replicate as ledger records to the shard's
ring successors (:mod:`rabia_tpu.fleet.ledger`) — the exact gateways
that inherit the shard when this process dies — and planned rebalance
ships full sessions ahead of the MOVED wave (:mod:`fleet.handoff`).

Run standalone via the testing/recovery.py child protocol:
``python -m rabia_tpu.fleet.gateway_proc --child <idx> <json
fleet_ports> <json upstream_addrs> <n_shards> [extras]`` — emits one
``{"event": "ready", ...}`` JSON line on stdout once listening.
"""

from __future__ import annotations

import asyncio
import json
import logging
import struct
import time
import uuid
from dataclasses import dataclass, field
from typing import Optional

from rabia_tpu.core.config import TcpNetworkConfig
from rabia_tpu.core.errors import RabiaError, TimeoutError_
from rabia_tpu.core.messages import (
    AdminKind,
    AdminRequest,
    AdminResponse,
    ClientHello,
    ProtocolMessage,
    ReadIndex,
    ReadIndexMode,
    Result,
    ResultStatus,
    Submit,
)
from rabia_tpu.core.serialization import Serializer
from rabia_tpu.core.types import NodeId
from rabia_tpu.fleet.handoff import (
    decode_handoff,
    encode_handoff,
    export_sessions,
    import_sessions,
)
from rabia_tpu.fleet.ledger import (
    LedgerRecord,
    apply_record,
    decode_records,
    encode_records,
)
from rabia_tpu.fleet.ring import HashRing, RingMember, moved_shards
from rabia_tpu.gateway.session import (
    SUBMIT_DUP_CACHED,
    SUBMIT_DUP_INFLIGHT,
    SUBMIT_FRESH,
    SUBMIT_SHED_WINDOW,
    SessionTable,
)
from rabia_tpu.obs.flight import (
    FRE_FLEET_FWD,
    FRE_FLEET_LEDGER_APPLY,
    FRE_FLEET_LEDGER_SEND,
    FRE_FLEET_MOVED,
    FRE_FLEET_RECV,
    FRE_FLEET_RESULT,
    FlightRecorder,
    batch_id_for,
    build_fleet_trace_slice,
    fr_hash,
)
from rabia_tpu.obs.journal import AnomalyJournal
from rabia_tpu.obs.registry import MetricsRegistry
from rabia_tpu.obs.telemetry import TelemetrySampler

logger = logging.getLogger("rabia_tpu.fleet")


@dataclass
class FleetGatewayConfig:
    name: str = "gw0"
    bind_host: str = "127.0.0.1"
    bind_port: int = 0  # ephemeral
    # replica-cluster gateway endpoints the fleet proxies Submits to;
    # a shard's traffic always rides upstreams[shard % len(upstreams)]
    # so the replica-side coalescing tier sees concentrated arrivals
    upstreams: tuple[tuple[str, int], ...] = ()
    n_shards: int = 4
    # ledger replication factor: completed results copy to the shard's
    # first rf ring successors (successor[0] is this gateway itself)
    replication_factor: int = 2
    default_window: int = 64
    session_ttl: float = 600.0
    result_cache_cap: int = 4096
    session_lease: Optional[float] = None
    gc_interval: float = 1.0
    # a forwarded Submit unanswered this long is aborted locally and
    # shed RETRY — the client's resubmit dedups upstream
    forward_timeout: float = 30.0
    # a DUP_INFLIGHT waiter (imported handoff reservation) unresolved
    # this long is aborted + shed RETRY; the retry re-forwards
    waiter_timeout: float = 10.0
    handoff_timeout: float = 10.0
    # per-second telemetry ring (obs/telemetry.TelemetrySampler), served
    # as AdminKind.TIMELINE like the replica gateways. 0 disables it.
    telemetry_interval: float = 1.0
    telemetry_cap: int = 900
    # shard-group scale-out (fleet/groups.py): when non-empty, each
    # inner tuple is ONE consensus group's replica-gateway endpoints
    # (index = group id) and a Submit routes GroupMap.group_of(shard)
    # -> that lane — within the lane the same shard % len spread as the
    # flat tier. `groups` is the GroupMap doc; None = the deterministic
    # even partition over len(upstream_groups) groups. Empty
    # upstream_groups = the flat (ungrouped) tier, `upstreams` above.
    upstream_groups: tuple[tuple[tuple[str, int], ...], ...] = ()
    groups: Optional[dict] = None


@dataclass
class FleetStats:
    submits: int = 0
    forwarded: int = 0
    cached_replays: int = 0
    moved: int = 0
    shed: int = 0
    forward_timeouts: int = 0
    ledger_sent: int = 0
    ledger_applied: int = 0
    handoff_out_sessions: int = 0
    handoff_in_sessions: int = 0


class _UpstreamLink:
    """One session-mux connection to a replica gateway (MUX_MAGIC lane,
    the same wire contract as testing/loadsession.MuxConn). Frames sent
    pre-connect are buffered and flushed once the handshake completes;
    a dropped link reconnects on the next send. Inbound frames demux by
    their 16-byte session prefix back into the owning FleetGateway."""

    def __init__(self, owner: "FleetGateway", host: str, port: int) -> None:
        self.owner = owner
        self.host = host
        self.port = port
        self.reader: Optional[asyncio.StreamReader] = None
        self.writer: Optional[asyncio.StreamWriter] = None
        self._backlog: list[bytes] = []
        self._connecting: Optional[asyncio.Task] = None
        self._read_task: Optional[asyncio.Task] = None

    def send(self, session_id: bytes, data: bytes) -> None:
        frame = struct.pack("<I", 16 + len(data)) + session_id + data
        if self.writer is not None:
            try:
                self.writer.write(frame)
                return
            except Exception:
                self._drop()
        self._backlog.append(frame)
        if self._connecting is None or self._connecting.done():
            self._connecting = asyncio.ensure_future(self._connect())

    def _drop(self) -> None:
        if self.writer is not None:
            try:
                self.writer.close()
            except Exception:
                pass
        self.reader = None
        self.writer = None

    async def _connect(self) -> None:
        from rabia_tpu.net.tcp import MUX_MAGIC

        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(self.host, self.port), 10.0
            )
            writer.write(MUX_MAGIC)
            await asyncio.wait_for(reader.readexactly(16), 10.0)
        except Exception as e:
            logger.warning(
                "fleet %s: upstream %s:%d connect failed: %s",
                self.owner.config.name, self.host, self.port, e,
            )
            # backlog stays; the forward-timeout sweep sheds the pending
            # submits RETRY and the clients' resubmits retry the dial
            return
        self.reader, self.writer = reader, writer
        if self._read_task is not None:
            self._read_task.cancel()
        self._read_task = asyncio.ensure_future(self._read_loop())
        backlog, self._backlog = self._backlog, []
        for frame in backlog:
            writer.write(frame)

    async def _read_loop(self) -> None:
        try:
            while self.reader is not None:
                hdr = await self.reader.readexactly(4)
                (ln,) = struct.unpack("<I", hdr)
                data = await self.reader.readexactly(ln)
                if ln < 16:
                    continue
                self.owner._on_upstream(data[:16], data[16:])
        except (asyncio.IncompleteReadError, asyncio.CancelledError,
                ConnectionError, OSError):
            self._drop()

    async def close(self) -> None:
        for t in (self._connecting, self._read_task):
            if t is not None:
                t.cancel()
        if self.writer is not None:
            try:
                self.writer.close()
                await self.writer.wait_closed()
            except Exception:
                pass
        self.reader = None
        self.writer = None


class FleetGateway:
    """One routed-fleet gateway process (see module doc)."""

    def __init__(
        self,
        config: Optional[FleetGatewayConfig] = None,
        node_id: Optional[NodeId] = None,
    ) -> None:
        self.config = config or FleetGatewayConfig()
        self.node_id = node_id or NodeId(uuid.uuid4())
        self.serializer = Serializer()
        self.sessions = SessionTable(
            default_window=self.config.default_window,
            session_ttl=self.config.session_ttl,
            result_cache_cap=self.config.result_cache_cap,
            lease_ttl=self.config.session_lease,
        )
        self.ring = HashRing()
        self.stats = FleetStats()
        self._net = None
        self._running = False
        self._run_task: Optional[asyncio.Task] = None
        self._tasks: set = set()
        # seqs forwarded upstream and not yet answered:
        # (client_id, seq) -> (shard, deadline)
        self._pending: dict[tuple[uuid.UUID, int], tuple[int, float]] = {}
        # DUP_INFLIGHT reservations with no local forward (imported by
        # handoff; outcome arrives as a ledger record or times out)
        self._waiting: dict[tuple[uuid.UUID, int], float] = {}
        # client -> shard of its last fresh Submit (the handoff work
        # list: sessions homed to a moved shard transfer with it)
        self._session_shard: dict[uuid.UUID, int] = {}
        self._upstreams: list[_UpstreamLink] = []
        # shard-group routing state (fleet/groups.py): None = flat tier
        self.groups = None
        self._group_links: dict[int, list[_UpstreamLink]] = {}
        self._admin_nonce = 0
        self._admin_futs: dict[int, asyncio.Future] = {}
        # local monotonic completion counter: the frontier_mark domain
        # for this table (session GC runs against it, not an engine
        # state version — the fleet tier has no engine)
        self._frontier = 0
        # fleet-side observability plane: a flight ring for the routing
        # hops (FRE_FLEET_* kinds, batch-hash keyed so a (client_id, seq)
        # trace joins with the replica tier), an anomaly journal, and a
        # telemetry ring started in start(). The row index parses from
        # the gateway name ("gw3" -> 3) — it only disambiguates fleet
        # slices among themselves, never against replica rows (slices
        # carry tier="fleet").
        self.flight = FlightRecorder()
        self.journal = AnomalyJournal()
        self._telemetry: Optional[TelemetrySampler] = None
        digits = "".join(
            c for c in self.config.name if c.isdigit()
        )
        self._row = int(digits) if digits else 0
        self.metrics = MetricsRegistry(namespace="rabia")
        self._register_metrics()

    # -- metrics ------------------------------------------------------------

    def _register_metrics(self) -> None:
        m, s = self.metrics, self.stats
        tag = {"fleet_gw": self.config.name}
        m.counter("fleet_submits_total", "Submits received", tag,
                  fn=lambda: s.submits)
        m.counter("fleet_forwarded_total", "Submits proxied upstream", tag,
                  fn=lambda: s.forwarded)
        m.counter("fleet_cached_replays_total",
                  "replays answered from the fleet session cache", tag,
                  fn=lambda: s.cached_replays)
        m.counter("fleet_moved_total", "MOVED redirects answered", tag,
                  fn=lambda: s.moved)
        m.counter("fleet_shed_total", "RETRY sheds (window/timeout)", tag,
                  fn=lambda: s.shed)
        m.counter("fleet_ledger_sent_total",
                  "ledger records replicated out", tag,
                  fn=lambda: s.ledger_sent)
        m.counter("fleet_ledger_applied_total",
                  "replicated ledger records imported", tag,
                  fn=lambda: s.ledger_applied)
        m.counter("fleet_handoff_sessions_out_total",
                  "sessions exported on rebalance", tag,
                  fn=lambda: s.handoff_out_sessions)
        m.counter("fleet_handoff_sessions_in_total",
                  "sessions imported on rebalance", tag,
                  fn=lambda: s.handoff_in_sessions)
        m.gauge("fleet_sessions", "live client sessions", tag,
                fn=lambda: len(self.sessions))
        m.gauge("fleet_pending_forwards", "submits in flight upstream",
                tag, fn=lambda: len(self._pending))
        m.gauge("fleet_ring_version", "adopted ring membership version",
                tag, fn=lambda: self.ring.version)
        m.gauge("fleet_group_map_version",
                "adopted shard-group map version (-1 = flat tier)", tag,
                fn=lambda: (
                    self.groups.version if self.groups is not None else -1
                ))

    # -- lifecycle ----------------------------------------------------------

    async def start(self) -> None:
        from rabia_tpu.net.tcp import TcpNetwork

        self._net = TcpNetwork(
            self.node_id,
            TcpNetworkConfig(
                bind_host=self.config.bind_host,
                bind_port=self.config.bind_port,
            ),
        )
        if self.config.upstream_groups:
            from rabia_tpu.fleet.groups import GroupMap

            self.groups = (
                GroupMap.from_doc(self.config.groups)
                if self.config.groups is not None
                else GroupMap.initial(
                    self.config.n_shards,
                    len(self.config.upstream_groups),
                )
            )
            self._group_links = {
                g: [_UpstreamLink(self, h, p) for h, p in addrs]
                for g, addrs in enumerate(self.config.upstream_groups)
            }
            self._upstreams = [
                link
                for links in self._group_links.values()
                for link in links
            ]
        else:
            self._upstreams = [
                _UpstreamLink(self, host, port)
                for host, port in self.config.upstreams
            ]
        if self.config.telemetry_interval > 0 and self._telemetry is None:
            self._telemetry = TelemetrySampler(
                self.metrics,
                node=self.config.name,
                interval=self.config.telemetry_interval,
                cap=self.config.telemetry_cap,
            ).start()
        self._running = True
        self._run_task = asyncio.ensure_future(self._run())

    @property
    def port(self) -> int:
        return self._net.port if self._net is not None else 0

    def member(self) -> RingMember:
        """This gateway's own ring address card."""
        return RingMember(
            name=self.config.name,
            host=self.config.bind_host,
            port=self.port,
            node=self.node_id,
        )

    async def close(self) -> None:
        self._running = False
        if self._telemetry is not None:
            # final flush so the ring covers the run's last instant
            self._telemetry.sample()
            self._telemetry.close()
            self._telemetry = None
        for t in (self._run_task, *self._tasks):
            if t is not None:
                t.cancel()
        await asyncio.gather(
            *(t for t in (self._run_task, *self._tasks) if t),
            return_exceptions=True,
        )
        self._tasks.clear()
        for up in self._upstreams:
            await up.close()
        if self._net is not None:
            await self._net.close()
            self._net = None

    def _spawn(self, coro) -> None:
        task = asyncio.ensure_future(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    # -- ring ---------------------------------------------------------------

    def adopt_ring(self, ring: HashRing) -> None:
        """Install a membership view WITHOUT handoff (bootstrap path —
        every member adopts the same initial doc before serving)."""
        self.ring = ring
        self._register_ring_peers(ring)

    def _register_ring_peers(self, ring: HashRing) -> None:
        for mem in ring.members.values():
            if mem.name != self.config.name:
                self._net.add_peer(mem.node, mem.host, mem.port)

    def _owns(self, shard: int) -> bool:
        owner = self.ring.owner(shard)
        return owner is None or owner.name == self.config.name

    # -- shard groups -------------------------------------------------------

    def adopt_groups(self, new_map) -> bool:
        """Install a strictly newer GroupMap (the routing flip of the
        safe rebalance order — the new owner's replica gateways widened
        their accepted ranges BEFORE this runs). Sessions stay put: the
        fleet session cache answers replays that cross the flip, so the
        re-routed group never sees an already-committed seq."""
        if self.groups is None:
            raise RuntimeError(
                f"fleet {self.config.name}: not configured with "
                "upstream_groups; cannot adopt a group map"
            )
        if new_map.version <= self.groups.version:
            return False
        if new_map.n_shards != self.groups.n_shards:
            raise ValueError("group map covers a different shard space")
        if any(
            g not in self._group_links for g in new_map.groups()
        ):
            raise ValueError(
                "group map names a group with no upstream lane"
            )
        self.groups = new_map
        return True

    async def _rebalance(self, new_ring: HashRing) -> None:
        """Adopt a new membership view: hand sessions on departing
        shards to their new owners FIRST, then start answering MOVED.
        A redirected client's replay therefore always finds its dedup
        state already imported at the destination."""
        self._register_ring_peers(new_ring)
        moved = moved_shards(self.ring, new_ring, self.config.n_shards)
        losing = {
            s: owner for s, owner in moved.items()
            if (m := self.ring.owner(s)) is not None
            and m.name == self.config.name
        }
        by_target: dict[str, list[uuid.UUID]] = {}
        for cid, shard in self._session_shard.items():
            target = losing.get(shard)
            if target is not None:
                by_target.setdefault(target, []).append(cid)
        for target_name, cids in by_target.items():
            target = new_ring.members.get(target_name)
            if target is None:
                continue
            exports = export_sessions(self.sessions, cids)
            if not exports:
                continue
            self.stats.handoff_out_sessions += len(exports)
            try:
                await self._admin_call(
                    target.node,
                    AdminKind.HANDOFF,
                    encode_handoff(exports),
                    timeout=self.config.handoff_timeout,
                )
            except Exception as e:
                # the new owner still recovers via replicated ledger
                # records + upstream dedup; log and move on
                logger.warning(
                    "fleet %s: handoff of %d sessions to %s failed: %s",
                    self.config.name, len(exports), target_name, e,
                )
        self.ring = new_ring

    # -- receive loop -------------------------------------------------------

    async def _run(self) -> None:
        last_gc = time.time()
        while self._running:
            try:
                sender, data = await self._net.receive(
                    timeout=self.config.gc_interval
                )
            except TimeoutError_:
                sender = None
            except asyncio.CancelledError:
                return
            if sender is not None:
                try:
                    msg = self.serializer.deserialize(data)
                except RabiaError as e:
                    logger.warning(
                        "fleet %s: dropping bad frame from %s: %s",
                        self.config.name, sender, e,
                    )
                else:
                    self._handle(sender, msg)
            now = time.time()
            if now - last_gc >= self.config.gc_interval:
                last_gc = now
                self._sweep(now)
                self.sessions.gc(self._frontier, now)

    def _sweep(self, now: float) -> None:
        """Shed forwarded submits and imported-reservation waiters whose
        deadline passed: abort the local reservation and answer RETRY —
        the client's resubmit re-forwards and dedups upstream."""
        for key, (shard, deadline) in list(self._pending.items()):
            if now >= deadline:
                del self._pending[key]
                cid, seq = key
                self.sessions.abort(cid, seq)
                self.stats.forward_timeouts += 1
                self.stats.shed += 1
                self._send_result(
                    cid, seq, ResultStatus.RETRY, (b"fleet-forward-timeout",)
                )
        for key, deadline in list(self._waiting.items()):
            if now >= deadline:
                del self._waiting[key]
                cid, seq = key
                self.sessions.abort(cid, seq)
                self.stats.shed += 1
                self._send_result(
                    cid, seq, ResultStatus.RETRY, (b"fleet-waiter-timeout",)
                )

    def _handle(self, sender: NodeId, msg: ProtocolMessage) -> None:
        p = msg.payload
        if isinstance(p, (ClientHello, Submit)) or (
            isinstance(p, ReadIndex) and p.mode == ReadIndexMode.READ
        ):
            # same invariant as the replica gateway: a client's transport
            # identity IS its session id
            if sender.value != p.client_id:
                logger.warning(
                    "fleet %s: client frame session/transport mismatch "
                    "(%s via %s)",
                    self.config.name, p.client_id, sender,
                )
                return
        if isinstance(p, ClientHello):
            window, last_seq = self.sessions.hello(p.client_id, p.max_inflight)
            self._send(
                ClientHello(
                    client_id=p.client_id, ack=True,
                    last_seq=last_seq, max_inflight=window,
                ),
                sender,
            )
        elif isinstance(p, Submit):
            self._on_submit(p)
        elif isinstance(p, ReadIndex) and p.mode == ReadIndexMode.READ:
            # reads carry no session window state: straight pass-through
            # under the client's session id; the Result demuxes back by
            # its (client_id, seq) falling outside the pending map
            self._forward(p.client_id, p)
        elif isinstance(p, AdminRequest):
            self._on_admin(sender, p)
        elif isinstance(p, AdminResponse):
            fut = self._admin_futs.pop(p.nonce, None)
            if fut is not None and not fut.done():
                fut.set_result(p)
        # anything else on the fleet port is noise; ignore

    # -- submit path --------------------------------------------------------

    def _on_submit(self, p: Submit) -> None:
        self.stats.submits += 1
        # the fleet hop of the cross-tier trace: every Submit records
        # its arrival under the SAME deterministic batch hash the
        # replica tier keys its lifecycle events with
        bhash = fr_hash(batch_id_for(p.client_id, p.seq))
        self.flight.record(FRE_FLEET_RECV, shard=p.shard, batch=bhash)
        decision, cstatus, cpayload = self.sessions.submit_check(
            p.client_id, p.seq, p.ack_upto
        )
        if decision == SUBMIT_DUP_CACHED:
            # the raw cached status is the ORIGINAL outcome; a replayed
            # OK answers CACHED on the wire (byte-identical payload)
            self.stats.cached_replays += 1
            wire = (
                ResultStatus.CACHED
                if cstatus == ResultStatus.OK
                else cstatus
            )
            self._send_result(p.client_id, p.seq, wire, cpayload)
            return
        if decision == SUBMIT_DUP_INFLIGHT:
            key = (p.client_id, p.seq)
            if key not in self._pending and key not in self._waiting:
                # an imported handoff reservation: the outcome arrives
                # as a replicated ledger record, or the waiter times out
                self._waiting[key] = (
                    time.time() + self.config.waiter_timeout
                )
            return  # the completion answers (net routes by session id)
        if decision == SUBMIT_SHED_WINDOW:
            self.stats.shed += 1
            self._send_result(
                p.client_id, p.seq, ResultStatus.RETRY,
                (b"session-window-full",)
            )
            return
        # SUBMIT_FRESH — the seq is reserved; route it
        if not self._owns(p.shard):
            owner = self.ring.owner(p.shard)
            self.sessions.abort(p.client_id, p.seq)
            self.stats.moved += 1
            self.flight.record(
                FRE_FLEET_MOVED, shard=p.shard, batch=bhash,
            )
            self._send_result(
                p.client_id, p.seq, ResultStatus.MOVED,
                (
                    f"{owner.host}:{owner.port}".encode(),
                    owner.node.value.bytes,
                ),
            )
            return
        self._session_shard[p.client_id] = p.shard
        self._pending[(p.client_id, p.seq)] = (
            p.shard, time.time() + self.config.forward_timeout
        )
        self.stats.forwarded += 1
        self.flight.record(FRE_FLEET_FWD, shard=p.shard, batch=bhash)
        self._forward(p.client_id, p)

    def _forward(self, client_id: uuid.UUID, payload) -> None:
        """Proxy a client frame upstream under the client's own session
        id — the replica gateway sees the client itself."""
        if not self._upstreams:
            if isinstance(payload, Submit):
                self._pending.pop((client_id, payload.seq), None)
                self.sessions.abort(client_id, payload.seq)
                self.stats.shed += 1
                self._send_result(
                    client_id, payload.seq, ResultStatus.RETRY,
                    (b"no-upstream",)
                )
            return
        shard = getattr(payload, "shard", 0)
        if self.groups is not None and 0 <= shard < self.groups.n_shards:
            # group-routed lane: the owning group's upstreams, spread
            # shard % len within the lane (coalescing concentration)
            links = self._group_links[self.groups.group_of(shard)]
            up = links[shard % len(links)]
        else:
            up = self._upstreams[shard % len(self._upstreams)]
        data = self.serializer.serialize(
            ProtocolMessage.new(NodeId(client_id), payload, None)
        )
        up.send(client_id.bytes, data)

    def _on_upstream(self, session_id: bytes, data: bytes) -> None:
        """A frame from a replica gateway for one proxied session."""
        try:
            msg = self.serializer.deserialize(data)
        except RabiaError:
            return
        p = msg.payload
        if not isinstance(p, Result):
            return  # hellos etc. are never proxied; ignore
        key = (p.client_id, p.seq)
        entry = self._pending.pop(key, None)
        if entry is None:
            # a read result or a late/duplicate answer: pass through
            self._send(p, NodeId(p.client_id))
            return
        shard, _deadline = entry
        self.flight.record(
            FRE_FLEET_RESULT, shard=shard, arg=int(p.status),
            batch=fr_hash(batch_id_for(p.client_id, p.seq)),
        )
        if p.status == ResultStatus.RETRY:
            # upstream shed it: nothing committed, nothing to cache
            self.sessions.abort(p.client_id, p.seq)
        else:
            # CACHED upstream means the original outcome was OK — store
            # the RAW status so this table's own replay answers CACHED
            # with the identical payload
            raw = (
                ResultStatus.OK
                if p.status == ResultStatus.CACHED
                else p.status
            )
            self._complete(p.client_id, p.seq, shard, int(raw), p.payload)
        self._send(p, NodeId(p.client_id))

    def _complete(
        self,
        client_id: uuid.UUID,
        seq: int,
        shard: int,
        raw_status: int,
        payload: tuple[bytes, ...],
    ) -> None:
        self._frontier += 1
        self.sessions.complete_op(
            client_id, seq, raw_status, payload, self._frontier
        )
        self._replicate(client_id, seq, shard, raw_status, payload)

    # -- ledger replication -------------------------------------------------

    def _replicate(
        self,
        client_id: uuid.UUID,
        seq: int,
        shard: int,
        status: int,
        payload: tuple[bytes, ...],
    ) -> None:
        """Fire-and-forget the completed record to the shard's other
        ring successors — exactly the members that inherit the shard if
        this gateway dies. A record lost in flight is NOT a correctness
        hole (upstream dedup + deterministic batch ids), it just costs
        the replay one upstream round-trip."""
        rf = self.config.replication_factor
        if rf <= 1 or len(self.ring) <= 1:
            return
        blob = encode_records([
            LedgerRecord(
                client_id=client_id, seq=seq, shard=shard,
                status=status, payload=tuple(payload),
            )
        ])
        bhash = fr_hash(batch_id_for(client_id, seq))
        for mem in self.ring.successors(shard, rf):
            if mem.name == self.config.name:
                continue
            self.stats.ledger_sent += 1
            digits = "".join(c for c in mem.name if c.isdigit())
            self.flight.record(
                FRE_FLEET_LEDGER_SEND, shard=shard,
                peer=int(digits) if digits else 0, batch=bhash,
            )
            self._admin_nonce += 1
            self._send(
                AdminRequest(
                    kind=int(AdminKind.LEDGER),
                    nonce=self._admin_nonce,
                    query=blob,
                ),
                mem.node,
            )

    def _apply_ledger(self, blob: bytes) -> int:
        applied = 0
        for rec in decode_records(blob):
            self._frontier += 1
            decision = apply_record(
                self.sessions, rec.client_id, rec.seq, rec.status,
                rec.payload, self._frontier,
            )
            if decision in (SUBMIT_FRESH, SUBMIT_DUP_INFLIGHT):
                applied += 1
                self.stats.ledger_applied += 1
                self.flight.record(
                    FRE_FLEET_LEDGER_APPLY, shard=rec.shard,
                    arg=int(rec.status) & 0xFF,
                    batch=fr_hash(batch_id_for(rec.client_id, rec.seq)),
                )
                self._session_shard.setdefault(rec.client_id, rec.shard)
                self._answer_if_waiting(rec.client_id, rec.seq)
        return applied

    def _answer_if_waiting(self, client_id: uuid.UUID, seq: int) -> None:
        """A completion landed for a seq a client is parked on (imported
        inflight reservation): answer it now."""
        if self._waiting.pop((client_id, seq), None) is None:
            return
        cached = self.sessions.cached_result(client_id, seq)
        if cached is None:
            return
        wire = (
            ResultStatus.CACHED
            if cached.status == ResultStatus.OK
            else cached.status
        )
        self._send_result(client_id, seq, wire, cached.payload)

    # -- admin plane --------------------------------------------------------

    def _on_admin(self, sender: NodeId, p: AdminRequest) -> None:
        try:
            status, body = self._admin_body(p)
        except Exception as e:  # never let an admin probe kill the loop
            status, body = 1, str(e).encode()
        self._send(
            AdminResponse(nonce=p.nonce, status=status, body=body), sender
        )

    def _admin_body(self, p: AdminRequest) -> tuple[int, bytes]:
        kind = p.kind
        if kind == AdminKind.METRICS:
            return 0, self.metrics.render_prometheus().encode()
        if kind == AdminKind.HEALTH:
            return 0, json.dumps(self.health()).encode()
        if kind == AdminKind.RING:
            query = json.loads(p.query.decode() or '{"op": "get"}')
            if query.get("op") == "set":
                new_ring = HashRing.from_doc(query["ring"])
                self._spawn(self._rebalance(new_ring))
                return 0, json.dumps(
                    {"adopting": new_ring.version}
                ).encode()
            if query.get("op") == "set_groups":
                from rabia_tpu.fleet.groups import GroupMap

                adopted = self.adopt_groups(
                    GroupMap.from_doc(query["groups"])
                )
                return 0, json.dumps({
                    "adopted": adopted,
                    "version": self.groups.version,
                }).encode()
            return 0, json.dumps(self._ring_doc()).encode()
        if kind == AdminKind.HANDOFF:
            exports = decode_handoff(bytes(p.query))
            self._frontier += 1
            summary = import_sessions(
                self.sessions, exports, self._frontier
            )
            self.stats.handoff_in_sessions += summary.sessions
            for e in exports:
                for seq, _status, _parts in e.results:
                    self._answer_if_waiting(e.client_id, seq)
            return 0, json.dumps({
                "sessions": summary.sessions,
                "results": summary.results,
                "inflight": summary.inflight,
                "skipped": summary.skipped,
            }).encode()
        if kind == AdminKind.LEDGER:
            applied = self._apply_ledger(bytes(p.query))
            return 0, json.dumps({"applied": applied}).encode()
        if kind == AdminKind.JOURNAL:
            jkind, last = None, 64
            if p.query:
                try:
                    q = json.loads(p.query)
                    jkind = q.get("kind")
                    last = max(0, int(q.get("last", 64)))
                except (ValueError, TypeError, AttributeError):
                    return 1, b"malformed journal query"
            return 0, json.dumps(
                {"anomalies": self.journal.snapshot(limit=last, kind=jkind)}
            ).encode()
        if kind == AdminKind.TRACE:
            # the fleet hop of a cross-tier trace: same TraceSlice
            # schema the replica gateways serve, selected by the same
            # deterministic batch hash, marked tier="fleet" so the
            # merged timeline renders the hop under the gateway's name
            try:
                q = json.loads(p.query) if p.query else {}
                if "batch" in q:
                    bid = uuid.UUID(hex=q["batch"])
                else:
                    bid = batch_id_for(
                        uuid.UUID(hex=q["client"]), int(q["seq"])
                    )
            except (ValueError, TypeError, KeyError):
                return 1, b"malformed trace query"
            doc = build_fleet_trace_slice(
                self.flight, self.config.name, self._row, fr_hash(bid)
            )
            doc["batch_id"] = bid.hex
            return 0, json.dumps(doc).encode()
        if kind == AdminKind.TIMELINE:
            if self._telemetry is None:
                return 1, b"telemetry sampler disabled"
            last = None
            if p.query:
                try:
                    last = json.loads(p.query).get("last")
                    if last is not None:
                        last = int(last)
                except (ValueError, TypeError, AttributeError):
                    return 1, b"malformed timeline query"
            return 0, json.dumps(self._telemetry.document(last)).encode()
        return 1, b"unsupported admin kind for fleet gateway"

    def _ring_doc(self) -> dict:
        cfg = self.config
        return {
            "self": cfg.name,
            "node": self.node_id.value.hex,
            "ring": self.ring.to_doc(),
            "n_shards": cfg.n_shards,
            "owned_shards": self.ring.owned_shards(cfg.name, cfg.n_shards),
            "sessions": len(self.sessions),
            "groups": (
                self.groups.to_doc() if self.groups is not None else None
            ),
        }

    def health(self) -> dict:
        s = self.stats
        return {
            "role": "fleet-gateway",
            "name": self.config.name,
            "node": self.node_id.value.hex,
            "ring_version": self.ring.version,
            "ring_members": sorted(self.ring.members),
            "owned_shards": self.ring.owned_shards(
                self.config.name, self.config.n_shards
            ),
            # the replica-cluster endpoints this gateway proxies to —
            # the fleet aggregator walks these to scrape the replica
            # tier without out-of-band configuration
            "upstreams": [[h, p] for h, p in self.config.upstreams],
            "upstream_groups": [
                [[h, p] for h, p in grp]
                for grp in self.config.upstream_groups
            ],
            "groups": (
                self.groups.to_doc() if self.groups is not None else None
            ),
            "sessions": len(self.sessions),
            "pending_forwards": len(self._pending),
            "waiting": len(self._waiting),
            "anomalies": self.journal.counts(),
            "stats": {
                "submits": s.submits,
                "forwarded": s.forwarded,
                "cached_replays": s.cached_replays,
                "moved": s.moved,
                "shed": s.shed,
                "forward_timeouts": s.forward_timeouts,
                "ledger_sent": s.ledger_sent,
                "ledger_applied": s.ledger_applied,
                "handoff_out_sessions": s.handoff_out_sessions,
                "handoff_in_sessions": s.handoff_in_sessions,
            },
        }

    async def _admin_call(
        self,
        peer: NodeId,
        kind: AdminKind,
        query: bytes,
        timeout: float,
    ) -> AdminResponse:
        self._admin_nonce += 1
        nonce = self._admin_nonce
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._admin_futs[nonce] = fut
        try:
            self._send(
                AdminRequest(kind=int(kind), nonce=nonce, query=query),
                peer,
            )
            resp = await asyncio.wait_for(fut, timeout)
            if resp.status != 0:
                raise RuntimeError(
                    f"admin {kind.name} to {peer.short()}: "
                    f"status={resp.status} {resp.body[:120]!r}"
                )
            return resp
        finally:
            self._admin_futs.pop(nonce, None)

    # -- send helpers -------------------------------------------------------

    def _send(self, payload, recipient: NodeId) -> None:
        msg = ProtocolMessage.new(self.node_id, payload, recipient)
        data = self.serializer.serialize(msg)
        try:
            self._net.send_to_nowait(recipient, data)
        except RabiaError:
            logger.warning(
                "fleet %s: send of %s to %s failed",
                self.config.name,
                type(payload).__name__,
                recipient.short(),
            )

    def _send_result(
        self,
        client_id: uuid.UUID,
        seq: int,
        status: int,
        payload: tuple[bytes, ...],
    ) -> None:
        # a client's transport identity IS NodeId(client_id); the net
        # routes to its newest connection (mux rebind on reconnect)
        self._send(
            Result(
                client_id=client_id, seq=seq, status=int(status),
                payload=tuple(payload),
            ),
            NodeId(client_id),
        )


# ---------------------------------------------------------------------------
# child protocol (testing/recovery.py shape): one fleet gateway per
# OS process, ready line on stdout, runs until SIGTERM
# ---------------------------------------------------------------------------


def _child_main(argv: list[str]) -> int:
    idx = int(argv[0])
    fleet_ports = json.loads(argv[1])  # one bind port per fleet member
    upstream_addrs = json.loads(argv[2])  # [[host, port], ...]
    n_shards = int(argv[3])
    extras = json.loads(argv[4]) if len(argv) > 4 else {}

    import os

    async def run() -> int:
        gw = FleetGateway(
            FleetGatewayConfig(
                name=f"gw{idx}",
                bind_port=int(fleet_ports[idx]),
                upstreams=tuple(
                    (str(h), int(p)) for h, p in upstream_addrs
                ),
                n_shards=n_shards,
                replication_factor=int(extras.get("rf", 2)),
                forward_timeout=float(extras.get("forward_timeout", 30.0)),
                # shard-group routing (fleet/groups.py): extras carry
                # the per-group upstream lanes + the GroupMap doc
                upstream_groups=tuple(
                    tuple((str(h), int(p)) for h, p in grp)
                    for grp in extras.get("upstream_groups", [])
                ),
                groups=extras.get("groups"),
            ),
            # deterministic ids so parents build the ring and MOVED
            # targets without a handshake (recovery.py's 1000+i idiom,
            # offset to keep the id spaces disjoint)
            node_id=NodeId.from_int(2000 + idx),
        )
        await gw.start()
        ring = HashRing()
        for j, port in enumerate(fleet_ports):
            ring.add(RingMember(
                name=f"gw{j}", host="127.0.0.1", port=int(port),
                node=NodeId.from_int(2000 + j),
            ))
        gw.adopt_ring(ring)
        print(
            json.dumps({
                "event": "ready",
                "pid": os.getpid(),
                "name": gw.config.name,
                "port": gw.port,
                "owned_shards": ring.owned_shards(gw.config.name, n_shards),
            }),
            flush=True,
        )
        await asyncio.Event().wait()  # until SIGTERM/SIGKILL
        return 0

    return asyncio.run(run())


if __name__ == "__main__":
    import sys

    if len(sys.argv) >= 2 and sys.argv[1] == "--child":
        sys.exit(_child_main(sys.argv[2:]))
    print(
        "usage: python -m rabia_tpu.fleet.gateway_proc --child ... "
        "(spawned by fleet/harness.py FleetProcHarness)",
        file=sys.stderr,
    )
    sys.exit(2)
