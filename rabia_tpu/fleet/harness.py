"""Fleet test/bench fabric: resolver, MOVED-following client, harnesses.

Four pieces shared by tests/test_fleet.py, the chaos plane's fleet
fabric (rabia_tpu/chaos/runner.py) and ``benchmarks/loadgen.py
--fleet``:

- :class:`FleetResolver` — a client-side hash-ring view: shard ->
  gateway address, updated from ``MOVED`` redirects and refreshable
  from any live member's ``AdminKind.RING`` frame;
- :class:`FleetSession` — ONE client identity across the whole fleet.
  Follows MOVED, retries RETRY, and fails over to ring successors when
  a gateway dies mid-call — always re-sending the SAME seq, so the
  session tables (fleet tier, then replica tier, then the engine's
  deterministic batch ids) enforce exactly-once end to end;
- :class:`FleetHarness` — in-process: a real-TCP GatewayCluster plus N
  in-process :class:`~rabia_tpu.fleet.gateway_proc.FleetGateway`\\ s on
  the same loop, with rebalance/kill hooks;
- :class:`FleetProcHarness` — each fleet gateway as its own OS process
  (the testing/recovery.py child protocol), so a SIGKILL is a real
  crash with no in-process cleanup.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import subprocess
import sys
import uuid
from typing import Optional, Sequence

from rabia_tpu.core.messages import AdminKind, Result, ResultStatus
from rabia_tpu.core.serialization import Serializer
from rabia_tpu.core.types import NodeId
from rabia_tpu.fleet.gateway_proc import FleetGateway, FleetGatewayConfig
from rabia_tpu.fleet.ring import HashRing, RingMember
from rabia_tpu.testing.loadsession import LoadSession, MuxConn
from rabia_tpu.testing.multiproc import REPO, free_ports

Addr = tuple[str, int]


class FleetResolver:
    """Client-side ring view with per-shard MOVED overrides."""

    def __init__(self, ring: HashRing) -> None:
        self.ring = ring
        self.overrides: dict[int, Addr] = {}

    def addr_for(self, shard: int) -> Optional[Addr]:
        ov = self.overrides.get(shard)
        if ov is not None:
            return ov
        m = self.ring.owner(shard)
        return (m.host, m.port) if m is not None else None

    def candidates(self, shard: int) -> list[Addr]:
        """Failover order: current answer first, then every distinct
        ring successor clockwise from the shard's point."""
        out: list[Addr] = []
        first = self.addr_for(shard)
        if first is not None:
            out.append(first)
        for m in self.ring.successors(shard, len(self.ring)):
            a = (m.host, m.port)
            if a not in out:
                out.append(a)
        return out

    def note_moved(self, shard: int, addr: Addr) -> None:
        self.overrides[shard] = addr

    def update(self, ring: HashRing) -> None:
        self.ring = ring
        self.overrides.clear()

    async def refresh(self, timeout: float = 5.0) -> bool:
        """Re-fetch the ring from any live member (after a kill the
        stale view's MOVED chain dead-ends; survivors know the truth)."""
        from rabia_tpu.gateway.client import admin_fetch

        addrs = {(m.host, m.port) for m in self.ring.members.values()}
        addrs.update(self.overrides.values())
        for host, port in addrs:
            try:
                body = await admin_fetch(
                    host, port, kind=int(AdminKind.RING), timeout=timeout
                )
                doc = json.loads(body.decode())
                self.update(HashRing.from_doc(doc["ring"]))
                return True
            except Exception:
                continue
        return False


class FleetConnPool:
    """Shared mux connections: one :class:`MuxConn` per gateway address
    serves EVERY session's frames there — the 10^5-session lane (a
    session costs a dict entry, not a socket)."""

    def __init__(self, ser: Serializer) -> None:
        self.ser = ser
        self.muxes: dict[Addr, MuxConn] = {}
        self._dialing: dict[Addr, asyncio.Lock] = {}

    async def attach(
        self, session: LoadSession, addr: Addr, timeout: float = 10.0
    ) -> LoadSession:
        lock = self._dialing.setdefault(addr, asyncio.Lock())
        async with lock:
            mux = self.muxes.get(addr)
            if mux is None or mux.writer is None or mux.writer.is_closing():
                mux = MuxConn(self.ser)
                await mux.connect(addr[0], addr[1], timeout)
                self.muxes[addr] = mux
        return await session.connect_mux(mux, timeout)

    def drop(self, addr: Addr) -> None:
        mux = self.muxes.pop(addr, None)
        if mux is not None:
            asyncio.ensure_future(mux.close())

    async def close(self) -> None:
        muxes, self.muxes = list(self.muxes.values()), {}
        for mux in muxes:
            await mux.close()


class FleetSession:
    """One client identity routed across the fleet (see module doc)."""

    def __init__(
        self,
        ser: Serializer,
        resolver: FleetResolver,
        client_id: Optional[uuid.UUID] = None,
        pool: Optional[FleetConnPool] = None,
        call_timeout: float = 5.0,
    ) -> None:
        self.ser = ser
        self.resolver = resolver
        self.client_id = client_id or uuid.uuid4()
        self.pool = pool
        self.call_timeout = call_timeout
        self.conns: dict[Addr, LoadSession] = {}
        self._seq = 0
        self._dial_lock = asyncio.Lock()
        self.redirects = 0  # MOVED hops followed
        self.failovers = 0  # dead-gateway candidate advances

    async def _conn(self, addr: Addr, timeout: float) -> LoadSession:
        ls = self.conns.get(addr)
        if ls is not None:
            return ls
        # serialize dials: two concurrent submits racing a fresh dial
        # would register two LoadSessions under ONE client id (the
        # second overwrites the first's mux slot, stranding its futures)
        async with self._dial_lock:
            ls = self.conns.get(addr)
            if ls is not None:
                return ls
            ls = LoadSession(self.ser, client_id=self.client_id)
            if self.pool is not None:
                await self.pool.attach(ls, addr, timeout)
            else:
                await ls.connect(addr[0], addr[1], timeout)
            self.conns[addr] = ls
            return ls

    async def _drop(self, addr: Addr) -> None:
        ls = self.conns.pop(addr, None)
        if ls is not None:
            try:
                await ls.close()
            except Exception:
                pass
        if self.pool is not None:
            self.pool.drop(addr)

    async def submit(
        self, shard: int, commands: Sequence[bytes], timeout: float = 20.0
    ) -> Result:
        self._seq += 1
        return await self.submit_seq(self._seq, shard, commands, timeout)

    async def submit_seq(
        self,
        seq: int,
        shard: int,
        commands: Sequence[bytes],
        timeout: float = 20.0,
    ) -> Result:
        """Drive one seq to an answer, re-sending the SAME seq across
        MOVED redirects, RETRY backoffs and gateway failovers."""
        if seq > self._seq:
            self._seq = seq
        loop = asyncio.get_event_loop()
        deadline = loop.time() + timeout
        tried: set[Addr] = set()
        addr = self.resolver.addr_for(shard)
        refreshed = False
        while True:
            remaining = deadline - loop.time()
            if remaining <= 0 or addr is None:
                raise TimeoutError(
                    f"fleet submit (client={self.client_id}, seq={seq}, "
                    f"shard={shard}) unanswered within {timeout}s"
                )
            call = min(self.call_timeout, remaining)
            try:
                ls = await self._conn(addr, call)
                res = await ls.submit_seq(seq, shard, commands, call)
            except (asyncio.TimeoutError, TimeoutError, ConnectionError,
                    OSError) as e:
                await self._drop(addr)
                tried.add(addr)
                addr = next(
                    (a for a in self.resolver.candidates(shard)
                     if a not in tried),
                    None,
                )
                if addr is not None:
                    self.failovers += 1
                    continue
                if not refreshed:
                    # every known candidate dead or stale: ask a
                    # survivor for the current ring, then start over
                    refreshed = await self.resolver.refresh(
                        timeout=min(5.0, max(0.5, remaining))
                    )
                    if refreshed:
                        tried.clear()
                        addr = self.resolver.addr_for(shard)
                        continue
                raise TimeoutError(
                    f"fleet submit seq={seq}: no live gateway ({e})"
                ) from None
            st = res.status
            if st == ResultStatus.MOVED:
                host, _, port = res.payload[0].decode().rpartition(":")
                addr = (host, int(port))
                self.resolver.note_moved(shard, addr)
                self.redirects += 1
                continue
            if st == ResultStatus.RETRY:
                await asyncio.sleep(min(0.05, max(0.0, remaining)))
                continue
            return res

    async def close(self) -> None:
        conns, self.conns = list(self.conns.values()), {}
        for ls in conns:
            try:
                await ls.close()
            except Exception:
                pass


class FleetHarness:
    """In-process fleet: real-TCP replica cluster + N FleetGateways on
    this loop, with the rebalance and kill hooks chaos/tests drive."""

    def __init__(
        self,
        n_gateways: int = 2,
        n_replicas: int = 3,
        n_shards: int = 4,
        replication_factor: int = 2,
        persistence: bool | str = True,
        gateway_config=None,
        forward_timeout: float = 20.0,
        waiter_timeout: float = 5.0,
        vnodes: int = 16,
    ) -> None:
        from rabia_tpu.testing.gateway_cluster import GatewayCluster

        self.n_gateways = n_gateways
        self.n_shards = n_shards
        self.rf = replication_factor
        self.vnodes = vnodes
        self.forward_timeout = forward_timeout
        self.waiter_timeout = waiter_timeout
        self.cluster = GatewayCluster(
            n_replicas=n_replicas,
            n_shards=n_shards,
            gateway_config=gateway_config,
            persistence=persistence,
        )
        self.gateways: list[Optional[FleetGateway]] = []
        self.ser = Serializer()

    async def start(self) -> None:
        await self.cluster.start()
        upstreams = tuple(
            (ep.host, ep.port) for ep in self.cluster.endpoints()
        )
        self.gateways = [
            FleetGateway(
                FleetGatewayConfig(
                    name=f"gw{i}",
                    upstreams=upstreams,
                    n_shards=self.n_shards,
                    replication_factor=self.rf,
                    forward_timeout=self.forward_timeout,
                    waiter_timeout=self.waiter_timeout,
                ),
                node_id=NodeId.from_int(2000 + i),
            )
            for i in range(self.n_gateways)
        ]
        for gw in self.gateways:
            await gw.start()
        ring = self.build_ring(range(self.n_gateways))
        for gw in self.gateways:
            gw.adopt_ring(ring.copy())

    def build_ring(self, indices) -> HashRing:
        ring = HashRing(vnodes=self.vnodes)
        for i in indices:
            gw = self.gateways[i]
            ring.add(gw.member())
        return ring

    def live_indices(self) -> list[int]:
        return [i for i, g in enumerate(self.gateways) if g is not None]

    def resolver(self) -> FleetResolver:
        ring = self.build_ring(self.live_indices())
        return FleetResolver(ring)

    async def rebalance(self, indices) -> None:
        """Push a new membership view to every LIVE gateway; members
        losing shards hand their sessions off before answering MOVED."""
        ring = self.build_ring(indices)
        await asyncio.gather(*(
            self.gateways[i]._rebalance(ring.copy())
            for i in self.live_indices()
        ))

    async def kill_gateway(self, i: int) -> None:
        """Abrupt death: NO handoff runs (close only tears the tasks
        down); survivors then adopt the shrunken ring. Redirected
        replays must be answered by the replicated ledger records."""
        gw = self.gateways[i]
        self.gateways[i] = None
        if gw is not None:
            await gw.close()
        await self.rebalance(self.live_indices())

    async def stop(self) -> None:
        for i, gw in enumerate(self.gateways):
            if gw is not None:
                await gw.close()
                self.gateways[i] = None
        await self.cluster.stop()


class FleetProcHarness:
    """N fleet gateways as real OS processes (SIGKILL-able), proxying
    to an externally managed replica cluster's gateway endpoints."""

    def __init__(
        self,
        upstream_addrs: list[Addr],
        n_gateways: int = 2,
        n_shards: int = 4,
        extras: Optional[dict] = None,
    ) -> None:
        from rabia_tpu.testing.recovery import ReplicaProc

        self._proc_cls = ReplicaProc
        self.upstream_addrs = [list(a) for a in upstream_addrs]
        self.n = n_gateways
        self.n_shards = n_shards
        self.extras = dict(extras or {})
        self.ports = free_ports(n_gateways)
        self.procs: list[Optional[object]] = [None] * n_gateways

    def _spawn(self, i: int):
        env = dict(os.environ)
        env["PYTHONPATH"] = f"{REPO}{os.pathsep}" + env.get("PYTHONPATH", "")
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "rabia_tpu.fleet.gateway_proc",
                "--child", str(i),
                json.dumps(self.ports), json.dumps(self.upstream_addrs),
                str(self.n_shards), json.dumps(self.extras),
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            text=True,
            env=env,
            cwd=REPO,
        )
        rp = self._proc_cls(proc)
        self.procs[i] = rp
        return rp

    def start(self, timeout: float = 60.0) -> list[dict]:
        for i in range(self.n):
            self._spawn(i)
        return [
            self.procs[i].wait_event("ready", timeout) for i in range(self.n)
        ]

    def ring(self, indices: Optional[Sequence[int]] = None) -> HashRing:
        ring = HashRing()
        for i in (range(self.n) if indices is None else indices):
            ring.add(RingMember(
                name=f"gw{i}", host="127.0.0.1", port=self.ports[i],
                node=NodeId.from_int(2000 + i),
            ))
        return ring

    async def push_ring(
        self, indices: Sequence[int], timeout: float = 10.0
    ) -> HashRing:
        """The control-plane move an operator makes after a member
        dies or joins: push the new membership to every named member
        over the RING admin frame ({"op": "set"}); each adoption runs
        the handoff protocol for shards it is losing."""
        from rabia_tpu.gateway.client import admin_fetch

        ring = self.ring(indices)
        query = json.dumps(
            {"op": "set", "ring": ring.to_doc()}
        ).encode()
        for i in indices:
            await admin_fetch(
                "127.0.0.1", self.ports[i],
                kind=int(AdminKind.RING), timeout=timeout, query=query,
            )
        return ring

    def kill9(self, i: int) -> None:
        rp = self.procs[i]
        assert rp is not None
        rp.proc.send_signal(signal.SIGKILL)
        rp.proc.wait(timeout=10)
        self.procs[i] = None

    def stop(self) -> None:
        for rp in self.procs:
            if rp is not None and rp.proc.poll() is None:
                rp.proc.send_signal(signal.SIGTERM)
        for rp in self.procs:
            if rp is not None:
                try:
                    rp.proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    rp.proc.kill()
