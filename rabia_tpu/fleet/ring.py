"""Consistent-hash router: shard -> owning fleet gateway.

Classic ring with virtual nodes (blake2b points, deterministic across
processes — every gateway and every client resolver computes the SAME
ownership from the same membership doc, no coordination service). The
property the fleet tier leans on is **bounded movement**: removing a
member moves only the shards that member owned, and each of those moves
to the shard's next distinct successor on the ring — which is exactly
the gateway the dedup-ledger replication targeted
(:mod:`rabia_tpu.fleet.ledger`), so failover lands replays where the
records already are. Adding a member steals only the shards whose ring
point now falls to the newcomer.

Membership docs serialize to JSON (the ``AdminKind.RING`` body, the
``python -m rabia_tpu ring`` CLI, and the handoff trigger all speak it):
``{"version": N, "vnodes": V, "members": [{"name", "host", "port",
"node": hex}]}``.
"""

from __future__ import annotations

import hashlib
import uuid
from bisect import bisect_right
from dataclasses import dataclass
from typing import Optional

from rabia_tpu.core.types import NodeId

DEFAULT_VNODES = 64


def _point(data: bytes) -> int:
    return int.from_bytes(hashlib.blake2b(data, digest_size=8).digest(), "big")


def shard_point(shard: int) -> int:
    """The ring point a shard hashes to (stable across processes)."""
    return _point(b"shard:%d" % int(shard))


@dataclass(frozen=True)
class RingMember:
    """One fleet gateway's address card on the ring."""

    name: str
    host: str
    port: int
    node: NodeId  # the gateway's transport identity (MOVED carries it)

    def to_doc(self) -> dict:
        return {
            "name": self.name,
            "host": self.host,
            "port": self.port,
            "node": self.node.value.hex,
        }

    @staticmethod
    def from_doc(doc: dict) -> "RingMember":
        return RingMember(
            name=str(doc["name"]),
            host=str(doc["host"]),
            port=int(doc["port"]),
            node=NodeId(uuid.UUID(hex=doc["node"])),
        )


class HashRing:
    """Virtual-node consistent-hash ring over :class:`RingMember`s.

    ``version`` increments on every membership change; a gateway answers
    ``MOVED`` from its CURRENT view, and a client resolver updates its
    view from the redirect — stale views converge by following at most
    one redirect per change.
    """

    def __init__(self, vnodes: int = DEFAULT_VNODES) -> None:
        self.vnodes = max(1, int(vnodes))
        self.version = 0
        self.members: dict[str, RingMember] = {}
        self._points: list[int] = []  # sorted vnode hash points
        self._owners: list[str] = []  # member name per point

    # -- membership ---------------------------------------------------------

    def add(self, member: RingMember) -> None:
        self.members[member.name] = member
        self.version += 1
        self._rebuild()

    def remove(self, name: str) -> Optional[RingMember]:
        gone = self.members.pop(name, None)
        if gone is not None:
            self.version += 1
            self._rebuild()
        return gone

    def _rebuild(self) -> None:
        pts: list[tuple[int, str]] = []
        for name in self.members:
            for v in range(self.vnodes):
                pts.append((_point(f"{name}#{v}".encode()), name))
        pts.sort()
        self._points = [p for p, _ in pts]
        self._owners = [n for _, n in pts]

    # -- resolution ---------------------------------------------------------

    def owner(self, shard: int) -> Optional[RingMember]:
        """The member owning ``shard`` (None on an empty ring)."""
        if not self._points:
            return None
        i = bisect_right(self._points, shard_point(shard)) % len(self._points)
        return self.members[self._owners[i]]

    def successors(self, shard: int, k: int) -> list[RingMember]:
        """The shard's first ``k`` DISTINCT members clockwise from its
        ring point (``[0]`` is the owner). The replication group for the
        shard's dedup ledger is ``successors(shard, rf)``."""
        if not self._points:
            return []
        out: list[RingMember] = []
        seen: set[str] = set()
        start = bisect_right(self._points, shard_point(shard))
        n = len(self._points)
        for j in range(n):
            name = self._owners[(start + j) % n]
            if name not in seen:
                seen.add(name)
                out.append(self.members[name])
                if len(out) >= k:
                    break
        return out

    def owned_shards(self, name: str, n_shards: int) -> list[int]:
        return [
            s for s in range(n_shards)
            if (m := self.owner(s)) is not None and m.name == name
        ]

    # -- wire ---------------------------------------------------------------

    def to_doc(self) -> dict:
        return {
            "version": self.version,
            "vnodes": self.vnodes,
            "members": [
                self.members[n].to_doc() for n in sorted(self.members)
            ],
        }

    @staticmethod
    def from_doc(doc: dict) -> "HashRing":
        ring = HashRing(vnodes=int(doc.get("vnodes", DEFAULT_VNODES)))
        for m in doc.get("members", []):
            ring.members[str(m["name"])] = RingMember.from_doc(m)
        ring._rebuild()
        ring.version = int(doc.get("version", 0))
        return ring

    def copy(self) -> "HashRing":
        return HashRing.from_doc(self.to_doc())

    def __len__(self) -> int:
        return len(self.members)


def moved_shards(old: HashRing, new: HashRing, n_shards: int) -> dict[int, str]:
    """Shards whose owner changed between two views:
    ``{shard: new_owner_name}``. This is both the handoff work list and
    the bounded-movement assertion surface (a one-member change moves
    only that member's shards)."""
    out: dict[int, str] = {}
    for s in range(n_shards):
        a, b = old.owner(s), new.owner(s)
        if b is not None and (a is None or a.name != b.name):
            out[s] = b.name
    return out
