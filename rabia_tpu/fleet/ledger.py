"""Replicated dedup ledger: completed-result records for gateway failover.

Round 13 left non-lead coalesced aliases proposer-local: a replay that
lands on a DIFFERENT gateway than the one that drove the original Submit
could not be answered from any cache until the session lease timed out.
The fleet tier closes that hole above the replica layer: when a fleet
gateway completes a Submit (OK or terminal ERROR), it encodes the
``(client_id, seq) -> (status, payload)`` record and replicates it to
the shard's gateway group — the shard's ring successors
(:meth:`~rabia_tpu.fleet.ring.HashRing.successors`), which by
bounded-movement consistent hashing are exactly the gateways that take
the shard over on failover. A replay arriving at the new owner is then
answered **byte-identical** from the imported record instead of being
re-forwarded (and the engine's deterministic-batch-id ledger backstops
the replication race: a record lost in flight re-proposes under the
SAME batch id and still cannot double-apply).

:func:`apply_record` imports a record through the session table's
op-level conformance surface (hello-free ``submit_check`` +
``complete_op``), so it behaves identically on the Python semantics
owner and the native C table — pinned by the gateway-ops conformance
gate's ``ledger`` op (testing/conformance.py).

Wire format (the ``AdminKind.LEDGER`` query body; little-endian):
``u32 count`` then per record ``[16B client id][u64 seq][u32 shard]
[u8 status][u32 nparts][nparts x (u32 len + bytes)]``.
"""

from __future__ import annotations

import struct
import uuid
from dataclasses import dataclass

from rabia_tpu.gateway.session import (
    SUBMIT_DUP_CACHED,
    SUBMIT_DUP_INFLIGHT,
    SUBMIT_FRESH,
)


@dataclass(frozen=True)
class LedgerRecord:
    """One completed ``(client_id, seq)`` outcome, replication-ready."""

    client_id: uuid.UUID
    seq: int
    shard: int
    status: int
    payload: tuple[bytes, ...]


def encode_records(records: list[LedgerRecord]) -> bytes:
    out = [struct.pack("<I", len(records))]
    for r in records:
        out.append(r.client_id.bytes)
        out.append(struct.pack("<QIB", r.seq, r.shard, r.status))
        out.append(struct.pack("<I", len(r.payload)))
        for part in r.payload:
            out.append(struct.pack("<I", len(part)))
            out.append(part)
    return b"".join(out)


def decode_records(data: bytes) -> list[LedgerRecord]:
    pos = 4
    (count,) = struct.unpack_from("<I", data, 0)
    records: list[LedgerRecord] = []
    for _ in range(count):
        cid = uuid.UUID(bytes=data[pos : pos + 16])
        pos += 16
        seq, shard, status = struct.unpack_from("<QIB", data, pos)
        pos += 13
        (nparts,) = struct.unpack_from("<I", data, pos)
        pos += 4
        parts = []
        for _ in range(nparts):
            (ln,) = struct.unpack_from("<I", data, pos)
            pos += 4
            parts.append(bytes(data[pos : pos + ln]))
            pos += ln
        records.append(
            LedgerRecord(
                client_id=cid, seq=int(seq), shard=int(shard),
                status=int(status), payload=tuple(parts),
            )
        )
    return records


def apply_record(
    table,
    client_id: uuid.UUID,
    seq: int,
    status: int,
    payload: tuple[bytes, ...],
    frontier_mark: int,
    now=None,
) -> int:
    """Import one replicated completed-result record into a session
    table (Python or native — identical semantics, conformance-pinned).

    The record lands through the normal op surface: ``submit_check``
    classifies the seq, then

    - ``FRESH``: the reservation just taken is completed with the
      record — the replay-answering cache entry;
    - ``DUP_INFLIGHT``: a reservation already existed (an imported
      handoff reservation, or a replay raced ahead of the record) —
      completing it resolves the pending seq with the authoritative
      outcome;
    - ``DUP_CACHED``: already answered here; the record is a no-op;
    - ``SHED_WINDOW``: the session's inflight window is full of real
      reservations — the record is dropped and a later replay
      re-forwards (the engine's deterministic-id ledger still blocks a
      double apply).

    Returns the ``submit_check`` decision so callers (and the
    conformance gate) can observe which path the import took."""
    decision, _st, _pl = table.submit_check(client_id, seq, 0, now=now)
    if decision in (SUBMIT_FRESH, SUBMIT_DUP_INFLIGHT):
        table.complete_op(
            client_id, seq, status, payload, frontier_mark, now=now
        )
    elif decision == SUBMIT_DUP_CACHED:
        pass  # already answered here, byte-identity guaranteed upstream
    return decision
