"""Routed gateway fleet: many standalone gateway processes behind a
consistent-hash front door (docs/FLEET.md).

- :mod:`rabia_tpu.fleet.ring` — the consistent-hash router mapping
  shard -> owning gateway, with bounded-movement rebalance;
- :mod:`rabia_tpu.fleet.ledger` — completed-result records replicated
  to the shard's gateway group (exactly-once across gateway failover);
- :mod:`rabia_tpu.fleet.handoff` — session transfer on planned
  rebalance (windows, ack frontiers, inflight reservations);
- :mod:`rabia_tpu.fleet.gateway_proc` — the standalone gateway itself:
  holds sessions and forward windows, proxies Submits to the replica
  cluster over the mux transport lane, answers ``MOVED`` for shards it
  does not own;
- :mod:`rabia_tpu.fleet.harness` — in-process fleet harness + the
  MOVED-following client session used by tests/chaos/bench;
- :mod:`rabia_tpu.fleet.groups` — shard-group scale-out: the versioned
  GroupMap partitioning the shard space into independent consensus
  groups (own replica processes, WAL, coalescing windows), the
  GroupRouter resolving shard -> owning group's upstream, and the
  process-group harnesses.
"""

from rabia_tpu.fleet.ring import HashRing, RingMember, moved_shards
from rabia_tpu.fleet.ledger import LedgerRecord, apply_record
from rabia_tpu.fleet.gateway_proc import FleetGateway, FleetGatewayConfig
from rabia_tpu.fleet.groups import (
    GroupMap,
    GroupRouter,
    GroupProcHarness,
    GroupedFleetHarness,
    moved_group_shards,
)

__all__ = [
    "HashRing",
    "RingMember",
    "moved_shards",
    "LedgerRecord",
    "apply_record",
    "FleetGateway",
    "FleetGatewayConfig",
    "GroupMap",
    "GroupRouter",
    "GroupProcHarness",
    "GroupedFleetHarness",
    "moved_group_shards",
]
