"""Shard-group scale-out: partitioned consensus groups (docs/FLEET.md).

One replica process set can only spend one machine's cores and one WAL's
fsync lane. This module partitions the SHARD SPACE itself into
independent consensus **groups** — each group is a complete Rabia
cluster (its own replica processes, its own runtime, its own WAL
directory, its own coalescing windows) that owns a contiguous range of
the global shard ids. Nothing crosses a group boundary: Submits route by
shard to the owning group, coalesced PayloadBlocks pack only one shard
(hence one group), read-index probe rounds stay inside the owning
group's quorum, and the dedup/alias exactly-once ledger is per group —
deterministic batch ids derive from ``(client_id, seq)``, so a replay
that lands on a re-routed group dedups against whatever that group
already applied.

The pieces:

- :class:`GroupMap` — the versioned routing doc (the hash ring's
  bounded-movement idiom applied to contiguous ranges): sorted
  half-open ``[lo, hi) -> group id`` ranges covering the whole shard
  space. ``move_range`` bumps the version and moves ONLY the shards in
  the moved range (:func:`moved_group_shards` is the assertion
  surface). JSON doc on the wire: ``{"version": N, "n_shards": S,
  "ranges": [[lo, hi, gid], ...]}``.
- :class:`GroupRouter` — GroupMap + per-group upstream address lists;
  resolves ``shard -> (host, port)`` with the same ``shard % len``
  spreading the flat fleet tier uses inside one group. Version-gated
  ``adopt`` so a stale push never rolls routing back.
- :class:`GroupProcHarness` — one durable
  :class:`~rabia_tpu.testing.recovery.RecoveryHarness` per group (real
  OS processes, real SIGKILL), each under its own WAL subtree, each
  child told its group id + owned ranges so the replica gateways
  ENFORCE group locality (out-of-range Submits shed retryable).
- :class:`GroupedFleetHarness` — fleet gateways
  (:mod:`rabia_tpu.fleet.gateway_proc`) configured with
  ``upstream_groups`` so the routed-fleet front door sends each Submit
  to the owning group's upstream lane.

Rebalance is ROUTING-PLANE ONLY (state does not migrate between
groups — see docs/FLEET.md for the honest limitation): the safe order
is widen-the-new-owner first (replica gateways accept the range), flip
the GroupMap at the routing tier, then shrink the old owner. A replay
that crosses the flip dedups at the routing tier's session cache, or —
past it — against the group ledger its original commit lives in.
"""

from __future__ import annotations

import asyncio
import os
from bisect import bisect_right
from typing import Optional, Sequence

__all__ = [
    "GroupMap",
    "GroupRouter",
    "GroupProcHarness",
    "GroupedFleetHarness",
    "moved_group_shards",
]


class GroupMap:
    """Versioned contiguous shard-range -> group-id map.

    Invariants (checked on every mutation): ranges are sorted,
    half-open, non-overlapping, and cover ``[0, n_shards)`` exactly.
    ``version`` bumps on every change; routers adopt only strictly
    newer docs (the hash ring's convergence rule).
    """

    def __init__(
        self, n_shards: int, ranges: Sequence[tuple[int, int, int]]
    ) -> None:
        self.n_shards = int(n_shards)
        self.version = 0
        self._ranges: list[tuple[int, int, int]] = []
        self._set_ranges(ranges)

    # -- construction -------------------------------------------------------

    @staticmethod
    def initial(n_shards: int, n_groups: int) -> "GroupMap":
        """The even contiguous partition: group g owns
        ``[g*S/G, (g+1)*S/G)`` (remainder spread over the low groups).
        Deterministic across processes — every router computes the SAME
        bootstrap map from ``(n_shards, n_groups)`` alone."""
        if n_groups < 1 or n_groups > n_shards:
            raise ValueError(
                f"n_groups must be in [1, {n_shards}], got {n_groups}"
            )
        base, rem = divmod(n_shards, n_groups)
        ranges = []
        lo = 0
        for g in range(n_groups):
            hi = lo + base + (1 if g < rem else 0)
            ranges.append((lo, hi, g))
            lo = hi
        return GroupMap(n_shards, ranges)

    def _set_ranges(
        self, ranges: Sequence[tuple[int, int, int]]
    ) -> None:
        rs = sorted(
            (int(lo), int(hi), int(g)) for lo, hi, g in ranges
        )
        cursor = 0
        for lo, hi, g in rs:
            if lo != cursor or hi <= lo or g < 0:
                raise ValueError(
                    f"ranges must tile [0, {self.n_shards}) contiguously; "
                    f"got {rs}"
                )
            cursor = hi
        if cursor != self.n_shards:
            raise ValueError(
                f"ranges cover [0, {cursor}), need [0, {self.n_shards})"
            )
        # merge adjacent same-group ranges so the doc stays canonical
        # (two equal maps serialize identically regardless of history)
        merged: list[tuple[int, int, int]] = []
        for lo, hi, g in rs:
            if merged and merged[-1][2] == g and merged[-1][1] == lo:
                merged[-1] = (merged[-1][0], hi, g)
            else:
                merged.append((lo, hi, g))
        self._ranges = merged
        self._los = [lo for lo, _hi, _g in merged]

    # -- resolution ---------------------------------------------------------

    def group_of(self, shard: int) -> int:
        if not (0 <= shard < self.n_shards):
            raise ValueError(
                f"shard {shard} outside [0, {self.n_shards})"
            )
        i = bisect_right(self._los, shard) - 1
        return self._ranges[i][2]

    def groups(self) -> list[int]:
        return sorted({g for _lo, _hi, g in self._ranges})

    def ranges(self) -> list[tuple[int, int, int]]:
        return list(self._ranges)

    def ranges_of(self, group: int) -> list[tuple[int, int]]:
        return [
            (lo, hi) for lo, hi, g in self._ranges if g == int(group)
        ]

    def shards_of(self, group: int) -> list[int]:
        return [
            s
            for lo, hi, g in self._ranges
            if g == int(group)
            for s in range(lo, hi)
        ]

    # -- mutation -----------------------------------------------------------

    def move_range(self, lo: int, hi: int, group: int) -> None:
        """Reassign ``[lo, hi)`` to ``group``; every shard outside the
        moved range keeps its owner (bounded movement, asserted by
        :func:`moved_group_shards` in tests)."""
        if not (0 <= lo < hi <= self.n_shards):
            raise ValueError(
                f"[{lo}, {hi}) outside [0, {self.n_shards})"
            )
        out: list[tuple[int, int, int]] = []
        for rlo, rhi, g in self._ranges:
            # the part of [rlo, rhi) below / above the moved range
            if rlo < lo:
                out.append((rlo, min(rhi, lo), g))
            if rhi > hi:
                out.append((max(rlo, hi), rhi, g))
        out.append((lo, hi, int(group)))
        self._set_ranges(out)
        self.version += 1

    # -- wire ---------------------------------------------------------------

    def to_doc(self) -> dict:
        return {
            "version": self.version,
            "n_shards": self.n_shards,
            "ranges": [[lo, hi, g] for lo, hi, g in self._ranges],
        }

    @staticmethod
    def from_doc(doc: dict) -> "GroupMap":
        gm = GroupMap(
            int(doc["n_shards"]),
            [tuple(r) for r in doc["ranges"]],
        )
        gm.version = int(doc.get("version", 0))
        return gm

    def copy(self) -> "GroupMap":
        return GroupMap.from_doc(self.to_doc())

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, GroupMap)
            and self.n_shards == other.n_shards
            and self._ranges == other._ranges
        )

    def __repr__(self) -> str:
        return (
            f"GroupMap(v{self.version}, "
            + ", ".join(
                f"[{lo},{hi})->g{g}" for lo, hi, g in self._ranges
            )
            + ")"
        )


def moved_group_shards(old: GroupMap, new: GroupMap) -> dict[int, int]:
    """Shards whose owning group changed between two maps:
    ``{shard: new_group}`` — the bounded-movement assertion surface
    (a ``move_range(lo, hi, g)`` moves only shards in ``[lo, hi)``)."""
    if old.n_shards != new.n_shards:
        raise ValueError("maps cover different shard spaces")
    return {
        s: new.group_of(s)
        for s in range(old.n_shards)
        if old.group_of(s) != new.group_of(s)
    }


class GroupRouter:
    """GroupMap + per-group upstream addresses -> a shard's dial target.

    Within a group the same deterministic ``shard % len(addrs)`` spread
    the flat fleet tier uses applies, so a group's replica-side
    coalescing windows still see concentrated per-shard arrivals."""

    def __init__(
        self,
        group_map: GroupMap,
        upstreams: dict[int, Sequence[tuple[str, int]]],
    ) -> None:
        self.group_map = group_map
        self.upstreams: dict[int, list[tuple[str, int]]] = {
            int(g): [(str(h), int(p)) for h, p in addrs]
            for g, addrs in upstreams.items()
        }
        for g in group_map.groups():
            if not self.upstreams.get(g):
                raise ValueError(f"group {g} has no upstream addresses")

    def group_of(self, shard: int) -> int:
        return self.group_map.group_of(shard)

    def upstream_for(self, shard: int) -> tuple[str, int]:
        addrs = self.upstreams[self.group_map.group_of(shard)]
        return addrs[shard % len(addrs)]

    def candidates(self, shard: int) -> list[tuple[str, int]]:
        """Every address of the owning group, preferred first — the
        client failover order when the preferred replica is down."""
        addrs = self.upstreams[self.group_map.group_of(shard)]
        k = shard % len(addrs)
        return addrs[k:] + addrs[:k]

    def adopt(self, new_map: GroupMap) -> bool:
        """Install a strictly newer map; a stale or same-version push is
        ignored (returns False) so races never roll routing back."""
        if new_map.version <= self.group_map.version:
            return False
        if new_map.n_shards != self.group_map.n_shards:
            raise ValueError("adopted map covers a different shard space")
        self.group_map = new_map
        return True


# ---------------------------------------------------------------------------
# Process-group harness: one durable replica process set per group
# ---------------------------------------------------------------------------


class GroupProcHarness:
    """N independent durable consensus groups, each a
    :class:`~rabia_tpu.testing.recovery.RecoveryHarness` of real OS
    processes under its own WAL subtree. Every child is configured with
    the FULL global shard space plus its group's owned ranges, so the
    replica gateways enforce group locality and the per-shard metric
    labels stay globally meaningful across groups."""

    def __init__(
        self,
        group_map: GroupMap,
        n_replicas: int = 3,
        wal_root: Optional[str] = None,
        extras: Optional[dict] = None,
    ) -> None:
        import tempfile

        from rabia_tpu.testing.recovery import RecoveryHarness

        self.group_map = group_map
        self.n_replicas = n_replicas
        self.wal_root = wal_root or tempfile.mkdtemp(prefix="rabia-groups-")
        self.harnesses: dict[int, RecoveryHarness] = {}
        for g in group_map.groups():
            gx = dict(extras or {})
            gx["group"] = g
            gx["group_shards"] = [
                [lo, hi] for lo, hi in group_map.ranges_of(g)
            ]
            self.harnesses[g] = RecoveryHarness(
                n_replicas=n_replicas,
                n_shards=group_map.n_shards,
                wal_root=os.path.join(self.wal_root, f"group-{g}"),
                extras=gx,
            )

    def start(self, timeout: float = 120.0) -> dict[int, list[dict]]:
        """Spawn every group's replicas; returns ready reports by group.
        Groups spawn together and are awaited together, so wall time is
        one group's startup, not the sum."""
        for h in self.harnesses.values():
            for i in range(h.n):
                h._spawn(i)
        return {
            g: [
                h.procs[i].wait_event("ready", timeout)
                for i in range(h.n)
            ]
            for g, h in self.harnesses.items()
        }

    def endpoints(self, group: int):
        return self.harnesses[group].endpoints()

    def upstream_addrs(self) -> dict[int, list[tuple[str, int]]]:
        """``{group: [(host, port), ...]}`` of replica gateway ports —
        the :class:`GroupRouter` construction input."""
        return {
            g: [("127.0.0.1", p) for p in h.gw_ports]
            for g, h in self.harnesses.items()
        }

    def router(self) -> GroupRouter:
        return GroupRouter(self.group_map, self.upstream_addrs())

    def kill9(self, group: int, idx: int) -> None:
        self.harnesses[group].kill9(idx)

    def restart(self, group: int, idx: int, timeout: float = 120.0) -> dict:
        return self.harnesses[group].restart(idx, timeout)

    def alive(self) -> dict[int, int]:
        """Live replica processes per group (the watchdog membership
        sample: a killed proposer reads as members_alive < total)."""
        out: dict[int, int] = {}
        for g, h in self.harnesses.items():
            out[g] = sum(
                1
                for rp in h.procs
                if rp is not None and rp.proc.poll() is None
            )
        return out

    async def rebalance(self, lo: int, hi: int, group: int) -> GroupMap:
        """Move ``[lo, hi)`` to ``group`` in the SAFE order: widen the
        new owner's replica gateways first (they accept the range before
        any router sends it), then flip the map, then shrink the old
        owners. Returns the new map (callers push it to their routing
        tier — this harness owns only the replica plane)."""
        new_map = self.group_map.copy()
        new_map.move_range(lo, hi, group)
        await self._push_group_ranges(group, new_map.ranges_of(group))
        old_map, self.group_map = self.group_map, new_map
        for g in old_map.groups():
            if g != group and new_map.ranges_of(g) != old_map.ranges_of(g):
                await self._push_group_ranges(g, new_map.ranges_of(g))
        # refresh spawn extras so a replica restarted AFTER the move
        # comes up owning the post-rebalance ranges, not the stale ones
        for g, h in self.harnesses.items():
            h.extras["group_shards"] = [
                [lo_, hi_] for lo_, hi_ in new_map.ranges_of(g)
            ]
        return new_map

    async def _push_group_ranges(
        self, group: int, ranges: list[tuple[int, int]]
    ) -> None:
        import json

        from rabia_tpu.core.messages import AdminKind
        from rabia_tpu.gateway.client import admin_fetch

        h = self.harnesses[group]
        query = json.dumps(
            {"op": "set_group", "shards": [[lo, hi] for lo, hi in ranges]}
        ).encode()
        for i, port in enumerate(h.gw_ports):
            rp = h.procs[i]
            if rp is None or rp.proc.poll() is not None:
                continue  # a dead replica re-reads ranges on restart
            await admin_fetch(
                "127.0.0.1", port, kind=int(AdminKind.RING),
                timeout=10.0, query=query,
            )

    def stop(self) -> None:
        for h in self.harnesses.values():
            h.stop()


# ---------------------------------------------------------------------------
# Grouped fleet harness: fleet gateways routing to N replica planes
# ---------------------------------------------------------------------------


class GroupedFleetHarness:
    """Fleet gateways (:class:`~rabia_tpu.fleet.gateway_proc
    .FleetGateway`) configured with ``upstream_groups`` so the routed
    front door sends each Submit to the owning group's upstream lane.
    Owns only the routing tier — the replica planes behind it are
    whatever the caller built (in-process :class:`~rabia_tpu.testing
    .gateway_cluster.GatewayCluster`s or a :class:`GroupProcHarness`)."""

    def __init__(
        self,
        group_map: GroupMap,
        upstreams: dict[int, Sequence[tuple[str, int]]],
        n_gateways: int = 1,
        replication_factor: int = 1,
        forward_timeout: float = 30.0,
    ) -> None:
        self.group_map = group_map
        self.upstreams = {
            int(g): [(str(h), int(p)) for h, p in addrs]
            for g, addrs in upstreams.items()
        }
        self.n_gateways = n_gateways
        self.replication_factor = replication_factor
        self.forward_timeout = forward_timeout
        self.gateways: list = []

    async def start(self) -> None:
        from rabia_tpu.core.types import NodeId
        from rabia_tpu.fleet.gateway_proc import (
            FleetGateway,
            FleetGatewayConfig,
        )
        from rabia_tpu.fleet.ring import HashRing

        groups = sorted(self.upstreams)
        upstream_groups = tuple(
            tuple(self.upstreams[g]) for g in groups
        )
        if groups != list(range(len(groups))):
            raise ValueError(
                "group ids must be dense 0..G-1 (they index "
                f"upstream_groups); got {groups}"
            )
        for i in range(self.n_gateways):
            gw = FleetGateway(
                FleetGatewayConfig(
                    name=f"gw{i}",
                    # flattened list: what the fleet aggregator walks to
                    # scrape the replica tier (every group's replicas)
                    upstreams=tuple(
                        a for grp in upstream_groups for a in grp
                    ),
                    upstream_groups=upstream_groups,
                    groups=self.group_map.to_doc(),
                    n_shards=self.group_map.n_shards,
                    replication_factor=self.replication_factor,
                    forward_timeout=self.forward_timeout,
                ),
                node_id=NodeId.from_int(2000 + i),
            )
            await gw.start()
            self.gateways.append(gw)
        ring = HashRing()
        for gw in self.gateways:
            ring.add(gw.member())
        for gw in self.gateways:
            gw.adopt_ring(ring.copy())

    def endpoints(self):
        from rabia_tpu.gateway.server import GatewayEndpoint

        return [
            GatewayEndpoint(
                node_id=gw.node_id,
                host=gw.config.bind_host,
                port=gw.port,
            )
            for gw in self.gateways
        ]

    def adopt_groups(self, new_map: GroupMap) -> None:
        """Flip routing on every fleet gateway (the middle step of the
        safe rebalance order — replica-side ranges widen first)."""
        self.group_map = new_map
        for gw in self.gateways:
            gw.adopt_groups(new_map.copy())

    async def stop(self) -> None:
        for gw in self.gateways:
            await gw.close()
        self.gateways.clear()
