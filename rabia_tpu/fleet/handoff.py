"""Session handoff: transfer live sessions between fleet gateways.

When the ring reassigns shards (member added/removed, planned drain),
the departing gateway exports every session homed to a moved shard —
window grant, ack frontier, cached results, inflight reservations — and
ships the blob to the new owner inside an ``AdminKind.HANDOFF`` admin
frame. Only after the import is acked does the departing gateway start
answering ``MOVED`` for those shards, so a redirected client's replay
always finds its dedup state already resident at the new owner.

Export reads the Python :class:`~rabia_tpu.gateway.session.SessionTable`
directly (the fleet gateway's table; the semantics owner). Import goes
through the op-level conformance surface only (``hello`` →
``submit_check`` → ``complete_op``), so it lands identically on the
native C table — the same property :mod:`rabia_tpu.fleet.ledger` leans
on. Two invariants make the replay lossless:

- results GC'd before export had ``seq <= ack_upto`` — the client
  acknowledged receipt and will never replay them;
- inflight seqs import as bare reservations (``SUBMIT_FRESH``, left
  open). The authoritative outcome arrives later as a replicated
  ledger record (``DUP_INFLIGHT`` → ``complete_op``) or the client's
  own replay re-drives it under the same deterministic batch id.

Wire format (little-endian): ``u32 nsessions`` then per session
``[16B client id][u32 window][u64 ack_upto][u32 nresults x (u64 seq,
u8 status, u32 nparts, parts)][u32 ninflight x u64 seq]``.
"""

from __future__ import annotations

import struct
import uuid
from dataclasses import dataclass, field
from typing import Iterable, Optional

from rabia_tpu.gateway.session import (
    SUBMIT_FRESH,
    SessionTable,
)


@dataclass(frozen=True)
class SessionExport:
    """One session's transferable state."""

    client_id: uuid.UUID
    window: int
    ack_upto: int
    # (seq, status, payload-parts) — the replayable result cache
    results: tuple[tuple[int, int, tuple[bytes, ...]], ...]
    inflight: tuple[int, ...]  # reserved-but-unfinished seqs


@dataclass
class HandoffSummary:
    """What an import actually landed (surfaced in logs/metrics)."""

    sessions: int = 0
    results: int = 0
    inflight: int = 0
    skipped: int = 0  # non-FRESH collisions (already present/shed here)
    clients: list = field(default_factory=list)


def export_sessions(
    table: SessionTable, client_ids: Iterable[uuid.UUID]
) -> list[SessionExport]:
    out: list[SessionExport] = []
    for cid in client_ids:
        sess = table.sessions.get(cid)
        if sess is None:
            continue
        out.append(
            SessionExport(
                client_id=cid,
                window=sess.window,
                ack_upto=sess.ack_upto,
                results=tuple(
                    (seq, r.status, r.payload)
                    for seq, r in sorted(sess.results.items())
                ),
                inflight=tuple(sorted(sess.inflight)),
            )
        )
    return out


def encode_handoff(exports: list[SessionExport]) -> bytes:
    out = [struct.pack("<I", len(exports))]
    for e in exports:
        out.append(e.client_id.bytes)
        out.append(struct.pack("<IQ", e.window, e.ack_upto))
        out.append(struct.pack("<I", len(e.results)))
        for seq, status, parts in e.results:
            out.append(struct.pack("<QBI", seq, status, len(parts)))
            for part in parts:
                out.append(struct.pack("<I", len(part)))
                out.append(part)
        out.append(struct.pack("<I", len(e.inflight)))
        for seq in e.inflight:
            out.append(struct.pack("<Q", seq))
    return b"".join(out)


def decode_handoff(data: bytes) -> list[SessionExport]:
    pos = 4
    (count,) = struct.unpack_from("<I", data, 0)
    exports: list[SessionExport] = []
    for _ in range(count):
        cid = uuid.UUID(bytes=data[pos : pos + 16])
        pos += 16
        window, ack_upto = struct.unpack_from("<IQ", data, pos)
        pos += 12
        (nres,) = struct.unpack_from("<I", data, pos)
        pos += 4
        results = []
        for _ in range(nres):
            seq, status, nparts = struct.unpack_from("<QBI", data, pos)
            pos += 13
            parts = []
            for _ in range(nparts):
                (ln,) = struct.unpack_from("<I", data, pos)
                pos += 4
                parts.append(bytes(data[pos : pos + ln]))
                pos += ln
            results.append((int(seq), int(status), tuple(parts)))
        (ninf,) = struct.unpack_from("<I", data, pos)
        pos += 4
        inflight = struct.unpack_from("<%dQ" % ninf, data, pos)
        pos += 8 * ninf
        exports.append(
            SessionExport(
                client_id=cid,
                window=int(window),
                ack_upto=int(ack_upto),
                results=tuple(results),
                inflight=tuple(int(s) for s in inflight),
            )
        )
    return exports


def import_sessions(
    table,
    exports: list[SessionExport],
    frontier_mark: int,
    now: Optional[float] = None,
) -> HandoffSummary:
    """Land exported sessions on the new owner's table via the op API.

    Per session: ``hello`` re-opens it with the granted window, then
    every cached result replays as ``submit_check`` (carrying the
    exported ack frontier — ``submit_check`` is the op-level way to
    advance it) followed by ``complete_op``, and every inflight seq
    reserves via ``submit_check`` and is deliberately left open. A
    non-FRESH decision means this table already knows the seq (replay
    raced the handoff, or a ledger record landed first) — counted as
    ``skipped``, never overwritten: first completion wins everywhere.
    """
    summary = HandoffSummary()
    for e in exports:
        table.hello(e.client_id, e.window, now=now)
        summary.sessions += 1
        summary.clients.append(e.client_id)
        for seq, status, parts in e.results:
            decision, _st, _pl = table.submit_check(
                e.client_id, seq, e.ack_upto, now=now
            )
            if decision == SUBMIT_FRESH:
                table.complete_op(
                    e.client_id, seq, status, parts, frontier_mark, now=now
                )
                summary.results += 1
            else:
                summary.skipped += 1
        for seq in e.inflight:
            decision, _st, _pl = table.submit_check(
                e.client_id, seq, e.ack_upto, now=now
            )
            if decision == SUBMIT_FRESH:
                summary.inflight += 1  # left reserved on purpose
            else:
                summary.skipped += 1
    return summary
